"""Benchmark: fused columnar SQL pipeline throughput on the TPU chip.

Measures the flagship whole-stage pipeline (filter -> project -> sort-based
group-by aggregate, DESIGN.md §2) on device over a ~8M-row batch — the
scan+filter+project+agg hot path of SURVEY.md §3.3 (BASELINE.md milestone
config 1/2). The same pipeline runs on pandas host CPU as the baseline, so
``vs_baseline`` is the TPU speedup over single-core pandas (the reference
repo publishes no numeric GPU baselines — BASELINE.md: "chart image only").

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np


def build_inputs(n_rows: int, cap: int):
    rng = np.random.default_rng(42)
    keys = np.zeros(cap, dtype=np.int64)
    keys[:n_rows] = rng.integers(0, 1024, n_rows)
    key_valid = np.zeros(cap, dtype=bool)
    key_valid[:n_rows] = True
    vals = np.zeros(cap, dtype=np.float64)
    vals[:n_rows] = rng.normal(0, 10, n_rows)
    val_valid = np.zeros(cap, dtype=bool)
    val_valid[:n_rows] = rng.random(n_rows) < 0.95
    flags = np.zeros(cap, dtype=bool)
    flags[:n_rows] = rng.random(n_rows) < 0.8
    return keys, key_valid, vals, val_valid, flags


def bench_tpu(n_rows: int, cap: int, iters: int = 10) -> float:
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.ops import kernels as K
    from spark_rapids_tpu.ops import aggregates as agg_k

    keys, key_valid, vals, val_valid, flags = build_inputs(n_rows, cap)

    def fused_stage(keys, key_valid, vals, val_valid, flags, num_rows):
        live = jnp.arange(cap) < num_rows
        keep = live & flags & val_valid & (vals > 0)
        cols = [Column(dt.INT64, keys, key_valid),
                Column(dt.FLOAT64, vals, val_valid)]
        compacted, count = K.compact_columns(cols, keep)
        kcol, vcol = compacted
        projected = Column(dt.FLOAT64, vcol.data * 2.0 + 1.0, vcol.validity)
        out_keys, out_aggs, n_groups = agg_k.groupby_aggregate(
            [kcol], [agg_k.AggSpec("sum", projected),
                     agg_k.AggSpec("count", projected),
                     agg_k.AggSpec("max", projected)], count, cap)
        return (out_keys[0].data, out_aggs[0].data, out_aggs[1].data,
                out_aggs[2].data, n_groups)

    fn = jax.jit(fused_stage)
    args = (jnp.asarray(keys), jnp.asarray(key_valid), jnp.asarray(vals),
            jnp.asarray(val_valid), jnp.asarray(flags), jnp.int32(n_rows))
    # compile + warm (block_until_ready is unreliable over the device tunnel;
    # a host scalar fetch is the only true completion barrier)
    out = fn(*args)
    _ = int(out[-1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
        _ = int(out[-1])   # force completion via host fetch
    dt_s = (time.perf_counter() - t0) / iters
    return n_rows / dt_s


def bench_pandas(n_rows: int, cap: int, iters: int = 3) -> float:
    import pandas as pd
    keys, key_valid, vals, val_valid, flags = build_inputs(n_rows, cap)
    df = pd.DataFrame({
        "k": keys[:n_rows],
        "v": np.where(val_valid[:n_rows], vals[:n_rows], np.nan),
        "flag": flags[:n_rows]})
    t0 = time.perf_counter()
    for _ in range(iters):
        sub = df[df["flag"] & (df["v"] > 0)]
        proj = sub.assign(p=sub["v"] * 2.0 + 1.0)
        _ = proj.groupby("k")["p"].agg(["sum", "count", "max"])
    dt_s = (time.perf_counter() - t0) / iters
    return n_rows / dt_s


def main():
    n_rows = 8_000_000
    cap = 1 << 23
    import jax
    platform = jax.devices()[0].platform
    if platform == "cpu":
        # smaller size when benching without an accelerator (CI sanity)
        n_rows = 1_000_000
        cap = 1 << 20
    tpu_rows_per_s = bench_tpu(n_rows, cap)
    cpu_rows_per_s = bench_pandas(n_rows, cap)
    print(json.dumps({
        "metric": "fused filter+project+groupby throughput",
        "value": round(tpu_rows_per_s / 1e6, 2),
        "unit": "Mrows/s",
        "vs_baseline": round(tpu_rows_per_s / cpu_rows_per_s, 2),
    }))


if __name__ == "__main__":
    main()
