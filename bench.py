"""Benchmark: fused columnar SQL pipeline throughput on the TPU chip.

Measures the flagship whole-stage pipeline — filter -> project -> group-by
aggregate (sum/count/avg) — over a 64M-row batch, the scan+filter+project+agg
hot path of SURVEY.md §3.3 (BASELINE.md milestone config 1/2). The group-by
rides the dense-range MXU path (ops/aggregates.py groupby_dense): no sort, no
compaction — elementwise passes plus chunked one-hot matmuls on the systolic
array. The key range (the static slot count) comes from input statistics, the
same information a parquet scan gets for free from row-group min/max stats.

The identical query runs on single-core pandas as the baseline, so
``vs_baseline`` is the TPU speedup over single-core pandas (the reference
repo publishes no numeric GPU baselines — BASELINE.md: "chart image only").

Methodology: iterations are dispatched back-to-back and ALL results are
forced at the end (inputs varied per iteration to defeat any caching), i.e.
steady-state throughput with the device pipeline kept full — the execution
cadence of a scan feeding consecutive batches. A per-iteration host sync
would instead measure the tunnel's fixed round-trip latency.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import json
import os
import time

import numpy as np

N_KEYS = 1024


def _k_slots() -> int:
    """Static slot bucket from the key span (bucket(span+2), the same
    derivation the engine's dense dispatch uses) — not a hard-coded 2048."""
    from spark_rapids_tpu.columnar.column import bucket
    return bucket(N_KEYS + 2, 128)


K_SLOTS = None          # resolved in main() after imports


def build_inputs(n_rows: int, cap: int):
    rng = np.random.default_rng(42)
    keys = np.zeros(cap, dtype=np.int64)
    keys[:n_rows] = rng.integers(0, N_KEYS, n_rows)
    key_valid = np.zeros(cap, dtype=bool)
    key_valid[:n_rows] = True
    vals = np.zeros(cap, dtype=np.float64)
    vals[:n_rows] = rng.normal(0, 10, n_rows)
    val_valid = np.zeros(cap, dtype=bool)
    val_valid[:n_rows] = rng.random(n_rows) < 0.95
    flags = np.zeros(cap, dtype=bool)
    flags[:n_rows] = rng.random(n_rows) < 0.8
    return keys, key_valid, vals, val_valid, flags


def bench_tpu(n_rows: int, cap: int, iters: int = 8):
    """One fused jit per iteration: filter -> project -> dense MXU group-by.
    Returns (rows_per_s, sample result arrays for validation)."""
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.ops import aggregates as agg_k

    keys, key_valid, vals, val_valid, flags = build_inputs(n_rows, cap)

    def fused(keys, key_valid, vals, val_valid, flags, num_rows):
        live = jnp.arange(cap) < num_rows
        keep = live & flags & val_valid & (vals > 0)
        kcol = Column(dt.INT64, keys, key_valid)
        proj = Column(dt.FLOAT64, vals * 2.0 + 1.0, val_valid)
        rmin = jnp.min(jnp.where(keep & key_valid, keys,
                                 jnp.iinfo(jnp.int64).max))
        rmin = jnp.where(jnp.any(keep & key_valid), rmin, 0)
        out_keys, out_aggs, n_groups = agg_k.groupby_dense(
            kcol, [agg_k.AggSpec("sum", proj),
                   agg_k.AggSpec("count", proj),
                   agg_k.AggSpec("avg", proj)],
            num_rows, K_SLOTS, rmin, extra_mask=keep)
        return (out_keys[0].data, out_keys[0].validity,
                out_aggs[0].data, out_aggs[1].data, out_aggs[2].data,
                n_groups)

    f = jax.jit(fused)
    args = (jnp.asarray(keys), jnp.asarray(key_valid), jnp.asarray(vals),
            jnp.asarray(val_valid), jnp.asarray(flags))
    jax.block_until_ready(args)

    warm = f(*args, jnp.int32(n_rows))
    sample = [np.asarray(x) for x in warm]        # forces compile + run

    t0 = time.perf_counter()
    outs = [f(*args, jnp.int32(n_rows - i)) for i in range(iters)]
    for o in outs:                                 # force EVERY iteration
        np.asarray(o[3])
    dt_s = (time.perf_counter() - t0) / iters
    return n_rows / dt_s, sample


def bench_pandas(n_rows: int, cap: int, iters: int = 2):
    import pandas as pd
    keys, key_valid, vals, val_valid, flags = build_inputs(n_rows, cap)
    df = pd.DataFrame({
        "k": keys[:n_rows],
        "v": np.where(val_valid[:n_rows], vals[:n_rows], np.nan),
        "flag": flags[:n_rows]})
    t0 = time.perf_counter()
    for _ in range(iters):
        sub = df[df["flag"] & (df["v"] > 0)]
        proj = sub.assign(p=sub["v"] * 2.0 + 1.0)
        res = proj.groupby("k")["p"].agg(["sum", "count", "mean"])
    dt_s = (time.perf_counter() - t0) / iters
    return n_rows / dt_s, res


def validate(sample, pd_res):
    """The two engines must agree on the sample run (counts exact, sums/avgs
    to float-agg tolerance, same group set) — a bench that drifts from the
    oracle is void."""
    gk, gkv, gsum, gcnt, gavg, ng = sample
    ng = int(ng)
    got = {int(k): (s, int(c), a)
           for k, kv, s, c, a in zip(gk[:ng], gkv[:ng], gsum[:ng],
                                     gcnt[:ng], gavg[:ng]) if kv}
    assert ng == len(got) == len(pd_res), (ng, len(got), len(pd_res))
    for k, row in pd_res.iterrows():
        s, c, a = got[int(k)]
        assert c == int(row["count"]), (k, c, row["count"])
        assert abs(s - row["sum"]) <= 1e-6 * max(1.0, abs(row["sum"])), \
            (k, s, row["sum"])
        assert abs(a - row["mean"]) <= 1e-6 * max(1.0, abs(row["mean"])), \
            (k, a, row["mean"])
    return len(got)


def bench_engine(sf: float, query: str, iters: int = 2,
                 extra_conf=None, with_oracle: bool = True):
    """End-to-end ENGINE throughput: the query runs through the API /
    planner / fused execution (not a hand-built kernel), timed WARM (min
    of post-cold iterations — the steady-state number the history gate
    judges) after one cold (compile) iteration; baseline is pandas
    running the same query. Returns (rows/s, pandas rows/s, cold_s)."""
    from benchmarks import datagen, queries as Q
    from spark_rapids_tpu.api.session import TpuSession
    conf = {"spark.rapids.tpu.sql.explain": "NONE"}
    conf.update(extra_conf or {})
    session = TpuSession.builder.config(conf).getOrCreate()
    tables = datagen.register_tables(session, sf)
    n_rows = int(datagen.LINEITEM_PER_SF * sf)
    qfn = Q.QUERIES[query]
    t0 = time.perf_counter()
    qfn(tables).collect_batch().fetch_to_host()
    cold_s = time.perf_counter() - t0
    hots = []
    for _ in range(iters):
        t0 = time.perf_counter()
        qfn(tables).collect_batch().fetch_to_host()
        hots.append(time.perf_counter() - t0)
    hot_s = min(hots)

    if not with_oracle:
        return n_rows / hot_s, 0.0, cold_s
    # pandas oracle on the same data (single-core, like the r01 baseline)
    li = __import__("pandas").DataFrame(datagen.gen_lineitem(sf))
    t0 = time.perf_counter()
    _pandas_query(query, li)
    pd_s = time.perf_counter() - t0
    return n_rows / hot_s, n_rows / pd_s, cold_s


def bench_shuffle(n_rows: int, iters: int = 2):
    """Engine shuffle-exchange throughput: repartition ``n_rows`` through
    TpuShuffleExchangeExec (hash keys) and report GB/s of shuffle bytes
    moved over exchange wall time, plus which data plane carried it
    (docs/shuffle.md). The hot iteration is the measurement; the cold one
    pays compiles."""
    import numpy as np
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.shuffle.exchange import shuffle_report
    session = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    rng = np.random.default_rng(11)
    df = session.createDataFrame({
        "k": [int(x) for x in rng.integers(0, 1 << 20, n_rows)],
        "v": [float(x) for x in rng.normal(0, 10, n_rows)]})
    best = None
    for it in range(max(1, iters) + 1):
        t0 = time.perf_counter()
        batch = df.repartition(8, col("k")).collect_batch()
        wall = time.perf_counter() - t0
        assert batch.num_rows == n_rows, (batch.num_rows, n_rows)
        rep = shuffle_report(session.last_plan())
        # write-side bytes only: the same definition note_plane and the
        # tpu_shuffle_gbps gauge use (each shuffled byte counted once)
        moved = sum(e.get("bytesWritten", 0) for e in rep)
        plane = rep[0]["plane"] if rep else None
        if it == 0 or moved <= 0:
            continue                       # cold iteration pays compiles
        gbps = moved / wall / 1e9
        if best is None or gbps > best["shuffle_gbps"]:
            best = {"shuffle_gbps": round(gbps, 4),
                    "shuffle_bytes": moved,
                    "shuffle_plane": plane,
                    "shuffle_wall_s": round(wall, 4)}
    return best


def bench_warm_restart(cache_dir=None, sf: float = 0.002):
    """Warm-restart micro-bench (ISSUE 10): run a query in a fresh child
    process pointed at ``compile.cacheDir``, then fork ANOTHER fresh
    process on the same cache dir — the second must classify ZERO cold
    compiles (every build is a persistent-cache disk hit) and its wall
    time is the restart cost a redeploy actually pays. Returns the
    artifact fields incl. the lower-is-better history series values."""
    import os
    import subprocess
    import sys
    import tempfile
    if cache_dir is None:
        cache_dir = tempfile.mkdtemp(prefix="srt_compile_cache_")
    child = r"""
import json, sys, time
t0 = time.time()
from spark_rapids_tpu.api.session import TpuSession
from benchmarks import datagen, queries as Q
session = TpuSession.builder.config({
    "spark.rapids.tpu.sql.explain": "NONE",
    "spark.rapids.tpu.sql.compile.cacheDir": sys.argv[1]}).getOrCreate()
tables = datagen.register_tables(session, float(sys.argv[2]))
Q.QUERIES["q6"](tables).collect_batch().fetch_to_host()
from spark_rapids_tpu.analysis import recompile
rep = recompile.report()
print(json.dumps({
    "wall_s": round(time.time() - t0, 3),
    "cold": sum(v["coldCompiles"] for v in rep.values()),
    "disk": sum(v["diskHits"] for v in rep.values()),
    "compile_s": round(sum(v["compileS"] for v in rep.values()), 3)}))
"""
    here = os.path.dirname(os.path.abspath(__file__))

    def run_child():
        out = subprocess.run(
            [sys.executable, "-c", child, cache_dir, str(sf)],
            capture_output=True, text=True, timeout=900, cwd=here)
        if out.returncode != 0:
            raise RuntimeError(f"warm-restart child failed: "
                               f"{out.stderr.strip()[-300:]}")
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = run_child()          # seeds the XLA cache + signature index
    warm = run_child()          # must pay zero cold builds
    return {
        "compile_cache_dir": cache_dir,
        "compile_s": cold["compile_s"],
        "cold_restart_s": cold["wall_s"],
        "warm_restart_s": warm["wall_s"],
        "warm_restart_cold_compiles": warm["cold"],
        "warm_restart_disk_hits": warm["disk"],
        "warm_restart_ok": warm["cold"] == 0,
    }


def bench_serving(sf: float = 0.01, iters: int = 24):
    """Serving front-door micro-bench (ISSUE 12, docs/plan_cache.md):
    steady-state q6 executions with ROTATING date-range literals through
    a prepared statement — after one cold (plan + compile) iteration,
    every execute is a parse-free plan-cache-served rebind+run, the warm
    serving hot path a dashboard tier lives on. Reports plans served per
    second (higher better) and the warm-traffic window wall seconds
    (lower better), both stamped into the history gate, plus the
    plan-cache counters as honesty checks (hits must cover the loop and
    exactly ONE plan may have been built)."""
    import datetime
    from benchmarks import datagen
    from spark_rapids_tpu.api.session import TpuSession
    session = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    tables = datagen.register_tables(session, sf)
    tables["lineitem"].createOrReplaceTempView("serving_lineitem")
    stmt = session.prepare(
        "SELECT sum(l_extendedprice * l_discount) AS revenue "
        "FROM serving_lineitem "
        "WHERE l_shipdate >= :lo AND l_shipdate < :hi "
        "AND l_discount >= 0.05 AND l_discount <= 0.07 "
        "AND l_quantity < 24")

    def window(i):
        lo = datetime.date(1993, 1, 1) + datetime.timedelta(
            days=30 * (i % 24))
        return lo, lo + datetime.timedelta(days=365)

    lo, hi = window(0)
    stmt.execute(lo=lo, hi=hi)          # cold: plans once, compiles
    t0 = time.perf_counter()
    for i in range(1, iters + 1):       # warm traffic, literals rotate
        lo, hi = window(i)
        stmt.execute(lo=lo, hi=hi)
    wall = time.perf_counter() - t0
    st = session.serving_stats()
    return {
        "plan_cache_plans_per_s": round(iters / wall, 2),
        "warm_traffic_q6_s": round(wall, 4),
        "serving_iters": iters,
        "serving_plan_hits": st["planHits"],
        "serving_plans_built": st["plansBuilt"],
        "serving_ok": st["plansBuilt"] == 1 and st["planHits"] >= iters,
    }


def bench_donation_hbm(n_rows: int):
    """Peak live device bytes of a fused filter consuming one batch,
    donation on vs off: with ``compile.donate`` the input columns free
    the moment the program ingests them, so steady-state residency drops
    by ~the consumed batch. Measured deterministically from
    jax.live_arrays() after the call and fed into the ``xla_live`` HBM
    watermark so the artifact's telemetry tail carries the peak."""
    import gc
    import jax
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.ops import predicates as pr
    from spark_rapids_tpu.plan import physical as P
    from spark_rapids_tpu.service.telemetry import watermark

    def live_bytes():
        return sum(int(a.size * a.dtype.itemsize)
                   for a in jax.live_arrays())

    schema = dt.Schema([dt.Field("v", dt.FLOAT64)])
    pred = pr.GreaterThan(ex.BoundReference(0, dt.FLOAT64, True),
                          ex.Literal(0.0, dt.FLOAT64))
    rng = np.random.default_rng(7)
    out = {}
    wm = watermark("xla_live")
    for donate in (True, False):
        TpuSession.builder.config({
            "spark.rapids.tpu.sql.explain": "NONE",
            "spark.rapids.tpu.sql.compile.donate":
                "true" if donate else "false"}).getOrCreate()
        stage = P.FusedStage([pred], schema, schema, mode="filter")
        gc.collect()
        batch = ColumnarBatch.from_pydict(
            {"v": rng.normal(0, 10, n_rows)}, schema)
        stage(batch)           # warm: compile outside the measurement
        del batch
        gc.collect()
        base = live_bytes()
        batch = ColumnarBatch.from_pydict(
            {"v": rng.normal(0, 10, n_rows)}, schema)
        res = stage(batch)
        wm.update(live_bytes())
        peak = live_bytes() - base
        out["hbm_live_peak_donate_on" if donate
            else "hbm_live_peak_donate_off"] = peak
        del batch, res
        gc.collect()
    if out.get("hbm_live_peak_donate_off"):
        out["hbm_donate_savings_pct"] = round(
            100.0 * (1 - out["hbm_live_peak_donate_on"] /
                     out["hbm_live_peak_donate_off"]), 1)
    return out


def _rows_close(a, b, rel_tol=1e-9):
    """Row-wise equality with fp tolerance: a stage retry re-runs the
    map, so slices can land in a different order and float aggregation
    order (legally) drifts at the last bits — bitwise identity across
    retries is not a guarantee any shuffle engine makes."""
    import math
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if not math.isclose(va, vb, rel_tol=rel_tol,
                                    abs_tol=1e-12):
                    return False
            elif va != vb:
                return False
    return True


def bench_chaos(sf: float = 0.002):
    """Chaos mode (ISSUE 13, docs/resilience.md): a q6-shaped MULTI-BATCH
    shuffled run — lineitem rides a hash-repartition exchange before the
    q6 filter+aggregate, so the shuffle map/fetch paths are on the
    critical path — executed under injected faults: one failed fetch and
    one poisoned map-task batch, both absorbed by the stage-retry driver
    (exec/recovery.py). Honesty checks: results match the fault-free
    run (fp-tolerant — a retry legally reorders float aggregation, see
    :func:`_rows_close`), >=1 stage retry recorded, every armed fault
    fired.
    The chaos wall seconds stamp the history gate as
    ``chaos_q6_recovery_s`` (lower is better), so recovery-time
    regressions fail the bench like any perf regression."""
    from benchmarks import datagen
    from spark_rapids_tpu.analysis import faults
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.service.telemetry import MetricsRegistry
    from benchmarks import queries as Q
    session = TpuSession.builder.config({
        "spark.rapids.tpu.sql.explain": "NONE",
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
        "spark.rapids.tpu.sql.recovery.retryBackoff": "0.0",
        # the injection points live on the DCN map/fetch paths; under
        # mesh auto the exchange would lower to ICI and the chaos run
        # would silently fire nothing
        "spark.rapids.tpu.sql.shuffle.plane": "dcn",
    }).getOrCreate()
    tables = dict(datagen.register_tables(session, sf))
    tables["lineitem"] = tables["lineitem"].repartition(
        4, col("l_orderkey"))

    def run():
        return Q.QUERIES["q6"](tables).collect()

    def retries():
        return float(MetricsRegistry.get().counter(
            "tpu_stage_retries_total", "x").value)

    run()                                    # cold: compile
    t0 = time.perf_counter()
    baseline = run()                         # warm fault-free reference
    fault_free_s = time.perf_counter() - t0
    before = retries()
    try:
        faults.install("fetch.fail;task.poison")
        t0 = time.perf_counter()
        got = run()
        chaos_s = time.perf_counter() - t0
        fired = faults.fired_total()
    finally:
        faults.reset()                       # never leak chaos downstream
    stage_retries = retries() - before
    ok = _rows_close(got, baseline) and stage_retries >= 1 and fired == 2
    return {
        "chaos_q6_recovery_s": round(chaos_s, 4),
        "chaos_q6_fault_free_s": round(fault_free_s, 4),
        "chaos_q6_overhead_s": round(chaos_s - fault_free_s, 4),
        "chaos_stage_retries": int(stage_retries),
        "chaos_faults_fired": int(fired),
        "chaos_ok": ok,
    }


def bench_aqe_skew(n_rows: int = 20_000):
    """AQE skewed-workload bench (ISSUE 16, docs/aqe.md): a deliberately
    skewed q3-shaped join+aggregate — one hot key owns 90% of the fact
    side, so one reduce partition dwarfs the rest — run warm with
    adaptive execution ON (``aqe_skew_q3_s``, lower is better) and OFF,
    with the on/off wall ratio stamped as ``aqe_ab_q3`` (< 1 means the
    re-planner pays for itself on skew).

    Honesty checks gate the stamp (``aqe_ok``): identical rows on/off;
    at least one APPLIED coalesce, skew-split, join-promote and
    join-demote decision across the legs; each decision visible in
    EXPLAIN ANALYZE, the query log record, and the
    ``tpu_aqe_decisions_total`` telemetry counter; and the demoted
    re-planned stage passing contract validation in ERROR mode. The
    skew leg repeats on a mesh/ICI-attached plan (needs >= 2 devices;
    recorded in ``aqe_ici_skew_split``): the first execution records the
    stage-stats baseline, the second falls the skewed stage back to DCN
    and splits."""
    import glob
    import tempfile
    from benchmarks import queries as Q  # noqa: F401  (q3 shape reference)
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.service.telemetry import MetricsRegistry

    hot = int(n_rows * 0.9)
    ks = [7] * hot + [i % 40 for i in range(n_rows - hot)]
    vs = [float(i % 13) for i in range(n_rows)]
    dim_k = list(range(41))
    dim_w = [k * 10.0 for k in dim_k]
    log_dir = tempfile.mkdtemp(prefix="aqe_bench_log_")

    def q3_shaped(s):
        fact = s.createDataFrame({"k": ks, "v": vs})
        dim = s.createDataFrame({"k": dim_k, "w": dim_w})
        return (fact.join(dim, on="k", how="inner")
                .groupBy("k").agg(F.sum(col("v") + col("w")).alias("rev")))

    def timed(q):
        q.collect()                          # cold: compile
        t0 = time.perf_counter()
        rows = sorted(q.collect())
        return rows, time.perf_counter() - t0

    base_conf = {
        "spark.rapids.tpu.sql.explain": "NONE",
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionThreshold":
            "4096",
    }
    counts = {"coalesce": 0, "skew-split": 0, "join-promote": 0,
              "join-demote": 0}
    surfaced = {"explain": set(), "log": set(), "telemetry": set()}

    def note(session, log_rec=None):
        """Fold one leg's decisions into the honesty tallies."""
        applied = [d for d in session.last_aqe_decisions() if d["applied"]]
        for d in applied:
            if d["rule"] in counts:
                counts[d["rule"]] += 1
        text = session.explain_analyze()
        for d in applied:
            if f"* aqe {d['rule']}:" in text:
                surfaced["explain"].add(d["rule"])
        for rule, c in ((log_rec or {}).get("aqe", {})
                        .get("rules", {}).items()):
            if c.get("applied"):
                surfaced["log"].add(rule)
        return applied

    # -- skew leg: AQE on (with query log) vs off ---------------------------
    s_on = TpuSession.builder.config(dict(
        base_conf, **{
            "spark.rapids.tpu.sql.adaptive.enabled": "true",
            "spark.rapids.tpu.sql.telemetry.queryLog.dir": log_dir,
        })).getOrCreate()
    rows_on, on_s = timed(q3_shaped(s_on))
    log_rec = None
    try:
        lines = []
        for p in glob.glob(os.path.join(log_dir, "query_log-*.jsonl")):
            with open(p) as f:
                lines += [json.loads(ln) for ln in f if ln.strip()]
        log_rec = lines[-1] if lines else None
    except Exception:
        pass
    note(s_on, log_rec)
    s_off = TpuSession.builder.config(dict(
        base_conf, **{
            "spark.rapids.tpu.sql.adaptive.enabled": "false",
            # same log overhead as the ON leg: the A/B compares planning,
            # not artifact writes
            "spark.rapids.tpu.sql.telemetry.queryLog.dir":
                tempfile.mkdtemp(prefix="aqe_bench_log_off_"),
        })).getOrCreate()
    rows_off, off_s = timed(q3_shaped(s_off))

    # -- ICI leg: the skewed stage falls back to DCN on repeat execution ----
    ici_ok = False
    ici_skipped = None
    try:
        import jax
        if len(jax.devices()) < 2:
            ici_skipped = (f"{len(jax.devices())} device(s): mesh needs a "
                           "multi-device ICI plane")
        else:
            s_ici = TpuSession.builder.config(dict(
                base_conf, **{
                    "spark.rapids.tpu.sql.adaptive.enabled": "true",
                    "spark.rapids.tpu.sql.mesh.enabled": "true",
                    "spark.rapids.tpu.sql.shuffle.plane": "ici",
                    "spark.rapids.tpu.sql.mesh.maxStageBytes": "1024",
                })).getOrCreate()
            q = q3_shaped(s_ici)
            q.collect()                  # run 1 records the baseline
            rows_ici = sorted(q.collect())
            ici_ok = rows_ici == rows_on and any(
                d["rule"] == "skew-split" and d["applied"] and
                "[ici->dcn]" in str(d.get("after"))
                for d in note(s_ici))
    except Exception as e:
        ici_skipped = str(e)[:120]

    # -- join-switch legs: promote (observed small) / demote (observed big)
    promote_demote_ok = True
    try:
        s_sw = TpuSession.builder.config({
            "spark.rapids.tpu.sql.explain": "NONE",
            "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "65536",
            "spark.rapids.tpu.sql.adaptive.enabled": "true",
            # acceptance: the demoted re-planned stage must PASS contract
            # validation in error mode
            "spark.rapids.tpu.sql.analysis.validatePlan": "error",
        }).getOrCreate()
        big = s_sw.createDataFrame({"k": [i % 50 for i in range(2000)],
                                    "v": [float(i) for i in range(2000)]})
        # estimates say a 32k-row build side shuffles; the aggregate's
        # observed output (50 groups) lands under threshold -> promote
        small = (s_sw.createDataFrame(
            {"k": [i % 50 for i in range(32000)],
             "w": [float(i) for i in range(32000)]})
            .groupBy("k").agg(F.sum(col("w")).alias("w")))
        big.join(small, on="k", how="inner").collect()
        note(s_sw)
        # arrow-side estimates say broadcast; device strings pad to the
        # max length, so the OBSERVED build blows the threshold -> demote
        strs = ["x" * (2000 if i == 0 else 2) for i in range(200)]
        fact = s_sw.createDataFrame({"k": [i % 200 for i in range(4000)],
                                     "v": [float(i) for i in range(4000)]})
        dim = s_sw.createDataFrame({"k": list(range(200)), "t": strs})
        fact.join(dim, on="k", how="inner").select(
            col("k"), col("v")).collect()
        note(s_sw)
    except Exception:
        promote_demote_ok = False

    # telemetry surface: every counted rule has a counter sample
    try:
        snap = MetricsRegistry.get().snapshot()["metrics"]
        for sample in snap.get("tpu_aqe_decisions_total",
                               {}).get("samples", ()):
            surfaced["telemetry"].add(sample["labels"].get("rule"))
    except Exception:
        pass

    need = set(counts)
    ok = (_rows_close(rows_on, rows_off) and promote_demote_ok and
          all(counts[r] >= 1 for r in need) and
          need <= surfaced["explain"] and
          need <= surfaced["telemetry"] and
          # the query log leg only sees the skew/coalesce rules
          {"coalesce", "skew-split"} <= surfaced["log"] and
          (ici_ok or ici_skipped is not None))
    out = {
        "aqe_skew_q3_s": round(on_s, 4),
        "aqe_off_q3_s": round(off_s, 4),
        "aqe_ab_q3": round(on_s / off_s, 3) if off_s > 0 else None,
        "aqe_decisions": dict(counts),
        "aqe_ici_skew_split": ici_ok,
        "aqe_ok": ok,
    }
    if ici_skipped:
        out["aqe_ici_skipped"] = ici_skipped
    return out


def _pandas_query(query: str, li):
    import pandas as pd
    if query == "q6":
        d0, d1 = 8766, 9131
        sub = li[(li.l_shipdate >= d0) & (li.l_shipdate < d1) &
                 (li.l_discount >= 0.05) & (li.l_discount <= 0.07) &
                 (li.l_quantity < 24)]
        return (sub.l_extendedprice * sub.l_discount).sum()
    if query == "q1":
        sub = li[li.l_shipdate <= 10471]
        g = sub.assign(
            disc_price=sub.l_extendedprice * (1 - sub.l_discount),
            charge=sub.l_extendedprice * (1 - sub.l_discount) *
            (1 + sub.l_tax))
        return g.groupby(["l_returnflag", "l_linestatus"]).agg(
            sum_qty=("l_quantity", "sum"),
            sum_base=("l_extendedprice", "sum"),
            sum_disc=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            cnt=("l_quantity", "count"))
    raise ValueError(query)


def main():
    global K_SLOTS
    # preflight (benchmarks/preflight.py): SHORT child-process probe; a
    # dead tunnel DEGRADES this run to an explicit cpu-backed measurement
    # instead of emitting value: 0 (the BENCH_r04/r05 dark rounds —
    # two rounds of perf signal lost to an infra error string)
    from benchmarks.preflight import preflight
    pf = preflight(timeout_s=45)
    backend = pf["backend"]
    probe = pf["deviceProbe"]
    import jax
    K_SLOTS = _k_slots()
    platform = jax.devices()[0].platform
    degraded = backend == "cpu-degraded"
    if platform == "cpu":
        # smaller size when benching without an accelerator (CI sanity /
        # degraded mode): still a real, non-zero measurement
        n_rows, cap = 1_000_000, 1 << 20
        engine_sf = 0.002
    else:
        n_rows, cap = 64_000_000, 1 << 26
        # 24M lineitem rows: the engine's fixed per-query cost (a handful
        # of host round-trips on the tunnel link) amortizes while pandas
        # scales linearly; scan batches ride the device cache so hot runs
        # pay no upload
        engine_sf = 4.0

    tpu_rows_per_s, sample = bench_tpu(n_rows, cap)
    cpu_rows_per_s, pd_res = bench_pandas(n_rows, cap)
    n_groups = validate(sample, pd_res)

    # engine end-to-end (API -> planner -> fused execution) on q6 and q1
    engine = {}
    for q in ("q6", "q1"):
        try:
            eng_rps, pd_rps, cold_s = bench_engine(engine_sf, q)
            engine[f"engine_{q}_mrows_per_s"] = round(eng_rps / 1e6, 3)
            engine[f"engine_{q}_vs_pandas"] = round(eng_rps / pd_rps, 2)
            engine[f"engine_{q}_cold_s"] = round(cold_s, 1)
        except Exception as e:            # engine bench must not kill the line
            engine[f"engine_{q}_error"] = str(e)[:120]

    # fusion A/B (ISSUE 11): warm engine q6 with the stage compiler OFF —
    # the on/off speedup rides the history gate so a regression in what
    # whole-stage fusion buys is judged, not just remembered
    if "engine_q6_mrows_per_s" in engine:
        try:
            off_rps, _pd, _cold = bench_engine(
                engine_sf, "q6", with_oracle=False,
                extra_conf={"spark.rapids.tpu.sql.fusion.wholeStage":
                            "false"})
            engine["engine_q6_fusion_off_mrows_per_s"] = round(
                off_rps / 1e6, 3)
            if off_rps > 0:
                engine["fusion_ab_q6"] = round(
                    engine["engine_q6_mrows_per_s"] / (off_rps / 1e6), 2)
        except Exception as e:
            engine["fusion_ab_error"] = str(e)[:120]

    # shuffle-exchange throughput (ISSUE 8: shuffle GB/s + plane in every
    # bench artifact; judged by the same regression gate as the pipeline)
    shuffle = None
    try:
        shuffle = bench_shuffle(200_000 if platform == "cpu" else 4_000_000)
        if shuffle:
            engine.update(shuffle)
    except Exception as e:
        engine["shuffle_error"] = str(e)[:120]

    # compile-time discipline (ISSUE 10): warm-restart micro-bench — a
    # fresh process on the same compile.cacheDir must pay ZERO cold
    # builds — plus the donation HBM micro-bench (peak live device bytes
    # with compile.donate on vs off, via the xla_live watermark)
    warm = None
    try:
        # fixed tiny sf: the micro-bench measures compile caching, which
        # is shape-dependent and data-size independent
        warm = bench_warm_restart(sf=0.01 if platform != "cpu" else 0.002)
        engine.update(warm)
    except Exception as e:
        engine["warm_restart_error"] = str(e)[:120]
    try:
        engine.update(bench_donation_hbm(
            1_000_000 if platform == "cpu" else 16_000_000))
    except Exception as e:
        engine["donation_error"] = str(e)[:120]

    # serving front door (ISSUE 12): steady-state plans/s + warm-traffic
    # latency of literal-rotating q6 through the prepared path
    serving = None
    try:
        serving = bench_serving(sf=0.01 if platform != "cpu" else 0.002)
        engine.update(serving)
    except Exception as e:
        engine["serving_error"] = str(e)[:120]

    # chaos mode (ISSUE 13): q6-shaped shuffled run under injected
    # faults — recovery wall seconds ride the gate lower-is-better
    chaos = None
    try:
        chaos = bench_chaos(sf=0.01 if platform != "cpu" else 0.002)
        engine.update(chaos)
    except Exception as e:
        engine["chaos_error"] = str(e)[:120]

    # adaptive execution (ISSUE 16): deliberately skewed q3-shaped join —
    # AQE-on wall + on/off ratio ride the gate lower-is-better
    aqe_bench = None
    try:
        aqe_bench = bench_aqe_skew(
            200_000 if platform != "cpu" else 20_000)
        engine.update(aqe_bench)
    except Exception as e:
        engine["aqe_error"] = str(e)[:120]

    bytes_per_row = 8 + 1 + 8 + 1 + 1            # key, kvalid, val, vvalid, flag
    gbytes_per_s = tpu_rows_per_s * bytes_per_row / 1e9
    # one-hot matmul flops: rows x slots x 2 (mul+add) x planned feature
    # planes (occupancy + contrib + hi/lo/nan for the fused sum/count/avg)
    from spark_rapids_tpu.columnar import dtypes as _dt
    from spark_rapids_tpu.columnar.column import Column as _Col
    from spark_rapids_tpu.ops import aggregates as _agg
    _c = _Col(_dt.FLOAT64, np.zeros(8), np.zeros(8, dtype=bool))
    n_feats = _agg.dense_feature_count(
        [_agg.AggSpec("sum", _c), _agg.AggSpec("count", _c),
         _agg.AggSpec("avg", _c)])
    tflops = tpu_rows_per_s * K_SLOTS * 2 * n_feats / 1e12
    line = {
        "metric": "fused filter+project+groupby throughput",
        "value": round(tpu_rows_per_s / 1e6, 2),
        "unit": "Mrows/s",
        "vs_baseline": round(tpu_rows_per_s / cpu_rows_per_s, 2),
        "rows": n_rows,
        "groups": n_groups,
        "input_gb_per_s": round(gbytes_per_s, 2),
        "matmul_tflops": round(tflops, 2),
        "baseline_mrows_per_s": round(cpu_rows_per_s / 1e6, 2),
        "engine_sf": engine_sf,
        # explicit backend + probe record (ISSUE 6: no more dark rounds —
        # a degraded run is labeled, not zeroed)
        "backend": "cpu-degraded" if degraded else platform,
        "probe_s": probe["latencyS"],
    }
    if degraded and probe.get("error"):
        line["probe_error"] = probe["error"]
    line.update(engine)

    # regression gate (benchmarks/history.py): stamp this round against
    # the best prior clean same-backend round and append it to the
    # history JSONL, so round-over-round trajectory lives in the
    # artifact instead of in whoever remembers r03
    try:
        from benchmarks import history as bh
        queries = {"fused_pipeline": line["value"]}
        for q in ("q6", "q1"):
            v = engine.get(f"engine_{q}_mrows_per_s")
            if v is not None:
                queries[f"engine_{q}"] = v
        # whole-query orchestration series (ISSUE 11): the fused-microbench
        # to warm-engine-q6 gap (lower is better — this is the ~500x of
        # BENCH_r03) and the fusion on/off A/B speedup
        q6 = engine.get("engine_q6_mrows_per_s")
        if q6:
            from benchmarks.history import WHOLE_QUERY_GAP
            gap = line["value"] / q6
            queries[WHOLE_QUERY_GAP] = round(gap, 3)
            line["whole_query_gap"] = round(gap, 3)
        if engine.get("fusion_ab_q6"):
            from benchmarks.history import FUSION_AB_Q6
            queries[FUSION_AB_Q6] = engine["fusion_ab_q6"]
        if shuffle and shuffle.get("shuffle_gbps"):
            # shuffle GB/s rides the same higher-is-better gate
            # (benchmarks/history.SHUFFLE_GBPS series)
            from benchmarks.history import SHUFFLE_GBPS
            queries[SHUFFLE_GBPS] = shuffle["shuffle_gbps"]
        if warm and warm.get("warm_restart_ok"):
            # compile seconds + warm-restart wall ride the gate as
            # lower-is-better series (history.INVERTED_QUERIES)
            from benchmarks.history import COMPILE_S, WARM_RESTART_S
            queries[COMPILE_S] = warm["compile_s"]
            queries[WARM_RESTART_S] = warm["warm_restart_s"]
        if serving and serving.get("serving_ok"):
            # serving front door (ISSUE 12): plans/s higher-is-better,
            # warm-traffic wall lower-is-better (INVERTED_QUERIES)
            from benchmarks.history import (PLAN_CACHE_PLANS_PER_S,
                                            WARM_TRAFFIC_Q6_S)
            queries[PLAN_CACHE_PLANS_PER_S] = \
                serving["plan_cache_plans_per_s"]
            queries[WARM_TRAFFIC_Q6_S] = serving["warm_traffic_q6_s"]
        if chaos and chaos.get("chaos_ok"):
            # chaos recovery wall (ISSUE 13): stamped only when the
            # honesty checks held (identical rows, >=1 stage retry,
            # every armed fault fired) — lower-is-better
            from benchmarks.history import CHAOS_Q6_RECOVERY_S
            queries[CHAOS_Q6_RECOVERY_S] = chaos["chaos_q6_recovery_s"]
        if aqe_bench and aqe_bench.get("aqe_ok"):
            # adaptive execution (ISSUE 16): stamped only when the
            # honesty checks held (rows on == off, every rule applied
            # at least once and visible on all decision surfaces) —
            # both lower-is-better
            from benchmarks.history import AQE_AB_Q3, AQE_SKEW_Q3_S
            queries[AQE_SKEW_Q3_S] = aqe_bench["aqe_skew_q3_s"]
            if aqe_bench.get("aqe_ab_q3"):
                queries[AQE_AB_Q3] = aqe_bench["aqe_ab_q3"]
        gate = bh.stamp(
            "bench", queries, backend=line["backend"], degraded=degraded,
            error=probe.get("error") if degraded else None,
            higher_is_better=True,
            meta={"rows": n_rows, "engine_sf": engine_sf})
        line["regression"] = {q: v.get("verdict")
                              for q, v in gate["verdicts"].items()}
        line["regression_overall"] = gate["overall"]
    except Exception as e:        # the gate must not kill the bench line
        line["regression_error"] = str(e)[:120]

    # process-telemetry tail (service/telemetry): the registry numbers a
    # round-over-round reader diffs (parity with the MULTICHIP artifact)
    try:
        from spark_rapids_tpu.service.telemetry import compact_snapshot
        line["telemetry"] = compact_snapshot()
    except Exception:
        pass

    print(json.dumps(line))


if __name__ == "__main__":
    main()
