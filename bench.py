"""Benchmark: fused columnar SQL pipeline throughput on the TPU chip.

Measures the flagship whole-stage pipeline (filter -> project -> sort-based
group-by aggregate, DESIGN.md §2) on device over a ~8M-row batch — the
scan+filter+project+agg hot path of SURVEY.md §3.3 (BASELINE.md milestone
config 1/2). The same pipeline runs on pandas host CPU as the baseline, so
``vs_baseline`` is the TPU speedup over single-core pandas (the reference
repo publishes no numeric GPU baselines — BASELINE.md: "chart image only").

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np


def build_inputs(n_rows: int, cap: int):
    rng = np.random.default_rng(42)
    keys = np.zeros(cap, dtype=np.int64)
    keys[:n_rows] = rng.integers(0, 1024, n_rows)
    key_valid = np.zeros(cap, dtype=bool)
    key_valid[:n_rows] = True
    vals = np.zeros(cap, dtype=np.float64)
    vals[:n_rows] = rng.normal(0, 10, n_rows)
    val_valid = np.zeros(cap, dtype=bool)
    val_valid[:n_rows] = rng.random(n_rows) < 0.95
    flags = np.zeros(cap, dtype=bool)
    flags[:n_rows] = rng.random(n_rows) < 0.8
    return keys, key_valid, vals, val_valid, flags


def bench_tpu(n_rows: int, cap: int, iters: int = 10) -> float:
    """Two-phase fused pipeline, the TpuHashAggregateExec shape:
    jit1: filter -> project -> sort -> segment structure (+ group count sync)
    jit2 (static K): MXU one-hot-matmul reductions + key gather.
    """
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.column import Column, bucket
    from spark_rapids_tpu.ops import kernels as K
    from spark_rapids_tpu.ops import aggregates as agg_k

    keys, key_valid, vals, val_valid, flags = build_inputs(n_rows, cap)

    def phase1(keys, key_valid, vals, val_valid, flags, num_rows):
        live = jnp.arange(cap) < num_rows
        keep = live & flags & val_valid & (vals > 0)
        cols = [Column(dt.INT64, keys, key_valid),
                Column(dt.FLOAT64, vals, val_valid)]
        (kcol, vcol), count = K.compact_columns(cols, keep)
        proj = Column(dt.FLOAT64, vcol.data * 2.0 + 1.0, vcol.validity)
        order = K.sort_indices([K.SortKey(kcol)], count, cap)
        sk = K.gather_column(kcol, order)
        sv = K.gather_column(proj, order)
        live2 = jnp.arange(cap) < count
        starts = K.segment_starts_from_sorted_keys([sk], count, cap)
        seg_ids = K.segment_ids(starts)
        start_perm, _ = K.compaction_indices(starts)
        n_groups = jnp.sum(starts).astype(jnp.int32)
        return (sk.data, sk.validity, sv.data, sv.validity, seg_ids,
                start_perm, live2, n_groups)

    def phase2(Kb, skd, skv, svd, svv, seg_ids, start_perm, live2):
        vcol = Column(dt.FLOAT64, svd, svv)
        s = agg_k.segment_aggregate_matmul(
            agg_k.AggSpec("sum", vcol), seg_ids, live2, Kb)
        c = agg_k.segment_aggregate_matmul(
            agg_k.AggSpec("count", vcol), seg_ids, live2, Kb)
        a = agg_k.segment_aggregate_matmul(
            agg_k.AggSpec("avg", vcol), seg_ids, live2, Kb)
        gkeys = skd[start_perm[:Kb]]
        return gkeys, s.data, c.data, a.data

    f1 = jax.jit(phase1)
    f2 = jax.jit(phase2, static_argnums=0)
    args = (jnp.asarray(keys), jnp.asarray(key_valid), jnp.asarray(vals),
            jnp.asarray(val_valid), jnp.asarray(flags), jnp.int32(n_rows))

    def run_once():
        out1 = f1(*args)
        ng = int(out1[-1])              # host sync (the n_groups read the
        Kb = bucket(max(ng, 1))         # exec performs at every agg boundary)
        out2 = f2(Kb, *out1[:-1])
        return int(np.asarray(out2[2][0])), ng

    run_once()  # compile + warm both phases
    t0 = time.perf_counter()
    for _ in range(iters):
        run_once()
    dt_s = (time.perf_counter() - t0) / iters
    return n_rows / dt_s


def bench_pandas(n_rows: int, cap: int, iters: int = 3) -> float:
    import pandas as pd
    keys, key_valid, vals, val_valid, flags = build_inputs(n_rows, cap)
    df = pd.DataFrame({
        "k": keys[:n_rows],
        "v": np.where(val_valid[:n_rows], vals[:n_rows], np.nan),
        "flag": flags[:n_rows]})
    t0 = time.perf_counter()
    for _ in range(iters):
        sub = df[df["flag"] & (df["v"] > 0)]
        proj = sub.assign(p=sub["v"] * 2.0 + 1.0)
        _ = proj.groupby("k")["p"].agg(["sum", "count", "max"])
    dt_s = (time.perf_counter() - t0) / iters
    return n_rows / dt_s


def main():
    n_rows = 8_000_000
    cap = 1 << 23
    import jax
    platform = jax.devices()[0].platform
    if platform == "cpu":
        # smaller size when benching without an accelerator (CI sanity)
        n_rows = 1_000_000
        cap = 1 << 20
    tpu_rows_per_s = bench_tpu(n_rows, cap)
    cpu_rows_per_s = bench_pandas(n_rows, cap)
    print(json.dumps({
        "metric": "fused filter+project+groupby throughput",
        "value": round(tpu_rows_per_s / 1e6, 2),
        "unit": "Mrows/s",
        "vs_baseline": round(tpu_rows_per_s / cpu_rows_per_s, 2),
    }))


if __name__ == "__main__":
    main()
