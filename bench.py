"""Benchmark: fused columnar SQL pipeline throughput on the TPU chip.

Measures the flagship whole-stage pipeline — filter -> project -> group-by
aggregate (sum/count/avg) — over a 64M-row batch, the scan+filter+project+agg
hot path of SURVEY.md §3.3 (BASELINE.md milestone config 1/2). The group-by
rides the dense-range MXU path (ops/aggregates.py groupby_dense): no sort, no
compaction — elementwise passes plus chunked one-hot matmuls on the systolic
array. The key range (the static slot count) comes from input statistics, the
same information a parquet scan gets for free from row-group min/max stats.

The identical query runs on single-core pandas as the baseline, so
``vs_baseline`` is the TPU speedup over single-core pandas (the reference
repo publishes no numeric GPU baselines — BASELINE.md: "chart image only").

Methodology: iterations are dispatched back-to-back and ALL results are
forced at the end (inputs varied per iteration to defeat any caching), i.e.
steady-state throughput with the device pipeline kept full — the execution
cadence of a scan feeding consecutive batches. A per-iteration host sync
would instead measure the tunnel's fixed round-trip latency.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import json
import time

import numpy as np

K_SLOTS = 2048          # static slot bucket for 1024 distinct keys (+null)
N_KEYS = 1024


def build_inputs(n_rows: int, cap: int):
    rng = np.random.default_rng(42)
    keys = np.zeros(cap, dtype=np.int64)
    keys[:n_rows] = rng.integers(0, N_KEYS, n_rows)
    key_valid = np.zeros(cap, dtype=bool)
    key_valid[:n_rows] = True
    vals = np.zeros(cap, dtype=np.float64)
    vals[:n_rows] = rng.normal(0, 10, n_rows)
    val_valid = np.zeros(cap, dtype=bool)
    val_valid[:n_rows] = rng.random(n_rows) < 0.95
    flags = np.zeros(cap, dtype=bool)
    flags[:n_rows] = rng.random(n_rows) < 0.8
    return keys, key_valid, vals, val_valid, flags


def bench_tpu(n_rows: int, cap: int, iters: int = 8):
    """One fused jit per iteration: filter -> project -> dense MXU group-by.
    Returns (rows_per_s, sample result arrays for validation)."""
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.ops import aggregates as agg_k

    keys, key_valid, vals, val_valid, flags = build_inputs(n_rows, cap)

    def fused(keys, key_valid, vals, val_valid, flags, num_rows):
        live = jnp.arange(cap) < num_rows
        keep = live & flags & val_valid & (vals > 0)
        kcol = Column(dt.INT64, keys, key_valid)
        proj = Column(dt.FLOAT64, vals * 2.0 + 1.0, val_valid)
        rmin = jnp.min(jnp.where(keep & key_valid, keys,
                                 jnp.iinfo(jnp.int64).max))
        rmin = jnp.where(jnp.any(keep & key_valid), rmin, 0)
        out_keys, out_aggs, n_groups = agg_k.groupby_dense(
            kcol, [agg_k.AggSpec("sum", proj),
                   agg_k.AggSpec("count", proj),
                   agg_k.AggSpec("avg", proj)],
            num_rows, K_SLOTS, rmin, extra_mask=keep)
        return (out_keys[0].data, out_keys[0].validity,
                out_aggs[0].data, out_aggs[1].data, out_aggs[2].data,
                n_groups)

    f = jax.jit(fused)
    args = (jnp.asarray(keys), jnp.asarray(key_valid), jnp.asarray(vals),
            jnp.asarray(val_valid), jnp.asarray(flags))
    jax.block_until_ready(args)

    warm = f(*args, jnp.int32(n_rows))
    sample = [np.asarray(x) for x in warm]        # forces compile + run

    t0 = time.perf_counter()
    outs = [f(*args, jnp.int32(n_rows - i)) for i in range(iters)]
    for o in outs:                                 # force EVERY iteration
        np.asarray(o[3])
    dt_s = (time.perf_counter() - t0) / iters
    return n_rows / dt_s, sample


def bench_pandas(n_rows: int, cap: int, iters: int = 2):
    import pandas as pd
    keys, key_valid, vals, val_valid, flags = build_inputs(n_rows, cap)
    df = pd.DataFrame({
        "k": keys[:n_rows],
        "v": np.where(val_valid[:n_rows], vals[:n_rows], np.nan),
        "flag": flags[:n_rows]})
    t0 = time.perf_counter()
    for _ in range(iters):
        sub = df[df["flag"] & (df["v"] > 0)]
        proj = sub.assign(p=sub["v"] * 2.0 + 1.0)
        res = proj.groupby("k")["p"].agg(["sum", "count", "mean"])
    dt_s = (time.perf_counter() - t0) / iters
    return n_rows / dt_s, res


def validate(sample, pd_res):
    """The two engines must agree on the sample run (counts exact, sums/avgs
    to float-agg tolerance, same group set) — a bench that drifts from the
    oracle is void."""
    gk, gkv, gsum, gcnt, gavg, ng = sample
    ng = int(ng)
    got = {int(k): (s, int(c), a)
           for k, kv, s, c, a in zip(gk[:ng], gkv[:ng], gsum[:ng],
                                     gcnt[:ng], gavg[:ng]) if kv}
    assert ng == len(got) == len(pd_res), (ng, len(got), len(pd_res))
    for k, row in pd_res.iterrows():
        s, c, a = got[int(k)]
        assert c == int(row["count"]), (k, c, row["count"])
        assert abs(s - row["sum"]) <= 1e-6 * max(1.0, abs(row["sum"])), \
            (k, s, row["sum"])
        assert abs(a - row["mean"]) <= 1e-6 * max(1.0, abs(row["mean"])), \
            (k, a, row["mean"])
    return len(got)


def main():
    import jax
    platform = jax.devices()[0].platform
    if platform == "cpu":
        # smaller size when benching without an accelerator (CI sanity)
        n_rows, cap = 1_000_000, 1 << 20
    else:
        n_rows, cap = 64_000_000, 1 << 26

    tpu_rows_per_s, sample = bench_tpu(n_rows, cap)
    cpu_rows_per_s, pd_res = bench_pandas(n_rows, cap)
    n_groups = validate(sample, pd_res)

    bytes_per_row = 8 + 1 + 8 + 1 + 1            # key, kvalid, val, vvalid, flag
    gbytes_per_s = tpu_rows_per_s * bytes_per_row / 1e9
    # one-hot matmul flops: rows x slots x 2 (mul+add) x planned feature
    # planes (occupancy + contrib + hi/lo/nan for the fused sum/count/avg)
    from spark_rapids_tpu.columnar import dtypes as _dt
    from spark_rapids_tpu.columnar.column import Column as _Col
    from spark_rapids_tpu.ops import aggregates as _agg
    _c = _Col(_dt.FLOAT64, np.zeros(8), np.zeros(8, dtype=bool))
    n_feats = _agg.dense_feature_count(
        [_agg.AggSpec("sum", _c), _agg.AggSpec("count", _c),
         _agg.AggSpec("avg", _c)])
    tflops = tpu_rows_per_s * K_SLOTS * 2 * n_feats / 1e12
    print(json.dumps({
        "metric": "fused filter+project+groupby throughput",
        "value": round(tpu_rows_per_s / 1e6, 2),
        "unit": "Mrows/s",
        "vs_baseline": round(tpu_rows_per_s / cpu_rows_per_s, 2),
        "rows": n_rows,
        "groups": n_groups,
        "input_gb_per_s": round(gbytes_per_s, 2),
        "matmul_tflops": round(tflops, 2),
        "baseline_mrows_per_s": round(cpu_rows_per_s / 1e6, 2),
    }))


if __name__ == "__main__":
    main()
