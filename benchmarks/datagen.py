"""TPC-H-like data generator (seeded, pure numpy).

Analog of the reference's benchmark datasets (TpchLikeSpark.scala /
integration_tests data_gen.py seeded generators, SURVEY.md §4/§6). Scale
factor 1 ~= 6M lineitem rows / 1.5M orders, matching TPC-H row ratios;
columns cover the types the queries exercise (ints, floats, dates, strings).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

LINEITEM_PER_SF = 6_000_000
ORDERS_PER_SF = 1_500_000
CUSTOMER_PER_SF = 150_000
PART_PER_SF = 200_000
SUPPLIER_PER_SF = 10_000

_EPOCH_1992 = 8035     # days 1970-01-01 -> 1992-01-01
_DATE_RANGE = 2556     # ~7 years of order dates

RETURN_FLAGS = ["A", "N", "R"]
LINE_STATUS = ["F", "O"]
SHIP_MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]


def _ps_suppkey(partkey: np.ndarray, i: np.ndarray, n_supp: int
                ) -> np.ndarray:
    """TPC-H's deterministic partsupp supplier derivation
    ((partkey + i*(S/4 + (partkey-1)/S)) % S + 1): lineitem draws i in
    0..3 with the SAME formula, so every (l_partkey, l_suppkey) pair
    exists in partsupp — the q9 join actually joins."""
    s = max(n_supp, 1)
    return ((partkey + i * (s // 4 + (partkey - 1) // s)) % s + 1
            ).astype(np.int64)


def gen_lineitem(sf: float, seed: int = 42) -> Dict[str, np.ndarray]:
    n = int(LINEITEM_PER_SF * sf)
    rng = np.random.default_rng(seed)
    n_orders = max(int(ORDERS_PER_SF * sf), 1)
    n_supp = max(int(SUPPLIER_PER_SF * sf), 1)
    quantity = rng.integers(1, 51, n).astype(np.int64)
    extendedprice = np.round(rng.uniform(900, 105_000, n), 2)
    discount = np.round(rng.uniform(0.0, 0.1, n), 2)
    tax = np.round(rng.uniform(0.0, 0.08, n), 2)
    shipdate = (_EPOCH_1992 + rng.integers(0, _DATE_RANGE, n)).astype(np.int32)
    partkey = rng.integers(1, int(PART_PER_SF * sf) + 2, n).astype(np.int64)
    return {
        "l_orderkey": rng.integers(1, n_orders + 1, n).astype(np.int64),
        "l_partkey": partkey,
        "l_suppkey": _ps_suppkey(partkey, rng.integers(0, 4, n), n_supp),
        "l_quantity": quantity,
        "l_extendedprice": extendedprice,
        "l_discount": discount,
        "l_tax": tax,
        "l_returnflag": np.array(RETURN_FLAGS)[rng.integers(0, 3, n)],
        "l_linestatus": np.array(LINE_STATUS)[rng.integers(0, 2, n)],
        "l_shipdate": shipdate,
        "l_commitdate": (shipdate + rng.integers(-30, 30, n)).astype(np.int32),
        "l_receiptdate": (shipdate + rng.integers(1, 30, n)).astype(np.int32),
        "l_shipmode": np.array(SHIP_MODES)[rng.integers(0, len(SHIP_MODES), n)],
    }


_COMMENT_WORDS = ["carefully", "quickly", "special", "requests", "pending",
                  "deposits", "accounts", "ironic", "express", "final"]


def gen_orders(sf: float, seed: int = 43) -> Dict[str, np.ndarray]:
    n = int(ORDERS_PER_SF * sf)
    rng = np.random.default_rng(seed)
    w = np.array(_COMMENT_WORDS)
    comments = np.char.add(np.char.add(
        w[rng.integers(0, len(w), n)], " "), w[rng.integers(0, len(w), n)])
    # TPC-H leaves a third of customers with no orders (custkey skips
    # multiples of 3) so NOT-EXISTS queries like q22 have survivors
    ck = rng.integers(1, int(CUSTOMER_PER_SF * sf) + 2, n).astype(np.int64)
    ck = np.where(ck % 3 == 0, ck + 1, ck)
    return {
        "o_orderkey": np.arange(1, n + 1, dtype=np.int64),
        "o_custkey": ck,
        "o_orderstatus": np.array(["F", "O", "P"])[rng.integers(0, 3, n)],
        "o_totalprice": np.round(rng.uniform(850, 560_000, n), 2),
        "o_orderdate": (_EPOCH_1992 + rng.integers(0, _DATE_RANGE - 151, n)
                        ).astype(np.int32),
        "o_orderpriority": np.array(PRIORITIES)[rng.integers(0, 5, n)],
        "o_shippriority": np.zeros(n, dtype=np.int64),
        "o_comment": comments,
    }


def gen_customer(sf: float, seed: int = 44) -> Dict[str, np.ndarray]:
    n = int(CUSTOMER_PER_SF * sf)
    rng = np.random.default_rng(seed)
    cc = rng.integers(10, 35, n)          # phone country code, TPC-H style
    p1 = rng.integers(100, 999, n)
    p2 = rng.integers(100, 999, n)
    p3 = rng.integers(1000, 9999, n)
    phone = np.char.add(np.char.add(np.char.add(np.char.add(
        np.char.add(np.char.add(cc.astype(str), "-"), p1.astype(str)),
        "-"), p2.astype(str)), "-"), p3.astype(str))
    return {
        "c_custkey": np.arange(1, n + 1, dtype=np.int64),
        "c_name": np.array([f"Customer#{i:09d}" for i in range(1, n + 1)]),
        "c_nationkey": rng.integers(0, 25, n).astype(np.int64),
        "c_acctbal": np.round(rng.uniform(-999, 9999, n), 2),
        "c_mktsegment": np.array(SEGMENTS)[rng.integers(0, 5, n)],
        "c_phone": phone,
    }


_TYPE_SYLLABLES = (["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY",
                    "PROMO"],
                   ["ANODIZED", "BURNISHED", "PLATED", "POLISHED",
                    "BRUSHED"],
                   ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"])

CONTAINERS = ["SM CASE", "SM BOX", "MED BOX", "MED BAG", "LG CASE",
              "LG BOX", "JUMBO PKG", "WRAP PACK"]

NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
           "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ",
           "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU",
           "CHINA", "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA",
           "UNITED KINGDOM", "UNITED STATES"]
# region index per nation, TPC-H appendix layout
_NATION_REGION = [0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4,
                  2, 4, 0, 0, 0, 1, 2, 3, 4, 2, 3, 3, 1]


def gen_part(sf: float, seed: int = 45) -> Dict[str, np.ndarray]:
    n = max(int(PART_PER_SF * sf), 1)
    rng = np.random.default_rng(seed)
    syl = [np.array(s)[rng.integers(0, len(s), n)] for s in _TYPE_SYLLABLES]
    p_type = np.array([f"{a} {b} {c}" for a, b, c in zip(*syl)])
    brands = np.array([f"Brand#{i}{j}" for i, j in
                       zip(rng.integers(1, 6, n), rng.integers(1, 6, n))])
    return {
        "p_partkey": np.arange(1, n + 1, dtype=np.int64),
        "p_type": p_type,
        "p_brand": brands,
        "p_container": np.array(CONTAINERS)[
            rng.integers(0, len(CONTAINERS), n)],
        "p_size": rng.integers(1, 51, n).astype(np.int64),
        "p_retailprice": np.round(rng.uniform(900, 2000, n), 2),
    }


def gen_supplier(sf: float, seed: int = 46) -> Dict[str, np.ndarray]:
    n = max(int(SUPPLIER_PER_SF * sf), 1)
    rng = np.random.default_rng(seed)
    return {
        "s_suppkey": np.arange(1, n + 1, dtype=np.int64),
        "s_name": np.array([f"Supplier#{i:09d}" for i in range(1, n + 1)]),
        # cycling keys: every nation has suppliers at ANY scale factor
        # (uniform draws left whole nations supplier-less at tiny SF,
        # turning nation-filtered query tests vacuous)
        "s_nationkey": (np.arange(n, dtype=np.int64) % 25),
        "s_acctbal": np.round(rng.uniform(-999, 9999, n), 2),
    }


def gen_partsupp(sf: float, seed: int = 47) -> Dict[str, np.ndarray]:
    """4 suppliers per part via TPC-H's deterministic derivation — the
    same formula gen_lineitem uses, so (l_partkey, l_suppkey) always has
    a partsupp row and the PK (ps_partkey, ps_suppkey) is unique."""
    n_part = max(int(PART_PER_SF * sf), 1)
    n_supp = max(int(SUPPLIER_PER_SF * sf), 1)
    rng = np.random.default_rng(seed)
    pk = np.repeat(np.arange(1, n_part + 2, dtype=np.int64), 4)
    i = np.tile(np.arange(4, dtype=np.int64), n_part + 1)
    return {
        "ps_partkey": pk,
        "ps_suppkey": _ps_suppkey(pk, i, n_supp),
        "ps_availqty": rng.integers(1, 10_000, len(pk)).astype(np.int64),
        "ps_supplycost": np.round(rng.uniform(1, 1000, len(pk)), 2),
    }


def gen_nation() -> Dict[str, np.ndarray]:
    return {
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": np.array(NATIONS),
        "n_regionkey": np.array(_NATION_REGION, dtype=np.int64),
    }


def gen_region() -> Dict[str, np.ndarray]:
    return {
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": np.array(REGIONS),
    }


def to_arrow(cols: Dict[str, np.ndarray]):
    import pyarrow as pa
    arrays = {}
    for k, v in cols.items():
        if v.dtype == np.int32 and (k.endswith("date")):
            arrays[k] = pa.array(v, type=pa.date32())
        else:
            arrays[k] = pa.array(v)
    return pa.table(arrays)


def register_tables(session, sf: float):
    """Create the TPC-H-like DataFrames (and temp views) on a session."""
    tables = {
        "lineitem": to_arrow(gen_lineitem(sf)),
        "orders": to_arrow(gen_orders(sf)),
        "customer": to_arrow(gen_customer(sf)),
        "part": to_arrow(gen_part(sf)),
        "supplier": to_arrow(gen_supplier(sf)),
        "partsupp": to_arrow(gen_partsupp(sf)),
        "nation": to_arrow(gen_nation()),
        "region": to_arrow(gen_region()),
    }
    dfs = {}
    for name, tbl in tables.items():
        df = session.createDataFrame(tbl)
        df.createOrReplaceTempView(name)
        dfs[name] = df
    return dfs


# ---------------------------------------------------------------------------
# TPC-DS-like tables (the subset q5/q97 exercise; analog of the reference's
# TpcdsLikeSpark.scala table defs). SF1 ~= 2.9M store_sales rows.
# ---------------------------------------------------------------------------

STORE_SALES_PER_SF = 2_880_000
CATALOG_SALES_PER_SF = 1_440_000
WEB_SALES_PER_SF = 720_000
RETURN_FRACTION = 10          # 1/10th of sales volume as returns
DS_CUSTOMER_PER_SF = 100_000
DS_ITEM_PER_SF = 18_000
N_STORES = 12
N_CATALOG_PAGES = 60
N_WEB_SITES = 6
_D_DATE_BASE = 2450815        # d_date_sk epoch used by date_dim


def gen_date_dim() -> Dict[str, np.ndarray]:
    """5 years of days: d_date_sk plus month_seq/year/moy/dow/dom/qoy for
    the q97 window, the q3/q42/q52 star joins, and the day-of-week /
    quarter pivots (q43/q79-family)."""
    n = 365 * 5
    days = np.arange(n)
    moy = ((days % 365) // 31 + 1).astype(np.int64)
    return {
        "d_date_sk": np.arange(_D_DATE_BASE, _D_DATE_BASE + n,
                               dtype=np.int64),
        "d_month_seq": (1176 + (days // 30)).astype(np.int64),
        "d_year": (1998 + days // 365).astype(np.int64),
        "d_moy": moy,
        "d_dow": (days % 7).astype(np.int64),
        "d_dom": ((days % 31) + 1).astype(np.int64),
        "d_qoy": ((moy - 1) // 3 + 1).astype(np.int64),
    }


_DS_CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Music",
                  "Shoes", "Sports", "Women"]
_DS_CLASSES = ["accent", "bath", "bedding", "blinds", "curtains",
               "decor", "fiction", "pop", "rock", "classical"]
_DS_COLORS = ["azure", "beige", "coral", "cyan", "gold", "ivory",
              "linen", "navy", "plum", "teal"]


def gen_item() -> Dict[str, np.ndarray]:
    n = DS_ITEM_PER_SF
    rng = np.random.default_rng(53)
    brand_id = rng.integers(1, 1000, n).astype(np.int64)
    class_id = (np.arange(n) % len(_DS_CLASSES) + 1).astype(np.int64)
    manufact_id = rng.integers(1, 100, n).astype(np.int64)
    return {
        "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
        "i_item_id": np.array([f"AAAAAAAA{i:08d}" for i in range(1, n + 1)]),
        "i_brand_id": brand_id,
        # 1:1 with the id (the TPC-DS schema relationship q3/q52's
        # two-column grouping relies on)
        "i_brand": np.char.add("brand#", brand_id.astype(str)),
        "i_category_id": (np.arange(n) % len(_DS_CATEGORIES) + 1
                          ).astype(np.int64),
        "i_category": np.array(_DS_CATEGORIES)[
            np.arange(n) % len(_DS_CATEGORIES)],
        "i_class_id": class_id,
        "i_class": np.array(_DS_CLASSES)[class_id - 1],
        "i_manufact_id": manufact_id,
        "i_manufact": np.char.add("manufact#", manufact_id.astype(str)),
        "i_manager_id": rng.integers(1, 100, n).astype(np.int64),
        "i_current_price": np.round(rng.uniform(0.5, 300, n), 2),
        "i_color": np.array(_DS_COLORS)[rng.integers(0, len(_DS_COLORS), n)],
    }


# fixed-cardinality demographic/address dims (TPC-DS keeps these
# scale-independent; TpcdsLikeSpark.scala table defs)
DS_ADDR_COUNT = 25_000
DS_HDEMO_COUNT = 7_200
DS_CDEMO_COUNT = 19_208
DS_PROMO_COUNT = 300
_DS_STATES = ["AL", "CA", "CO", "FL", "GA", "IL", "IN", "KS", "KY", "LA",
              "MI", "MN", "MO", "NC", "NY", "OH", "OK", "OR", "TN", "TX"]
_DS_CITIES = ["Antioch", "Bethel", "Centerville", "Fairview", "Five Points",
              "Georgetown", "Greenville", "Liberty", "Midway", "Mount Zion",
              "Oak Grove", "Oakland", "Pleasant Hill", "Riverside", "Salem",
              "Shiloh", "Springfield", "Union", "Walnut Grove", "Woodville"]
_DS_COUNTIES = [c + " County" for c in
                ["Adams", "Clark", "Franklin", "Jackson", "Jefferson",
                 "Lincoln", "Madison", "Monroe", "Union", "Washington"]]
_DS_BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000",
                     ">10000", "Unknown"]
_DS_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
                 "4 yr Degree", "Advanced Degree", "Unknown"]
_DS_MARITAL = ["S", "M", "D", "W", "U"]


def gen_customer_address() -> Dict[str, np.ndarray]:
    n = DS_ADDR_COUNT
    rng = np.random.default_rng(54)
    return {
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
        "ca_city": np.array(_DS_CITIES)[rng.integers(0, len(_DS_CITIES), n)],
        "ca_county": np.array(_DS_COUNTIES)[
            rng.integers(0, len(_DS_COUNTIES), n)],
        "ca_state": np.array(_DS_STATES)[rng.integers(0, len(_DS_STATES), n)],
        "ca_zip": np.char.zfill(
            rng.integers(10000, 99999, n).astype(str), 5),
        "ca_country": np.full(n, "United States"),
        "ca_gmt_offset": rng.integers(-8, -4, n).astype(np.int64),
    }


def gen_household_demographics() -> Dict[str, np.ndarray]:
    n = DS_HDEMO_COUNT
    rng = np.random.default_rng(55)
    return {
        "hd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, n).astype(np.int64),
        "hd_vehicle_count": rng.integers(-1, 5, n).astype(np.int64),
        "hd_buy_potential": np.array(_DS_BUY_POTENTIAL)[
            rng.integers(0, len(_DS_BUY_POTENTIAL), n)],
    }


def gen_customer_demographics() -> Dict[str, np.ndarray]:
    n = DS_CDEMO_COUNT
    rng = np.random.default_rng(56)
    return {
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "cd_gender": np.array(["M", "F"])[rng.integers(0, 2, n)],
        "cd_marital_status": np.array(_DS_MARITAL)[
            rng.integers(0, len(_DS_MARITAL), n)],
        "cd_education_status": np.array(_DS_EDUCATION)[
            rng.integers(0, len(_DS_EDUCATION), n)],
    }


def gen_ds_customer() -> Dict[str, np.ndarray]:
    n = DS_CUSTOMER_PER_SF
    rng = np.random.default_rng(57)
    first = ["James", "Mary", "John", "Linda", "Robert", "Susan",
             "Michael", "Karen", "David", "Nancy"]
    last = ["Smith", "Jones", "Brown", "Davis", "Miller", "Wilson",
            "Moore", "Taylor", "White", "Clark"]
    return {
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
        "c_customer_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(1, n + 1)]),
        "c_current_addr_sk": rng.integers(1, DS_ADDR_COUNT + 1, n
                                          ).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(1, DS_HDEMO_COUNT + 1, n
                                           ).astype(np.int64),
        "c_first_name": np.array(first)[rng.integers(0, len(first), n)],
        "c_last_name": np.array(last)[rng.integers(0, len(last), n)],
        "c_birth_year": rng.integers(1930, 1999, n).astype(np.int64),
        "c_preferred_cust_flag": np.array(["Y", "N"])[
            rng.integers(0, 2, n)],
    }


def gen_promotion() -> Dict[str, np.ndarray]:
    n = DS_PROMO_COUNT
    rng = np.random.default_rng(58)
    return {
        "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
        "p_channel_email": np.array(["Y", "N"])[rng.integers(0, 2, n)],
        "p_channel_event": np.array(["Y", "N"])[rng.integers(0, 2, n)],
    }


def gen_time_dim() -> Dict[str, np.ndarray]:
    """One row per minute of the day (the t_hour/t_minute bands the
    q88/q96-family counts slice on)."""
    n = 24 * 60
    mins = np.arange(n)
    return {
        "t_time_sk": mins.astype(np.int64),
        "t_hour": (mins // 60).astype(np.int64),
        "t_minute": (mins % 60).astype(np.int64),
    }


def _sales_channel(n: int, rng, key_prefix: str, n_units: int,
                   date_span: int) -> Dict[str, np.ndarray]:
    list_price = np.round(rng.uniform(1, 300, n), 2)
    return {
        f"{key_prefix}_sold_date_sk": (
            _D_DATE_BASE + rng.integers(0, date_span, n)).astype(np.int64),
        f"{key_prefix}_sold_time_sk": rng.integers(0, 24 * 60, n
                                                   ).astype(np.int64),
        f"{key_prefix}_customer_sk": rng.integers(
            1, DS_CUSTOMER_PER_SF + 1, n).astype(np.int64),
        f"{key_prefix}_cdemo_sk": rng.integers(
            1, DS_CDEMO_COUNT + 1, n).astype(np.int64),
        f"{key_prefix}_hdemo_sk": rng.integers(
            1, DS_HDEMO_COUNT + 1, n).astype(np.int64),
        f"{key_prefix}_addr_sk": rng.integers(
            1, DS_ADDR_COUNT + 1, n).astype(np.int64),
        f"{key_prefix}_item_sk": rng.integers(
            1, DS_ITEM_PER_SF + 1, n).astype(np.int64),
        f"{key_prefix}_promo_sk": rng.integers(
            1, DS_PROMO_COUNT + 1, n).astype(np.int64),
        f"{key_prefix}_unit_sk": rng.integers(1, n_units + 1, n
                                              ).astype(np.int64),
        # ~4 line items share one ticket/order (the q68/q73/q79 per-basket
        # group key and the xBB co-purchase self-join key)
        f"{key_prefix}_order_number": rng.integers(1, max(n // 4, 2), n
                                                   ).astype(np.int64),
        f"{key_prefix}_quantity": rng.integers(1, 101, n).astype(np.int64),
        f"{key_prefix}_list_price": list_price,
        f"{key_prefix}_sales_price": np.round(
            list_price * rng.uniform(0.2, 1.0, n), 2),
        f"{key_prefix}_coupon_amt": np.round(
            np.where(rng.uniform(0, 1, n) < 0.2,
                     rng.uniform(0, 50, n), 0.0), 2),
        f"{key_prefix}_wholesale_cost": np.round(rng.uniform(1, 100, n), 2),
        f"{key_prefix}_ext_sales_price": np.round(
            rng.uniform(1, 300, n), 2),
        f"{key_prefix}_net_profit": np.round(rng.uniform(-50, 120, n), 2),
    }


def _returns_channel(n: int, rng, key_prefix: str, n_units: int,
                     date_span: int) -> Dict[str, np.ndarray]:
    return {
        f"{key_prefix}_returned_date_sk": (
            _D_DATE_BASE + rng.integers(0, date_span, n)).astype(np.int64),
        f"{key_prefix}_order_number": rng.integers(
            1, max(n * RETURN_FRACTION // 4, 2), n).astype(np.int64),
        f"{key_prefix}_customer_sk": rng.integers(
            1, DS_CUSTOMER_PER_SF + 1, n).astype(np.int64),
        f"{key_prefix}_item_sk": rng.integers(
            1, DS_ITEM_PER_SF + 1, n).astype(np.int64),
        f"{key_prefix}_unit_sk": rng.integers(1, n_units + 1, n
                                              ).astype(np.int64),
        f"{key_prefix}_return_quantity": rng.integers(1, 20, n
                                                      ).astype(np.int64),
        f"{key_prefix}_return_amt": np.round(rng.uniform(1, 200, n), 2),
        f"{key_prefix}_net_loss": np.round(rng.uniform(0, 80, n), 2),
    }


def register_tpcds_tables(session, sf: float, date_span: int = 365 * 5):
    """TPC-DS-like subset: three sales channels + returns + dims."""
    rng = np.random.default_rng(52)
    n_ss = max(int(STORE_SALES_PER_SF * sf), 10)
    n_cs = max(int(CATALOG_SALES_PER_SF * sf), 10)
    n_ws = max(int(WEB_SALES_PER_SF * sf), 10)
    tables = {
        "store_sales": _sales_channel(n_ss, rng, "ss", N_STORES, date_span),
        "store_returns": _returns_channel(
            n_ss // RETURN_FRACTION, rng, "sr", N_STORES, date_span),
        "catalog_sales": _sales_channel(
            n_cs, rng, "cs", N_CATALOG_PAGES, date_span),
        "catalog_returns": _returns_channel(
            n_cs // RETURN_FRACTION, rng, "cr", N_CATALOG_PAGES, date_span),
        "web_sales": _sales_channel(n_ws, rng, "ws", N_WEB_SITES, date_span),
        "web_returns": _returns_channel(
            n_ws // RETURN_FRACTION, rng, "wr", N_WEB_SITES, date_span),
        "date_dim": gen_date_dim(),
        "item": gen_item(),
        "customer": gen_ds_customer(),
        "customer_address": gen_customer_address(),
        "household_demographics": gen_household_demographics(),
        "customer_demographics": gen_customer_demographics(),
        "promotion": gen_promotion(),
        "time_dim": gen_time_dim(),
        "store": {
            "s_store_sk": np.arange(1, N_STORES + 1, dtype=np.int64),
            "s_store_id": np.array(
                [f"AAAAAAAA{i:04d}" for i in range(1, N_STORES + 1)]),
            "s_city": np.array(_DS_CITIES)[
                np.arange(N_STORES) % len(_DS_CITIES)],
            "s_county": np.array(_DS_COUNTIES)[
                np.arange(N_STORES) % len(_DS_COUNTIES)],
            "s_state": np.array(_DS_STATES)[
                np.arange(N_STORES) % len(_DS_STATES)],
            "s_number_employees": (200 + 25 * np.arange(N_STORES)
                                   ).astype(np.int64),
            "s_gmt_offset": np.full(N_STORES, -5, dtype=np.int64),
        },
        "catalog_page": {
            "cp_catalog_page_sk": np.arange(1, N_CATALOG_PAGES + 1,
                                            dtype=np.int64),
            "cp_catalog_page_id": np.array(
                [f"AAAAAAAA{i:04d}" for i in range(1, N_CATALOG_PAGES + 1)]),
        },
        "web_site": {
            "web_site_sk": np.arange(1, N_WEB_SITES + 1, dtype=np.int64),
            "web_site_id": np.array(
                [f"AAAAAAAA{i:04d}" for i in range(1, N_WEB_SITES + 1)]),
        },
    }
    dfs = {}
    for name, cols in tables.items():
        df = session.createDataFrame(to_arrow(
            {k: np.asarray(v) for k, v in cols.items()}))
        df.createOrReplaceTempView(name)
        dfs[name] = df
    return dfs
