"""TPC-H-like data generator (seeded, pure numpy).

Analog of the reference's benchmark datasets (TpchLikeSpark.scala /
integration_tests data_gen.py seeded generators, SURVEY.md §4/§6). Scale
factor 1 ~= 6M lineitem rows / 1.5M orders, matching TPC-H row ratios;
columns cover the types the queries exercise (ints, floats, dates, strings).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

LINEITEM_PER_SF = 6_000_000
ORDERS_PER_SF = 1_500_000
CUSTOMER_PER_SF = 150_000
PART_PER_SF = 200_000
SUPPLIER_PER_SF = 10_000

_EPOCH_1992 = 8035     # days 1970-01-01 -> 1992-01-01
_DATE_RANGE = 2556     # ~7 years of order dates

RETURN_FLAGS = ["A", "N", "R"]
LINE_STATUS = ["F", "O"]
SHIP_MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]


def gen_lineitem(sf: float, seed: int = 42) -> Dict[str, np.ndarray]:
    n = int(LINEITEM_PER_SF * sf)
    rng = np.random.default_rng(seed)
    n_orders = max(int(ORDERS_PER_SF * sf), 1)
    quantity = rng.integers(1, 51, n).astype(np.int64)
    extendedprice = np.round(rng.uniform(900, 105_000, n), 2)
    discount = np.round(rng.uniform(0.0, 0.1, n), 2)
    tax = np.round(rng.uniform(0.0, 0.08, n), 2)
    shipdate = (_EPOCH_1992 + rng.integers(0, _DATE_RANGE, n)).astype(np.int32)
    return {
        "l_orderkey": rng.integers(1, n_orders + 1, n).astype(np.int64),
        "l_partkey": rng.integers(1, int(PART_PER_SF * sf) + 2, n).astype(np.int64),
        "l_suppkey": rng.integers(1, int(SUPPLIER_PER_SF * sf) + 2, n).astype(np.int64),
        "l_quantity": quantity,
        "l_extendedprice": extendedprice,
        "l_discount": discount,
        "l_tax": tax,
        "l_returnflag": np.array(RETURN_FLAGS)[rng.integers(0, 3, n)],
        "l_linestatus": np.array(LINE_STATUS)[rng.integers(0, 2, n)],
        "l_shipdate": shipdate,
        "l_commitdate": (shipdate + rng.integers(-30, 30, n)).astype(np.int32),
        "l_receiptdate": (shipdate + rng.integers(1, 30, n)).astype(np.int32),
        "l_shipmode": np.array(SHIP_MODES)[rng.integers(0, len(SHIP_MODES), n)],
    }


def gen_orders(sf: float, seed: int = 43) -> Dict[str, np.ndarray]:
    n = int(ORDERS_PER_SF * sf)
    rng = np.random.default_rng(seed)
    return {
        "o_orderkey": np.arange(1, n + 1, dtype=np.int64),
        "o_custkey": rng.integers(1, int(CUSTOMER_PER_SF * sf) + 2, n).astype(np.int64),
        "o_orderstatus": np.array(["F", "O", "P"])[rng.integers(0, 3, n)],
        "o_totalprice": np.round(rng.uniform(850, 560_000, n), 2),
        "o_orderdate": (_EPOCH_1992 + rng.integers(0, _DATE_RANGE - 151, n)
                        ).astype(np.int32),
        "o_orderpriority": np.array(PRIORITIES)[rng.integers(0, 5, n)],
        "o_shippriority": np.zeros(n, dtype=np.int64),
    }


def gen_customer(sf: float, seed: int = 44) -> Dict[str, np.ndarray]:
    n = int(CUSTOMER_PER_SF * sf)
    rng = np.random.default_rng(seed)
    return {
        "c_custkey": np.arange(1, n + 1, dtype=np.int64),
        "c_name": np.array([f"Customer#{i:09d}" for i in range(1, n + 1)]),
        "c_nationkey": rng.integers(0, 25, n).astype(np.int64),
        "c_acctbal": np.round(rng.uniform(-999, 9999, n), 2),
        "c_mktsegment": np.array(SEGMENTS)[rng.integers(0, 5, n)],
    }


_TYPE_SYLLABLES = (["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY",
                    "PROMO"],
                   ["ANODIZED", "BURNISHED", "PLATED", "POLISHED",
                    "BRUSHED"],
                   ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"])


def gen_part(sf: float, seed: int = 45) -> Dict[str, np.ndarray]:
    n = max(int(PART_PER_SF * sf), 1)
    rng = np.random.default_rng(seed)
    syl = [np.array(s)[rng.integers(0, len(s), n)] for s in _TYPE_SYLLABLES]
    p_type = np.array([f"{a} {b} {c}" for a, b, c in zip(*syl)])
    return {
        "p_partkey": np.arange(1, n + 1, dtype=np.int64),
        "p_type": p_type,
        "p_retailprice": np.round(rng.uniform(900, 2000, n), 2),
    }


def to_arrow(cols: Dict[str, np.ndarray]):
    import pyarrow as pa
    arrays = {}
    for k, v in cols.items():
        if v.dtype == np.int32 and (k.endswith("date")):
            arrays[k] = pa.array(v, type=pa.date32())
        else:
            arrays[k] = pa.array(v)
    return pa.table(arrays)


def register_tables(session, sf: float):
    """Create the TPC-H-like DataFrames (and temp views) on a session."""
    tables = {
        "lineitem": to_arrow(gen_lineitem(sf)),
        "orders": to_arrow(gen_orders(sf)),
        "customer": to_arrow(gen_customer(sf)),
        "part": to_arrow(gen_part(sf)),
    }
    dfs = {}
    for name, tbl in tables.items():
        df = session.createDataFrame(tbl)
        df.createOrReplaceTempView(name)
        dfs[name] = df
    return dfs
