"""Bench round history + regression gate.

BENCH_r04/r05 shipped dark (``value: 0`` from a dead device tunnel) and
nobody noticed until a human diffed JSON by hand — and even CLEAN rounds
carried no round-over-round signal: the trajectory of the bench lived in
nobody's head. The rule now:

* every BENCH / MULTICHIP / runner round APPENDS one line to a history
  JSONL (``benchmarks/reports/bench_history.jsonl``), keyed by query,
  carrying its backend label and degraded/error state;
* ``cpu-degraded`` and errored rounds are EXCLUDED from baselines (they
  are real, labeled measurements — but an infra fallback must never
  become the bar new rounds are judged against);
* each new round is stamped with a per-query regression verdict against
  the best prior clean round **on the same backend** (a cpu round judged
  against an accelerator baseline is noise, not signal):
  ``fail`` at >= 25% worse, ``warn`` at >= 10% worse, ``improvement``
  when better, ``ok`` in between, ``no-baseline`` for a first round.

``bench.py``, ``benchmarks/runner.py`` and the MULTICHIP dryrun all
stamp through :func:`stamp`; the verdicts ride the artifact JSON so the
next dark or slow round is visible in the round itself.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

WARN_PCT = 0.10
FAIL_PCT = 0.25

#: query name of the shuffle-exchange throughput series (GB/s moved
#: through TpuShuffleExchangeExec, higher is better): stamped by bench.py
#: inside the ``bench`` kind, so a shuffle-plane regression fails the
#: same gate a pipeline-throughput regression does (docs/shuffle.md)
SHUFFLE_GBPS = "shuffle_gbps"

#: compile-time series stamped by bench.py (docs/compile.md): total
#: first-call compile seconds of the cold engine run (COMPILE_S) and the
#: wall seconds of a warm-restart child process replaying the same query
#: against the same compile.cacheDir (WARM_RESTART_S). Both are
#: lower-is-better INSIDE the otherwise higher-is-better ``bench`` kind —
#: round_entry records the per-query direction override so the gate
#: judges them correctly.
COMPILE_S = "compile_s"
WARM_RESTART_S = "warm_restart_s"

#: whole-query orchestration series stamped by bench.py (ISSUE 11,
#: docs/fusion.md): WHOLE_QUERY_GAP is the ratio of the fused-microbench
#: Mrows/s to the warm engine q6 Mrows/s — the ~500x orchestration gap
#: BENCH_r03 measured, judged as a lower-is-better series so the gate
#: fails when whole-query throughput falls behind kernel throughput
#: again. FUSION_AB_Q6 is the q6 fusion on/off A/B speedup (>= 1 means
#: stage fusion pays), higher is better.
WHOLE_QUERY_GAP = "whole_query_gap"
FUSION_AB_Q6 = "fusion_ab_q6"

#: serving front-door series stamped by bench.py (ISSUE 12,
#: docs/plan_cache.md): PLAN_CACHE_PLANS_PER_S is the steady-state rate
#: of plan-cache-served q6 executions with ROTATING literals (parse +
#: analyze + rebind + execute per iteration; higher is better) —
#: the plans/s the serving tier can sustain; WARM_TRAFFIC_Q6_S is the
#: wall seconds of that warm literal-rotating traffic window (lower is
#: better, the serving latency analog of warm_restart_s).
PLAN_CACHE_PLANS_PER_S = "plan_cache_plans_per_s"
WARM_TRAFFIC_Q6_S = "warm_traffic_q6_s"

#: chaos-mode series stamped by bench.py (ISSUE 13, docs/resilience.md):
#: wall seconds of a q6-shaped shuffled run completing UNDER injected
#: faults (a failed fetch + a poisoned map batch absorbed by stage
#: retry) with results identical to the fault-free run — lower is
#: better, so a recovery-time regression fails the gate like any perf
#: regression. Stamped only when the chaos honesty checks pass
#: (identical rows, >=1 stage retry, every armed fault fired).
CHAOS_Q6_RECOVERY_S = "chaos_q6_recovery_s"

#: traffic-replay series stamped by benchmarks/replay.py (ISSUE 15,
#: docs/service.md §7): REPLAY_QPS is completed queries per second of N
#: concurrent mixed-tenant TPC-H streams through ONE engine under
#: lockdep=enforce (higher is better); REPLAY_P50_S / REPLAY_P99_S are
#: the submit->result latency percentiles of that traffic (lower is
#: better) — the first p99-under-concurrent-load numbers the north star
#: asks for. REPLAY_CHAOS_P99_S is the same p99 with the chaos harness
#: armed (--faults), stamped only when results matched the fault-free
#: oracle and every armed fault fired.
REPLAY_QPS = "replay_qps"
REPLAY_P50_S = "replay_p50_s"
REPLAY_P99_S = "replay_p99_s"
REPLAY_CHAOS_P99_S = "replay_chaos_p99_s"
#: REPLAY_PREEMPT_P99_S is the gold-tenant p99 of the preemption-armed
#: mixed-priority leg (scheduler policy=wfq, ISSUE 20): high-priority
#: latency while low-priority work is being suspended/resumed around it
#: (lower is better; stamped only when >=1 suspend/resume cycle was
#: actually observed and every query, preempted ones included, returned
#: oracle-correct rows).
REPLAY_PREEMPT_P99_S = "replay_preempt_p99_s"

#: adaptive-execution series stamped by bench.py (ISSUE 16, docs/aqe.md):
#: AQE_SKEW_Q3_S is the warm wall seconds of a deliberately skewed
#: q3-shaped join+aggregate with the re-planner ON (lower is better);
#: AQE_AB_Q3 is the AQE on/off wall ratio on that workload (lower is
#: better; < 1 means adaptive re-planning pays for itself under skew).
#: Stamped only when the bench's honesty checks pass (identical rows
#: on/off, every decision rule applied and visible on every surface).
AQE_SKEW_Q3_S = "aqe_skew_q3_s"
AQE_AB_Q3 = "aqe_ab_q3"

#: cold-path series stamped by benchmarks/runner.py --prewarm and
#: benchmarks/replay.py (ISSUE 17, docs/compile.md §5): COLD_Q6_S is the
#: FRESH-PROCESS wall seconds of q6 served with a warmed compile-cache
#: dir and prewarm — the first-touch latency the async pool + prewarm
#: exist to kill (lower is better; stamped only when the honesty checks
#: pass: rows identical to the sync path, zero query-triggered cold
#: compiles on the query thread). FIRST_ROW_P99_S is the p99 of
#: submit->first-batch wall seconds across the replay bench's streaming
#: queries (lower is better) — the time-to-first-row the streaming
#: collect exists to shrink.
COLD_Q6_S = "cold_q6_s"
FIRST_ROW_P99_S = "first_row_p99_s"

#: queries whose direction flips relative to their round's
#: ``higherIsBetter`` flag (seconds-valued series riding a throughput
#: round): recorded per entry so old history lines stay judgeable
INVERTED_QUERIES = frozenset({COMPILE_S, WARM_RESTART_S, WHOLE_QUERY_GAP,
                              WARM_TRAFFIC_Q6_S, CHAOS_Q6_RECOVERY_S,
                              REPLAY_P50_S, REPLAY_P99_S,
                              REPLAY_CHAOS_P99_S, REPLAY_PREEMPT_P99_S,
                              AQE_SKEW_Q3_S, AQE_AB_Q3,
                              COLD_Q6_S, FIRST_ROW_P99_S})

#: default history file, committed with the repo so the gate has memory
#: across rounds (each bench round is a fresh process)
DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "reports", "bench_history.jsonl")


def default_path() -> str:
    """The history file every stamper uses unless told otherwise. The
    env override exists so the TEST suite (which drives bench/dryrun
    code paths) never appends synthetic rounds to the committed file."""
    return os.environ.get("SPARK_RAPIDS_TPU_BENCH_HISTORY") or DEFAULT_PATH


def load(path: Optional[str] = None) -> List[Dict]:
    """Every parseable round in the history file, in append order.
    Corrupt lines are skipped — a torn write from a killed round must
    not take the whole gate down."""
    path = path or default_path()
    if not os.path.exists(path):
        return []
    out: List[Dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except ValueError:
                continue
            if isinstance(entry, dict) and "queries" in entry:
                out.append(entry)
    return out


def append(entry: Dict, path: Optional[str] = None) -> str:
    """Append one round line (parent dirs created defensively)."""
    path = path or default_path()
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(entry) + "\n")
    return path


def round_entry(kind: str, queries: Dict[str, float], *, backend: str,
                degraded: bool = False, error: Optional[str] = None,
                higher_is_better: bool = True,
                meta: Optional[Dict] = None) -> Dict:
    """Build one history line. ``kind`` namespaces the comparison series
    (e.g. ``bench``, ``multichip``, ``runner-tpch-sf0.01``): values are
    only ever compared within one kind. ``queries`` maps query name ->
    the round's number (Mrows/s for BENCH — higher better; hot seconds
    for the runner — lower better)."""
    entry = {
        "atS": round(time.time(), 3),
        "kind": kind,
        "backend": backend,
        "degraded": bool(degraded),
        "higherIsBetter": bool(higher_is_better),
        "queries": {q: v for q, v in queries.items() if v is not None},
    }
    inverted = sorted(q for q in entry["queries"] if q in INVERTED_QUERIES)
    if inverted:
        # per-query direction override (seconds series inside a
        # throughput round): the gate flips higherIsBetter for these
        entry["invertedQueries"] = inverted
    if error:
        entry["error"] = str(error)[:400]
    if meta:
        entry["meta"] = meta
    return entry


def _hib_for(entry: Dict, query: str) -> bool:
    """Effective direction for one query in one round: the round's
    ``higherIsBetter`` flag, flipped for its ``invertedQueries``."""
    hib = bool(entry.get("higherIsBetter", True))
    if query in entry.get("invertedQueries", ()) or \
            query in INVERTED_QUERIES:
        return not hib
    return hib


def _clean(entry: Dict, kind: str, backend: str) -> bool:
    """A round usable as baseline: same series, same backend, not
    degraded, not errored."""
    return (entry.get("kind") == kind and
            entry.get("backend") == backend and
            not entry.get("degraded") and
            not entry.get("error"))


def baseline(history: List[Dict], kind: str, backend: str,
             query: str, higher_is_better: bool = True) -> Optional[float]:
    """Best prior clean same-backend value for ``query`` (max when higher
    is better, min otherwise); None with no usable prior round. Zero /
    negative values never qualify — a zeroed metric is a dark round, not
    a record."""
    vals = [e["queries"][query] for e in history
            if _clean(e, kind, backend) and
            isinstance(e["queries"].get(query), (int, float)) and
            e["queries"][query] > 0]
    if not vals:
        return None
    return max(vals) if higher_is_better else min(vals)


def verdict_for(value: Optional[float], base: Optional[float],
                higher_is_better: bool = True) -> Dict:
    """One query's regression verdict vs its baseline."""
    if value is None or value <= 0:
        return {"verdict": "no-measurement", "baseline": base}
    if base is None:
        return {"verdict": "no-baseline", "value": value}
    # normalized so positive change == better, regardless of direction
    if higher_is_better:
        change = (value - base) / base
    else:
        change = (base - value) / base
    out = {"value": value, "baseline": base,
           "changePct": round(change * 100, 2)}
    if change <= -FAIL_PCT:
        out["verdict"] = "fail"
    elif change <= -WARN_PCT:
        out["verdict"] = "warn"
    elif change > 0:
        out["verdict"] = "improvement"
    else:
        out["verdict"] = "ok"
    return out


def verdicts(history: List[Dict], entry: Dict) -> Dict[str, Dict]:
    """Per-query verdicts for ``entry`` against ``history``. A degraded
    or errored round is never judged (its values are infra artifacts):
    every query reads ``excluded``."""
    kind = entry["kind"]
    backend = entry["backend"]
    out: Dict[str, Dict] = {}
    for q, v in entry["queries"].items():
        if entry.get("degraded") or entry.get("error"):
            out[q] = {"verdict": "excluded",
                      "reason": "degraded/errored round: measured and "
                                "recorded, never judged or used as "
                                "baseline"}
            continue
        hib = _hib_for(entry, q)
        out[q] = verdict_for(v, baseline(history, kind, backend, q, hib),
                             hib)
    return out


def worst(vs: Dict[str, Dict]) -> str:
    """The round's overall verdict (the single word a dashboard shows)."""
    order = ("fail", "warn", "no-measurement", "ok", "improvement",
             "no-baseline", "excluded")
    present = {v.get("verdict") for v in vs.values()}
    for level in order:
        if level in present:
            return level
    return "no-data"


def stamp(kind: str, queries: Dict[str, float], *, backend: str,
          degraded: bool = False, error: Optional[str] = None,
          higher_is_better: bool = True, meta: Optional[Dict] = None,
          path: Optional[str] = None) -> Dict:
    """The one-call gate: verdicts for this round against the existing
    history, then append the round so the NEXT one sees it. Returns
    ``{"verdicts": {q: ...}, "overall": str, "rounds": n}``. Never
    raises — a broken history file downgrades to no-baseline verdicts,
    and an unwritable file loses persistence, not the round's report."""
    path = path or default_path()
    try:
        history = load(path)
    except Exception:
        history = []
    entry = round_entry(kind, queries, backend=backend, degraded=degraded,
                        error=error, higher_is_better=higher_is_better,
                        meta=meta)
    vs = verdicts(history, entry)
    entry["regression"] = {q: v.get("verdict") for q, v in vs.items()}
    try:
        append(entry, path)
    except Exception:
        pass
    return {"verdicts": vs, "overall": worst(vs),
            "rounds": len(history) + 1}
