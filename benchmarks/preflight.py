"""Device preflight for benchmark entry points.

BENCH_r04/r05 recorded ``value: 0`` because a dead device tunnel hung
``jax.devices()`` and the probe timeout turned the whole round into an
error string — two rounds of perf signal lost to infra (ROADMAP open
item 5). The rule now: every bench artifact carries an explicit
``backend`` plus the probe result, and a failed probe DEGRADES to a real
CPU-backed measurement (labeled ``cpu-degraded``) instead of emitting a
zero.

The probe runs ``jax.devices()`` in a CHILD process (a dead tunnel hangs
the call indefinitely; the child takes the hang) with a SHORT timeout —
the tunnel either answers in seconds or not at all, and a 180 s wait
only delays the degraded fallback.
"""

from __future__ import annotations

import time
from typing import Dict

DEFAULT_TIMEOUT_S = 30.0


def probe_devices(timeout_s: float = DEFAULT_TIMEOUT_S) -> Dict:
    """Probe the jax backend in a child process.

    Returns ``{"ok", "latencyS", "platform", "deviceCount", "error"}``;
    ``ok=False`` means the tunnel/backend is unusable and the caller
    should fall back to an explicit cpu-degraded run."""
    import subprocess
    import sys
    t0 = time.perf_counter()
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(d[0].platform, len(d))"],
            capture_output=True, timeout=timeout_s, text=True)
    except subprocess.TimeoutExpired:
        return {"ok": False, "latencyS": round(time.perf_counter() - t0, 2),
                "platform": None, "deviceCount": 0,
                "error": f"device probe timed out after {timeout_s}s "
                         "(jax.devices() hung; tunnel unreachable)"}
    latency = round(time.perf_counter() - t0, 2)
    if out.returncode == 0 and out.stdout.strip():
        # parse only the LAST line: sitecustomize banners / runtime init
        # notices on stdout must not crash the module that exists to make
        # the bench crash-proof
        tokens = out.stdout.strip().splitlines()[-1].split()
        if len(tokens) >= 2 and tokens[-1].isdigit():
            return {"ok": True, "latencyS": latency,
                    "platform": tokens[-2], "deviceCount": int(tokens[-1]),
                    "error": None}
        return {"ok": False, "latencyS": latency, "platform": None,
                "deviceCount": 0,
                "error": ("device probe printed unparseable output: "
                          + out.stdout.strip()[-200:])}
    tail = (out.stderr or "").strip().splitlines()[-3:]
    return {"ok": False, "latencyS": latency, "platform": None,
            "deviceCount": 0,
            "error": (f"device probe failed (rc={out.returncode}): "
                      + " | ".join(tail)[:400])}


def force_cpu_backend() -> None:
    """Pin THIS process to the CPU backend before any jax device use
    (the degraded-mode switch: safe only while jax hasn't initialized a
    backend yet, which is why the probe runs in a child)."""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _publish_probe(backend: str, probe: Dict) -> None:
    """Probe latency + backend into the process metrics registry
    (service/telemetry): scrape surfaces answer "which backend, how far
    away" for the lifetime of the bench process. Best-effort — the
    module that exists to make the bench crash-proof must not crash it.
    Runs AFTER the backend decision, so a degraded run has already
    pinned JAX_PLATFORMS=cpu before the engine package imports."""
    try:
        from spark_rapids_tpu.service.telemetry import MetricsRegistry
        reg = MetricsRegistry.get()
        reg.gauge("tpu_preflight_probe_seconds",
                  "child-process jax.devices() probe latency").set(
            probe.get("latencyS") or 0.0)
        reg.gauge("tpu_preflight_backend_info",
                  "constant 1; resolved bench backend label",
                  backend=backend).set(1)
    except Exception:
        pass


def preflight(timeout_s: float = DEFAULT_TIMEOUT_S) -> Dict:
    """Probe and, on failure, force the CPU backend. Returns
    ``{"backend": <platform or "cpu-degraded">, "deviceProbe": {...}}`` —
    the fields every BENCH/MULTICHIP artifact now records."""
    probe = probe_devices(timeout_s)
    if probe["ok"]:
        _publish_probe(probe["platform"], probe)
        return {"backend": probe["platform"], "deviceProbe": probe}
    force_cpu_backend()
    _publish_probe("cpu-degraded", probe)
    return {"backend": "cpu-degraded", "deviceProbe": probe}
