"""TPC-H-like query definitions on the DataFrame API.

Analog of TpchLikeSpark.scala's query objects (reference
integration_tests/.../tpch/). Each query takes the dict of DataFrames from
datagen.register_tables and returns a DataFrame.
"""

from __future__ import annotations

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit

# 1995-09-01 / 1994-01-01 / 1995-01-01 as days since epoch
_D_1994_01_01 = 8766
_D_1995_01_01 = 9131
_D_1995_03_15 = 9204
_D_1995_09_01 = 9374
_D_1998_09_02 = 10471


def q1(t):
    """Pricing summary report: the scan -> filter -> wide aggregate."""
    l = t["lineitem"]
    disc_price = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    charge = disc_price * (lit(1.0) + col("l_tax"))
    return (l.filter(col("l_shipdate") <= lit(_D_1998_09_02))
            .groupBy("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count("*").alias("count_order"))
            .orderBy("l_returnflag", "l_linestatus"))


def q3(t):
    """Shipping priority: 3-way join + aggregate + top-N."""
    c = t["customer"].filter(col("c_mktsegment") == lit("BUILDING"))
    o = t["orders"].filter(col("o_orderdate") < lit(_D_1995_03_15))
    l = t["lineitem"].filter(col("l_shipdate") > lit(_D_1995_03_15))
    revenue = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (c.join(o, on=(col("c_custkey") == col("o_custkey")))
            .join(l, on=(col("o_orderkey") == col("l_orderkey")))
            .groupBy("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(F.sum(revenue).alias("revenue"))
            .orderBy(col("revenue").desc(), col("o_orderdate").asc())
            .limit(10))


def q6(t):
    """Forecasting revenue change: tight filter + global sum."""
    l = t["lineitem"]
    return (l.filter((col("l_shipdate") >= lit(_D_1994_01_01)) &
                     (col("l_shipdate") < lit(_D_1995_01_01)) &
                     (col("l_discount") >= lit(0.05)) &
                     (col("l_discount") <= lit(0.07)) &
                     (col("l_quantity") < lit(24)))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q12(t):
    """Shipmode priority: join + conditional aggregation."""
    l = t["lineitem"].filter(
        col("l_shipmode").isin("MAIL", "SHIP") &
        (col("l_commitdate") < col("l_receiptdate")) &
        (col("l_shipdate") < col("l_commitdate")) &
        (col("l_receiptdate") >= lit(_D_1994_01_01)) &
        (col("l_receiptdate") < lit(_D_1995_01_01)))
    o = t["orders"]
    high = F.when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"), lit(1)) \
        .otherwise(lit(0))
    low = F.when(~col("o_orderpriority").isin("1-URGENT", "2-HIGH"), lit(1)) \
        .otherwise(lit(0))
    return (o.join(l, on=(col("o_orderkey") == col("l_orderkey")))
            .groupBy("l_shipmode")
            .agg(F.sum(high).alias("high_line_count"),
                 F.sum(low).alias("low_line_count"))
            .orderBy("l_shipmode"))


def q14(t):
    """Promotion effect: join + conditional aggregate ratio."""
    l = t["lineitem"].filter((col("l_shipdate") >= lit(_D_1995_09_01)) &
                             (col("l_shipdate") < lit(_D_1995_09_01 + 30)))
    p = t["part"]
    disc_price = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    promo = F.when(col("p_type").startswith("PROMO"),
                   disc_price).otherwise(lit(0.0))
    return (l.join(p, on=(col("l_partkey") == col("p_partkey")))
            .agg((lit(100.0) * F.sum(promo) / F.sum(disc_price))
                 .alias("promo_revenue")))


def q18(t):
    """Large-volume customers: self-join through a filtered aggregate
    (the HAVING-subquery shape), then a 3-way join + top-N. Threshold
    tuned to this generator's order sizes (TPC-H uses 300)."""
    l = t["lineitem"]
    big = (l.groupBy("l_orderkey")
           .agg(F.sum("l_quantity").alias("sum_qty"))
           .filter(col("sum_qty") > lit(120)))
    o = t["orders"]
    c = t["customer"]
    return (big.join(o, on=(col("l_orderkey") == col("o_orderkey")))
            .join(c, on=(col("o_custkey") == col("c_custkey")))
            .select(col("c_name"), col("c_custkey"), col("o_orderkey"),
                    col("o_orderdate"), col("o_totalprice"),
                    col("sum_qty"))
            .orderBy(col("o_totalprice").desc(), col("o_orderdate").asc())
            .limit(100))


def q4(t):
    """Order priority checking: EXISTS subquery -> left semi join."""
    o = t["orders"].filter((col("o_orderdate") >= lit(_D_1994_01_01)) &
                           (col("o_orderdate") < lit(_D_1994_01_01 + 91)))
    l = t["lineitem"].filter(col("l_commitdate") < col("l_receiptdate"))
    return (o.join(l, on=(col("o_orderkey") == col("l_orderkey")),
                   how="left_semi")
            .groupBy("o_orderpriority")
            .agg(F.count("*").alias("order_count"))
            .orderBy("o_orderpriority"))


def q5(t):
    """Local supplier volume: 6-way join through nation/region."""
    revenue = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    r = t["region"].filter(col("r_name") == lit("ASIA"))
    n = t["nation"].join(r, on=(col("n_regionkey") == col("r_regionkey")))
    s = t["supplier"].join(n, on=(col("s_nationkey") == col("n_nationkey")))
    o = t["orders"].filter((col("o_orderdate") >= lit(_D_1994_01_01)) &
                           (col("o_orderdate") < lit(_D_1995_01_01)))
    c = t["customer"]
    return (c.join(o, on=(col("c_custkey") == col("o_custkey")))
            .join(t["lineitem"],
                  on=(col("o_orderkey") == col("l_orderkey")))
            .join(s, on=[col("l_suppkey") == col("s_suppkey"),
                         col("c_nationkey") == col("s_nationkey")])
            .groupBy("n_name")
            .agg(F.sum(revenue).alias("revenue"))
            .orderBy(col("revenue").desc()))


def q7(t):
    """Volume shipping between two nations: nation joined twice."""
    n1 = (t["nation"].filter(col("n_name").isin("FRANCE", "GERMANY"))
          .withColumnRenamed("n_name", "supp_nation")
          .withColumnRenamed("n_nationkey", "supp_nationkey"))
    n2 = (t["nation"].filter(col("n_name").isin("FRANCE", "GERMANY"))
          .withColumnRenamed("n_name", "cust_nation")
          .withColumnRenamed("n_nationkey", "cust_nationkey"))
    s = t["supplier"].join(
        n1, on=(col("s_nationkey") == col("supp_nationkey")))
    c = t["customer"].join(
        n2, on=(col("c_nationkey") == col("cust_nationkey")))
    # inclusive 1995-01-01 .. 1996-12-31: 365 + 366 days -> start + 730
    l = t["lineitem"].filter((col("l_shipdate") >= lit(_D_1995_01_01)) &
                             (col("l_shipdate") <= lit(_D_1995_01_01 + 730)))
    volume = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    joined = (l.join(s, on=(col("l_suppkey") == col("s_suppkey")))
              .join(t["orders"],
                    on=(col("l_orderkey") == col("o_orderkey")))
              .join(c, on=(col("o_custkey") == col("c_custkey")))
              .filter(((col("supp_nation") == lit("FRANCE")) &
                       (col("cust_nation") == lit("GERMANY"))) |
                      ((col("supp_nation") == lit("GERMANY")) &
                       (col("cust_nation") == lit("FRANCE")))))
    return (joined
            .withColumn("l_year", F.year(col("l_shipdate")))
            .groupBy("supp_nation", "cust_nation", "l_year")
            .agg(F.sum(volume).alias("revenue"))
            .orderBy("supp_nation", "cust_nation", "l_year"))


def q10(t):
    """Returned item reporting: 4-way join + revenue top-20."""
    o = t["orders"].filter((col("o_orderdate") >= lit(_D_1994_01_01)) &
                           (col("o_orderdate") < lit(_D_1994_01_01 + 91)))
    l = t["lineitem"].filter(col("l_returnflag") == lit("R"))
    revenue = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (t["customer"]
            .join(o, on=(col("c_custkey") == col("o_custkey")))
            .join(l, on=(col("o_orderkey") == col("l_orderkey")))
            .join(t["nation"],
                  on=(col("c_nationkey") == col("n_nationkey")))
            .groupBy("c_custkey", "c_name", "c_acctbal", "n_name")
            .agg(F.sum(revenue).alias("revenue"))
            .orderBy(col("revenue").desc(), col("c_custkey").asc())
            .limit(20))


def q17(t):
    """Small-quantity-order revenue: correlated avg subquery -> per-part
    aggregate joined back (the reference plans the same decorrelation)."""
    p = t["part"].filter((col("p_brand") == lit("Brand#23")) &
                         (col("p_container") == lit("MED BOX")))
    l = t["lineitem"]
    avg_qty = (l.groupBy("l_partkey")
               .agg((lit(0.2) * F.avg("l_quantity")).alias("qty_limit"))
               .withColumnRenamed("l_partkey", "al_partkey"))
    return (l.join(p, on=(col("l_partkey") == col("p_partkey")))
            .join(avg_qty, on=(col("l_partkey") == col("al_partkey")))
            .filter(col("l_quantity") < col("qty_limit"))
            .agg((F.sum("l_extendedprice") / lit(7.0)).alias("avg_yearly")))


def q19(t):
    """Discounted revenue: disjunctive join predicate over part attrs."""
    l = t["lineitem"].filter(
        col("l_shipmode").isin("AIR", "REG AIR"))
    p = t["part"]
    revenue = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    cond1 = ((col("p_brand") == lit("Brand#12")) &
             col("p_container").isin("SM CASE", "SM BOX") &
             (col("l_quantity") >= lit(1)) & (col("l_quantity") <= lit(11)) &
             (col("p_size") <= lit(5)))
    cond2 = ((col("p_brand") == lit("Brand#23")) &
             col("p_container").isin("MED BAG", "MED BOX") &
             (col("l_quantity") >= lit(10)) & (col("l_quantity") <= lit(20)) &
             (col("p_size") <= lit(10)))
    cond3 = ((col("p_brand") == lit("Brand#34")) &
             col("p_container").isin("LG CASE", "LG BOX") &
             (col("l_quantity") >= lit(20)) & (col("l_quantity") <= lit(30)) &
             (col("p_size") <= lit(15)))
    return (l.join(p, on=(col("l_partkey") == col("p_partkey")))
            .filter(cond1 | cond2 | cond3)
            .agg(F.sum(revenue).alias("revenue")))


def q8(t):
    """National market share: 8-way join + conditional ratio per year."""
    r = t["region"].filter(col("r_name") == lit("AMERICA"))
    n1 = (t["nation"].join(r, on=(col("n_regionkey") == col("r_regionkey")))
          .withColumnRenamed("n_nationkey", "cust_nationkey"))
    n2 = (t["nation"]
          .withColumnRenamed("n_nationkey", "supp_nationkey")
          .withColumnRenamed("n_name", "supp_nation"))
    p = t["part"].filter(col("p_type") == lit("ECONOMY ANODIZED STEEL"))
    o = t["orders"].filter((col("o_orderdate") >= lit(_D_1995_01_01)) &
                           (col("o_orderdate") <= lit(_D_1995_01_01 + 730)))
    volume = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    brazil = F.when(col("supp_nation") == lit("BRAZIL"),
                    volume).otherwise(lit(0.0))
    joined = (t["lineitem"]
              .join(p, on=(col("l_partkey") == col("p_partkey")))
              .join(t["supplier"],
                    on=(col("l_suppkey") == col("s_suppkey")))
              .join(o, on=(col("l_orderkey") == col("o_orderkey")))
              .join(t["customer"],
                    on=(col("o_custkey") == col("c_custkey")))
              .join(n1, on=(col("c_nationkey") == col("cust_nationkey")))
              .join(n2, on=(col("s_nationkey") == col("supp_nationkey"))))
    return (joined.withColumn("o_year", F.year(col("o_orderdate")))
            .groupBy("o_year")
            .agg((F.sum(brazil) / F.sum(volume)).alias("mkt_share"))
            .orderBy("o_year"))


def q9(t):
    """Product type profit: partsupp two-key join + per-nation/year sums.
    (p_name LIKE adapted to p_type contains — the generator has no
    p_name.)"""
    p = t["part"].filter(col("p_type").contains("BRUSHED"))
    amount = (col("l_extendedprice") * (lit(1.0) - col("l_discount")) -
              col("ps_supplycost") * col("l_quantity"))
    joined = (t["lineitem"]
              .join(p, on=(col("l_partkey") == col("p_partkey")))
              .join(t["supplier"],
                    on=(col("l_suppkey") == col("s_suppkey")))
              .join(t["partsupp"],
                    on=[col("l_partkey") == col("ps_partkey"),
                        col("l_suppkey") == col("ps_suppkey")])
              .join(t["orders"],
                    on=(col("l_orderkey") == col("o_orderkey")))
              .join(t["nation"],
                    on=(col("s_nationkey") == col("n_nationkey"))))
    return (joined.withColumn("o_year", F.year(col("o_orderdate")))
            .groupBy("n_name", "o_year")
            .agg(F.sum(amount).alias("sum_profit"))
            .orderBy(col("n_name").asc(), col("o_year").desc()))


def q13(t):
    """Customer distribution: left outer join (right side pre-filtered on
    the comment predicate — equivalent since it only references orders)
    + two-level aggregation."""
    o = t["orders"].filter(
        ~(col("o_comment").contains("special") &
          col("o_comment").contains("requests")))
    per_cust = (t["customer"]
                .join(o, on=(col("c_custkey") == col("o_custkey")),
                      how="left")
                .groupBy("c_custkey")
                .agg(F.count("o_orderkey").alias("c_count")))
    return (per_cust.groupBy("c_count")
            .agg(F.count("*").alias("custdist"))
            .orderBy(col("custdist").desc(), col("c_count").desc()))


def q16(t):
    """Parts/supplier relationship: anti join (NOT IN subquery) + count
    DISTINCT over a multi-key string group. (s_comment LIKE adapted to
    negative account balances — the generator has no s_comment.)"""
    bad_supp = t["supplier"].filter(col("s_acctbal") < lit(0))
    ps = (t["partsupp"]
          .join(bad_supp, on=(col("ps_suppkey") == col("s_suppkey")),
                how="left_anti"))
    p = t["part"].filter(
        (col("p_brand") != lit("Brand#45")) &
        ~col("p_type").startswith("MEDIUM POLISHED") &
        col("p_size").isin(49, 14, 23, 45, 19, 3, 36, 9))
    return (ps.join(p, on=(col("ps_partkey") == col("p_partkey")))
            .groupBy("p_brand", "p_type", "p_size")
            .agg(F.countDistinct(col("ps_suppkey")).alias("supplier_cnt"))
            .orderBy(col("supplier_cnt").desc(), col("p_brand").asc(),
                     col("p_type").asc(), col("p_size").asc()))


def q22(t):
    """Global sales opportunity: scalar avg subquery (cross join) + NOT
    EXISTS (left anti) + substring country codes."""
    cntry = F.substring(col("c_phone"), 1, 2)
    codes = ("13", "31", "23", "29", "30", "18", "17")
    cust = t["customer"].filter(cntry.isin(*codes))
    avg_bal = (cust.filter(col("c_acctbal") > lit(0.0))
               .agg(F.avg("c_acctbal").alias("avg_bal")))
    return (cust.crossJoin(avg_bal)
            .filter(col("c_acctbal") > col("avg_bal"))
            .join(t["orders"],
                  on=(col("c_custkey") == col("o_custkey")),
                  how="left_anti")
            .withColumn("cntrycode", cntry)
            .groupBy("cntrycode")
            .agg(F.count("*").alias("numcust"),
                 F.sum("c_acctbal").alias("totacctbal"))
            .orderBy("cntrycode"))


def q2(t):
    """Minimum-cost supplier: correlated min subquery decorrelated into a
    per-part min over the region-filtered partsupp, joined back on
    (partkey, supplycost)."""
    r = t["region"].filter(col("r_name") == lit("EUROPE"))
    n = t["nation"].join(r, on=(col("n_regionkey") == col("r_regionkey")))
    s = t["supplier"].join(n, on=(col("s_nationkey") == col("n_nationkey")))
    eu_ps = t["partsupp"].join(
        s, on=(col("ps_suppkey") == col("s_suppkey")))
    min_cost = (eu_ps.groupBy("ps_partkey")
                .agg(F.min("ps_supplycost").alias("min_cost"))
                .withColumnRenamed("ps_partkey", "mc_partkey"))
    p = t["part"].filter((col("p_size") == lit(15)) &
                         col("p_type").endswith("BRASS"))
    return (p.join(eu_ps, on=(col("p_partkey") == col("ps_partkey")))
            .join(min_cost,
                  on=[col("p_partkey") == col("mc_partkey"),
                      col("ps_supplycost") == col("min_cost")])
            .select(col("s_acctbal"), col("s_name"), col("n_name"),
                    col("p_partkey"), col("p_type"))
            .orderBy(col("s_acctbal").desc(), col("n_name").asc(),
                     col("s_name").asc(), col("p_partkey").asc())
            .limit(100))


def q11(t):
    """Important stock: per-part value vs a scalar fraction of the
    national total (cross-join scalar subquery)."""
    n = t["nation"].filter(col("n_name") == lit("GERMANY"))
    s = t["supplier"].join(n, on=(col("s_nationkey") == col("n_nationkey")))
    ps = t["partsupp"].join(s, on=(col("ps_suppkey") == col("s_suppkey")))
    value = col("ps_supplycost") * col("ps_availqty")
    per_part = ps.groupBy("ps_partkey").agg(F.sum(value).alias("value"))
    total = ps.agg((F.sum(value) * lit(0.0001)).alias("threshold"))
    return (per_part.crossJoin(total)
            .filter(col("value") > col("threshold"))
            .select(col("ps_partkey"), col("value"))
            .orderBy(col("value").desc(), col("ps_partkey").asc()))


def q15(t):
    """Top supplier: revenue view + scalar max (cross join). The float
    max-equality uses a 1e-6 relative band: the two engines' sums differ
    in the last ulp, which exact equality would amplify into a different
    row set."""
    l = t["lineitem"].filter((col("l_shipdate") >= lit(_D_1994_01_01)) &
                             (col("l_shipdate") < lit(_D_1994_01_01 + 90)))
    revenue = (l.groupBy("l_suppkey")
               .agg(F.sum(col("l_extendedprice") *
                          (lit(1.0) - col("l_discount")))
                    .alias("total_revenue")))
    max_rev = revenue.agg(F.max("total_revenue").alias("max_revenue"))
    return (t["supplier"]
            .join(revenue, on=(col("s_suppkey") == col("l_suppkey")))
            .crossJoin(max_rev)
            .filter(col("total_revenue") >=
                    col("max_revenue") * lit(1.0 - 1e-6))
            .select(col("s_suppkey"), col("s_name"), col("total_revenue"))
            .orderBy("s_suppkey"))


def q20(t):
    """Potential part promotion: nested IN subqueries decorrelated — the
    per-(part, supplier) 1994 lineitem volume joins partsupp, the
    availability filter applies, and suppliers semi-join the survivors.
    (p_name LIKE adapted to p_type contains.)"""
    p = t["part"].filter(col("p_type").contains("TIN"))
    li94 = (t["lineitem"]
            .filter((col("l_shipdate") >= lit(_D_1994_01_01)) &
                    (col("l_shipdate") < lit(_D_1995_01_01)))
            .groupBy("l_partkey", "l_suppkey")
            .agg((lit(0.5) * F.sum("l_quantity")).alias("half_qty")))
    qualifying = (t["partsupp"]
                  .join(p, on=(col("ps_partkey") == col("p_partkey")),
                        how="left_semi")
                  .join(li94,
                        on=[col("ps_partkey") == col("l_partkey"),
                            col("ps_suppkey") == col("l_suppkey")])
                  .filter(col("ps_availqty") > col("half_qty")))
    n = t["nation"].filter(col("n_name") == lit("CANADA"))
    return (t["supplier"]
            .join(n, on=(col("s_nationkey") == col("n_nationkey")))
            .join(qualifying,
                  on=(col("s_suppkey") == col("ps_suppkey")),
                  how="left_semi")
            .select(col("s_name"))
            .orderBy("s_name"))


def q21(t):
    """Suppliers who kept orders waiting: the EXISTS/NOT-EXISTS pair over
    lineitem aliases decorrelates into per-order distinct-supplier counts
    (>=2 suppliers total, exactly 1 late supplier)."""
    li = t["lineitem"]
    ord_supp = (li.groupBy("l_orderkey")
                .agg(F.countDistinct(col("l_suppkey")).alias("nsupp"))
                .withColumnRenamed("l_orderkey", "os_orderkey"))
    late = li.filter(col("l_receiptdate") > col("l_commitdate"))
    late_supp = (late.groupBy("l_orderkey")
                 .agg(F.countDistinct(col("l_suppkey")).alias("nlate"))
                 .withColumnRenamed("l_orderkey", "ls_orderkey"))
    o = t["orders"].filter(col("o_orderstatus") == lit("F"))
    # FRANCE (nation index 6): covered by the cycling supplier keys at
    # every scale factor (SAUDI ARABIA's index 20 is supplier-less below
    # SF 0.0021, which would make the tiny-scale golden test vacuous)
    n = t["nation"].filter(col("n_name") == lit("FRANCE"))
    s = t["supplier"].join(n, on=(col("s_nationkey") == col("n_nationkey")))
    return (late
            .join(o, on=(col("l_orderkey") == col("o_orderkey")))
            .join(s, on=(col("l_suppkey") == col("s_suppkey")))
            .join(ord_supp.filter(col("nsupp") >= lit(2)),
                  on=(col("l_orderkey") == col("os_orderkey")))
            .join(late_supp.filter(col("nlate") == lit(1)),
                  on=(col("l_orderkey") == col("ls_orderkey")))
            .groupBy("s_name")
            .agg(F.count("*").alias("numwait"))
            .orderBy(col("numwait").desc(), col("s_name").asc())
            .limit(100))


QUERIES = {"q1": q1, "q2": q2, "q3": q3, "q4": q4, "q5": q5, "q6": q6,
           "q7": q7, "q8": q8, "q9": q9, "q10": q10, "q11": q11,
           "q12": q12, "q13": q13, "q14": q14, "q15": q15, "q16": q16,
           "q17": q17, "q18": q18, "q19": q19, "q20": q20, "q21": q21,
           "q22": q22}
