"""Traffic-replay benchmark: N concurrent TPC-H streams, one engine.

The bench suite so far measured queries ONE AT A TIME — the "heavy
traffic from millions of users" scenario (ROADMAP item 4) was invisible:
no number said what p99 latency or queries/second this engine sustains
when concurrent tenants hammer shared TPU state. This module is that
measurement:

* ``streams`` worker streams (the TPC-H throughput-test shape) submit
  TPC-H-shaped queries to ONE :class:`QueryService` over ONE session,
  alternating between a high-priority ``gold`` tenant and a
  low-priority ``bronze`` tenant (mixed-tenant traffic);
* parameters ROTATE through prepared statements (the PR 12 serving
  front door): every stream re-executes the same plan with different
  literal windows, so the replay measures the serving hot path, not
  repeated planning;
* the whole replay runs under ``lockdep=enforce`` — a lock-order
  inversion anywhere in the concurrent engine fails the bench loudly;
* ``faults`` arms the chaos harness (PR 13) during the replay: results
  must still match the fault-free oracle and recovery must be absorbed
  by stage retries under concurrent load.

Artifact series (benchmarks/history.py, kind ``replay``):
``replay_qps`` (higher better), ``replay_p50_s`` / ``replay_p99_s``
(submit->result latency percentiles, lower better),
``first_row_p99_s`` (submit->FIRST-BATCH p99 of the streaming leg's
``submit_stream`` traffic, lower better), ``replay_chaos_p99_s``
for the chaos mode, and ``replay_preempt_p99_s`` (gold p99 of the
preemption-armed mixed-priority leg, --preempt: weighted-fair
scheduling suspends a running low-priority query so the high-priority
arrival runs first, then resumes it — ISSUE 20). Stamped only when
every query returned oracle-correct rows (under chaos, every armed
fault additionally fired; under --preempt, at least one suspend/resume
cycle was additionally observed) — a wrong-answer replay is void, not
fast.

CLI::

    python -m benchmarks.replay --sf 0.002 --streams 4 --iters 6
    python -m benchmarks.replay --faults "fetch.fail;task.poison"
    python -m benchmarks.replay --preempt --iters 6
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import threading
import time
from typing import Dict, List, Optional

#: the default chaos spec for ``--faults default`` (one failed fetch +
#: one poisoned map batch, the bench_chaos pair, absorbed by stage retry)
DEFAULT_FAULTS = "fetch.fail;task.poison"


def _rows_close(a, b, rel_tol=1e-9) -> bool:
    """Row-wise equality with fp tolerance (the bench.py rule: retries
    and concurrent scheduling legally reorder float aggregation)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if not math.isclose(va, vb, rel_tol=rel_tol,
                                    abs_tol=1e-12):
                    return False
            elif va != vb:
                return False
    return True


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted latency list."""
    if not sorted_vals:
        return 0.0
    idx = max(0, math.ceil(q * len(sorted_vals)) - 1)
    return sorted_vals[idx]


def _window(i: int):
    """Rotating one-year date window (epoch days), 24 phases."""
    import datetime
    lo = datetime.date(1993, 1, 1) + datetime.timedelta(days=30 * (i % 24))
    return lo, lo + datetime.timedelta(days=365)


#: the replay's prepared-statement shapes (SQL with :name placeholders
#: bound per iteration). q6-shaped: tight filter + global sum; q1-shaped:
#: filter + grouped wide aggregate. Both read the lineitem view.
_Q6_SQL = ("SELECT sum(l_extendedprice * l_discount) AS revenue "
           "FROM replay_lineitem "
           "WHERE l_shipdate >= :lo AND l_shipdate < :hi "
           "AND l_discount >= 0.05 AND l_discount <= 0.07 "
           "AND l_quantity < 24")
_Q1_SQL = ("SELECT l_returnflag, sum(l_quantity) AS sum_qty, "
           "avg(l_extendedprice) AS avg_price, count(*) AS cnt "
           "FROM replay_lineitem WHERE l_shipdate < :hi "
           "GROUP BY l_returnflag ORDER BY l_returnflag")


def _build_session(faults: Optional[str], extra_conf: Optional[dict]):
    from spark_rapids_tpu.api.session import TpuSession
    conf = {
        "spark.rapids.tpu.sql.explain": "NONE",
        # the whole replay runs under ENFORCE: any lock-order inversion
        # in the concurrent engine raises instead of logging
        "spark.rapids.tpu.sql.analysis.lockdep": "enforce",
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
    }
    if faults:
        # chaos injection points live on the DCN map/fetch paths
        conf["spark.rapids.tpu.sql.shuffle.plane"] = "dcn"
        conf["spark.rapids.tpu.sql.recovery.retryBackoff"] = "0.0"
    conf.update(extra_conf or {})
    return TpuSession.builder.config(conf).getOrCreate()


def run_replay(sf: float = 0.002, streams: int = 4,
               queries_per_stream: int = 6,
               faults: Optional[str] = None,
               stamp: bool = True,
               history_path: Optional[str] = None,
               extra_conf: Optional[dict] = None) -> Dict:
    """Drive the replay and return the artifact dict (see module doc).
    ``faults`` arms the chaos harness for the traffic window (results
    still must match the fault-free oracle)."""
    import jax
    from benchmarks import datagen
    from benchmarks import queries as Q
    from spark_rapids_tpu.analysis import faults as faults_mod
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.service.server import QueryService, TenantSpec
    from spark_rapids_tpu.service.telemetry import MetricsRegistry

    session = _build_session(faults, extra_conf)
    tables = datagen.register_tables(session, sf)
    tables["lineitem"].createOrReplaceTempView("replay_lineitem")

    # chaos traffic must traverse a DCN exchange (the injection points):
    # a q6-shaped aggregate over a hash-repartitioned lineitem
    shuffled = dict(tables)
    shuffled["lineitem"] = tables["lineitem"].repartition(
        4, col("l_orderkey"))

    def make_query(stream: int, i: int):
        """(kind, execute-thunk-args) for stream position i."""
        if faults:
            return ("shuffle_q6", None)
        return ("q6", _window(stream + i)) if (stream + i) % 2 == 0 \
            else ("q1", _window(stream + i))

    # ---- fault-free oracle: every (kind, params) executed DIRECTLY once
    oracle: Dict[tuple, list] = {}
    for s in range(streams):
        for i in range(queries_per_stream):
            kind, win = make_query(s, i)
            key = (kind, win)
            if key in oracle:
                continue
            if kind == "shuffle_q6":
                oracle[key] = Q.QUERIES["q6"](shuffled).collect()
            else:
                stmt = session.prepare(_Q6_SQL if kind == "q6"
                                       else _Q1_SQL)
                params = {"lo": win[0], "hi": win[1]} if kind == "q6" \
                    else {"hi": win[1]}
                oracle[key] = stmt.execute(**params).rows()

    def retries_total() -> float:
        try:
            return float(MetricsRegistry.get().counter(
                "tpu_stage_retries_total", "x").value)
        except Exception:
            return 0.0

    svc = QueryService(session, tenants=[
        TenantSpec("gold", priority=10, slots=max(1, streams // 2),
                   memory_budget_bytes=1 << 30),
        TenantSpec("bronze", priority=0, slots=max(1, streams // 2),
                   memory_budget_bytes=256 << 20)])

    latencies: List[float] = []
    first_rows: List[float] = []
    wrong: List[str] = []
    errors: List[str] = []
    lat_mu = threading.Lock()  # lint: raw-lock-ok bench-local result list, dies with the run

    # streaming leg (fault-free mode): per stream, a few queries go
    # through submit_stream and the submit->FIRST-BATCH wall is measured
    # — the time-to-first-row number the streaming collect exists to
    # shrink (ISSUE 17; stamped as first_row_p99_s). Oracle rows come
    # from the same frames' materializing collect.
    streaming_per_stream = 0 if faults else max(1, queries_per_stream // 3)
    stream_oracle: Dict[str, list] = {}
    if streaming_per_stream:
        stream_oracle = {k: Q.QUERIES[k](tables).collect()
                         for k in ("q1", "q6")}

    def stream_body(s: int) -> None:
        # one PreparedStatement per shape PER STREAM: a statement binds
        # in place, so it must never have two in-flight executes
        stmts = {"q6": session.prepare(_Q6_SQL),
                 "q1": session.prepare(_Q1_SQL)}
        tenant = "gold" if s % 2 == 0 else "bronze"
        for i in range(queries_per_stream):
            kind, win = make_query(s, i)
            if kind == "shuffle_q6":
                ticket = svc.submit(
                    tenant, Q.QUERIES["q6"](shuffled),
                    label=f"s{s}-{i}-{kind}")
            else:
                params = {"lo": win[0], "hi": win[1]} if kind == "q6" \
                    else {"hi": win[1]}
                ticket = svc.submit(tenant, stmts[kind], params=params,
                                    label=f"s{s}-{i}-{kind}")
            try:
                rows = ticket.result(timeout=600).rows()
            except Exception as e:
                with lat_mu:
                    errors.append(f"s{s}-{i}-{kind}: "
                                  f"{type(e).__name__}: {e}"[:200])
                continue
            ok = _rows_close(rows, oracle[(kind, win)])
            with lat_mu:
                latencies.append(ticket.latency_s())
                if not ok:
                    wrong.append(f"s{s}-{i}-{kind}")
        for j in range(streaming_per_stream):
            kind = "q6" if (s + j) % 2 == 0 else "q1"
            ticket = svc.submit_stream(tenant, Q.QUERIES[kind](tables),
                                       label=f"s{s}-stream{j}-{kind}")
            rows = []
            fr = None
            try:
                for b in ticket.stream():
                    if fr is None:
                        fr = time.perf_counter() - ticket.submitted_at
                    rows.extend(b.rows())
                ticket.result(timeout=600)
            except Exception as e:
                with lat_mu:
                    errors.append(f"s{s}-stream{j}-{kind}: "
                                  f"{type(e).__name__}: {e}"[:200])
                continue
            ok = _rows_close(rows, stream_oracle[kind])
            with lat_mu:
                if fr is not None:
                    first_rows.append(fr)
                if not ok:
                    wrong.append(f"s{s}-stream{j}-{kind}")

    retries0 = retries_total()
    armed = 0
    try:
        if faults:
            armed = faults_mod.install(faults)
        t0 = time.perf_counter()
        threads = [threading.Thread(target=stream_body, args=(s,),
                                    name=f"replay-stream-{s}")
                   for s in range(streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        fired = faults_mod.fired_total() if faults else 0
    finally:
        if faults:
            faults_mod.reset()         # never leak chaos downstream
        svc.close()
    stage_retries = retries_total() - retries0

    total = streams * queries_per_stream
    expected_streaming = streams * streaming_per_stream
    latencies.sort()
    first_rows.sort()
    qps = len(latencies) / wall if wall > 0 else 0.0
    p50 = _percentile(latencies, 0.50)
    p99 = _percentile(latencies, 0.99)
    ok = (not wrong and not errors and len(latencies) == total and
          len(first_rows) == expected_streaming and
          (not faults or (fired >= armed and stage_retries >= 1)))
    line: Dict = {
        "metric": "traffic replay",
        "backend": jax.devices()[0].platform,
        "sf": sf,
        "streams": streams,
        "queries": total,
        "completed": len(latencies),
        "wall_s": round(wall, 4),
        "replay_qps": round(qps, 3),
        "replay_p50_s": round(p50, 4),
        "replay_p99_s": round(p99, 4),
        "faults_spec": faults or "",
        "faults_fired": int(fired),
        "stage_retries": int(stage_retries),
        "replay_ok": ok,
        "service": svc.stats(),
    }
    if expected_streaming:
        line["streaming_queries"] = len(first_rows)
        line["first_row_p50_s"] = round(_percentile(first_rows, 0.50), 4)
        line["first_row_p99_s"] = round(_percentile(first_rows, 0.99), 4)
    if wrong:
        line["wrong_results"] = wrong[:10]
    if errors:
        line["errors"] = errors[:10]
    if faults:
        line["replay_chaos_p99_s"] = round(p99, 4)

    if stamp and ok:
        # the regression gate (benchmarks/history.py): replay latency
        # and throughput ride the same verdict machinery as every bench
        from benchmarks import history as bh
        if faults:
            queries = {bh.REPLAY_CHAOS_P99_S: line["replay_chaos_p99_s"]}
        else:
            queries = {bh.REPLAY_QPS: line["replay_qps"],
                       bh.REPLAY_P50_S: line["replay_p50_s"],
                       bh.REPLAY_P99_S: line["replay_p99_s"]}
            if expected_streaming:
                queries[bh.FIRST_ROW_P99_S] = line["first_row_p99_s"]
        gate = bh.stamp("replay", queries, backend=line["backend"],
                        higher_is_better=True,
                        meta={"sf": sf, "streams": streams,
                              "faults": faults or ""},
                        path=history_path)
        line["regression"] = {q: v.get("verdict")
                              for q, v in gate["verdicts"].items()}
        line["regression_overall"] = gate["overall"]
    return line


def run_preempt_replay(sf: float = 0.002, rounds: int = 6,
                       stamp: bool = True,
                       history_path: Optional[str] = None) -> Dict:
    """Preemption-armed mixed-priority leg (ISSUE 20, docs/service.md
    §4): ONE worker slot, weighted-fair scheduling with preemption ON.

    Each round submits a long low-priority ``bronze`` shuffle query,
    waits for it to occupy the slot, then a high-priority ``gold`` query
    arrives: the scheduler requests suspension of the running bronze
    query, which parks its working set at the next cancel poll; gold
    runs in the freed slot; a resumer thread re-admits the parked query,
    which must still return oracle-correct rows. Stamps
    ``replay_preempt_p99_s`` (gold submit->result p99, lower better)
    ONLY when at least one full suspend/resume cycle was actually
    observed and EVERY query — the preempted ones included — matched
    the fault-free oracle: a preemption leg where nothing got preempted
    (or a preempted query came back wrong) is void, not fast.
    """
    import jax
    from benchmarks import datagen
    from benchmarks import queries as Q
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.service.server import QueryService, TenantSpec

    session = _build_session(None, {
        "spark.rapids.tpu.sql.service.scheduler.policy": "wfq",
        "spark.rapids.tpu.sql.service.scheduler.preemption": "true",
        # a preempted query's park/resume must keep the buffer ledger
        # clean — enforce raises on any leaked lifecycle, so the leg
        # doubles as the suspend-path leak check
        "spark.rapids.tpu.sql.analysis.bufferLedger": "enforce",
        # more partitions -> more per-partition cancel polls, so the
        # running bronze query reaches a suspension point quickly
        "spark.rapids.tpu.sql.shuffle.partitions": "8",
    })
    tables = datagen.register_tables(session, sf)
    tables["lineitem"].createOrReplaceTempView("replay_lineitem")
    shuffled = dict(tables)
    shuffled["lineitem"] = tables["lineitem"].repartition(
        8, col("l_orderkey"))

    # fault-free oracles, executed directly before the service opens
    bronze_oracle = Q.QUERIES["q6"](shuffled).collect()
    gold_stmt = session.prepare(_Q6_SQL)
    gold_oracle: Dict[int, list] = {}
    for i in range(rounds):
        lo, hi = _window(i)
        gold_oracle[i] = gold_stmt.execute(lo=lo, hi=hi).rows()

    # one slot total: a gold arrival while bronze runs ALWAYS finds the
    # service saturated, which is the preemption precondition. Gold's
    # larger weight keeps its service-unit clock slower, so the freed
    # slot goes to gold, not straight back to the resumed bronze.
    svc = QueryService(session, max_workers=1, tenants=[
        TenantSpec("gold", priority=10, slots=1, weight=4.0,
                   memory_budget_bytes=1 << 30),
        TenantSpec("bronze", priority=0, slots=1, weight=1.0,
                   memory_budget_bytes=256 << 20)])

    stop = threading.Event()

    def resumer() -> None:
        # the re-admission half of the cycle: parked queries go back
        # through the scheduler as soon as they are seen
        while not stop.is_set():
            for qid in svc.suspended_queries():
                try:
                    svc.resume(qid)
                except Exception:
                    # a ticket resumed by a racing pass or a closing
                    # service is not a bench failure
                    pass
            stop.wait(0.01)

    gold_lat: List[float] = []
    wrong: List[str] = []
    errors: List[str] = []
    bronze_tickets = []
    res_thread = threading.Thread(target=resumer, daemon=True,
                                  name="preempt-replay-resumer")
    res_thread.start()
    try:
        for i in range(rounds):
            bt = svc.submit("bronze", Q.QUERIES["q6"](shuffled),
                            label=f"bronze-{i}")
            bronze_tickets.append((i, bt))
            # wait for bronze to actually occupy the slot (preemption
            # only targets RUNNING queries)
            deadline = time.perf_counter() + 5.0
            while time.perf_counter() < deadline:
                if svc.stats()["running"] >= 1:
                    break
                time.sleep(0.002)
            lo, hi = _window(i)
            gt = svc.submit("gold", gold_stmt,
                            params={"lo": lo, "hi": hi},
                            label=f"gold-{i}")
            try:
                rows = gt.result(timeout=600).rows()
            except Exception as e:
                errors.append(f"gold-{i}: {type(e).__name__}: {e}"[:200])
                continue
            gold_lat.append(gt.latency_s())
            if not _rows_close(rows, gold_oracle[i]):
                wrong.append(f"gold-{i}")
        # the preempted queries must come back and come back RIGHT
        for i, bt in bronze_tickets:
            try:
                rows = bt.result(timeout=600).rows()
            except Exception as e:
                errors.append(f"bronze-{i}: {type(e).__name__}: {e}"[:200])
                continue
            if not _rows_close(rows, bronze_oracle):
                wrong.append(f"bronze-{i}")
    finally:
        stop.set()
        res_thread.join(timeout=5)
        stats = svc.stats()
        svc.close()

    bronze_stats = stats["tenants"]["bronze"]
    preempted = int(bronze_stats["preempted"])
    resumed = int(bronze_stats["resumed"])
    gold_lat.sort()
    p99 = _percentile(gold_lat, 0.99)
    # honesty: the leg is void without >=1 OBSERVED suspend/resume
    # cycle — otherwise it silently degrades into a plain WFQ replay
    ok = (not wrong and not errors and len(gold_lat) == rounds and
          preempted >= 1 and resumed >= 1)
    line: Dict = {
        "metric": "preempt replay",
        "backend": jax.devices()[0].platform,
        "sf": sf,
        "rounds": rounds,
        "gold_completed": len(gold_lat),
        "preempted": preempted,
        "resumed": resumed,
        "replay_preempt_p99_s": round(p99, 4),
        "replay_ok": ok,
        "service": stats,
    }
    if wrong:
        line["wrong_results"] = wrong[:10]
    if errors:
        line["errors"] = errors[:10]
    if stamp and ok:
        from benchmarks import history as bh
        gate = bh.stamp(
            "replay",
            {bh.REPLAY_PREEMPT_P99_S: line["replay_preempt_p99_s"]},
            backend=line["backend"], higher_is_better=True,
            meta={"sf": sf, "mode": "preempt", "rounds": rounds},
            path=history_path)
        line["regression"] = {q: v.get("verdict")
                              for q, v in gate["verdicts"].items()}
        line["regression_overall"] = gate["overall"]
    return line


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="concurrent mixed-tenant TPC-H traffic replay "
                    "through the multi-tenant query service")
    ap.add_argument("--sf", type=float, default=0.002,
                    help="TPC-H scale factor of the generated tables")
    ap.add_argument("--streams", type=int, default=4,
                    help="concurrent submission streams")
    ap.add_argument("--iters", type=int, default=6,
                    help="queries per stream")
    ap.add_argument("--faults", default=None,
                    help="chaos spec for the replay window ('default' = "
                         f"{DEFAULT_FAULTS!r})")
    ap.add_argument("--preempt", action="store_true",
                    help="run the preemption-armed mixed-priority leg "
                         "(wfq + suspend/resume) instead of the stream "
                         "replay")
    ap.add_argument("--no-stamp", action="store_true",
                    help="skip the bench-history regression stamp")
    args = ap.parse_args(argv)
    if args.preempt:
        line = run_preempt_replay(sf=args.sf, rounds=args.iters,
                                  stamp=not args.no_stamp)
    else:
        faults = DEFAULT_FAULTS if args.faults == "default" else args.faults
        line = run_replay(sf=args.sf, streams=args.streams,
                          queries_per_stream=args.iters, faults=faults,
                          stamp=not args.no_stamp)
    print(json.dumps(line, default=str))
    return 0 if line.get("replay_ok") else 1


if __name__ == "__main__":
    sys.exit(main())
