#!/bin/bash
# SF1 verified correctness gate, banked in groups (single-core machine:
# one group at a time, each writes its own report as it completes).
cd /root/repo
for grp in "q1,q3,q4,q6,q12,q14,q15,q19,q22:fast" \
           "q5,q10,q2,q7,q8,q11,q16,q17,q20:mid" \
           "q13,q18:med2" "q9:q9" "q21:q21"; do
  qs="${grp%%:*}"; name="${grp##*:}"
  echo "=== $name start $(date +%H:%M) ==="
  PYTHONPATH= JAX_PLATFORMS=cpu timeout 4800 python -m benchmarks.runner \
    --sf 1 --queries "$qs" --iterations 1 --verify \
    --output "benchmarks/reports/tpch_sf1_${name}_r5.json" \
    > /dev/null 2>&1
  echo "=== $name rc=$? done $(date +%H:%M) ==="
done
