"""BenchmarkRunner: run TPC-H-like queries, write JSON reports.

Analog of the reference's BenchmarkRunner / BenchUtils
(integration_tests/.../BenchmarkRunner.scala, tests/common/BenchUtils.scala;
docs/benchmarks.md): per-query iterations with cold/hot timings, collected row
counts, plan summaries, optional CPU-engine result verification with epsilon
(BenchUtils.compareResults epsilon=1e-4).

Usage: python -m benchmarks.runner --sf 0.01 --queries q1,q6 --iterations 2
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict, List, Optional

from . import datagen, queries as Q


def run_benchmark(sf: float = 0.01, query_names: Optional[List[str]] = None,
                  iterations: int = 2, verify: bool = False,
                  output: Optional[str] = None, suite: str = "tpch",
                  concurrent_tasks: Optional[int] = None,
                  trace_dir: Optional[str] = None,
                  probe_timeout_s: float = 30.0,
                  history_path: Optional[str] = None,
                  compile_cache_dir: Optional[str] = None,
                  prewarm: bool = False) -> Dict:
    import os
    # device preflight BEFORE any engine/jax use: a dead tunnel degrades
    # this run to an explicit cpu-degraded measurement instead of hanging
    # or emitting a zero (the BENCH_r04/r05 dark rounds)
    from .preflight import preflight
    pf = preflight(probe_timeout_s)
    from spark_rapids_tpu.api.session import TpuSession
    if concurrent_tasks is None:
        # pin device admission to host parallelism: the engine default (2)
        # under a 4-thread task pool makes CPU-backend reports measure
        # semaphore admission thrash instead of engine time
        concurrent_tasks = os.cpu_count() or 4
    if trace_dir is None and output:
        trace_dir = f"{output}.traces"
    session = TpuSession.builder.config(
        "spark.rapids.tpu.sql.explain", "NONE").config(
        "spark.rapids.tpu.sql.concurrentTpuTasks",
        concurrent_tasks).config(
        # per-query Chrome-trace timelines (exec/tracing.SpanRecorder):
        # recorded when a trace dir exists to dump them into
        "spark.rapids.tpu.sql.tracing.timeline",
        "true" if trace_dir else "false").config(
        # lock-order graph + per-lock wait/hold attribution on for bench
        # runs (the documented tests/bench default for analysis.lockdep)
        "spark.rapids.tpu.sql.analysis.lockdep", "record").config(
        # buffer-lifecycle ledger in record mode (analysis/ledger.py):
        # every bench round reports leaks/use-after-free without ever
        # failing a measurement — the lockdep discipline for HBM
        "spark.rapids.tpu.sql.analysis.bufferLedger", "record").config(
        # persistent compile cache: repeated runner invocations against
        # the same dir pay disk hits instead of cold builds
        "spark.rapids.tpu.sql.compile.cacheDir",
        compile_cache_dir or "").config(
        # cache prewarm (docs/compile.md §5): bootstrap replays the
        # hottest fused-stage signatures from the corpus beside the
        # signature index onto the background compile pool, so a fresh
        # process serves known queries with zero query-triggered builds
        "spark.rapids.tpu.sql.compile.prewarm.enabled",
        "true" if (prewarm and compile_cache_dir) else
        "false").getOrCreate()
    prewarm_info = None
    if prewarm and compile_cache_dir:
        # wait for the bootstrap-submitted prewarm builds BEFORE the
        # query loop: cold_s below then measures a genuinely prewarmed
        # first touch, and the honesty check (zero query-triggered cold
        # compiles) is meaningful
        from spark_rapids_tpu.exec import compile_pool
        compile_pool.drain(timeout_s=120.0)
        prewarm_info = compile_pool.stats()
    if trace_dir:
        # defensive: --trace-dir may name a nested path that does not
        # exist yet; a failed trace write must never fail the run
        try:
            os.makedirs(trace_dir, exist_ok=True)
        except OSError:
            trace_dir = None
    # the listener API (session.register_query_listener) delivers the
    # executed plan + metrics tree per query; the LAST capture per name
    # lands in the report as that query's per-operator metrics tree
    # (registered around the query loop below, unregistered in a finally
    # — getOrCreate can hand this session to later callers)
    captures: List = []

    if suite == "tpcds":
        from . import tpcds_queries
        queries = tpcds_queries.TPCDS_QUERIES
        register = datagen.register_tpcds_tables
    elif suite == "tpcxbb":
        from . import tpcxbb_queries
        queries = tpcxbb_queries.TPCXBB_QUERIES
        register = datagen.register_tpcds_tables
    else:
        queries = Q.QUERIES
        register = datagen.register_tables
    t_gen0 = time.perf_counter()
    tables = register(session, sf)
    gen_s = time.perf_counter() - t_gen0

    report: Dict = {"suite": suite, "sf": sf, "datagen_s": round(gen_s, 3),
                    "concurrentTpuTasks": concurrent_tasks,
                    "backend": pf["backend"],
                    "deviceProbe": pf["deviceProbe"],
                    "queries": {}}
    names = query_names or list(queries)
    try:
        for name in names:
            session.register_query_listener(captures.append)
            from spark_rapids_tpu.exec.device import TpuSemaphore
            from spark_rapids_tpu.analysis import lockdep, recompile
            qfn = queries[name]
            timings = []
            rows = 0
            sem0 = TpuSemaphore.get().stats()
            rc0 = recompile.snapshot()
            lk0 = lockdep.stats()
            from spark_rapids_tpu.analysis import ledger as _ledger
            led0 = _ledger.stats()
            for it in range(iterations):
                if it == 1:
                    # capture (listener snapshots + QueryExecution build)
                    # rides the COLD iteration only: hot_s = min of the
                    # later iterations must not time observability work
                    session.unregister_query_listener(captures.append)
                t0 = time.perf_counter()
                df = qfn(tables)
                batch = df.collect_batch().fetch_to_host()
                rows = batch.num_rows
                timings.append(round(time.perf_counter() - t0, 4))
            sem1 = TpuSemaphore.get().stats()
            entry = {
                "rows": rows,
                "cold_s": timings[0],
                "hot_s": min(timings[1:]) if len(timings) > 1 else timings[0],
                "timings_s": timings,
                # admission contention vs device occupancy, separable
                # (wait = blocked acquiring a permit; hold = acquire->release)
                "semaphore": {
                    "waitS": round(sem1["waitS"] - sem0["waitS"], 4),
                    "holdS": round(sem1["holdS"] - sem0["holdS"], 4),
                    "acquires": sem1["acquires"] - sem0["acquires"],
                },
                # distinct-compile counts across this query's iterations
                # (analysis/recompile.py): a kernel compiling per iteration
                # means its shapes never hit the fused cache
                "recompiles": recompile.delta(rc0),
            }
            # compile-time summary (exec/compile_cache): seconds this
            # query paid building programs, split cold vs persistent-
            # cache disk hit — with compile.cacheDir set, a repeat run
            # should show cold == 0
            rc = entry["recompiles"]
            compile_summary = {
                "coldCompiles": sum(v.get("coldCompiles", 0)
                                    for v in rc.values()),
                "diskHits": sum(v.get("diskHits", 0) for v in rc.values()),
                "compileS": round(sum(v.get("compileS", 0.0)
                                      for v in rc.values()), 4),
            }
            if any(compile_summary.values()):
                entry["compile"] = compile_summary
            flags = recompile.flagged(entry["recompiles"])
            if flags:
                entry["recompileFlags"] = flags
            # per-lock wait/hold deltas attributed to trace spans, next to
            # the semaphore wait/hold split (analysis/lockdep.py): which
            # lock a query's threads actually contended, and in which
            # named execute region
            locks = _lock_delta(lk0, lockdep.stats())
            if locks:
                entry["locks"] = locks
            # buffer-lifecycle verdict for this query: the end-of-query
            # audit of the LAST iteration (leaks, peak device bytes)
            # plus the run-counter deltas across all iterations — a
            # query whose iterations leak or touch dead buffers says so
            # in its own report entry
            led1 = _ledger.stats()
            led = {k: led1[k] - led0[k]
                   for k in ("leaks", "use_after_free",
                             "use_after_donate", "double_free")
                   if led1[k] - led0[k]}
            last_audit = getattr(session, "_last_ledger", None)
            if last_audit:
                entry["ledger"] = {
                    "leakedBuffers": last_audit.get("leakedBuffers", 0),
                    "leakedBytes": last_audit.get("leakedBytes", 0),
                    "peakDeviceBytes":
                        last_audit.get("peakDeviceBytes", 0),
                    **({"deltas": led} if led else {}),
                }
            elif led:
                entry["ledger"] = {"deltas": led}
            try:
                # per-exchange shuffle accounting (docs/shuffle.md): which
                # data plane each exchange took (ici collectives vs the
                # host/DCN path), bytes moved, and GB/s
                from spark_rapids_tpu.shuffle.exchange import shuffle_report
                shuffles = shuffle_report(session.last_plan())
                if shuffles:
                    entry["shuffle"] = shuffles
            except Exception:
                pass
            try:
                # stage-boundary exchange statistics + drift summary
                # (docs/observability.md §8) next to the metricsTree:
                # what each exchange actually produced (partition shape,
                # skew) and where the planner's row estimates missed —
                # the SAME artifact shapes the structured query log
                # writes, from the shared helpers
                from spark_rapids_tpu.service.query_log import (
                    drift_summary, stage_summaries)
                entry["queryId"] = session.last_query_id()
                stats = stage_summaries(session.last_plan())
                if stats:
                    entry["stageStats"] = stats
                drift = drift_summary(session.last_plan(),
                                      conf=session.conf)
                if drift["nodes"]:
                    entry["drift"] = drift
            except Exception:
                pass
            try:
                m = session.last_query_metrics()
                entry["planTimeS"] = m.get("planTimeS")
                entry["executeTimeS"] = m.get("executeTimeS")
                # sync includes the per-span breakdown (syncSpans): which named
                # execute region paid the device->host round trips
                entry["sync"] = m.get("sync")
                entry["spans"] = m.get("spans")
                # per-operator metrics tree of the captured (cold)
                # iteration (EXPLAIN ANALYZE's data, via the query
                # listener): which node paid the rows/time/syncs/recompiles
                if captures:
                    entry["metricsTree"] = [
                        {"depth": d, "operator": op,
                         "metrics": {k: (round(v, 4) if isinstance(v, float)
                                         else v)
                                     for k, v in mm.items()}}
                        for d, op, mm in captures[-1].metrics_tree]
            except Exception:
                pass
            if trace_dir:
                # Chrome-trace timeline of the last iteration in the
                # MERGED form (query-id-stamped spans, per-worker process
                # groups — open in chrome://tracing / ui.perfetto.dev):
                # a distributed run appends the remote workers' trace
                # dumps via session.merged_timeline(extra=...) and the
                # spans join under the shared query id. No recorder
                # (timeline off / short-circuited query) or a failed
                # write just skips the artifact.
                try:
                    path = os.path.join(trace_dir, f"{name}.trace.json")
                    entry["traceFile"] = session.merged_timeline(path=path)
                except Exception:
                    pass
            captures.clear()
            if verify:
                entry["verified"] = _verify(session, qfn(tables))
            report["queries"][name] = entry
    finally:
        session.unregister_query_listener(captures.append)
    # run-level size-class audit (analysis/recompile.size_class_report):
    # every compiled signature carrying a dimension that escaped the
    # power-of-two bucket discipline, traced to the leaking ints — the
    # "which un-bucketed dimension caused this recompile" answer
    from spark_rapids_tpu.analysis import recompile as _recompile
    leaks = _recompile.size_class_report()
    if leaks:
        report["sizeClassLeaks"] = leaks
    # run-level lockdep findings: order-inversion cycles (with both
    # acquisition stacks) and lock-held-across-transfer events
    from spark_rapids_tpu.analysis import lockdep
    lk = lockdep.report()
    if lk["cycles"] or lk["heldAcrossTransfer"]:
        report["lockdep"] = {
            "cycles": lk["cycles"],
            "heldAcrossTransfer": [
                {"locks": t["locks"], "transfer": t["transfer"]}
                for t in lk["heldAcrossTransfer"]],
        }
    # regression gate (benchmarks/history.py): per-query hot seconds vs
    # the best prior clean same-backend round of this suite+sf series;
    # the verdict lands both per query and as a report summary
    try:
        from . import history as bh
        degraded = report["backend"] == "cpu-degraded"
        gate = bh.stamp(
            f"runner-{suite}-sf{sf}",
            {name: e.get("hot_s") for name, e in report["queries"].items()},
            backend=report["backend"], degraded=degraded,
            error=report["deviceProbe"].get("error") if degraded else None,
            higher_is_better=False,        # hot seconds: lower is better
            meta={"iterations": iterations,
                  "concurrentTpuTasks": concurrent_tasks},
            path=history_path)
        for name, v in gate["verdicts"].items():
            if name in report["queries"]:
                report["queries"][name]["regression"] = v
        report["regression_overall"] = gate["overall"]
    except Exception as e:        # the gate must not kill the report
        report["regression_error"] = str(e)[:200]
    # cold-path series (ISSUE 17, docs/compile.md §5): with --prewarm
    # against a warmed cache dir, q6's FIRST iteration in this fresh
    # process is the cold_q6_s measurement. Stamped only when the
    # honesty checks pass: rows came back and the query thread paid
    # ZERO cold compiles (the builds all landed at prewarm time).
    if prewarm_info is not None:
        report["prewarm"] = prewarm_info
        try:
            from . import history as bh
            e = report["queries"].get("q6")
            if e is not None:
                comp = e.get("compile", {}) or {}
                honest = (comp.get("coldCompiles", 0) == 0
                          and e.get("rows", 0) > 0
                          and report["backend"] != "cpu-degraded")
                report["cold_path"] = {
                    "coldQ6S": e["cold_s"],
                    "queryColdCompiles": comp.get("coldCompiles", 0),
                    "queryDiskHits": comp.get("diskHits", 0),
                    "prewarmBuilt": prewarm_info.get("prewarmBuilt", 0),
                    "honest": honest,
                }
                if honest:
                    bh.stamp(
                        "cold_path",
                        {bh.COLD_Q6_S: e["cold_s"]},
                        backend=report["backend"],
                        higher_is_better=False,
                        meta={"rows": e.get("rows", 0),
                              "prewarmBuilt":
                                  prewarm_info.get("prewarmBuilt", 0),
                              "asyncBuilt":
                                  prewarm_info.get("asyncBuilt", 0),
                              "queryColdCompiles":
                                  comp.get("coldCompiles", 0)},
                        path=history_path)
        except Exception as e:
            report["cold_path_error"] = str(e)[:200]
    # process-telemetry registry snapshot rides the artifact (parity
    # with BENCH/MULTICHIP tails): semaphore/lockdep/sync/recompile/
    # spill/shuffle/HBM numbers for this whole run
    try:
        from spark_rapids_tpu.service.telemetry import compact_snapshot
        report["telemetry"] = compact_snapshot()
    except Exception:
        pass
    # run-level determinism summary (docs/analysis.md §6): static lint
    # verdict over the shipped tree plus the divergence-audit counters
    # for this run — a bench round that tripped the nondeterminism
    # analyzer or desynced mid-run says so in its own artifact
    try:
        import os as _os
        from spark_rapids_tpu.analysis import divergence as _div
        from spark_rapids_tpu.analysis import lint as _lint
        _pkg = _os.path.dirname(_os.path.abspath(_lint.__file__))
        _pkg = _os.path.dirname(_pkg)          # spark_rapids_tpu/
        _viol = _lint.run(_pkg)
        from spark_rapids_tpu.analysis import ledger as _led
        report["analysis"] = {
            "lintViolations": len(_viol),
            "divergence": _div.stats(),
            "ledger": _led.stats(),
        }
        _dv = report["analysis"]["divergence"]
        _lg = report["analysis"]["ledger"]
        print(f"ANALYSIS lint_violations={len(_viol)} "
              f"divergence_mode={_dv['mode']} "
              f"divergence_checks={_dv['checks']} desyncs={_dv['desyncs']} "
              f"ledger_mode={_lg['mode']} audits={_lg['audits']} "
              f"leaks={_lg['leaks']} "
              f"use_after_free={_lg['use_after_free']}")
    except Exception as e:        # the summary must not kill the report
        report["analysis_error"] = str(e)[:200]
    if output:
        with open(output, "w") as f:
            json.dump(report, f, indent=2)
    return report


def _lock_delta(before: Dict, after: Dict) -> Dict:
    """Per-lock wait/hold/acquires growth (moved to
    ``analysis/lockdep.stats_delta`` so query listeners share it)."""
    from spark_rapids_tpu.analysis import lockdep
    return lockdep.stats_delta(before, after)


def _verify(session, df, epsilon: float = 1e-4) -> bool:
    """CPU-engine compare (BenchUtils.compareResults analog)."""
    import math
    from spark_rapids_tpu.cpu.engine import execute as cpu_execute
    cpu = cpu_execute(df._analyzed())
    cpu_rows = sorted((tuple(r) for r in
                       cpu.itertuples(index=False, name=None)), key=repr)
    tpu_rows = sorted(df.collect(), key=repr)
    if len(cpu_rows) != len(tpu_rows):
        return False
    for cr, tr in zip(cpu_rows, tpu_rows):
        for cv, tv in zip(cr, tr):
            if cv is None or tv is None:
                if cv is not tv:
                    return False
                continue
            if isinstance(cv, float) and isinstance(tv, float):
                if math.isnan(cv) != math.isnan(tv):
                    return False
                if not math.isnan(cv) and \
                        abs(cv - tv) > epsilon * max(abs(cv), abs(tv), 1.0):
                    return False
            elif cv != tv:
                return False
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--suite", type=str, default="tpch",
                    choices=("tpch", "tpcds", "tpcxbb"))
    ap.add_argument("--queries", type=str, default=None)
    ap.add_argument("--iterations", type=int, default=2)
    ap.add_argument("--verify", action="store_true")
    ap.add_argument("--output", type=str, default=None)
    ap.add_argument("--concurrent-tasks", type=int, default=None,
                    help="concurrentTpuTasks (default: host cpu count)")
    ap.add_argument("--trace-dir", type=str, default=None,
                    help="directory for per-query Chrome-trace timelines "
                         "(default: <output>.traces when --output is set)")
    ap.add_argument("--probe-timeout", type=float, default=30.0,
                    help="device preflight probe timeout in seconds; on "
                         "failure the run degrades to an explicit "
                         "cpu-degraded backend instead of a zero")
    ap.add_argument("--history", type=str, default=None,
                    help="bench-history JSONL for the regression gate "
                         "(default: benchmarks/reports/bench_history.jsonl)")
    ap.add_argument("--compile-cache-dir", type=str, default=None,
                    help="persistent compile cache directory "
                         "(spark.rapids.tpu.sql.compile.cacheDir): repeat "
                         "runs against the same dir pay zero cold compiles")
    ap.add_argument("--prewarm", action="store_true",
                    help="compile the hottest recorded fused-stage "
                         "signatures on the background pool before the "
                         "query loop (requires --compile-cache-dir with a "
                         "prior run's corpus); stamps cold_q6_s when the "
                         "honesty checks pass")
    args = ap.parse_args()
    report = run_benchmark(args.sf,
                           args.queries.split(",") if args.queries else None,
                           args.iterations, args.verify, args.output,
                           suite=args.suite,
                           concurrent_tasks=args.concurrent_tasks,
                           trace_dir=args.trace_dir,
                           probe_timeout_s=args.probe_timeout,
                           history_path=args.history,
                           compile_cache_dir=args.compile_cache_dir,
                           prewarm=args.prewarm)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
