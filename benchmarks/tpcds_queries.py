"""TPC-DS-like query definitions on the DataFrame API (BASELINE.md
milestone 2's q5 + q97 plus the q3/q42/q52 star-join family).

Analog of the reference's TpcdsLikeSpark.scala query objects
(integration_tests/.../tpcds/). Each query takes the dict of DataFrames
from datagen.register_tpcds_tables and returns a DataFrame.
"""

from __future__ import annotations

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit

from . import datagen

_D0 = datagen._D_DATE_BASE


def _channel_rollup(sales, returns, dim, dim_key, dim_id, pfx, rpfx):
    """One q5 channel: sales UNION ALL returns -> 14-day date window ->
    unit dim join -> per-unit-id totals."""
    s = sales.select(
        col(f"{pfx}_sold_date_sk").alias("date_sk"),
        col(f"{pfx}_unit_sk").alias("unit_sk"),
        col(f"{pfx}_ext_sales_price").alias("sales_price"),
        col(f"{pfx}_net_profit").alias("profit"),
        lit(0.0).alias("return_amt"),
        lit(0.0).alias("net_loss"))
    r = returns.select(
        col(f"{rpfx}_returned_date_sk").alias("date_sk"),
        col(f"{rpfx}_unit_sk").alias("unit_sk"),
        lit(0.0).alias("sales_price"),
        lit(0.0).alias("profit"),
        col(f"{rpfx}_return_amt").alias("return_amt"),
        col(f"{rpfx}_net_loss").alias("net_loss"))
    d = dim.withColumnRenamed(dim_key, "unit_dim_sk")
    window = (col("date_sk") >= lit(_D0 + 60)) & \
        (col("date_sk") <= lit(_D0 + 74))
    return (s.union(r).filter(window)
            .join(d, on=(col("unit_sk") == col("unit_dim_sk")))
            .groupBy(dim_id)
            .agg(F.sum("sales_price").alias("sales"),
                 F.sum("return_amt").alias("returns"),
                 (F.sum("profit") - F.sum("net_loss")).alias("profit")))


def tpcds_q5(t):
    """Rollup of sales/returns/profit across the three channels
    (TpcdsLikeSpark Query5: channel unions -> date window -> dim joins ->
    ROLLUP(channel, id))."""
    ssr = _channel_rollup(t["store_sales"], t["store_returns"], t["store"],
                          "s_store_sk", "s_store_id", "ss", "sr") \
        .select(lit("store channel").alias("channel"),
                F.concat(lit("store"), col("s_store_id")).alias("id"),
                col("sales"), col("returns"), col("profit"))
    csr = _channel_rollup(t["catalog_sales"], t["catalog_returns"],
                          t["catalog_page"], "cp_catalog_page_sk",
                          "cp_catalog_page_id", "cs", "cr") \
        .select(lit("catalog channel").alias("channel"),
                F.concat(lit("catalog_page"),
                         col("cp_catalog_page_id")).alias("id"),
                col("sales"), col("returns"), col("profit"))
    wsr = _channel_rollup(t["web_sales"], t["web_returns"], t["web_site"],
                          "web_site_sk", "web_site_id", "ws", "wr") \
        .select(lit("web channel").alias("channel"),
                F.concat(lit("web_site"), col("web_site_id")).alias("id"),
                col("sales"), col("returns"), col("profit"))
    return (ssr.union(csr).union(wsr)
            .rollup("channel", "id")
            .agg(F.sum("sales").alias("sales"),
                 F.sum("returns").alias("returns"),
                 F.sum("profit").alias("profit"))
            .orderBy(col("channel").asc_nulls_last(),
                     col("id").asc_nulls_last())
            .limit(100))


def tpcds_q97(t):
    """Store/catalog purchase overlap: per-channel distinct
    (customer, item) pairs over a 12-month window, FULL OUTER joined,
    counted by presence (TpcdsLikeSpark Query97)."""
    d = t["date_dim"].filter((col("d_month_seq") >= lit(1190)) &
                             (col("d_month_seq") <= lit(1201)))
    ssci = (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .groupBy("ss_customer_sk", "ss_item_sk").agg(
                F.count("*").alias("_ss_n"))
            .select(col("ss_customer_sk").alias("s_customer_sk"),
                    col("ss_item_sk").alias("s_item_sk")))
    csci = (t["catalog_sales"]
            .join(d, on=(col("cs_sold_date_sk") == col("d_date_sk")))
            .groupBy("cs_customer_sk", "cs_item_sk").agg(
                F.count("*").alias("_cs_n"))
            .select(col("cs_customer_sk").alias("c_customer_sk"),
                    col("cs_item_sk").alias("c_item_sk")))
    both = ssci.join(
        csci,
        on=[col("s_customer_sk") == col("c_customer_sk"),
            col("s_item_sk") == col("c_item_sk")],
        how="full")
    store_only = F.when(col("s_customer_sk").isNotNull() &
                        col("c_customer_sk").isNull(),
                        lit(1)).otherwise(lit(0))
    catalog_only = F.when(col("s_customer_sk").isNull() &
                          col("c_customer_sk").isNotNull(),
                          lit(1)).otherwise(lit(0))
    store_and_catalog = F.when(col("s_customer_sk").isNotNull() &
                               col("c_customer_sk").isNotNull(),
                               lit(1)).otherwise(lit(0))
    return both.agg(F.sum(store_only).alias("store_only"),
                    F.sum(catalog_only).alias("catalog_only"),
                    F.sum(store_and_catalog).alias("store_and_catalog"))


def tpcds_q3(t):
    """Brand revenue for a manufacturer by year/month (TpcdsLikeSpark
    Query3's star-join shape: store_sales x date_dim x item)."""
    d = t["date_dim"].filter(col("d_moy") == lit(11))
    i = t["item"].filter(col("i_manufact_id") == lit(28))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(i, on=(col("ss_item_sk") == col("i_item_sk")))
            .groupBy("d_year", "i_brand_id", "i_brand")
            .agg(F.sum("ss_ext_sales_price").alias("sum_agg"))
            .orderBy(col("d_year").asc(), col("sum_agg").desc(),
                     col("i_brand_id").asc())
            .limit(100))


def tpcds_q42(t):
    """Category revenue for one year+month (Query42)."""
    d = t["date_dim"].filter((col("d_moy") == lit(11)) &
                             (col("d_year") == lit(2000)))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(t["item"],
                  on=(col("ss_item_sk") == col("i_item_sk")))
            .groupBy("d_year", "i_category_id", "i_category")
            .agg(F.sum("ss_ext_sales_price").alias("total"))
            .orderBy(col("total").desc(), col("d_year").asc(),
                     col("i_category_id").asc(), col("i_category").asc())
            .limit(100))


def tpcds_q52(t):
    """Brand revenue for one year+month (Query52 — q3's star-join shape
    with different month/year constants and no manufacturer filter)."""
    d = t["date_dim"].filter((col("d_moy") == lit(12)) &
                             (col("d_year") == lit(1999)))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(t["item"],
                  on=(col("ss_item_sk") == col("i_item_sk")))
            .groupBy("d_year", "i_brand_id", "i_brand")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .orderBy(col("d_year").asc(), col("ext_price").desc(),
                     col("i_brand_id").asc())
            .limit(100))


TPCDS_QUERIES = {"tpcds_q3": tpcds_q3, "tpcds_q5": tpcds_q5,
                 "tpcds_q42": tpcds_q42, "tpcds_q52": tpcds_q52,
                 "tpcds_q97": tpcds_q97}
