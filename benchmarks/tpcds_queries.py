"""TPC-DS-like query definitions on the DataFrame API (BASELINE.md
milestone 2's q5 + q97 plus the q3/q42/q52 star-join family).

Analog of the reference's TpcdsLikeSpark.scala query objects
(integration_tests/.../tpcds/). Each query takes the dict of DataFrames
from datagen.register_tpcds_tables and returns a DataFrame.
"""

from __future__ import annotations

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit

from . import datagen

_D0 = datagen._D_DATE_BASE


def _channel_rollup(sales, returns, dim, dim_key, dim_id, pfx, rpfx):
    """One q5 channel: sales UNION ALL returns -> 14-day date window ->
    unit dim join -> per-unit-id totals."""
    s = sales.select(
        col(f"{pfx}_sold_date_sk").alias("date_sk"),
        col(f"{pfx}_unit_sk").alias("unit_sk"),
        col(f"{pfx}_ext_sales_price").alias("sales_price"),
        col(f"{pfx}_net_profit").alias("profit"),
        lit(0.0).alias("return_amt"),
        lit(0.0).alias("net_loss"))
    r = returns.select(
        col(f"{rpfx}_returned_date_sk").alias("date_sk"),
        col(f"{rpfx}_unit_sk").alias("unit_sk"),
        lit(0.0).alias("sales_price"),
        lit(0.0).alias("profit"),
        col(f"{rpfx}_return_amt").alias("return_amt"),
        col(f"{rpfx}_net_loss").alias("net_loss"))
    d = dim.withColumnRenamed(dim_key, "unit_dim_sk")
    window = (col("date_sk") >= lit(_D0 + 60)) & \
        (col("date_sk") <= lit(_D0 + 74))
    return (s.union(r).filter(window)
            .join(d, on=(col("unit_sk") == col("unit_dim_sk")))
            .groupBy(dim_id)
            .agg(F.sum("sales_price").alias("sales"),
                 F.sum("return_amt").alias("returns"),
                 (F.sum("profit") - F.sum("net_loss")).alias("profit")))


def tpcds_q5(t):
    """Rollup of sales/returns/profit across the three channels
    (TpcdsLikeSpark Query5: channel unions -> date window -> dim joins ->
    ROLLUP(channel, id))."""
    ssr = _channel_rollup(t["store_sales"], t["store_returns"], t["store"],
                          "s_store_sk", "s_store_id", "ss", "sr") \
        .select(lit("store channel").alias("channel"),
                F.concat(lit("store"), col("s_store_id")).alias("id"),
                col("sales"), col("returns"), col("profit"))
    csr = _channel_rollup(t["catalog_sales"], t["catalog_returns"],
                          t["catalog_page"], "cp_catalog_page_sk",
                          "cp_catalog_page_id", "cs", "cr") \
        .select(lit("catalog channel").alias("channel"),
                F.concat(lit("catalog_page"),
                         col("cp_catalog_page_id")).alias("id"),
                col("sales"), col("returns"), col("profit"))
    wsr = _channel_rollup(t["web_sales"], t["web_returns"], t["web_site"],
                          "web_site_sk", "web_site_id", "ws", "wr") \
        .select(lit("web channel").alias("channel"),
                F.concat(lit("web_site"), col("web_site_id")).alias("id"),
                col("sales"), col("returns"), col("profit"))
    return (ssr.union(csr).union(wsr)
            .rollup("channel", "id")
            .agg(F.sum("sales").alias("sales"),
                 F.sum("returns").alias("returns"),
                 F.sum("profit").alias("profit"))
            .orderBy(col("channel").asc_nulls_last(),
                     col("id").asc_nulls_last())
            .limit(100))


def tpcds_q97(t):
    """Store/catalog purchase overlap: per-channel distinct
    (customer, item) pairs over a 12-month window, FULL OUTER joined,
    counted by presence (TpcdsLikeSpark Query97)."""
    d = t["date_dim"].filter((col("d_month_seq") >= lit(1190)) &
                             (col("d_month_seq") <= lit(1201)))
    ssci = (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .groupBy("ss_customer_sk", "ss_item_sk").agg(
                F.count("*").alias("_ss_n"))
            .select(col("ss_customer_sk").alias("s_customer_sk"),
                    col("ss_item_sk").alias("s_item_sk")))
    csci = (t["catalog_sales"]
            .join(d, on=(col("cs_sold_date_sk") == col("d_date_sk")))
            .groupBy("cs_customer_sk", "cs_item_sk").agg(
                F.count("*").alias("_cs_n"))
            .select(col("cs_customer_sk").alias("c_customer_sk"),
                    col("cs_item_sk").alias("c_item_sk")))
    both = ssci.join(
        csci,
        on=[col("s_customer_sk") == col("c_customer_sk"),
            col("s_item_sk") == col("c_item_sk")],
        how="full")
    store_only = F.when(col("s_customer_sk").isNotNull() &
                        col("c_customer_sk").isNull(),
                        lit(1)).otherwise(lit(0))
    catalog_only = F.when(col("s_customer_sk").isNull() &
                          col("c_customer_sk").isNotNull(),
                          lit(1)).otherwise(lit(0))
    store_and_catalog = F.when(col("s_customer_sk").isNotNull() &
                               col("c_customer_sk").isNotNull(),
                               lit(1)).otherwise(lit(0))
    return both.agg(F.sum(store_only).alias("store_only"),
                    F.sum(catalog_only).alias("catalog_only"),
                    F.sum(store_and_catalog).alias("store_and_catalog"))


def tpcds_q3(t):
    """Brand revenue for a manufacturer by year/month (TpcdsLikeSpark
    Query3's star-join shape: store_sales x date_dim x item)."""
    d = t["date_dim"].filter(col("d_moy") == lit(11))
    i = t["item"].filter(col("i_manufact_id") == lit(28))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(i, on=(col("ss_item_sk") == col("i_item_sk")))
            .groupBy("d_year", "i_brand_id", "i_brand")
            .agg(F.sum("ss_ext_sales_price").alias("sum_agg"))
            .orderBy(col("d_year").asc(), col("sum_agg").desc(),
                     col("i_brand_id").asc())
            .limit(100))


def tpcds_q42(t):
    """Category revenue for one year+month (Query42)."""
    d = t["date_dim"].filter((col("d_moy") == lit(11)) &
                             (col("d_year") == lit(2000)))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(t["item"],
                  on=(col("ss_item_sk") == col("i_item_sk")))
            .groupBy("d_year", "i_category_id", "i_category")
            .agg(F.sum("ss_ext_sales_price").alias("total"))
            .orderBy(col("total").desc(), col("d_year").asc(),
                     col("i_category_id").asc(), col("i_category").asc())
            .limit(100))


def tpcds_q52(t):
    """Brand revenue for one year+month (Query52 — q3's star-join shape
    with different month/year constants and no manufacturer filter)."""
    d = t["date_dim"].filter((col("d_moy") == lit(12)) &
                             (col("d_year") == lit(1999)))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(t["item"],
                  on=(col("ss_item_sk") == col("i_item_sk")))
            .groupBy("d_year", "i_brand_id", "i_brand")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .orderBy(col("d_year").asc(), col("ext_price").desc(),
                     col("i_brand_id").asc())
            .limit(100))


def tpcds_q7(t):
    """Demographic-filtered item averages (TpcdsLikeSpark Query7:
    ss x customer_demographics x date x item x promotion)."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == lit("M")) &
        (col("cd_marital_status") == lit("S")) &
        (col("cd_education_status") == lit("College")))
    d = t["date_dim"].filter(col("d_year") == lit(2000))
    p = t["promotion"].filter((col("p_channel_email") == lit("N")) |
                              (col("p_channel_event") == lit("N")))
    return (t["store_sales"]
            .join(cd, on=(col("ss_cdemo_sk") == col("cd_demo_sk")))
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(t["item"], on=(col("ss_item_sk") == col("i_item_sk")))
            .join(p, on=(col("ss_promo_sk") == col("p_promo_sk")))
            .groupBy("i_item_id")
            .agg(F.avg("ss_quantity").alias("agg1"),
                 F.avg("ss_list_price").alias("agg2"),
                 F.avg("ss_coupon_amt").alias("agg3"),
                 F.avg("ss_sales_price").alias("agg4"))
            .orderBy(col("i_item_id").asc())
            .limit(100))


def _channel_revenue_ratio(sales, t, pfx):
    """q12/q20/q98 shared shape: category-filtered item revenue over a
    30-day window with a per-class revenue-ratio WINDOW function."""
    from spark_rapids_tpu.api.window import Window
    d = t["date_dim"].filter(
        (col("d_date_sk") >= lit(_D0 + 45)) &
        (col("d_date_sk") <= lit(_D0 + 75)))
    i = t["item"].filter(col("i_category").isin("Books", "Home", "Sports"))
    per_item = (sales
                .join(d, on=(col(f"{pfx}_sold_date_sk") == col("d_date_sk")))
                .join(i, on=(col(f"{pfx}_item_sk") == col("i_item_sk")))
                .groupBy("i_item_id", "i_category", "i_class",
                         "i_current_price")
                .agg(F.sum(f"{pfx}_ext_sales_price").alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (per_item
            .select(col("i_item_id"), col("i_category"), col("i_class"),
                    col("i_current_price"), col("itemrevenue"),
                    (col("itemrevenue") * 100 /
                     F.sum("itemrevenue").over(w)).alias("revenueratio"))
            .orderBy(col("i_category").asc(), col("i_class").asc(),
                     col("i_item_id").asc(), col("i_current_price").asc(),
                     col("revenueratio").asc())
            .limit(100))


def tpcds_q12(t):
    """Web revenue ratio by class (TpcdsLikeSpark Query12)."""
    return _channel_revenue_ratio(t["web_sales"], t, "ws")


def tpcds_q20(t):
    """Catalog revenue ratio by class (TpcdsLikeSpark Query20)."""
    return _channel_revenue_ratio(t["catalog_sales"], t, "cs")


def tpcds_q98(t):
    """Store revenue ratio by class (TpcdsLikeSpark Query98)."""
    return _channel_revenue_ratio(t["store_sales"], t, "ss")


def tpcds_q15(t):
    """Catalog sales by zip with OR'd geography/price predicates
    (TpcdsLikeSpark Query15)."""
    d = t["date_dim"].filter((col("d_qoy") == lit(1)) &
                             (col("d_year") == lit(2000)))
    return (t["catalog_sales"]
            .join(t["customer"],
                  on=(col("cs_customer_sk") == col("c_customer_sk")))
            .join(t["customer_address"],
                  on=(col("c_current_addr_sk") == col("ca_address_sk")))
            .join(d, on=(col("cs_sold_date_sk") == col("d_date_sk")))
            .filter(F.substring(col("ca_zip"), 1, 2).isin("80", "85", "86")
                    | col("ca_state").isin("CA", "GA", "TX")
                    | (col("cs_sales_price") > lit(250)))
            .groupBy("ca_zip")
            .agg(F.sum("cs_sales_price").alias("total"))
            .orderBy(col("ca_zip").asc())
            .limit(100))


def tpcds_q19(t):
    """Brand revenue from out-of-state baskets (TpcdsLikeSpark Query19:
    ss x date x item x customer x customer_address x store with the
    customer-state != store-state twist)."""
    d = t["date_dim"].filter((col("d_moy") == lit(11)) &
                             (col("d_year") == lit(1999)))
    i = t["item"].filter(col("i_manager_id") == lit(7))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(i, on=(col("ss_item_sk") == col("i_item_sk")))
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .join(t["customer_address"],
                  on=(col("c_current_addr_sk") == col("ca_address_sk")))
            .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk")))
            .filter(col("ca_state") != col("s_state"))
            .groupBy("i_brand_id", "i_brand", "i_manufact_id", "i_manufact")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .orderBy(col("ext_price").desc(), col("i_brand_id").asc(),
                     col("i_manufact_id").asc())
            .limit(100))


def tpcds_q26(t):
    """Catalog analog of q7 (TpcdsLikeSpark Query26)."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == lit("F")) &
        (col("cd_marital_status") == lit("W")) &
        (col("cd_education_status") == lit("Secondary")))
    d = t["date_dim"].filter(col("d_year") == lit(2000))
    p = t["promotion"].filter((col("p_channel_email") == lit("N")) |
                              (col("p_channel_event") == lit("N")))
    return (t["catalog_sales"]
            .join(cd, on=(col("cs_cdemo_sk") == col("cd_demo_sk")))
            .join(d, on=(col("cs_sold_date_sk") == col("d_date_sk")))
            .join(t["item"], on=(col("cs_item_sk") == col("i_item_sk")))
            .join(p, on=(col("cs_promo_sk") == col("p_promo_sk")))
            .groupBy("i_item_id")
            .agg(F.avg("cs_quantity").alias("agg1"),
                 F.avg("cs_list_price").alias("agg2"),
                 F.avg("cs_coupon_amt").alias("agg3"),
                 F.avg("cs_sales_price").alias("agg4"))
            .orderBy(col("i_item_id").asc())
            .limit(100))


def tpcds_q33(t):
    """Manufacturer revenue across all three channels for one month and
    timezone (TpcdsLikeSpark Query33: per-channel star joins with a
    manufacturer list drawn from one category, UNION ALL, re-aggregate)."""
    manuf = (t["item"].filter(col("i_category") == lit("Electronics"))
             .select(col("i_manufact_id").alias("m_id")).distinct())

    def channel(sales, pfx):
        d = t["date_dim"].filter((col("d_year") == lit(2000)) &
                                 (col("d_moy") == lit(1)))
        ca = t["customer_address"].filter(col("ca_gmt_offset") == lit(-5))
        return (sales
                .join(d, on=(col(f"{pfx}_sold_date_sk") == col("d_date_sk")))
                .join(ca, on=(col(f"{pfx}_addr_sk") == col("ca_address_sk")))
                .join(t["item"],
                      on=(col(f"{pfx}_item_sk") == col("i_item_sk")))
                .join(manuf, on=(col("i_manufact_id") == col("m_id")),
                      how="left_semi")
                .groupBy("i_manufact_id")
                .agg(F.sum(f"{pfx}_ext_sales_price").alias("total_sales")))
    u = (channel(t["store_sales"], "ss")
         .union(channel(t["catalog_sales"], "cs"))
         .union(channel(t["web_sales"], "ws")))
    return (u.groupBy("i_manufact_id")
            .agg(F.sum("total_sales").alias("total_sales"))
            .orderBy(col("total_sales").desc(), col("i_manufact_id").asc())
            .limit(100))


def tpcds_q43(t):
    """Day-of-week sales pivot per store (TpcdsLikeSpark Query43: CASE
    sums over d_dow)."""
    d = t["date_dim"].filter(col("d_year") == lit(2000))
    j = (t["store_sales"]
         .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
         .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk"))))

    def dow(n):
        return F.sum(F.when(col("d_dow") == lit(n),
                            col("ss_sales_price")).otherwise(lit(0.0)))
    return (j.groupBy("s_store_id")
            .agg(dow(0).alias("sun_sales"), dow(1).alias("mon_sales"),
                 dow(2).alias("tue_sales"), dow(3).alias("wed_sales"),
                 dow(4).alias("thu_sales"), dow(5).alias("fri_sales"),
                 dow(6).alias("sat_sales"))
            .orderBy(col("s_store_id").asc())
            .limit(100))


def tpcds_q45(t):
    """Web sales by zip/city with an OR'd zip-prefix / item-list predicate
    (TpcdsLikeSpark Query45)."""
    d = t["date_dim"].filter((col("d_qoy") == lit(2)) &
                             (col("d_year") == lit(2000)))
    return (t["web_sales"]
            .join(t["customer"],
                  on=(col("ws_customer_sk") == col("c_customer_sk")))
            .join(t["customer_address"],
                  on=(col("c_current_addr_sk") == col("ca_address_sk")))
            .join(d, on=(col("ws_sold_date_sk") == col("d_date_sk")))
            .join(t["item"], on=(col("ws_item_sk") == col("i_item_sk")))
            .filter(F.substring(col("ca_zip"), 1, 2)
                    .isin("85", "86", "88") |
                    col("i_item_sk").isin(2, 3, 5, 7, 11, 13, 17, 19, 23,
                                          29))
            .groupBy("ca_zip", "ca_city")
            .agg(F.sum("ws_sales_price").alias("total"))
            .orderBy(col("ca_zip").asc(), col("ca_city").asc())
            .limit(100))


def tpcds_q48(t):
    """Quantity sum under OR'd demographic/price and state/profit bands
    (TpcdsLikeSpark Query48)."""
    d = t["date_dim"].filter(col("d_year") == lit(2000))
    demo_band = (
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("4 yr Degree")) &
         (col("ss_sales_price") >= lit(100)) &
         (col("ss_sales_price") <= lit(150))) |
        ((col("cd_marital_status") == lit("D")) &
         (col("cd_education_status") == lit("2 yr Degree")) &
         (col("ss_sales_price") >= lit(50)) &
         (col("ss_sales_price") <= lit(100))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("College")) &
         (col("ss_sales_price") >= lit(150)) &
         (col("ss_sales_price") <= lit(200))))
    geo_band = (
        (col("ca_state").isin("CO", "OH", "TX") &
         (col("ss_net_profit") >= lit(0)) &
         (col("ss_net_profit") <= lit(2000))) |
        (col("ca_state").isin("OR", "MN", "KY") &
         (col("ss_net_profit") >= lit(150)) &
         (col("ss_net_profit") <= lit(3000))) |
        (col("ca_state").isin("VA", "CA", "MS") &
         (col("ss_net_profit") >= lit(50)) &
         (col("ss_net_profit") <= lit(25000))))
    return (t["store_sales"]
            .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk")))
            .join(t["customer_demographics"],
                  on=(col("ss_cdemo_sk") == col("cd_demo_sk")))
            .join(t["customer_address"],
                  on=(col("ss_addr_sk") == col("ca_address_sk")))
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .filter(demo_band & geo_band)
            .agg(F.sum("ss_quantity").alias("total_quantity")))


def tpcds_q55(t):
    """Manager's brand revenue for one month (TpcdsLikeSpark Query55)."""
    d = t["date_dim"].filter((col("d_moy") == lit(11)) &
                             (col("d_year") == lit(1999)))
    i = t["item"].filter(col("i_manager_id") == lit(28))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(i, on=(col("ss_item_sk") == col("i_item_sk")))
            .groupBy("i_brand_id", "i_brand")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .orderBy(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100))


def tpcds_q61(t):
    """Promotional-to-total revenue ratio for one month/category/timezone
    (TpcdsLikeSpark Query61: two scalar aggregates cross-joined)."""
    d = t["date_dim"].filter((col("d_year") == lit(1998)) &
                             (col("d_moy") == lit(11)))
    i = t["item"].filter(col("i_category") == lit("Jewelry"))
    ca = t["customer_address"].filter(col("ca_gmt_offset") == lit(-5))
    base = (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(i, on=(col("ss_item_sk") == col("i_item_sk")))
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .join(ca, on=(col("c_current_addr_sk") == col("ca_address_sk"))))
    promo = t["promotion"].filter((col("p_channel_email") == lit("Y")) |
                                  (col("p_channel_event") == lit("Y")))
    promotions = (base
                  .join(promo, on=(col("ss_promo_sk") == col("p_promo_sk")))
                  .agg(F.sum("ss_ext_sales_price").alias("promotions")))
    total = base.agg(F.sum("ss_ext_sales_price").alias("total"))
    return (promotions.crossJoin(total)
            .select(col("promotions"), col("total"),
                    (col("promotions") / col("total") * 100)
                    .alias("promo_pct")))


def tpcds_q65(t):
    """Underperforming store/item pairs: per-pair revenue at most 10% of
    the store's average (TpcdsLikeSpark Query65: two aggregation levels
    joined)."""
    sa = (t["store_sales"]
          .groupBy("ss_unit_sk", "ss_item_sk")
          .agg(F.sum("ss_sales_price").alias("revenue")))
    sb = (sa.groupBy("ss_unit_sk")
          .agg(F.avg("revenue").alias("ave"))
          .withColumnRenamed("ss_unit_sk", "sb_unit_sk"))
    return (sa.join(sb, on=(col("ss_unit_sk") == col("sb_unit_sk")))
            .filter(col("revenue") <= col("ave") * 0.1)
            .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk")))
            .join(t["item"], on=(col("ss_item_sk") == col("i_item_sk")))
            .select(col("s_store_id"), col("i_item_id"), col("revenue"),
                    col("ave"))
            .orderBy(col("s_store_id").asc(), col("i_item_id").asc())
            .limit(100))


def tpcds_q68(t):
    """Per-basket extended totals where the purchase city differs from the
    customer's current city (TpcdsLikeSpark Query68: two
    customer_address roles in one query)."""
    d = t["date_dim"].filter((col("d_dom") >= lit(1)) &
                             (col("d_dom") <= lit(2)) &
                             col("d_year").isin(1998, 1999, 2000))
    s = t["store"].filter(col("s_city").isin("Fairview", "Midway"))
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == lit(4)) | (col("hd_vehicle_count") == lit(3)))
    bought = t["customer_address"].select(
        col("ca_address_sk").alias("b_addr_sk"),
        col("ca_city").alias("bought_city"))
    current = t["customer_address"].select(
        col("ca_address_sk").alias("cur_addr_sk"),
        col("ca_city").alias("current_city"))
    baskets = (t["store_sales"]
               .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
               .join(s, on=(col("ss_unit_sk") == col("s_store_sk")))
               .join(hd, on=(col("ss_hdemo_sk") == col("hd_demo_sk")))
               .join(bought, on=(col("ss_addr_sk") == col("b_addr_sk")))
               .groupBy("ss_order_number", "ss_customer_sk", "bought_city")
               .agg(F.sum("ss_coupon_amt").alias("amt"),
                    F.sum("ss_net_profit").alias("profit")))
    return (baskets
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .join(current,
                  on=(col("c_current_addr_sk") == col("cur_addr_sk")))
            .filter(col("current_city") != col("bought_city"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("current_city"), col("bought_city"),
                    col("ss_order_number"), col("amt"), col("profit"))
            .orderBy(col("c_last_name").asc(), col("ss_order_number").asc(),
                     col("c_first_name").asc(), col("current_city").asc(),
                     col("bought_city").asc(), col("amt").asc())
            .limit(100))


def tpcds_q73(t):
    """Customers with 1-5 item baskets under household filters
    (TpcdsLikeSpark Query73: per-basket count HAVING band)."""
    d = t["date_dim"].filter((col("d_dom") >= lit(1)) &
                             (col("d_dom") <= lit(2)) &
                             col("d_year").isin(1998, 1999, 2000))
    hd = t["household_demographics"].filter(
        col("hd_buy_potential").isin(">10000", "Unknown") &
        (col("hd_vehicle_count") > lit(0)))
    baskets = (t["store_sales"]
               .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
               .join(hd, on=(col("ss_hdemo_sk") == col("hd_demo_sk")))
               .groupBy("ss_order_number", "ss_customer_sk")
               .agg(F.count("*").alias("cnt"))
               .filter((col("cnt") >= lit(1)) & (col("cnt") <= lit(5))))
    return (baskets
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .select(col("c_last_name"), col("c_first_name"),
                    col("c_preferred_cust_flag"), col("ss_order_number"),
                    col("cnt"))
            .orderBy(col("cnt").desc(), col("c_last_name").asc(),
                     col("ss_order_number").asc(), col("c_first_name").asc(),
                     col("c_preferred_cust_flag").asc())
            .limit(100))


def tpcds_q79(t):
    """Monday-shopper basket profits at mid-size stores (TpcdsLikeSpark
    Query79)."""
    d = t["date_dim"].filter((col("d_dow") == lit(1)) &
                             col("d_year").isin(1998, 1999, 2000))
    s = t["store"].filter((col("s_number_employees") >= lit(200)) &
                          (col("s_number_employees") <= lit(295)))
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == lit(6)) | (col("hd_vehicle_count") > lit(2)))
    baskets = (t["store_sales"]
               .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
               .join(s, on=(col("ss_unit_sk") == col("s_store_sk")))
               .join(hd, on=(col("ss_hdemo_sk") == col("hd_demo_sk")))
               .groupBy("ss_order_number", "ss_customer_sk", "s_city")
               .agg(F.sum("ss_coupon_amt").alias("amt"),
                    F.sum("ss_net_profit").alias("profit")))
    return (baskets
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .select(col("c_last_name"), col("c_first_name"), col("s_city"),
                    col("ss_order_number"), col("amt"), col("profit"))
            .orderBy(col("c_last_name").asc(), col("c_first_name").asc(),
                     col("ss_order_number").asc(), col("s_city").asc(),
                     col("amt").asc())
            .limit(100))


def tpcds_q88(t):
    """Store-traffic counts in four time bands cross-joined into one row
    (TpcdsLikeSpark Query88's scalar-count matrix, 4 of the 8 bands)."""
    hd = t["household_demographics"].filter(
        ((col("hd_dep_count") == lit(4)) &
         (col("hd_vehicle_count") <= lit(3))) |
        ((col("hd_dep_count") == lit(2)) &
         (col("hd_vehicle_count") <= lit(1))))
    base = (t["store_sales"]
            .join(hd, on=(col("ss_hdemo_sk") == col("hd_demo_sk")))
            .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk"))))

    def band(h1, name):
        td = t["time_dim"].filter((col("t_hour") == lit(h1)))
        return (base.join(td, on=(col("ss_sold_time_sk") == col("t_time_sk")))
                .agg(F.count("*").alias(name)))
    return (band(8, "h8").crossJoin(band(9, "h9"))
            .crossJoin(band(10, "h10")).crossJoin(band(11, "h11")))


def tpcds_q96(t):
    """Single-band store-traffic count (TpcdsLikeSpark Query96)."""
    hd = t["household_demographics"].filter(col("hd_dep_count") == lit(3))
    td = t["time_dim"].filter((col("t_hour") == lit(20)) &
                              (col("t_minute") >= lit(30)))
    return (t["store_sales"]
            .join(hd, on=(col("ss_hdemo_sk") == col("hd_demo_sk")))
            .join(td, on=(col("ss_sold_time_sk") == col("t_time_sk")))
            .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk")))
            .agg(F.count("*").alias("cnt")))


def tpcds_q6(t):
    """States with many buyers of premium-priced items in one month
    (TpcdsLikeSpark Query6: category-average price subquery joined
    back)."""
    cat_avg = (t["item"]
               .groupBy("i_category")
               .agg((F.avg("i_current_price") * 1.2).alias("price_bar"))
               .withColumnRenamed("i_category", "avg_cat"))
    prem = (t["item"]
            .join(cat_avg, on=(col("i_category") == col("avg_cat")))
            .filter(col("i_current_price") > col("price_bar"))
            .select(col("i_item_sk").alias("prem_item")))
    d = t["date_dim"].filter((col("d_year") == lit(2000)) &
                             (col("d_moy") == lit(1)))
    return (t["store_sales"]
            .join(prem, on=(col("ss_item_sk") == col("prem_item")),
                  how="left_semi")
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .join(t["customer_address"],
                  on=(col("c_current_addr_sk") == col("ca_address_sk")))
            .groupBy("ca_state")
            .agg(F.count("*").alias("cnt"))
            .filter(col("cnt") >= lit(10))
            .orderBy(col("cnt").asc(), col("ca_state").asc())
            .limit(100))


def tpcds_q13(t):
    """Single-row averages under OR'd demographic/geography bands
    (TpcdsLikeSpark Query13)."""
    d = t["date_dim"].filter(col("d_year") == lit(2001))
    demo = (
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("Advanced Degree")) &
         (col("ss_sales_price") >= lit(100)) &
         (col("ss_sales_price") <= lit(150)) &
         (col("hd_dep_count") == lit(3))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("College")) &
         (col("ss_sales_price") >= lit(50)) &
         (col("ss_sales_price") <= lit(100)) &
         (col("hd_dep_count") == lit(1))) |
        ((col("cd_marital_status") == lit("W")) &
         (col("cd_education_status") == lit("2 yr Degree")) &
         (col("ss_sales_price") >= lit(150)) &
         (col("ss_sales_price") <= lit(200)) &
         (col("hd_dep_count") == lit(1))))
    geo = (
        (col("ca_state").isin("TX", "OH", "MI") &
         (col("ss_net_profit") >= lit(100)) &
         (col("ss_net_profit") <= lit(200))) |
        (col("ca_state").isin("OR", "MN", "KS") &
         (col("ss_net_profit") >= lit(150)) &
         (col("ss_net_profit") <= lit(300))) |
        (col("ca_state").isin("VA", "CA", "MS") &
         (col("ss_net_profit") >= lit(50)) &
         (col("ss_net_profit") <= lit(250))))
    return (t["store_sales"]
            .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk")))
            .join(t["customer_demographics"],
                  on=(col("ss_cdemo_sk") == col("cd_demo_sk")))
            .join(t["household_demographics"],
                  on=(col("ss_hdemo_sk") == col("hd_demo_sk")))
            .join(t["customer_address"],
                  on=(col("ss_addr_sk") == col("ca_address_sk")))
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .filter(demo & geo)
            .agg(F.avg("ss_quantity").alias("avg_qty"),
                 F.avg("ss_ext_sales_price").alias("avg_price"),
                 F.avg("ss_wholesale_cost").alias("avg_cost"),
                 F.sum("ss_wholesale_cost").alias("sum_cost")))


def _sales_returns_catalog_chain(t, agg_cols):
    """q25/q29 shared shape: store sale -> its return (same basket/item)
    -> a catalog re-purchase by the same customer of the same item."""
    ss = t["store_sales"]
    sr = t["store_returns"]
    cs = t["catalog_sales"]
    j = (ss.join(sr, on=[col("ss_order_number") == col("sr_order_number"),
                         col("ss_item_sk") == col("sr_item_sk")])
         .join(cs, on=[col("sr_customer_sk") == col("cs_customer_sk"),
                       col("sr_item_sk") == col("cs_item_sk")])
         .join(t["item"], on=(col("ss_item_sk") == col("i_item_sk")))
         .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk"))))
    return (j.groupBy("i_item_id", "i_brand", "s_store_id")
            .agg(*agg_cols)
            .orderBy(col("i_item_id").asc(), col("i_brand").asc(),
                     col("s_store_id").asc())
            .limit(100))


def tpcds_q25(t):
    """Profit across the sale->return->catalog-repurchase chain
    (TpcdsLikeSpark Query25)."""
    return _sales_returns_catalog_chain(t, [
        F.sum("ss_net_profit").alias("store_profit"),
        F.sum("sr_net_loss").alias("return_loss"),
        F.sum("cs_net_profit").alias("catalog_profit")])


def tpcds_q29(t):
    """Quantities across the sale->return->catalog-repurchase chain
    (TpcdsLikeSpark Query29)."""
    return _sales_returns_catalog_chain(t, [
        F.sum("ss_quantity").alias("store_qty"),
        F.sum("sr_return_quantity").alias("return_qty"),
        F.sum("cs_quantity").alias("catalog_qty")])


def tpcds_q27(t):
    """Demographic item averages rolled up over states (TpcdsLikeSpark
    Query27: the q7 shape + ROLLUP(i_item_id, s_state))."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == lit("F")) &
        (col("cd_marital_status") == lit("D")) &
        (col("cd_education_status") == lit("Primary")))
    d = t["date_dim"].filter(col("d_year") == lit(1999))
    s = t["store"].filter(col("s_state").isin("CA", "TX", "NY", "OH"))
    return (t["store_sales"]
            .join(cd, on=(col("ss_cdemo_sk") == col("cd_demo_sk")))
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(s, on=(col("ss_unit_sk") == col("s_store_sk")))
            .join(t["item"], on=(col("ss_item_sk") == col("i_item_sk")))
            .rollup("i_item_id", "s_state")
            .agg(F.avg("ss_quantity").alias("agg1"),
                 F.avg("ss_list_price").alias("agg2"),
                 F.avg("ss_coupon_amt").alias("agg3"),
                 F.avg("ss_sales_price").alias("agg4"))
            .orderBy(col("i_item_id").asc_nulls_last(),
                     col("s_state").asc_nulls_last())
            .limit(100))


def tpcds_q34(t):
    """Mid-size baskets at month edges under buy-potential filters
    (TpcdsLikeSpark Query34; count band adapted to the generator's
    ~4-line baskets)."""
    d = t["date_dim"].filter(
        ((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(3)) |
         (col("d_dom") >= lit(25)) & (col("d_dom") <= lit(28))) &
        col("d_year").isin(1998, 1999, 2000))
    hd = t["household_demographics"].filter(
        col("hd_buy_potential").isin(">10000", "Unknown") &
        (col("hd_vehicle_count") > lit(0)))
    baskets = (t["store_sales"]
               .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
               .join(hd, on=(col("ss_hdemo_sk") == col("hd_demo_sk")))
               .groupBy("ss_order_number", "ss_customer_sk")
               .agg(F.count("*").alias("cnt"))
               .filter((col("cnt") >= lit(2)) & (col("cnt") <= lit(5))))
    return (baskets
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .select(col("c_last_name"), col("c_first_name"),
                    col("c_preferred_cust_flag"), col("ss_order_number"),
                    col("cnt"))
            .orderBy(col("c_last_name").asc(), col("c_first_name").asc(),
                     col("c_preferred_cust_flag").asc(),
                     col("ss_order_number").asc(), col("cnt").asc())
            .limit(100))


def tpcds_q36(t):
    """Gross-margin rollup over category/class (TpcdsLikeSpark
    Query36)."""
    d = t["date_dim"].filter(col("d_year") == lit(2000))
    s = t["store"].filter(col("s_state").isin("CA", "TX", "NY", "OH",
                                              "FL", "IL", "GA", "MI"))
    return (t["store_sales"]
            .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
            .join(t["item"], on=(col("ss_item_sk") == col("i_item_sk")))
            .join(s, on=(col("ss_unit_sk") == col("s_store_sk")))
            .rollup("i_category", "i_class")
            .agg((F.sum("ss_net_profit") /
                  F.sum("ss_ext_sales_price")).alias("gross_margin"))
            .orderBy(col("i_category").asc_nulls_last(),
                     col("i_class").asc_nulls_last(),
                     col("gross_margin").asc())
            .limit(100))


def tpcds_q46(t):
    """Weekend baskets in selected cities where the bought city differs
    from the customer's (TpcdsLikeSpark Query46: the q68 shape with
    day-of-week + city filters)."""
    d = t["date_dim"].filter(col("d_dow").isin(6, 0) &
                             col("d_year").isin(1998, 1999, 2000))
    s = t["store"].filter(col("s_city").isin("Fairview", "Midway",
                                             "Salem", "Union"))
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == lit(4)) | (col("hd_vehicle_count") == lit(3)))
    bought = t["customer_address"].select(
        col("ca_address_sk").alias("b_addr_sk"),
        col("ca_city").alias("bought_city"))
    current = t["customer_address"].select(
        col("ca_address_sk").alias("cur_addr_sk"),
        col("ca_city").alias("current_city"))
    baskets = (t["store_sales"]
               .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
               .join(s, on=(col("ss_unit_sk") == col("s_store_sk")))
               .join(hd, on=(col("ss_hdemo_sk") == col("hd_demo_sk")))
               .join(bought, on=(col("ss_addr_sk") == col("b_addr_sk")))
               .groupBy("ss_order_number", "ss_customer_sk", "bought_city")
               .agg(F.sum("ss_coupon_amt").alias("amt"),
                    F.sum("ss_net_profit").alias("profit")))
    return (baskets
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .join(current,
                  on=(col("c_current_addr_sk") == col("cur_addr_sk")))
            .filter(col("current_city") != col("bought_city"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("current_city"), col("bought_city"),
                    col("ss_order_number"), col("amt"), col("profit"))
            .orderBy(col("c_last_name").asc(), col("c_first_name").asc(),
                     col("ss_order_number").asc(), col("bought_city").asc(),
                     col("amt").asc())
            .limit(100))


def tpcds_q50(t):
    """Return-latency bands per store (TpcdsLikeSpark Query50: CASE sums
    over sold->returned day gaps)."""
    j = (t["store_sales"]
         .join(t["store_returns"],
               on=[col("ss_order_number") == col("sr_order_number"),
                   col("ss_item_sk") == col("sr_item_sk"),
                   col("ss_customer_sk") == col("sr_customer_sk")])
         .join(t["store"], on=(col("ss_unit_sk") == col("s_store_sk"))))
    gap = col("sr_returned_date_sk") - col("ss_sold_date_sk")

    def band(cond, name):
        return F.sum(F.when(cond, lit(1)).otherwise(lit(0))).alias(name)
    return (j.groupBy("s_store_id", "s_city", "s_state")
            .agg(band(gap <= lit(30), "d30"),
                 band((gap > lit(30)) & (gap <= lit(60)), "d60"),
                 band((gap > lit(60)) & (gap <= lit(90)), "d90"),
                 band((gap > lit(90)) & (gap <= lit(120)), "d120"),
                 band(gap > lit(120), "dmore"))
            .orderBy(col("s_store_id").asc())
            .limit(100))


def tpcds_q71(t):
    """Brand revenue by hour across the three channels for one month
    (TpcdsLikeSpark Query71: time_dim union star)."""
    d = t["date_dim"].filter((col("d_moy") == lit(11)) &
                             (col("d_year") == lit(1999)))
    i = t["item"].filter(col("i_manager_id") == lit(1))
    td = t["time_dim"].filter(col("t_hour").isin(8, 9, 17, 18))

    def channel(sales, pfx):
        return (sales
                .join(d, on=(col(f"{pfx}_sold_date_sk") == col("d_date_sk")))
                .select(col(f"{pfx}_item_sk").alias("sold_item_sk"),
                        col(f"{pfx}_ext_sales_price").alias("ext_price"),
                        col(f"{pfx}_sold_time_sk").alias("time_sk")))
    u = (channel(t["web_sales"], "ws")
         .union(channel(t["catalog_sales"], "cs"))
         .union(channel(t["store_sales"], "ss")))
    return (u.join(i, on=(col("sold_item_sk") == col("i_item_sk")))
            .join(td, on=(col("time_sk") == col("t_time_sk")))
            .groupBy("i_brand_id", "i_brand", "t_hour", "t_minute")
            .agg(F.sum("ext_price").alias("ext_price"))
            .orderBy(col("ext_price").desc(), col("i_brand_id").asc(),
                     col("t_hour").asc(), col("t_minute").asc())
            .limit(100))


def tpcds_q76(t):
    """Channel/category/year counts and sums over a three-channel union
    (TpcdsLikeSpark Query76's union-report shape; the generator has no
    NULL fk columns, so the filter keys off promo channels instead)."""
    def channel(sales, pfx, name):
        d = t["date_dim"]
        p = t["promotion"].filter(col("p_channel_email") == lit("N"))
        return (sales
                .join(p, on=(col(f"{pfx}_promo_sk") == col("p_promo_sk")),
                      how="left_semi")
                .join(d, on=(col(f"{pfx}_sold_date_sk") == col("d_date_sk")))
                .join(t["item"],
                      on=(col(f"{pfx}_item_sk") == col("i_item_sk")))
                .select(lit(name).alias("channel"), col("d_year"),
                        col("d_qoy"), col("i_category"),
                        col(f"{pfx}_ext_sales_price").alias("ext_price")))
    u = (channel(t["store_sales"], "ss", "store")
         .union(channel(t["web_sales"], "ws", "web"))
         .union(channel(t["catalog_sales"], "cs", "catalog")))
    return (u.groupBy("channel", "d_year", "d_qoy", "i_category")
            .agg(F.count("*").alias("sales_cnt"),
                 F.sum("ext_price").alias("sales_amt"))
            .orderBy(col("channel").asc(), col("d_year").asc(),
                     col("d_qoy").asc(), col("i_category").asc())
            .limit(100))


def tpcds_q89(t):
    """Monthly class sales vs the store/category average: a windowed
    deviation report (TpcdsLikeSpark Query89 — avg OVER (PARTITION BY
    category, brand, store))."""
    from spark_rapids_tpu.api.window import Window
    d = t["date_dim"].filter(col("d_year") == lit(1999))
    i = t["item"].filter(col("i_category").isin("Books", "Electronics",
                                                "Sports"))
    monthly = (t["store_sales"]
               .join(d, on=(col("ss_sold_date_sk") == col("d_date_sk")))
               .join(i, on=(col("ss_item_sk") == col("i_item_sk")))
               .join(t["store"],
                     on=(col("ss_unit_sk") == col("s_store_sk")))
               .groupBy("i_category", "i_class", "i_brand", "s_store_id",
                        "d_moy")
               .agg(F.sum("ss_sales_price").alias("sum_sales")))
    w = Window.partitionBy("i_category", "i_brand", "s_store_id")
    out = monthly.select(
        col("i_category"), col("i_class"), col("i_brand"),
        col("s_store_id"), col("d_moy"), col("sum_sales"),
        F.avg("sum_sales").over(w).alias("avg_monthly_sales"))
    dev = (col("sum_sales") - col("avg_monthly_sales"))
    return (out.filter((dev > col("avg_monthly_sales") * 0.1) |
                       (dev < col("avg_monthly_sales") * -0.1))
            .orderBy(col("i_category").asc(), col("i_class").asc(),
                     col("i_brand").asc(), col("s_store_id").asc(),
                     col("d_moy").asc())
            .limit(100))


def tpcds_q90(t):
    """AM/PM web-sales ratio under dependent-count filters
    (TpcdsLikeSpark Query90: two scalar counts cross-joined)."""
    hd = t["household_demographics"].filter(col("hd_dep_count") == lit(6))

    def half(h_lo, h_hi, name):
        td = t["time_dim"].filter((col("t_hour") >= lit(h_lo)) &
                                  (col("t_hour") <= lit(h_hi)))
        return (t["web_sales"]
                .join(hd, on=(col("ws_hdemo_sk") == col("hd_demo_sk")))
                .join(td, on=(col("ws_sold_time_sk") == col("t_time_sk")))
                .agg(F.count("*").alias(name)))
    return (half(8, 9, "amc").crossJoin(half(19, 20, "pmc"))
            .select((col("amc").cast("double") /
                     col("pmc")).alias("am_pm_ratio")))


def tpcds_q93(t):
    """Effective sales after returns adjustment (TpcdsLikeSpark Query93:
    store_sales LEFT JOIN its returns on basket+item; returned quantity
    subtracts)."""
    sr = t["store_returns"].select(
        col("sr_order_number").alias("r_order"),
        col("sr_item_sk").alias("r_item"),
        col("sr_return_quantity"))
    j = t["store_sales"].join(
        sr, on=[col("ss_order_number") == col("r_order"),
                col("ss_item_sk") == col("r_item")], how="left")
    act = F.when(col("sr_return_quantity").isNotNull(),
                 (col("ss_quantity") - col("sr_return_quantity")) *
                 col("ss_sales_price")) \
        .otherwise(col("ss_quantity") * col("ss_sales_price"))
    return (j.groupBy("ss_customer_sk")
            .agg(F.sum(act).alias("sumsales"))
            .orderBy(col("sumsales").desc(), col("ss_customer_sk").asc())
            .limit(100))


TPCDS_QUERIES = {"tpcds_q3": tpcds_q3, "tpcds_q5": tpcds_q5,
                 "tpcds_q6": tpcds_q6, "tpcds_q13": tpcds_q13,
                 "tpcds_q25": tpcds_q25, "tpcds_q27": tpcds_q27,
                 "tpcds_q29": tpcds_q29, "tpcds_q34": tpcds_q34,
                 "tpcds_q36": tpcds_q36, "tpcds_q46": tpcds_q46,
                 "tpcds_q50": tpcds_q50, "tpcds_q71": tpcds_q71,
                 "tpcds_q76": tpcds_q76, "tpcds_q89": tpcds_q89,
                 "tpcds_q90": tpcds_q90, "tpcds_q93": tpcds_q93,
                 "tpcds_q7": tpcds_q7, "tpcds_q12": tpcds_q12,
                 "tpcds_q15": tpcds_q15, "tpcds_q19": tpcds_q19,
                 "tpcds_q20": tpcds_q20, "tpcds_q26": tpcds_q26,
                 "tpcds_q33": tpcds_q33, "tpcds_q42": tpcds_q42,
                 "tpcds_q43": tpcds_q43, "tpcds_q45": tpcds_q45,
                 "tpcds_q48": tpcds_q48, "tpcds_q52": tpcds_q52,
                 "tpcds_q55": tpcds_q55, "tpcds_q61": tpcds_q61,
                 "tpcds_q65": tpcds_q65, "tpcds_q68": tpcds_q68,
                 "tpcds_q73": tpcds_q73, "tpcds_q79": tpcds_q79,
                 "tpcds_q88": tpcds_q88, "tpcds_q96": tpcds_q96,
                 "tpcds_q97": tpcds_q97, "tpcds_q98": tpcds_q98}
