"""TPCxBB-like query definitions (BASELINE.md milestone 3: the reference
ships a TpcxbbLikeSpark.scala suite; this is the analog over the
TPC-DS-like retail tables from datagen.register_tpcds_tables).

Three representative retail-analytics shapes: per-unit channel comparison
(q06-like), top items by revenue concentration (q09-like), and repeat
customers across channels (q30-like cross-channel behavior)."""

from __future__ import annotations

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit

from . import datagen

_D0 = datagen._D_DATE_BASE


def tpcxbb_q06(t):
    """Customers whose web spend grew vs their store spend (channel
    comparison per customer with conditional sums)."""
    ss = (t["store_sales"]
          .groupBy("ss_customer_sk")
          .agg(F.sum("ss_ext_sales_price").alias("store_spend"))
          .withColumnRenamed("ss_customer_sk", "s_customer"))
    ws = (t["web_sales"]
          .groupBy("ws_customer_sk")
          .agg(F.sum("ws_ext_sales_price").alias("web_spend"))
          .withColumnRenamed("ws_customer_sk", "w_customer"))
    return (ss.join(ws, on=(col("s_customer") == col("w_customer")))
            .filter(col("web_spend") > col("store_spend"))
            .select(col("s_customer").alias("customer_sk"),
                    col("store_spend"), col("web_spend"))
            .orderBy(col("web_spend").desc(),
                     col("customer_sk").asc())
            .limit(100))


def tpcxbb_q09(t):
    """Store-sales revenue by store unit over a date window with a
    minimum-volume HAVING (aggregate pruning shape)."""
    window = ((col("ss_sold_date_sk") >= lit(_D0 + 30)) &
              (col("ss_sold_date_sk") <= lit(_D0 + 120)))
    return (t["store_sales"].filter(window)
            .join(t["store"],
                  on=(col("ss_unit_sk") == col("s_store_sk")))
            .groupBy("s_store_id")
            .agg(F.sum("ss_ext_sales_price").alias("revenue"),
                 F.count("*").alias("n_sales"))
            .filter(col("n_sales") > lit(10))
            .orderBy(col("revenue").desc(), col("s_store_id").asc()))


def tpcxbb_q30(t):
    """Cross-channel repeat buyers: customers present in BOTH catalog and
    web sales with their per-channel item breadth (semi-join + distinct
    counts)."""
    cs = (t["catalog_sales"]
          .groupBy("cs_customer_sk")
          .agg(F.countDistinct(col("cs_item_sk")).alias("catalog_items")))
    ws = (t["web_sales"]
          .groupBy("ws_customer_sk")
          .agg(F.countDistinct(col("ws_item_sk")).alias("web_items"))
          .withColumnRenamed("ws_customer_sk", "w_customer"))
    return (cs.join(ws, on=(col("cs_customer_sk") == col("w_customer")))
            .select(col("cs_customer_sk").alias("customer_sk"),
                    col("catalog_items"), col("web_items"))
            .orderBy((col("catalog_items") + col("web_items")).desc(),
                     col("customer_sk").asc())
            .limit(100))


def tpcxbb_q01(t):
    """Items co-purchased in the same store basket (TpcxbbLikeSpark
    Q01Like's affinity shape: fact self-join on the basket key, pair
    counts)."""
    a = (t["store_sales"]
         .select(col("ss_order_number").alias("o1"),
                 col("ss_item_sk").alias("item_a")))
    b = (t["store_sales"]
         .select(col("ss_order_number").alias("o2"),
                 col("ss_item_sk").alias("item_b")))
    return (a.join(b, on=(col("o1") == col("o2")))
            .filter(col("item_a") < col("item_b"))
            .groupBy("item_a", "item_b")
            .agg(F.count("*").alias("cnt"))
            .filter(col("cnt") >= lit(2))
            .orderBy(col("cnt").desc(), col("item_a").asc(),
                     col("item_b").asc())
            .limit(100))


def tpcxbb_q07(t):
    """States with many buyers of premium-priced items: price > 1.2x the
    category average (Q07Like: per-category avg subquery joined back)."""
    cat_avg = (t["item"]
               .groupBy("i_category")
               .agg(F.avg("i_current_price").alias("cat_avg"))
               .withColumnRenamed("i_category", "avg_cat"))
    premium = (t["item"]
               .join(cat_avg, on=(col("i_category") == col("avg_cat")))
               .filter(col("i_current_price") > col("cat_avg") * 1.2)
               .select(col("i_item_sk").alias("prem_item")))
    return (t["store_sales"]
            .join(premium, on=(col("ss_item_sk") == col("prem_item")),
                  how="left_semi")
            .join(t["customer"],
                  on=(col("ss_customer_sk") == col("c_customer_sk")))
            .join(t["customer_address"],
                  on=(col("c_current_addr_sk") == col("ca_address_sk")))
            .groupBy("ca_state")
            .agg(F.countDistinct(col("c_customer_sk")).alias("cnt"))
            .filter(col("cnt") >= lit(10))
            .orderBy(col("cnt").desc(), col("ca_state").asc())
            .limit(10))


def tpcxbb_q13(t):
    """Year-over-year store-spend growth per customer (Q13Like: two
    filtered aggregates joined, growth-ratio ordering)."""
    d = t["date_dim"]
    y1 = d.filter(col("d_year") == lit(1999))
    y2 = d.filter(col("d_year") == lit(2000))
    s1 = (t["store_sales"]
          .join(y1, on=(col("ss_sold_date_sk") == col("d_date_sk")))
          .groupBy("ss_customer_sk")
          .agg(F.sum("ss_net_profit").alias("first_year"))
          .withColumnRenamed("ss_customer_sk", "c1"))
    s2 = (t["store_sales"]
          .join(y2, on=(col("ss_sold_date_sk") == col("d_date_sk")))
          .groupBy("ss_customer_sk")
          .agg(F.sum("ss_net_profit").alias("second_year"))
          .withColumnRenamed("ss_customer_sk", "c2"))
    return (s1.join(s2, on=(col("c1") == col("c2")))
            .filter(col("first_year") > lit(0))
            .select(col("c1").alias("customer_sk"),
                    (col("second_year") / col("first_year")).alias("ratio"))
            .orderBy(col("ratio").desc(), col("customer_sk").asc())
            .limit(100))


def tpcxbb_q15(t):
    """Declining categories: least-squares slope of monthly store revenue
    per category, negative slopes only (Q15Like's regression shape via
    sum-of-products aggregates)."""
    j = (t["store_sales"]
         .join(t["date_dim"],
               on=(col("ss_sold_date_sk") == col("d_date_sk")))
         .join(t["item"], on=(col("ss_item_sk") == col("i_item_sk"))))
    monthly = (j.groupBy("i_category_id", "d_month_seq")
               .agg(F.sum("ss_net_profit").alias("y")))
    x = col("d_month_seq").cast("double")
    fitted = (monthly.groupBy("i_category_id")
              .agg(F.count("*").alias("n"), F.sum(x).alias("sx"),
                   F.sum(col("y")).alias("sy"),
                   F.sum(x * x).alias("sxx"),
                   F.sum(x * col("y")).alias("sxy")))
    slope = ((col("n") * col("sxy") - col("sx") * col("sy")) /
             (col("n") * col("sxx") - col("sx") * col("sx")))
    return (fitted
            .select(col("i_category_id"), slope.alias("slope"))
            .filter(col("slope") < lit(0.0))
            .orderBy(col("slope").asc(), col("i_category_id").asc()))


def tpcxbb_q16(t):
    """Web revenue the week before vs after an event date (Q16Like's
    before/after CASE sums per item)."""
    pivot = _D0 + 180
    d = t["date_dim"].filter((col("d_date_sk") >= lit(pivot - 30)) &
                             (col("d_date_sk") <= lit(pivot + 30)))
    j = (t["web_sales"]
         .join(d, on=(col("ws_sold_date_sk") == col("d_date_sk")))
         .join(t["item"], on=(col("ws_item_sk") == col("i_item_sk"))))
    before = F.sum(F.when(col("d_date_sk") < lit(pivot),
                          col("ws_ext_sales_price")).otherwise(lit(0.0)))
    after = F.sum(F.when(col("d_date_sk") >= lit(pivot),
                         col("ws_ext_sales_price")).otherwise(lit(0.0)))
    return (j.groupBy("i_category")
            .agg(before.alias("before_sales"), after.alias("after_sales"))
            .orderBy(col("i_category").asc()))


def tpcxbb_q20(t):
    """Customer return-behavior features for clustering input (Q20Like:
    orders/returns ratios per customer)."""
    sales = (t["store_sales"]
             .groupBy("ss_customer_sk")
             .agg(F.countDistinct(col("ss_order_number")).alias("orders"),
                  F.sum("ss_quantity").alias("items"),
                  F.sum("ss_ext_sales_price").alias("spend")))
    rets = (t["store_returns"]
            .groupBy("sr_customer_sk")
            .agg(F.count("*").alias("returns_"),
                 F.sum("sr_return_quantity").alias("ret_items"),
                 F.sum("sr_return_amt").alias("ret_amt"))
            .withColumnRenamed("sr_customer_sk", "r_customer"))
    return (sales.join(rets, on=(col("ss_customer_sk") == col("r_customer")))
            .select(col("ss_customer_sk").alias("customer_sk"),
                    (col("returns_").cast("double") /
                     col("orders")).alias("return_order_ratio"),
                    (col("ret_items").cast("double") /
                     col("items")).alias("return_item_ratio"),
                    (col("ret_amt") / col("spend")).alias("return_amt_ratio"))
            .orderBy(col("return_amt_ratio").desc(),
                     col("customer_sk").asc())
            .limit(100))


def tpcxbb_q24(t):
    """Cross-channel price sensitivity: per item, web vs store quantity
    share (Q24Like adapted to the generated channels)."""
    ws = (t["web_sales"]
          .groupBy("ws_item_sk")
          .agg(F.sum("ws_quantity").alias("web_q"))
          .withColumnRenamed("ws_item_sk", "w_item"))
    ss = (t["store_sales"]
          .groupBy("ss_item_sk")
          .agg(F.sum("ss_quantity").alias("store_q")))
    return (ss.join(ws, on=(col("ss_item_sk") == col("w_item")))
            .join(t["item"], on=(col("ss_item_sk") == col("i_item_sk")))
            .select(col("i_item_id"),
                    (col("web_q").cast("double") /
                     (col("web_q") + col("store_q"))).alias("web_share"))
            .filter(col("web_share") > lit(0.5))
            .orderBy(col("web_share").desc(), col("i_item_id").asc())
            .limit(100))


def tpcxbb_q29(t):
    """Category pairs co-purchased in one web order (Q29Like: the q01
    affinity shape at category grain over web orders)."""
    w = (t["web_sales"]
         .join(t["item"], on=(col("ws_item_sk") == col("i_item_sk")))
         .select(col("ws_order_number").alias("o"),
                 col("i_category_id").alias("cat"))
         .distinct())
    a = w.select(col("o").alias("o1"), col("cat").alias("cat_a"))
    b = w.select(col("o").alias("o2"), col("cat").alias("cat_b"))
    return (a.join(b, on=(col("o1") == col("o2")))
            .filter(col("cat_a") < col("cat_b"))
            .groupBy("cat_a", "cat_b")
            .agg(F.count("*").alias("cnt"))
            .orderBy(col("cnt").desc(), col("cat_a").asc(),
                     col("cat_b").asc())
            .limit(100))


TPCXBB_QUERIES = {"tpcxbb_q01": tpcxbb_q01, "tpcxbb_q06": tpcxbb_q06,
                  "tpcxbb_q07": tpcxbb_q07, "tpcxbb_q09": tpcxbb_q09,
                  "tpcxbb_q13": tpcxbb_q13, "tpcxbb_q15": tpcxbb_q15,
                  "tpcxbb_q16": tpcxbb_q16, "tpcxbb_q20": tpcxbb_q20,
                  "tpcxbb_q24": tpcxbb_q24, "tpcxbb_q29": tpcxbb_q29,
                  "tpcxbb_q30": tpcxbb_q30}
