"""TPCxBB-like query definitions (BASELINE.md milestone 3: the reference
ships a TpcxbbLikeSpark.scala suite; this is the analog over the
TPC-DS-like retail tables from datagen.register_tpcds_tables).

Three representative retail-analytics shapes: per-unit channel comparison
(q06-like), top items by revenue concentration (q09-like), and repeat
customers across channels (q30-like cross-channel behavior)."""

from __future__ import annotations

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit

from . import datagen

_D0 = datagen._D_DATE_BASE


def tpcxbb_q06(t):
    """Customers whose web spend grew vs their store spend (channel
    comparison per customer with conditional sums)."""
    ss = (t["store_sales"]
          .groupBy("ss_customer_sk")
          .agg(F.sum("ss_ext_sales_price").alias("store_spend"))
          .withColumnRenamed("ss_customer_sk", "s_customer"))
    ws = (t["web_sales"]
          .groupBy("ws_customer_sk")
          .agg(F.sum("ws_ext_sales_price").alias("web_spend"))
          .withColumnRenamed("ws_customer_sk", "w_customer"))
    return (ss.join(ws, on=(col("s_customer") == col("w_customer")))
            .filter(col("web_spend") > col("store_spend"))
            .select(col("s_customer").alias("customer_sk"),
                    col("store_spend"), col("web_spend"))
            .orderBy(col("web_spend").desc(),
                     col("customer_sk").asc())
            .limit(100))


def tpcxbb_q09(t):
    """Store-sales revenue by store unit over a date window with a
    minimum-volume HAVING (aggregate pruning shape)."""
    window = ((col("ss_sold_date_sk") >= lit(_D0 + 30)) &
              (col("ss_sold_date_sk") <= lit(_D0 + 120)))
    return (t["store_sales"].filter(window)
            .join(t["store"],
                  on=(col("ss_unit_sk") == col("s_store_sk")))
            .groupBy("s_store_id")
            .agg(F.sum("ss_ext_sales_price").alias("revenue"),
                 F.count("*").alias("n_sales"))
            .filter(col("n_sales") > lit(10))
            .orderBy(col("revenue").desc(), col("s_store_id").asc()))


def tpcxbb_q30(t):
    """Cross-channel repeat buyers: customers present in BOTH catalog and
    web sales with their per-channel item breadth (semi-join + distinct
    counts)."""
    cs = (t["catalog_sales"]
          .groupBy("cs_customer_sk")
          .agg(F.countDistinct(col("cs_item_sk")).alias("catalog_items")))
    ws = (t["web_sales"]
          .groupBy("ws_customer_sk")
          .agg(F.countDistinct(col("ws_item_sk")).alias("web_items"))
          .withColumnRenamed("ws_customer_sk", "w_customer"))
    return (cs.join(ws, on=(col("cs_customer_sk") == col("w_customer")))
            .select(col("cs_customer_sk").alias("customer_sk"),
                    col("catalog_items"), col("web_items"))
            .orderBy((col("catalog_items") + col("web_items")).desc(),
                     col("customer_sk").asc())
            .limit(100))


TPCXBB_QUERIES = {"tpcxbb_q06": tpcxbb_q06, "tpcxbb_q09": tpcxbb_q09,
                  "tpcxbb_q30": tpcxbb_q30}
