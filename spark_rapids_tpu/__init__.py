"""spark-rapids-tpu: a TPU-native columnar SQL execution framework.

Re-design of the RAPIDS Accelerator for Apache Spark (NVIDIA/spark-rapids @ v0.3.0)
for TPU: plan-rewrite engine -> columnar TpuExec operators -> jax/XLA/Pallas kernels
over padded Arrow-layout device buffers -> mesh/ICI shuffle. See SURVEY.md (reference
blueprint) and DESIGN.md (TPU-first decisions).
"""

import os

import jax

# Spark SQL semantics require 64-bit longs/doubles; jax defaults to 32-bit.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: fused-stage programs (sort-based
# group-bys especially) can take minutes to compile, and every fresh
# process would otherwise pay that again. Opt out / relocate with
# SPARK_RAPIDS_TPU_COMPILE_CACHE=off|<dir>. This import-time default is
# the XLA-level substrate only (>=2s compiles); setting
# spark.rapids.tpu.sql.compile.cacheDir upgrades it to the full managed
# cache — engine signature index, cold-vs-disk classification, compile
# seconds metering, and persistence of EVERY program
# (exec/compile_cache.py, docs/compile.md).
_cache_dir = os.environ.get("SPARK_RAPIDS_TPU_COMPILE_CACHE", "")
if _cache_dir.lower() != "off":
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            _cache_dir or os.path.expanduser("~/.cache/spark_rapids_tpu/xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:                     # older jax without the knob
        pass

__version__ = "0.1.0"

from .config import TpuConf  # noqa: E402,F401
from .columnar import dtypes  # noqa: E402,F401
from .columnar.batch import ColumnarBatch  # noqa: E402,F401
from .columnar.column import Column, Scalar  # noqa: E402,F401


def __getattr__(name):
    # lazy: importing the api pulls in the full plan/exec stack
    if name == "TpuSession":
        from .api.session import TpuSession
        return TpuSession
    if name == "functions":
        from .api import functions
        return functions
    raise AttributeError(name)
