"""Static analysis passes over the engine's own invariants.

Six cooperating passes (the ApiValidation.scala / assertIsOnTheGpu shape
of tooling, turned on the invariants this port's hot paths depend on):

* :mod:`.lint` — AST project linter (``python -m tools.lint``): no implicit
  device->host materialization in hot-path modules, conf/doc agreement,
  exec contract declarations.
* :mod:`.contracts` — plan-contract validator: ``validate_plan`` walks the
  converted physical tree before execution and checks schema/dtype
  agreement between execs, exchange distribution invariants, and that the
  conversion matches what tagging promised.
* :mod:`.sync_audit` — runtime sync auditor: arms ``jax.transfer_guard``
  around partition-drain task regions, with an explicit allowlist for the
  sanctioned host-transfer helpers.
* :mod:`.recompile` — recompile audit: distinct compiled shapes per fused
  kernel, flagging operators that compile once per batch shape (missed
  capacity-bucket padding).
* :mod:`.concurrency` — static concurrency linter over the
  thread-reachable modules: every lock on the lockdep registry
  (``raw-lock``), shared-state mutation under its owner's lock
  (``unguarded-state``), no blocking IO/readback/second-acquire inside a
  ``with <lock>:`` body (``lock-blocking``), the ``_instance``/``_lock``
  singleton pattern fully guarded (``singleton-guard``).
* :mod:`.lockdep` — runtime lock-order tracking: named-lock wrappers the
  engine's locks live on, a global acquisition-order graph with
  both-stack cycle reports (``record``) or raises (``enforce``),
  per-lock wait/hold stats attributed to trace spans, and
  held-across-host-transfer detection via ``sync_audit``.

docs/analysis.md documents all of them.

None of these import jax at module import time; the engine stays importable
in analysis-only contexts (the linter runs on a bare checkout).
"""
