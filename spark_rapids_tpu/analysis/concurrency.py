"""Static concurrency linter: the lexical half of the engine's locking
discipline (``analysis/lockdep.py`` is the runtime half).

Scope — the thread-reachable modules: ``exec/``, ``shuffle/``,
``analysis/``, ``config.py``, ``api/session.py``. These are the modules
whose code runs on partition-drain pool threads, shuffle accept/handler
threads, or is process-singleton state those threads share. Pure AST +
text; no engine import.

Rules (all wired into ``python -m tools.lint``, tier-1-enforced):

``raw-lock``
    A ``threading.Lock/RLock/Semaphore/BoundedSemaphore/Condition()``
    creation in a scoped module. Engine locks must be created through
    ``lockdep.named_lock``/``named_rlock`` so the runtime order graph and
    wait/hold attribution see them. (``threading.local`` and
    ``threading.Event`` are exempt — confinement and signalling, not
    mutual exclusion; ``analysis/lockdep.py`` itself is exempt: its
    internal leaf lock cannot be self-instrumented.)

``unguarded-state``
    Mutation of shared state outside a recognized ``with <lock>:`` guard.
    The discipline is ownership-scoped to stay decidable: a CLASS that
    owns a lock must mutate its instance/class attributes under it; a
    MODULE that owns a module-level lock must mutate its ``global``s
    under it. Lock-free classes are presumed thread-confined — giving a
    class shared state means giving it a (named) lock, which arms this
    rule. Exemptions: ``__init__``/``__new__`` bodies (construction is
    single-threaded), helpers named ``*_locked`` (the called-with-lock-
    held convention, e.g. ``_spill_device_to_locked``), attributes that
    hold ``threading.local()`` values, and targets reached *through* a
    thread-local attribute.

``lock-blocking``
    A call that can block — another lock/semaphore ``acquire`` or nested
    ``with <lock>:``, socket send/recv/accept/connect, file IO
    (``open``/``np.load``/``np.savez*``), ``subprocess``, ``time.sleep``,
    an ``allowed_host_transfer`` crossing, or a device readback
    (``np.asarray``, ``jax.device_get``, ``.block_until_ready()``,
    ``.item()``, ``float/int/bool`` over a jnp call) — lexically inside a
    ``with <lock>:`` body. Holding a mutex across a link round trip or a
    disk write serializes every peer thread behind IO.

``singleton-guard``
    For classes using the ``_instance``/``_lock`` singleton pattern:
    every read and write of ``_instance`` must sit inside a recognized
    ``with <lock>:`` guard.

Suppression mirrors the linter's host-sync pragma — one pragma per rule,
reason mandatory, on the flagged line (or the line above)::

    self._cache = v  # lint: unguarded-ok <why this is safe>
    save(path, *a)   # lint: lock-blocking-ok <why the hold is required>
    self._sem = threading.Semaphore(n)  # lint: raw-lock-ok <why raw>
    cls._instance    # lint: singleton-guard-ok <why unguarded>

Reason-less pragmas are themselves flagged (``pragma-reason``) and do
not suppress. Registry: ``python -m tools.lint --locks`` dumps every
lock creation site with its canonical name; duplicate lockdep names
across the package are flagged (``lock-name-dup``) because the runtime
order graph keys on them.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .lint import LintViolation

SCOPE_PREFIXES = ("exec/", "shuffle/", "analysis/")
SCOPE_FILES = ("config.py", "api/session.py",
               # the multi-tenant service is thread-reachable by
               # construction (worker pool + cross-thread submit)
               "service/server.py", "service/tenants.py")
# the instrumentation layer's own internals cannot be self-instrumented
RAW_LOCK_EXEMPT = ("analysis/lockdep.py",)

RAW_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore",
                  "Condition"}
NAMED_LOCK_CTORS = {"named_lock", "named_rlock", "NamedLock", "NamedRLock"}

PRAGMA_RE = re.compile(
    r"#\s*lint:\s*(raw-lock|unguarded|lock-blocking|singleton-guard)"
    r"-ok(.*)$")

# fallback guard recognition for locks the registry pass didn't see
# (e.g. a lock attribute assigned in another module)
GUARD_NAME_RE = re.compile(r"^_?[a-z0-9_]*(lock|mu|mutex)$")

CONSTRUCTORS = ("__init__", "__new__")


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in SCOPE_FILES


@dataclass
class LockSite:
    """One lock creation site (the rule-(a) registry entry)."""
    path: str
    rel: str
    line: int
    kind: str             # threading ctor or named_lock/named_rlock
    attr: str             # terminal attribute/variable name bound
    canonical: str        # module-qualified name, or the declared
                          # lockdep name for named locks


def _terminal_name(target: ast.AST) -> Optional[str]:
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def _pragmas(source: str) -> Dict[int, Tuple[str, str]]:
    """line -> (rule, reason)."""
    out: Dict[int, Tuple[str, str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = PRAGMA_RE.search(line)
        if m:
            rule = ("unguarded-state" if m.group(1) == "unguarded"
                    else m.group(1))
            out[i] = (rule, m.group(2).strip())
    return out


def _lock_ctor(value: ast.AST) -> Optional[str]:
    """'threading.X' / named-lock kind when ``value`` creates a lock."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    if isinstance(f, ast.Attribute) and f.attr in RAW_LOCK_CTORS and \
            isinstance(f.value, ast.Name) and f.value.id == "threading":
        return f"threading.{f.attr}"
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name in NAMED_LOCK_CTORS:
        return name
    return None


def _is_local_ctor(value: ast.AST) -> bool:
    return (isinstance(value, ast.Call) and
            isinstance(value.func, ast.Attribute) and
            value.func.attr == "local" and
            isinstance(value.func.value, ast.Name) and
            value.func.value.id == "threading")


class _Analyzer(ast.NodeVisitor):
    """Single-pass visitor emitting the unguarded-state / lock-blocking /
    singleton-guard hits over one module, after a pre-scan that decides
    lock ownership (which classes/modules own locks, which attributes
    are thread-local)."""

    def __init__(self, rel: str, tree: ast.Module) -> None:
        self.rel = rel
        self.hits: List[Tuple[int, str, str]] = []   # (line, rule, msg)
        self._class_stack: List[str] = []
        self._func_stack: List[str] = []
        self._global_stack: List[Set[str]] = []
        self._with_locks: List[str] = []     # guard names currently open
        # -- pre-scan results --
        self.lock_attrs: Set[str] = set()            # all lock-bound names
        self.localish: Set[str] = set()              # threading.local attrs
        self.module_locks: Set[str] = set()          # module-level lock vars
        self.class_locks: Dict[str, Set[str]] = {}   # class -> lock attrs
        self.singletons: Set[str] = set()            # classes w/ _instance+_lock
        self._prescan(tree)

    # -- pre-scan ------------------------------------------------------------

    def _prescan(self, tree: ast.Module) -> None:
        ctx_of: Dict[ast.AST, Tuple[Optional[str], bool]] = {}

        def walk(node, cls, in_func):
            for child in ast.iter_child_nodes(node):
                c, f = cls, in_func
                if isinstance(child, ast.ClassDef):
                    c = child.name
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    f = True
                ctx_of[child] = (c, f)
                walk(child, c, f)
        walk(tree, None, False)

        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            cls, in_func = ctx_of.get(node, (None, False))
            kind = _lock_ctor(node.value)
            for t in node.targets:
                name = _terminal_name(t)
                if name is None:
                    continue
                if kind is not None:
                    self.lock_attrs.add(name)
                    if isinstance(t, ast.Attribute) and cls is not None:
                        self.class_locks.setdefault(cls, set()).add(name)
                    elif isinstance(t, ast.Name):
                        if cls is not None:
                            self.class_locks.setdefault(cls,
                                                        set()).add(name)
                        elif not in_func:
                            self.module_locks.add(name)
                if _is_local_ctor(node.value):
                    self.localish.add(name)

        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                body_names = {
                    _terminal_name(t)
                    for st in node.body if isinstance(st, ast.Assign)
                    for t in st.targets}
                body_names |= {
                    st.target.id for st in node.body
                    if isinstance(st, ast.AnnAssign) and
                    isinstance(st.target, ast.Name)}
                if "_instance" in body_names and "_lock" in body_names:
                    self.singletons.add(node.name)

    # -- context helpers -----------------------------------------------------

    @property
    def _cls(self) -> Optional[str]:
        return self._class_stack[-1] if self._class_stack else None

    @property
    def _func(self) -> Optional[str]:
        return self._func_stack[-1] if self._func_stack else None

    def _is_guard(self, expr: ast.AST) -> Optional[str]:
        """The guard name when ``expr`` is a recognized lock object."""
        name = _terminal_name(expr)
        if name is None:
            return None
        if name in self.lock_attrs or GUARD_NAME_RE.match(name):
            return name
        return None

    def _exempt_func(self) -> bool:
        f = self._func
        return f in CONSTRUCTORS or (f is not None and
                                     f.endswith("_locked"))

    def _hit(self, node: ast.AST, rule: str, msg: str) -> None:
        self.hits.append((node.lineno, rule, msg))

    # -- traversal -----------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._func_stack.append(node.name)
        self._global_stack.append(set())
        outer_with = self._with_locks
        self._with_locks = []        # a def body runs later, not under the
        self.generic_visit(node)     # lexically-enclosing with
        self._with_locks = outer_with
        self._global_stack.pop()
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Global(self, node: ast.Global) -> None:
        if self._global_stack:
            self._global_stack[-1].update(node.names)

    def visit_With(self, node: ast.With) -> None:
        guards = []
        for item in node.items:
            g = self._is_guard(item.context_expr)
            if g is not None:
                guards.append(g)
                if self._with_locks:
                    self._hit(
                        node, "lock-blocking",
                        f"nested acquisition of {g} while holding "
                        f"{self._with_locks[-1]}: a second lock under a "
                        "held lock is a blocking wait and an order-graph "
                        "edge — document the order (pragma) or "
                        "restructure")
        for item in node.items:              # exprs evaluate pre-acquire,
            self.visit(item.context_expr)    # under only the OUTER locks
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self._with_locks.extend(guards)
        for st in node.body:
            self.visit(st)
        if guards:
            del self._with_locks[len(self._with_locks) - len(guards):]

    # -- mutations (unguarded-state) ----------------------------------------

    def _owning_class_locks(self) -> Set[str]:
        cls = self._cls
        return self.class_locks.get(cls, set()) if cls else set()

    def _through_local(self, target: ast.AST) -> bool:
        """Target chain passes through a threading.local attribute
        (self._held.value = ...) — thread-confined by construction."""
        node = target
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
            if isinstance(node, ast.Attribute) and node.attr in self.localish:
                return True
            if isinstance(node, ast.Name) and node.id in self.localish:
                return True
        return False

    def _base_attr(self, target: ast.AST) -> Optional[str]:
        """For self.X / cls.X / <ClassName>.X targets (possibly behind a
        subscript: self._buffers[k] = v), the attribute name X."""
        node = target
        while isinstance(node, ast.Subscript):
            node = node.value
        if not isinstance(node, ast.Attribute):
            return None
        base = node.value
        if isinstance(base, ast.Name) and base.id in (
                "self", "cls", self._cls):
            return node.attr
        return None

    def _check_mutation(self, node: ast.AST, targets: List[ast.AST],
                        value: Optional[ast.AST]) -> None:
        if self._exempt_func() or self._with_locks:
            return
        declared_global = self._global_stack[-1] if self._global_stack \
            else set()
        for t in targets:
            if self._through_local(t):
                continue
            if isinstance(t, ast.Name) and t.id in declared_global:
                if not self.module_locks or t.id in self.lock_attrs:
                    continue
                if value is not None and _is_local_ctor(value):
                    continue
                locks = ", ".join(sorted(self.module_locks))
                self._hit(
                    node, "unguarded-state",
                    f"global {t.id} mutated outside `with <lock>:` but "
                    f"the module owns a lock ({locks}) — guard the write "
                    "or pragma `# lint: unguarded-ok <reason>`")
                continue
            attr = self._base_attr(t)
            if attr is not None:
                if not self._owning_class_locks():
                    continue
                if attr in self.lock_attrs or attr in self.localish:
                    continue
                if value is not None and _is_local_ctor(value):
                    continue
                locks = ", ".join(sorted(self._owning_class_locks()))
                self._hit(
                    node, "unguarded-state",
                    f"{self._cls}.{attr} mutated outside `with <lock>:` "
                    f"but {self._cls} owns a lock ({locks}) — guard the "
                    "mutation, move it into a *_locked helper, or pragma "
                    "`# lint: unguarded-ok <reason>`")

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_mutation(node, node.targets, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_mutation(node, [node.target], None)
        self.generic_visit(node)

    # -- blocking calls under a lock + singleton guard ----------------------

    _SOCKET_VERBS = {"send", "sendall", "recv", "accept", "connect"}
    _SUBPROCESS = {"run", "check_call", "check_output", "Popen", "call"}

    def _blocking_reason(self, node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Attribute):
            a, v = f.attr, f.value
            if a == "acquire":
                return "lock/semaphore acquire"
            if a in self._SOCKET_VERBS:
                return f"socket .{a}()"
            if isinstance(v, ast.Name) and v.id in ("np", "numpy", "_np"):
                if a in ("load", "savez", "savez_compressed", "save"):
                    return f"np.{a} disk IO"
                if a == "asarray":
                    return "np.asarray device readback"
            if isinstance(v, ast.Name) and v.id == "subprocess" and \
                    a in self._SUBPROCESS:
                return f"subprocess.{a}"
            if isinstance(v, ast.Name) and v.id == "time" and a == "sleep":
                return "time.sleep"
            if a == "device_get" and isinstance(v, ast.Name) and \
                    v.id == "jax":
                return "jax.device_get readback"
            if a == "block_until_ready":
                return ".block_until_ready() device barrier"
            if a == "item" and not node.args and not node.keywords:
                return ".item() scalar readback"
        elif isinstance(f, ast.Name):
            if f.id == "open":
                return "open() file IO"
            if f.id == "allowed_host_transfer":
                return "allowed_host_transfer crossing"
            if f.id in ("float", "int", "bool") and len(node.args) == 1:
                arg = node.args[0]
                if isinstance(arg, ast.Call) and \
                        isinstance(arg.func, ast.Attribute) and \
                        isinstance(arg.func.value, ast.Name) and \
                        arg.func.value.id in ("jnp", "jax"):
                    return f"{f.id}() scalar readback over a jax call"
        return None

    def visit_Call(self, node: ast.Call) -> None:
        if self._with_locks:
            reason = self._blocking_reason(node)
            if reason is not None:
                self._hit(
                    node, "lock-blocking",
                    f"{reason} inside `with {self._with_locks[-1]}:` — "
                    "snapshot state under the lock, do the blocking work "
                    "unlocked, re-take to publish (or pragma "
                    "`# lint: lock-blocking-ok <reason>`)")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr == "_instance" and self.singletons and \
                isinstance(node.value, ast.Name):
            vid = node.value.id
            targets_singleton = vid in self.singletons or (
                vid in ("cls", self._cls) and self._cls in self.singletons)
            if targets_singleton and not any(
                    g == "_lock" or g.endswith("_lock")
                    for g in self._with_locks):
                self._hit(
                    node, "singleton-guard",
                    f"{vid}._instance accessed outside `with <cls>._lock:`"
                    " — the singleton pattern needs BOTH reads and writes "
                    "under the class lock (or pragma "
                    "`# lint: singleton-guard-ok <reason>`)")
        self.generic_visit(node)

    # -- registry ------------------------------------------------------------

    def collect_sites(self, tree: ast.Module, path: str) -> None:
        qual_of: Dict[ast.AST, str] = {}

        def walk(node, qual):
            for child in ast.iter_child_nodes(node):
                q = qual
                if isinstance(child, (ast.ClassDef, ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                qual_of[child] = q
                walk(child, q)
        walk(tree, "")

        self.sites: List[LockSite] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            kind = _lock_ctor(node.value)
            if kind is None:
                continue
            for t in node.targets:
                attr = _terminal_name(t)
                if attr is None:
                    continue
                if kind in NAMED_LOCK_CTORS:
                    call = node.value
                    canonical = (call.args[0].value
                                 if call.args and
                                 isinstance(call.args[0], ast.Constant)
                                 else f"{self.rel}:{attr}")
                else:
                    qual = qual_of.get(node, "")
                    # creation inside __init__ belongs to the class
                    qual = re.sub(r"\.__init__$", "", qual)
                    canonical = f"{self.rel}:{qual + '.' if qual else ''}" \
                                f"{attr}"
                self.sites.append(LockSite(
                    path=path, rel=self.rel, line=node.lineno, kind=kind,
                    attr=attr, canonical=canonical))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def lint_source(source: str, rel: str, path: Optional[str] = None
                ) -> List[LintViolation]:
    """Concurrency rules over one module (``rel`` relative to the
    package root). Returns [] for out-of-scope modules."""
    path = path or rel
    if not in_scope(rel):
        return []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []                       # lint.py already reports parse errors
    pragmas = _pragmas(source)
    out: List[LintViolation] = []

    for line, (rule, reason) in pragmas.items():
        if not reason:
            tag = "unguarded" if rule == "unguarded-state" else rule
            out.append(LintViolation(
                path, line, "pragma-reason",
                f"{tag}-ok pragma missing its justification "
                f"(format: `# lint: {tag}-ok <reason>`)"))

    a = _Analyzer(rel, tree)
    a.visit(tree)

    if rel not in RAW_LOCK_EXEMPT:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                kind = _lock_ctor(node)
                if kind is not None and kind.startswith("threading."):
                    a.hits.append((
                        node.lineno, "raw-lock",
                        f"{kind}() bypasses the lockdep registry — create "
                        "engine locks via analysis.lockdep.named_lock/"
                        "named_rlock so order tracking and wait/hold "
                        "attribution see them (or pragma "
                        "`# lint: raw-lock-ok <reason>`)"))

    for line, rule, msg in sorted(a.hits):
        suppressed = any(
            ln in pragmas and pragmas[ln][0] == rule and pragmas[ln][1]
            for ln in (line, line - 1))
        if not suppressed:
            out.append(LintViolation(path, line, rule, msg))
    return out


def lock_registry(package_dir: str) -> List[LockSite]:
    """Every lock/semaphore/condition creation site in the scoped
    modules, with canonical names (rule (a): the registry other rules
    and the runtime share)."""
    sites: List[LockSite] = []
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, package_dir).replace(os.sep, "/")
            if not in_scope(rel):
                continue
            with open(full, "r") as f:
                src = f.read()
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
            a = _Analyzer(rel, tree)
            a.collect_sites(tree, full)
            sites.extend(a.sites)
    return sites


def check_registry(sites: List[LockSite]) -> List[LintViolation]:
    """Cross-module registry checks: duplicate lockdep names (the
    runtime order graph keys on them, so two locks sharing a name would
    alias their edges)."""
    out: List[LintViolation] = []
    seen: Dict[str, LockSite] = {}
    for s in sites:
        if s.kind not in NAMED_LOCK_CTORS:
            continue
        prev = seen.get(s.canonical)
        if prev is not None and (prev.rel, prev.line) != (s.rel, s.line):
            out.append(LintViolation(
                s.path, s.line, "lock-name-dup",
                f"lockdep name {s.canonical!r} already registered at "
                f"{prev.rel}:{prev.line} — runtime order edges would "
                "alias; pick a unique canonical name"))
        else:
            seen[s.canonical] = s
    return out
