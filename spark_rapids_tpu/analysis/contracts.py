"""Plan-contract validator: static checks over the converted physical tree.

The reference's correctness story is static: GpuOverrides tags every node
children-first and ApiValidation.scala diffs the registered surface against
Spark. This pass is the physical-plan half of that story for the port —
after conversion, before execution, ``validate_plan`` walks the exec tree
and checks the invariants the executors assume:

* **schema agreement** — a passthrough exec (filter, coalesce, exchange,
  sort) emits exactly its child's schema; a join emits stream+build (or
  stream alone for semi/anti); a union's children agree on dtypes
  positionally.
* **bound references** — every ``BoundReference`` an exec will evaluate
  points inside the child schema it was bound against, with the dtype the
  child actually produces (a stale ordinal after a planner rewrite is a
  silent wrong-answer generator).
* **distribution invariants** — a ``per_partition_final`` aggregate sits
  on a hash exchange over its grouping keys (disjoint key ownership); a
  shuffled join's children are co-partitioned with equal partition counts.
* **tagging consistency** — a CPU fallback/bridge node only appears where
  the meta tree recorded a will-not-work reason; conversion must not
  quietly drop a subtree tagging promised to the device.

Every exec class *declares* its contract as a ``CONTRACT`` class attribute
(:func:`exec_contract`); the project linter enforces the declaration
exists, this pass enforces the declaration holds.

Modes (conf ``spark.rapids.tpu.sql.analysis.validatePlan``): ``off``,
``warn`` (default; violations append to the overrides explain output and
log once), ``error`` (reject the plan with :class:`PlanContractError`).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("spark_rapids_tpu.analysis.contracts")

SCHEMA_KINDS = ("passthrough", "defined", "union")
PARTITIONING_KINDS = ("preserve", "single", "defined", "source")


@dataclass(frozen=True)
class ExecContract:
    """Declared output contract of one physical exec class.

    ``schema``: how the output schema relates to the children —
    ``passthrough`` (identical to child 0), ``union`` (all children agree
    on dtypes positionally, output is child 0's), ``defined`` (exec
    constructs its own; shape-specific ``extras`` checks apply).

    ``partitioning``: ``preserve`` (output_partitions == child 0's),
    ``single`` (always 1), ``source`` (leaf; declares its own count),
    ``defined`` (exec-specific; extras may constrain it).

    ``bound``: mapping of expression-holding attribute name -> child index
    the expressions were bound against (ordinal/dtype checked).

    ``extras``: names of shape-specific validators implemented in this
    module (``join_schema``, ``copartitioned``, ``agg_distribution``,
    ``window_schema``, ``reorder_permutation``, ``empty_schema``).
    """

    schema: str = "defined"
    partitioning: str = "defined"
    bound: Tuple[Tuple[str, int], ...] = ()
    extras: Tuple[str, ...] = ()


def exec_contract(schema: str = "defined", partitioning: str = "defined",
                  bound: Optional[Dict[str, int]] = None,
                  extras: Tuple[str, ...] = ()) -> ExecContract:
    assert schema in SCHEMA_KINDS, schema
    assert partitioning in PARTITIONING_KINDS, partitioning
    return ExecContract(schema=schema, partitioning=partitioning,
                        bound=tuple(sorted((bound or {}).items())),
                        extras=tuple(extras))


@dataclass
class Violation:
    node: str                       # exec class name
    path: str                       # root->node class-name path
    message: str

    def __str__(self) -> str:
        return f"{self.node} [{self.path}]: {self.message}"


class PlanContractError(RuntimeError):
    """Raised in ``error`` mode; the message is the explain-integrated
    diagnostic (same text appended to ``Overrides.last_explain``)."""


# ---------------------------------------------------------------------------
# Schema helpers (duck-typed over columnar.dtypes.Schema)
# ---------------------------------------------------------------------------

def _fields_sig(schema) -> List[Tuple[str, Any]]:
    return [(f.name, f.dtype) for f in schema.fields]


def _dtypes_sig(schema) -> List[Any]:
    return [f.dtype for f in schema.fields]


def _schema_str(schema) -> str:
    return ", ".join(f"{n}:{t}" for n, t in _fields_sig(schema))


# ---------------------------------------------------------------------------
# Core walk
# ---------------------------------------------------------------------------

def validate_plan(root, meta=None) -> List[Violation]:
    """Static contract walk over a converted physical exec tree. Returns
    violations (empty on a clean plan). Never executes the plan and never
    touches the device."""
    out: List[Violation] = []
    promised = _meta_reasons(meta) if meta is not None else None

    def walk(node, path: str, idx: Optional[int] = None) -> None:
        name = type(node).__name__
        # child ordinal in the path: same-class siblings (a join's two
        # exchanges) must key DIFFERENT paths or EXPLAIN ANALYZE would
        # attach one child's violation under both
        here = f"{path}/{idx}.{name}" if path else name
        contract = getattr(type(node), "CONTRACT", None)
        if contract is None:
            out.append(Violation(name, here,
                                 "exec class declares no CONTRACT"))
        else:
            try:
                _check_node(node, contract, here, out)
            except Exception as e:      # a check crashing is itself a finding
                out.append(Violation(
                    name, here, f"contract check failed to run: {e!r}"))
        if promised is not None:
            _check_promise(node, promised, here, out)
        for i, c in enumerate(getattr(node, "children", ())):
            walk(c, here, i)

    walk(root, "")
    return out


def _check_node(node, contract: ExecContract, path: str,
                out: List[Violation]) -> None:
    name = type(node).__name__
    children = list(getattr(node, "children", ()))

    # -- schema kind --------------------------------------------------------
    if contract.schema == "passthrough":
        if not children:
            out.append(Violation(name, path,
                                 "passthrough schema but no children"))
        elif _fields_sig(node.schema) != _fields_sig(children[0].schema):
            out.append(Violation(
                name, path,
                "output schema diverges from child: "
                f"[{_schema_str(node.schema)}] vs "
                f"[{_schema_str(children[0].schema)}]"))
    elif contract.schema == "union":
        base = _dtypes_sig(children[0].schema) if children else []
        for i, c in enumerate(children[1:], start=1):
            if _dtypes_sig(c.schema) != base:
                out.append(Violation(
                    name, path,
                    f"union child {i} dtypes [{_schema_str(c.schema)}] "
                    f"disagree with child 0 [{_schema_str(children[0].schema)}]"))

    # -- partitioning kind --------------------------------------------------
    if contract.partitioning == "preserve" and children:
        if node.output_partitions != children[0].output_partitions:
            out.append(Violation(
                name, path,
                f"declares partition-preserving but outputs "
                f"{node.output_partitions} partitions over a "
                f"{children[0].output_partitions}-partition child"))
    elif contract.partitioning == "single":
        if node.output_partitions != 1:
            out.append(Violation(
                name, path,
                f"declares single-partition output but reports "
                f"{node.output_partitions}"))

    # -- bound references ---------------------------------------------------
    for attr, child_idx in contract.bound:
        if child_idx >= len(children):
            continue
        child_schema = children[child_idx].schema
        for ref in _bound_refs(getattr(node, attr, None)):
            if ref.ordinal < 0 or ref.ordinal >= len(child_schema.fields):
                out.append(Violation(
                    name, path,
                    f"{attr}: bound ordinal {ref.ordinal} outside child "
                    f"schema of {len(child_schema.fields)} columns"))
            elif child_schema.fields[ref.ordinal].dtype != ref.dtype:
                out.append(Violation(
                    name, path,
                    f"{attr}: bound ordinal {ref.ordinal} declares dtype "
                    f"{ref.dtype} but child produces "
                    f"{child_schema.fields[ref.ordinal].dtype}"))

    # -- shape-specific extras ---------------------------------------------
    for extra in contract.extras:
        _EXTRAS[extra](node, path, out)


def _bound_refs(value):
    """Yield every BoundReference inside an expression-holding attribute
    (expressions, lists of expressions, SortOrder lists, nested lists)."""
    from ..ops import expressions as ex
    from ..plan import logical as lp

    def rec(v):
        if v is None:
            return
        if isinstance(v, ex.Expression):
            yield from (n for n in v.collect(
                lambda x: isinstance(x, ex.BoundReference)))
        elif isinstance(v, lp.SortOrder):
            yield from rec(v.child)
        elif isinstance(v, (list, tuple)):
            for x in v:
                yield from rec(x)
    yield from rec(value)


# ---------------------------------------------------------------------------
# Extras: shape-specific validators
# ---------------------------------------------------------------------------

def _extra_join_schema(node, path: str, out: List[Violation]) -> None:
    """Join output = stream schema (semi/anti) or stream + build fields
    (dtype-exact; nullability is join-type-adjusted so only names/dtypes
    are compared)."""
    name = type(node).__name__
    stream, build = node.children[0].schema, node.children[1].schema
    got = _fields_sig(node.schema)
    if node.how in ("left_semi", "left_anti"):
        want = _fields_sig(stream)
    else:
        want = _fields_sig(stream) + _fields_sig(build)
    if got != want:
        out.append(Violation(
            name, path,
            f"{node.how} join schema [{_schema_str(node.schema)}] does not "
            f"concatenate its children's "
            f"([{_schema_str(stream)}] + [{_schema_str(build)}])"))
    n_stream, n_build = len(node.left_keys), len(node.right_keys)
    if n_stream != n_build or n_stream == 0:
        out.append(Violation(
            name, path,
            f"equi-join key arity mismatch: {n_stream} stream keys vs "
            f"{n_build} build keys"))


def _extra_copartitioned(node, path: str, out: List[Violation]) -> None:
    """A shuffled join's children must be co-partitioned: both exchanges,
    equal partition counts, equal key arity (partition i joins only
    partition i)."""
    name = type(node).__name__
    left, right = node.children
    ln = getattr(left, "num_partitions", None)
    rn = getattr(right, "num_partitions", None)
    if ln is None or rn is None:
        out.append(Violation(
            name, path,
            "shuffled join children are not exchanges "
            f"({type(left).__name__}, {type(right).__name__})"))
        return
    if ln != rn:
        out.append(Violation(
            name, path,
            f"co-partitioning broken: stream exchange has {ln} partitions, "
            f"build exchange {rn}"))
    lb = getattr(left, "by", None) or []
    rb = getattr(right, "by", None) or []
    if len(lb) != len(rb):
        out.append(Violation(
            name, path,
            f"co-partitioning key arity mismatch: {len(lb)} vs {len(rb)}"))


def _extra_agg_distribution(node, path: str, out: List[Violation]) -> None:
    """A final-mode aggregate that merges per partition requires the
    clustered distribution an exchange provides: hash exchange on the
    grouping keys, or a single-partition exchange for global aggregates
    (the reference's HashClusteredDistribution requirement)."""
    name = type(node).__name__
    if node.mode != "final" or not getattr(node, "per_partition_final", False):
        return
    child = node.children[0]
    n_keys = len(getattr(child, "by", None) or [])
    if getattr(child, "num_partitions", None) is None:
        out.append(Violation(
            name, path,
            "per-partition final merge over a non-exchange child "
            f"({type(child).__name__}): groups may straddle partitions"))
        return
    if node.grouping:
        if n_keys != len(node.grouping):
            out.append(Violation(
                name, path,
                f"final merge groups on {len(node.grouping)} keys but the "
                f"exchange below hashes {n_keys}"))
    elif child.num_partitions != 1:
        out.append(Violation(
            name, path,
            "global aggregate merged per partition over a "
            f"{child.num_partitions}-partition exchange"))


def _extra_window_schema(node, path: str, out: List[Violation]) -> None:
    """Window output = child fields + one generated column per window
    expression, in declaration order."""
    name = type(node).__name__
    child = node.children[0].schema
    got = _fields_sig(node.schema)
    want_names = [f.name for f in child.fields] + \
        [n for n, _w in node.window_exprs]
    if [n for n, _t in got] != want_names or \
            [t for _n, t in got[:len(child.fields)]] != _dtypes_sig(child):
        out.append(Violation(
            name, path,
            f"window schema [{_schema_str(node.schema)}] is not child "
            f"[{_schema_str(child)}] + {len(node.window_exprs)} window "
            "columns"))


def _extra_reorder_permutation(node, path: str, out: List[Violation]) -> None:
    """A column reorder must emit a permutation of its child's dtypes."""
    name = type(node).__name__
    got = sorted(map(str, _dtypes_sig(node.schema)))
    want = sorted(map(str, _dtypes_sig(node.children[0].schema)))
    if got != want:
        out.append(Violation(
            name, path,
            f"reorder output dtypes {got} are not a permutation of the "
            f"child's {want}"))


def _extra_empty_schema(node, path: str, out: List[Violation]) -> None:
    if len(node.schema.fields) != 0:
        out.append(Violation(
            type(node).__name__, path,
            "write exec must have an empty output schema"))


def _extra_exchange_plane(node, path: str, out: List[Violation]) -> None:
    """Two-plane exchange shape (docs/shuffle.md): the plan-time plane is
    one of auto|ici|dcn, a forced ICI plane carries the mesh it needs
    (auto may resolve either way at runtime; forced ici without a mesh is
    a planner bug that would otherwise surface mid-query), and the
    pipelined split depth is positive."""
    name = type(node).__name__
    plane = str(getattr(node, "plane", "auto") or "auto").lower()
    if plane not in ("auto", "ici", "dcn"):
        out.append(Violation(
            name, path,
            f"exchange plane {plane!r} is not one of auto|ici|dcn"))
        return
    if plane == "ici" and getattr(node, "mesh", None) is None:
        out.append(Violation(
            name, path,
            "plane forced to ici but the planner attached no device mesh "
            "(collectives cannot run; the conversion should have failed)"))
    depth = getattr(node, "split_depth", None)
    if depth is not None and int(depth) < 1:
        out.append(Violation(
            name, path,
            f"map-split pipeline depth {depth} must be >= 1"))


_EXTRAS = {
    "join_schema": _extra_join_schema,
    "copartitioned": _extra_copartitioned,
    "agg_distribution": _extra_agg_distribution,
    "window_schema": _extra_window_schema,
    "reorder_permutation": _extra_reorder_permutation,
    "empty_schema": _extra_empty_schema,
    "exchange_plane": _extra_exchange_plane,
}


# ---------------------------------------------------------------------------
# Tagging consistency: conversion vs what the meta walk promised
# ---------------------------------------------------------------------------

def _meta_reasons(meta) -> Dict[int, List[str]]:
    """id(logical plan node) -> accumulated will-not-work reasons."""
    out: Dict[int, List[str]] = {}

    def walk(m) -> None:
        out[id(m.plan)] = list(m.reasons)
        for c in m.children:
            walk(c)
    walk(meta)
    return out


def _check_promise(node, promised: Dict[int, List[str]], path: str,
                   out: List[Violation]) -> None:
    name = type(node).__name__
    if name not in ("CpuFallbackExec", "CpuOpBridgeExec"):
        return
    reasons = promised.get(id(getattr(node, "plan", None)))
    if reasons is not None and not reasons:
        out.append(Violation(
            name, path,
            "subtree fell back to CPU although tagging recorded no "
            "will-not-work reason (conversion contradicts the promise)"))


# ---------------------------------------------------------------------------
# Enforcement policy (the one production entry point; tests exercise it)
# ---------------------------------------------------------------------------

def format_violations(violations: List[Violation]) -> str:
    lines = ["! plan-contract violations "
             f"({len(violations)}; see docs/analysis.md):"]
    lines += [f"  ! contract: {v}" for v in violations]
    return "\n".join(lines)


def validate_cached_binding(root, params, validated_dtypes,
                            mode: str) -> Tuple[bool, List[Violation]]:
    """Cache-hit validation policy for the parameterized-plan cache
    (plan/plan_cache.py): the validated-plan status RIDES the cache
    entry, so a hit skips the full :func:`validate_plan` walk — as long
    as every runtime parameter still binds the dtype the entry was
    validated with. A parameter substitution that drifts a slot's dtype
    invalidates that status: the bound references the fused programs
    were compiled against would read values of another type, so the
    FULL walk re-runs, prefixed with one violation per drifted slot.

    Returns ``(revalidated, violations)``; raises
    :class:`PlanContractError` in ``error`` mode when drift is found
    (same policy as :func:`enforce`)."""
    mode = (mode or "warn").lower()
    if mode == "off":
        return False, []
    drifted: List[Violation] = []
    for p, want in zip(params, validated_dtypes):
        try:
            have = p.dtype
        except Exception:
            have = None
        if have != want:
            drifted.append(Violation(
                type(root).__name__, type(root).__name__,
                f"parameter :{p.param_name or p.slot} rebound as "
                f"{have} but the plan was validated with {want}; "
                "re-running full plan validation"))
    if not drifted:
        return False, []                 # the hit skips re-validation
    violations = drifted + validate_plan(root)
    diag = format_violations(violations)
    if mode == "error":
        raise PlanContractError(diag)
    logger.warning(
        "parameter dtype drift on a cached plan re-triggered "
        "validation:\n%s", diag)
    return True, violations


_warned_once = False


def validate_replan(root, mode: str) -> List[Violation]:
    """Re-validate a RUNTIME re-planned subtree (plan/aqe.py): an AQE
    coalesce/split/join-switch replacement must satisfy the same
    contracts the planner's original tree did — a silent co-partitioning
    or schema break here would produce wrong rows, not a crash. Same
    policy knob as plan-time validation
    (``spark.rapids.tpu.sql.analysis.validatePlan``): ``off`` skips,
    ``warn`` logs, ``error`` raises :class:`PlanContractError` before
    the replacement executes."""
    mode = (mode or "warn").lower()
    if mode == "off":
        return []
    violations = validate_plan(root)
    if not violations:
        return []
    diag = ("! AQE re-planned stage failed contract validation\n"
            + format_violations(violations))
    if mode == "error":
        raise PlanContractError(diag)
    logger.warning("%s", diag)
    return violations


def enforce(root, meta, mode: str
            ) -> Tuple[Optional[str], List[Violation]]:
    """Run validation per ``mode``: returns ``(diagnostic text to append
    to the explain output or None when clean/off, the violations
    themselves)`` — the structured list is what EXPLAIN ANALYZE attaches
    per node (matched on the root->node path); raises
    :class:`PlanContractError` in ``error`` mode."""
    mode = (mode or "warn").lower()
    if mode == "off":
        return None, []
    violations = validate_plan(root, meta)
    if not violations:
        return None, []
    diag = format_violations(violations)
    if mode == "error":
        raise PlanContractError(diag)
    global _warned_once
    if not _warned_once:
        _warned_once = True
        logger.warning(
            "plan-contract validation found violations (set "
            "spark.rapids.tpu.sql.analysis.validatePlan=error to reject, "
            "off to silence):\n%s", diag)
    else:
        logger.debug("plan-contract violations:\n%s", diag)
    return diag, violations
