"""Static nondeterminism linter: the lexical half of the engine's
lockstep-determinism discipline (``analysis/divergence.py`` is the
runtime half).

The standalone distributed mode has no driver: every worker executes the
same query sequence and independently mints identical shuffle ids, stage
ids and plan decisions (the lockstep contract, shuffle/manager.py). Any
nondeterminism on that path — wall-clock values feeding ids, unseeded
random, set-iteration order feeding an ordered decision, an unsorted
directory scan — silently diverges the workers' streams, and divergence
pairs WRONG shuffles before the fingerprint handshake can catch every
case. These rules make those sources loud at lint time.

Scope — the lockstep-reachable modules: ``shuffle/``, ``parallel/``,
``plan/`` and ``exec/query_context.py`` (the query/stage id mint).
Pure AST + text; no engine import.

Rules (all wired into ``python -m tools.lint``, tier-1-enforced):

``nondet-clock``
    A wall-clock read (``time.time/time_ns/perf_counter/monotonic/...``)
    whose value feeds an id-ish sink: an assignment target or a callee
    whose name matches id/seq/seed/key/fingerprint/digest. Clocks are
    fine for deadlines and timings — they must never mint identity or
    drive a plan decision both workers replay.

``nondet-random``
    A module-global ``random.*`` call (unseeded process RNG). Lockstep
    code that needs randomness must derive it from shared state via
    ``random.Random(seed)``.

``nondet-set-order``
    Direct iteration over a ``set``/``frozenset`` expression (``for``
    loop, or ``list/tuple/enumerate`` over one) — set order varies per
    process (hash seeding), so an ordered decision built from it
    diverges. Wrap in ``sorted(...)``.

``nondet-scan``
    An ``os.listdir``/``os.scandir``/``glob.glob``/``glob.iglob`` call
    not directly wrapped in ``sorted(...)`` — directory order is
    filesystem-dependent, so replaying workers see different orders.

``lockstep-id``
    A monotonic id source (an ``itertools.count(...)`` binding, or a
    manual ``_next*``/``*_seq``/``*_counter`` increment) in a scoped
    module whose canonical name is NOT declared in :data:`LOCKSTEP_IDS`.
    Every process-global id stream the lockstep contract leans on must
    be declared here and minted through its one audited funnel; the
    cross-module registry check also flags declared entries that no
    longer exist in the tree (stale registry).

Suppression mirrors the concurrency linter — ONE pragma tag for the
whole family, reason mandatory, on the flagged line or the line above::

    seq = self._conn_seq        # lint: nondeterminism-ok <why lockstep-safe>

Reason-less pragmas are themselves flagged (``pragma-reason``) and do
not suppress.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .lint import LintViolation

SCOPE_PREFIXES = ("shuffle/", "parallel/", "plan/")
SCOPE_FILES = ("exec/query_context.py",)

#: Every process-global monotonic id stream the lockstep contract relies
#: on, by canonical name (``<module>.<Class>.<attr>`` with ``/`` -> ``.``
#: and the class omitted for module-level bindings). A mint site in a
#: scoped module that is not declared here fails lint (``lockstep-id``);
#: a declared entry with no mint site in the tree fails too. Keep each
#: stream behind ONE audited funnel:
#:
#: * ``_QUERY_SEQ`` — the query-id counter (``mint_query_id``): workers
#:   running the same query sequence draw the same values, and every
#:   other id below namespaces on it.
#: * ``QueryContext._stage_seq`` — per-query exchange-boundary stage ids
#:   (``next_stage_id``), deterministic on the driving thread.
#: * ``WorkerContext._next_by_ns`` — per-query-NAMESPACE shuffle-id
#:   counters (``next_shuffle_id``): ids are ``(query seq << NS_SHIFT) +
#:   n``, so two concurrent distributed queries mint disjoint streams
#:   (docs/shuffle.md).
LOCKSTEP_IDS: Tuple[str, ...] = (
    "exec.query_context._QUERY_SEQ",
    "exec.query_context.QueryContext._stage_seq",
    "shuffle.manager.WorkerContext._next_by_ns",
)

PRAGMA_RE = re.compile(r"#\s*lint:\s*(nondeterminism)-ok(.*)$")

#: assignment targets / callees a clock value must not feed
ID_SINK_RE = re.compile(r"(?i)(?:^|_)(id|ids|seq|seed|key|keys|"
                        r"fingerprint|digest)s?$|mint")

CLOCK_FNS = {"time", "time_ns", "perf_counter", "perf_counter_ns",
             "monotonic", "monotonic_ns"}
RANDOM_FNS = {"random", "randint", "randrange", "choice", "choices",
              "shuffle", "sample", "uniform", "getrandbits", "randbytes"}
SCAN_FNS = {("os", "listdir"), ("os", "scandir"),
            ("glob", "glob"), ("glob", "iglob")}

#: manual monotonic-counter naming convention (rule ``lockstep-id``)
COUNTER_NAME_RE = re.compile(r"^_?next(_|$)|_next$|_seq$|_counter$")


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in SCOPE_FILES


def _pragmas(source: str) -> Dict[int, str]:
    """line -> reason (possibly empty) for nondeterminism-ok pragmas."""
    out: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = PRAGMA_RE.search(line)
        if m:
            out[i] = m.group(2).strip()
    return out


def _terminal_name(target: ast.AST) -> Optional[str]:
    """The terminal bound name, unwrapping subscripts: ``a``, ``x.a``
    and ``x.a[k]`` all yield ``a`` (a keyed counter dict is still one
    counter stream)."""
    if isinstance(target, ast.Subscript):
        return _terminal_name(target.value)
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def _dotted(func: ast.AST) -> Optional[Tuple[str, str]]:
    """('base', 'attr') for a one-level dotted callee like time.time."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (func.value.id, func.attr)
    return None


def _is_clock_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    # accept the `import time as _time` alias convention too
    return d is not None and d[1] in CLOCK_FNS and \
        d[0].lstrip("_") == "time"


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and \
            node.func.id in ("set", "frozenset"):
        return True
    return False


def _is_count_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if d == ("itertools", "count"):
        return True
    return isinstance(node.func, ast.Name) and node.func.id == "count"


@dataclass
class IdSite:
    """One monotonic-id mint site (the LOCKSTEP_IDS registry entry)."""
    path: str
    rel: str
    line: int
    kind: str             # 'itertools.count' or 'counter'
    canonical: str        # module-qualified declared name


def _module_of(rel: str) -> str:
    return rel[:-3].replace("/", ".") if rel.endswith(".py") else \
        rel.replace("/", ".")


def _class_ctx(tree: ast.Module) -> Dict[ast.AST, Optional[str]]:
    """node -> innermost enclosing class name (None at module level)."""
    ctx: Dict[ast.AST, Optional[str]] = {}

    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            c = child.name if isinstance(child, ast.ClassDef) else cls
            ctx[child] = c
            walk(child, c)
    walk(tree, None)
    return ctx


def _id_sites(tree: ast.Module, rel: str, path: str) -> List[IdSite]:
    """Every monotonic-id mint site in one module: itertools.count
    bindings plus manual counter increments (``x += n`` or
    ``x = x + n``-shaped rebinding of a ``_next*``/``*_seq``/
    ``*_counter`` name)."""
    mod = _module_of(rel)
    ctx = _class_ctx(tree)
    sites: List[IdSite] = []
    seen: Set[str] = set()

    def canonical(node: ast.AST, name: str) -> str:
        cls = ctx.get(node)
        return f"{mod}.{cls}.{name}" if cls else f"{mod}.{name}"

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if _is_count_call(node.value):
                for t in node.targets:
                    name = _terminal_name(t)
                    if name is None:
                        continue
                    sites.append(IdSite(path, rel, node.lineno,
                                        "itertools.count",
                                        canonical(node, name)))
            else:
                # manual counter advance: `self._next_x[...] = v + 1`
                for t in node.targets:
                    name = _terminal_name(t)
                    if name is None or not COUNTER_NAME_RE.search(name):
                        continue
                    if isinstance(node.value, ast.BinOp) and \
                            isinstance(node.value.op, ast.Add):
                        can = canonical(node, name)
                        if can not in seen:
                            seen.add(can)
                            sites.append(IdSite(path, rel, node.lineno,
                                                "counter", can))
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.op, ast.Add):
            name = _terminal_name(node.target)
            if name is not None and COUNTER_NAME_RE.search(name):
                can = canonical(node, name)
                if can not in seen:
                    seen.add(can)
                    sites.append(IdSite(path, rel, node.lineno,
                                        "counter", can))
    return sites


def _nondet_hits(tree: ast.Module) -> List[Tuple[int, str, str]]:
    """(line, rule, message) hits for the per-module value rules."""
    hits: List[Tuple[int, str, str]] = []

    # nondet-scan: collect scan calls, exempt the ones directly under
    # sorted(...)
    scan_calls: Dict[ast.AST, Tuple[int, str]] = {}
    exempt: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None and (d[0].lstrip("_"), d[1]) in SCAN_FNS:
                scan_calls[node] = (node.lineno, f"{d[0]}.{d[1]}")
            if isinstance(node.func, ast.Name) and \
                    node.func.id == "sorted" and node.args:
                exempt.add(node.args[0])
    for call, (line, name) in scan_calls.items():
        if call not in exempt:
            hits.append((
                line, "nondet-scan",
                f"{name}() order is filesystem-dependent — lockstep "
                "workers replaying this scan see different orders; wrap "
                "in sorted(...) (or pragma `# lint: nondeterminism-ok "
                "<reason>`)"))

    for node in ast.walk(tree):
        # nondet-random: module-global RNG
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None and d[0] == "random" and d[1] in RANDOM_FNS:
                hits.append((
                    node.lineno, "nondet-random",
                    f"random.{d[1]}() draws from the unseeded process "
                    "RNG — lockstep workers diverge; derive a "
                    "random.Random(seed) from shared state (or pragma "
                    "`# lint: nondeterminism-ok <reason>`)"))
            # clock value as argument to an id-ish callee
            if _dotted(node.func) is not None or \
                    isinstance(node.func, ast.Name):
                callee = node.func.attr \
                    if isinstance(node.func, ast.Attribute) \
                    else node.func.id
                if ID_SINK_RE.search(callee):
                    for arg in list(node.args) + \
                            [k.value for k in node.keywords]:
                        for sub in ast.walk(arg):
                            if _is_clock_call(sub):
                                hits.append((
                                    sub.lineno, "nondet-clock",
                                    "wall-clock value feeds "
                                    f"{callee}(...) — clocks must never "
                                    "mint lockstep identity (or pragma "
                                    "`# lint: nondeterminism-ok "
                                    "<reason>`)"))

        # nondet-clock: clock value assigned to an id-ish name
        if isinstance(node, ast.Assign):
            sink = None
            for t in node.targets:
                name = _terminal_name(t)
                if name is not None and ID_SINK_RE.search(name):
                    sink = name
                    break
            if sink is not None:
                for sub in ast.walk(node.value):
                    if _is_clock_call(sub):
                        hits.append((
                            sub.lineno, "nondet-clock",
                            f"wall-clock value assigned to {sink!r} — "
                            "clocks must never mint lockstep identity "
                            "(or pragma `# lint: nondeterminism-ok "
                            "<reason>`)"))
                        break

        # nondet-set-order
        set_iter = None
        if isinstance(node, ast.For) and _is_set_expr(node.iter):
            set_iter = node.iter
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in ("list", "tuple", "enumerate") and \
                node.args and _is_set_expr(node.args[0]):
            set_iter = node.args[0]
        if set_iter is not None:
            hits.append((
                set_iter.lineno, "nondet-set-order",
                "set/frozenset iteration order varies per process (hash "
                "seeding) — an ordered lockstep decision built from it "
                "diverges; wrap in sorted(...) (or pragma "
                "`# lint: nondeterminism-ok <reason>`)"))
    return hits


def lint_source(source: str, rel: str, path: Optional[str] = None
                ) -> List[LintViolation]:
    """Determinism rules over one module (``rel`` relative to the
    package root). Returns [] for out-of-scope modules."""
    path = path or rel
    if not in_scope(rel):
        return []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []                      # lint.py already reports parse errors
    pragmas = _pragmas(source)
    out: List[LintViolation] = []

    for line, reason in pragmas.items():
        if not reason:
            out.append(LintViolation(
                path, line, "pragma-reason",
                "nondeterminism-ok pragma missing its justification "
                "(format: `# lint: nondeterminism-ok <reason>`)"))

    hits = _nondet_hits(tree)
    for site in _id_sites(tree, rel, path):
        if site.canonical not in LOCKSTEP_IDS:
            hits.append((
                site.line, "lockstep-id",
                f"monotonic id source {site.canonical!r} ({site.kind}) "
                "is not declared in analysis/determinism.LOCKSTEP_IDS — "
                "every process-global id stream must be declared and "
                "minted through one audited funnel (or pragma "
                "`# lint: nondeterminism-ok <reason>`)"))

    for line, rule, msg in sorted(hits):
        suppressed = any(
            ln in pragmas and pragmas[ln]
            for ln in (line, line - 1))
        if not suppressed:
            out.append(LintViolation(path, line, rule, msg))
    return out


# ---------------------------------------------------------------------------
# Cross-module registry
# ---------------------------------------------------------------------------

def id_registry(package_dir: str) -> List[IdSite]:
    """Every monotonic-id mint site in the scoped modules (the
    LOCKSTEP_IDS registry's ground truth)."""
    sites: List[IdSite] = []
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, package_dir).replace(os.sep, "/")
            if not in_scope(rel):
                continue
            with open(full, "r") as f:
                src = f.read()
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
            sites.extend(_id_sites(tree, rel, full))
    return sites


def check_registry(sites: List[IdSite],
                   declared: Tuple[str, ...] = LOCKSTEP_IDS
                   ) -> List[LintViolation]:
    """Registry drift, the direction per-module linting cannot see: a
    LOCKSTEP_IDS entry whose mint site no longer exists in the tree.
    (Undeclared sites are flagged per-module by ``lint_source``.)"""
    out: List[LintViolation] = []
    found = {s.canonical for s in sites}
    for name in declared:
        if name not in found:
            out.append(LintViolation(
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "determinism.py"),
                0, "lockstep-id",
                f"LOCKSTEP_IDS declares {name!r} but no mint site for it "
                "exists in the scoped modules — stale registry entry"))
    return out
