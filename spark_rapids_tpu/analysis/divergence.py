"""Runtime cross-worker divergence audit: the runtime half of the
lockstep-determinism discipline (``analysis/determinism.py`` is the
static half).

The standalone distributed mode has no driver: every worker executes the
same query sequence and independently mints identical shuffle ids, stage
ids and plan decisions (the lockstep contract, shuffle/manager.py). When
that contract silently breaks, workers pair WRONG shuffles — wrong rows,
no error. The per-exchange fingerprint handshake catches id-stream
skew at fetch time; this audit catches the divergence itself, names the
FIRST divergent event, and turns the failure mode loud.

Mechanism: each worker folds its lockstep-relevant event stream —
shuffle-id mints, exchange fingerprint registrations, stage-id draws,
AQE decision records — into a per-query rolling SHA-1 digest, keeping a
bounded ring of ``(index, prefix-digest, label)`` entries as the
diagnostic window. The digest snapshot rides the existing shuffle META
round trip (transport.py): every metadata reply carries the serving
worker's snapshot for the fetching query, and the fetching worker
compares rings entry-by-entry. Because each ring entry carries the
PREFIX digest after folding event ``i``, the first index where the two
rings disagree IS the first divergent event.

Modes (conf ``spark.rapids.tpu.sql.analysis.divergence``):

* ``off`` — no folding, no checks (the default; zero hot-path cost
  beyond one module-flag read).
* ``record`` — divergences are logged, flight-recorded (kind
  ``desync``) and counted in ``tpu_desync_total``; execution continues
  (the fingerprint handshake still fails hard where streams pair
  wrongly).
* ``enforce`` — a divergence raises :class:`DesyncError` naming the
  first divergent event; ``exec/recovery.classify`` maps it to
  FAIL_QUERY — a desync is never retried, retrying cannot un-diverge
  the streams.

Every comparison bumps ``tpu_divergence_checks_total``. The chaos
harness point ``desync.inject`` (analysis/faults.py) folds one poisoned
event into THIS worker's stream, driving the full detection path
deterministically in tests.

A worker being BEHIND is not divergence: rings are compared only on the
indexes both sides retain, and a clean shared prefix with unequal counts
just means one side has not folded the later events yet.
"""

from __future__ import annotations

import hashlib
import logging
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from .lockdep import named_lock

log = logging.getLogger("spark_rapids_tpu.divergence")

MODES = ("off", "record", "enforce")

#: diagnostic window per query stream (events beyond it fold into the
#: rolling digest but lose their per-event diagnosis)
RING_CAPACITY = 64

#: bounded per-process query-stream table (oldest query evicted)
_MAX_QUERIES = 32


class DesyncError(RuntimeError):
    """Lockstep divergence between this worker and a peer, detected by
    the per-query digest audit. Deliberately NOT a ShuffleFetchError:
    every transport/stage retry ladder lets it propagate un-retried, and
    ``exec/recovery.classify`` maps it to FAIL_QUERY.

    Attributes carry the diagnosis the flight-recorder dump scopes on:
    ``query_id``, ``first_divergent_index`` (-1 when the streams
    diverged before the diagnostic window), and ``mine``/``theirs`` —
    each the ``(prefix_digest, label)`` pair at that index."""

    def __init__(self, message: str, *, query_id: Optional[str] = None,
                 index: Optional[int] = None,
                 mine: Optional[Any] = None,
                 theirs: Optional[Any] = None):
        super().__init__(message)
        self.query_id = query_id
        self.first_divergent_index = index
        self.mine = mine
        self.theirs = theirs


class _QueryStream:
    """One query's rolling digest + bounded diagnostic ring."""

    __slots__ = ("count", "sha", "ring")

    def __init__(self) -> None:
        self.count = 0
        self.sha = hashlib.sha1()
        self.ring: deque = deque(maxlen=RING_CAPACITY)

    def fold(self, label: str) -> None:
        self.count += 1
        self.sha.update(label.encode("utf-8", "replace"))
        self.sha.update(b"\x00")
        # the PREFIX digest after event `count`: comparing ring entries
        # at the same index compares whole prefixes, so the first
        # disagreeing index is the first divergent event
        self.ring.append((self.count, self.sha.hexdigest()[:8], label))

    @property
    def digest(self) -> str:
        return self.sha.hexdigest()[:16]


# ---------------------------------------------------------------------------
# Process-global mode + per-query streams
# ---------------------------------------------------------------------------

_mu = named_lock("analysis.divergence._mu")
_mode = "off"
_streams: "OrderedDict[str, _QueryStream]" = OrderedDict()
_checks_total = 0
_desyncs_total = 0
#: lock-free fast-path flag (the faults.ARMED pattern): read per mint on
#: hot paths, written under ``_mu`` only; a stale read costs one no-op
ARMED = False


def install(mode: str) -> None:
    """Set the audit mode directly (tests; sessions prime via
    :func:`refresh`)."""
    global _mode, ARMED
    m = str(mode or "off").lower()
    if m not in MODES:
        raise ValueError(f"unknown divergence mode {m!r} (want {MODES})")
    with _mu:
        _mode = m
        ARMED = m != "off"


def mode() -> str:
    return _mode


def armed() -> bool:
    return ARMED


def refresh(conf=None) -> None:
    """Prime the mode from a session conf (session bootstrap calls this
    eagerly, the faults/telemetry pattern)."""
    from .. import config as cfg
    conf = conf or cfg.TpuConf()
    install(str(conf.get(cfg.ANALYSIS_DIVERGENCE)))


def reset() -> None:
    """Disarm and drop every query stream + counter (test isolation)."""
    global _mode, ARMED, _checks_total, _desyncs_total
    with _mu:
        _mode = "off"
        ARMED = False
        _streams.clear()
        _checks_total = 0
        _desyncs_total = 0


def stats() -> Dict[str, Any]:
    """Per-process audit counters (the bench runner's summary line)."""
    with _mu:
        return {"mode": _mode, "checks": _checks_total,
                "desyncs": _desyncs_total, "queries": len(_streams)}


# ---------------------------------------------------------------------------
# Folding (the mint-site hooks call this)
# ---------------------------------------------------------------------------

def note_event(label: str, query_id: Optional[str] = None) -> None:
    """Fold one lockstep-relevant event into the ambient (or named)
    query's stream. No-op when the audit is off or no query is active —
    the call sites stay unconditional."""
    if not ARMED:
        return
    if query_id is None:
        from ..exec.query_context import current_query_id
        query_id = current_query_id()
    if query_id is None:
        return
    # chaos hook: fold ONE poisoned event into THIS worker's stream
    # before the real one — the peers' digests now disagree at exactly
    # this index, driving the full detection path deterministically
    from . import faults
    inject = faults.armed() and faults.fire("desync.inject")
    with _mu:
        st = _streams.get(query_id)
        if st is None:
            st = _streams[query_id] = _QueryStream()
            while len(_streams) > _MAX_QUERIES:
                _streams.popitem(last=False)
        if inject:
            st.fold("fault:desync.inject")
        st.fold(label)


def snapshot(query_id: Optional[str]) -> Optional[Dict[str, Any]]:
    """This worker's digest snapshot for ``query_id`` — what a metadata
    reply carries back to the fetching peer. A query this worker has not
    folded yet snapshots as the empty stream (the peer sees no common
    window and treats it as lag, not divergence)."""
    if not ARMED or not query_id:
        return None
    with _mu:
        st = _streams.get(query_id)
        if st is None:
            return {"count": 0, "digest": "", "ring": []}
        return {"count": st.count, "digest": st.digest,
                "ring": [list(e) for e in st.ring]}


# ---------------------------------------------------------------------------
# Comparison (the fetching client calls this on every metadata reply)
# ---------------------------------------------------------------------------

def check(query_id: Optional[str], peer: Optional[Dict[str, Any]],
          peer_label: str = "peer") -> None:
    """Compare this worker's stream for ``query_id`` against a peer
    snapshot. Divergence: ``record`` logs/counts, ``enforce`` raises
    :class:`DesyncError` naming the first divergent event. Lag (a clean
    shared prefix with unequal counts) passes."""
    global _checks_total, _desyncs_total
    if not ARMED or not query_id or not peer:
        return
    with _mu:
        _checks_total += 1
        st = _streams.get(query_id)
        mine_count = st.count if st is not None else 0
        mine_digest = st.digest if st is not None else ""
        mine_ring = list(st.ring) if st is not None else []
    try:
        from ..service.telemetry import MetricsRegistry
        MetricsRegistry.get().counter(
            "tpu_divergence_checks_total",
            "lockstep divergence digest comparisons").inc()
    except Exception:
        pass                     # telemetry must never change the audit
    if st is None:
        return                   # nothing folded locally yet: pure lag
    ours = {int(i): (d, l) for i, d, l in mine_ring}
    theirs = {int(i): (d, l) for i, d, l in (peer.get("ring") or ())}
    first = None
    for i in sorted(set(ours) & set(theirs)):
        if ours[i][0] != theirs[i][0]:
            first = i
            break
    if first is None:
        peer_count = int(peer.get("count") or 0)
        peer_digest = str(peer.get("digest") or "")
        if peer_count == mine_count and peer_digest and \
                mine_digest != peer_digest:
            # same length, same retained window, different digests: the
            # divergence predates the diagnostic ring
            first = -1
        else:
            return               # in sync, or one side merely behind
    mine_at = ours.get(first)
    theirs_at = theirs.get(first)
    if first >= 0:
        msg = (f"lockstep streams diverged on query {query_id} at event "
               f"#{first}: this worker folded {mine_at[1]!r}, "
               f"{peer_label} folded {theirs_at[1]!r}")
    else:
        msg = (f"lockstep streams diverged on query {query_id} before "
               f"the {RING_CAPACITY}-event diagnostic window (digest "
               f"{mine_digest} vs {peer.get('digest')}); re-run with "
               "the audit armed from query start for the first event")
    with _mu:
        _desyncs_total += 1
    try:
        from ..service.telemetry import MetricsRegistry, flight_record
        flight_record("desync", query_id, {
            "index": first, "peer": peer_label,
            "mine": list(mine_at) if mine_at else None,
            "theirs": list(theirs_at) if theirs_at else None})
        MetricsRegistry.get().counter(
            "tpu_desync_total",
            "lockstep divergences detected by the digest audit").inc()
    except Exception:
        pass
    if _mode == "enforce":
        raise DesyncError(msg, query_id=query_id, index=first,
                          mine=mine_at, theirs=theirs_at)
    log.warning("%s (divergence=record: continuing)", msg)
