"""Deterministic, conf-gated fault injection (the chaos harness).

The reference validates failure semantics against Spark's scheduler with
mocked transports (SURVEY.md §4 ring-1); standalone, recovery paths are
unreachable from tests unless the engine can *inject* the failures it
recovers from. This module is that harness: a handful of named injection
points wired into the shuffle/transport/task hot paths, armed by conf
``spark.rapids.tpu.sql.faults.spec`` (or :func:`install` directly), each
firing a bounded, deterministic number of times — counts, never
probabilities, so a chaos test is exactly reproducible.

Spec grammar (see docs/resilience.md)::

    spec     := clause (';' clause)*
    clause   := point [':' count] ['@' selector]
    point    := fetch.fail | conn.kill | task.poison | worker.die
              | mesh.drop | desync.inject | cancel.inject
              | preempt.inject
    count    := positive int, default 1 — firings before the clause
                disarms
    selector := 'p<pid>' ['b<batch>'] | 'b<batch>'   (task.poison)
              | '<n>'                                 (conn.kill: kill
                after n chunks of a send window, default 1)

Points and where they fire:

* ``fetch.fail`` — a shuffle fetch attempt raises an injected
  ConnectionError before touching the wire (transport client) or an
  injected ShuffleFetchError before the local pull (exchange reduce
  read) — the "fail a fetch on first attempt" probe.
* ``conn.kill`` — the transfer server tears the connection mid send
  window after ``n`` chunks (torn stream on the fetching client).
* ``task.poison`` — a partition task body raises
  :class:`~spark_rapids_tpu.exec.recovery.InjectedTaskFault`; with a
  ``b<batch>`` selector the exchange map loop poisons exactly batch N.
* ``worker.die`` — the shuffle server drops the next incoming
  connection unserved; registered :func:`on_fire` callbacks let a test
  or bench stop (and later restart) the server at that exact protocol
  point — a deterministic worker death.
* ``mesh.drop`` — the next exchange plane resolution sees the ICI mesh
  as having lost a participant (``exec/recovery.note_mesh_lost``) and
  declines gracefully to DCN.
* ``desync.inject`` — the divergence audit (analysis/divergence.py)
  folds one poisoned event into THIS worker's lockstep stream before
  its next real event: the peers' per-query digests now disagree at
  exactly that index, driving the full desync detection path
  (DesyncError with first-divergent-event diagnosis) deterministically.
* ``cancel.inject`` — the next ambient cancel poll
  (``exec/lifecycle.check_cancel``) cancels the polling query, driving
  the full cooperative-cancellation unwind (FAIL_QUERY, ledger-audited
  cleanup) without a second thread racing the poll.
* ``preempt.inject`` — the next ambient cancel poll requests suspension
  of the polling query: under the service the worker loop parks the
  ticket (spill + stage cursor); a direct collect fails loudly — there
  is no scheduler to park under (docs/service.md).

Every firing lands in the flight recorder (kind ``fault``) and bumps
``tpu_faults_injected_total``, so a recovery post-mortem shows the
injected cause right next to the recovery it triggered.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

from .lockdep import named_lock

POINTS = ("fetch.fail", "conn.kill", "task.poison", "worker.die",
          "mesh.drop", "desync.inject", "cancel.inject",
          "preempt.inject")

_CLAUSE_RE = re.compile(
    r"^(?P<point>[a-z.]+)(?::(?P<count>\d+))?(?:@(?P<sel>[a-z0-9]+))?$")
_TASK_SEL_RE = re.compile(r"^(?:p(?P<pid>\d+))?(?:b(?P<batch>\d+))?$")


class FaultSpecError(ValueError):
    """The faults.spec string does not parse — raised loudly at install
    (a chaos run with a typo'd spec must not silently run fault-free)."""


class _Fault:
    """One armed clause: remaining firings + optional selector."""

    def __init__(self, point: str, count: int,
                 pid: Optional[int] = None, batch: Optional[int] = None,
                 after: Optional[int] = None):
        self.point = point
        self.remaining = count
        self.pid = pid
        self.batch = batch
        self.after = after          # conn.kill: chunks before the kill

    def matches(self, pid=None, batch=None, chunk=None) -> bool:
        if self.pid is not None and pid != self.pid:
            return False
        if self.batch is not None and batch != self.batch:
            return False
        if self.point == "conn.kill":
            want = self.after if self.after is not None else 1
            if chunk is None or chunk < want:
                return False
        return True


def parse_spec(spec: str) -> List[_Fault]:
    """Parse the spec grammar into armed clauses; bad specs raise
    :class:`FaultSpecError` naming the offending clause."""
    out: List[_Fault] = []
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        m = _CLAUSE_RE.match(raw)
        if not m:
            raise FaultSpecError(f"unparseable faults clause {raw!r} "
                                 "(grammar: point[:count][@selector])")
        point = m.group("point")
        if point not in POINTS:
            raise FaultSpecError(
                f"unknown fault point {point!r} (known: {POINTS})")
        count = int(m.group("count") or 1)
        if count < 1:
            raise FaultSpecError(f"fault count must be >= 1 in {raw!r}")
        sel = m.group("sel")
        pid = batch = after = None
        if sel is not None:
            if point == "task.poison":
                sm = _TASK_SEL_RE.match(sel)
                if not sm or (sm.group("pid") is None and
                              sm.group("batch") is None):
                    raise FaultSpecError(
                        f"bad task.poison selector {sel!r} "
                        "(expect p<pid>, b<batch> or p<pid>b<batch>)")
                pid = int(sm.group("pid")) if sm.group("pid") else None
                batch = int(sm.group("batch")) if sm.group("batch") \
                    else None
            elif point == "conn.kill":
                if not sel.isdigit():
                    raise FaultSpecError(
                        f"bad conn.kill selector {sel!r} (expect the "
                        "chunk count to survive before the kill)")
                after = int(sel)
            else:
                raise FaultSpecError(
                    f"fault point {point} takes no selector ({raw!r})")
        out.append(_Fault(point, count, pid=pid, batch=batch, after=after))
    return out


# ---------------------------------------------------------------------------
# Process-global armed plan
# ---------------------------------------------------------------------------

_mu = named_lock("analysis.faults._mu")
_plan: List[_Fault] = []
_callbacks: Dict[str, List[Callable]] = {}
_fired_total = 0
#: lock-free fast-path flag read on hot paths (map-task batch loops):
#: True only while at least one clause is armed. Written under ``_mu``
#: only; a stale read costs one extra locked check, never a missed fire.
ARMED = False


def install(spec: str) -> int:
    """Arm the harness from a spec string (replacing any prior plan and
    zeroing :func:`fired_total` — counts are per armed plan, so a chaos
    test asserts exact firing counts); returns the number of armed
    clauses. ``install("")`` disarms."""
    global _plan, ARMED, _fired_total
    clauses = parse_spec(spec)
    with _mu:
        _plan = clauses
        _fired_total = 0
        ARMED = bool(clauses)
    return len(clauses)


#: the mesh-loss reason an injected mesh.drop records — reset() only
#: clears THIS loss (a real topology loss must survive a harness reset)
INJECTED_MESH_DROP_REASON = "injected mesh drop (faults.spec)"


def reset() -> None:
    """Disarm every clause, drop registered callbacks, and undo the one
    fault effect that outlives its firing: an injected mesh drop
    (tests / bench teardown — chaos must never leak downstream)."""
    global _plan, ARMED, _fired_total
    with _mu:
        _plan = []
        _callbacks.clear()
        _fired_total = 0
        ARMED = False
    from ..exec import recovery
    if recovery.mesh_lost() == INJECTED_MESH_DROP_REASON:
        recovery.clear_mesh_lost()


def refresh(conf=None) -> None:
    """Prime the harness from a session conf (session bootstrap calls
    this eagerly, the telemetry/lockdep pattern)."""
    from .. import config as cfg
    conf = conf or cfg.TpuConf()
    install(str(conf.get(cfg.FAULTS_SPEC)))


def armed() -> bool:
    return ARMED


def on_fire(point: str, callback: Callable[[], None]) -> None:
    """Register a callback run (outside the plan lock) when ``point``
    fires — the hook a chaos test uses to stop a server at the exact
    injected protocol point. Callback errors are swallowed: a broken
    chaos hook must not change the failure being injected."""
    if point not in POINTS:
        raise FaultSpecError(f"unknown fault point {point!r}")
    with _mu:
        _callbacks.setdefault(point, []).append(callback)


def fired_total() -> int:
    with _mu:
        return _fired_total


def fire(point: str, pid=None, batch=None, chunk=None) -> bool:
    """True exactly when an armed clause for ``point`` matches the call
    context: decrements the clause, flight-records the firing, bumps
    ``tpu_faults_injected_total`` and runs registered callbacks. The
    injection site raises its fault when this returns True."""
    global _fired_total, ARMED
    if not ARMED:
        return False
    with _mu:
        hit = None
        for f in _plan:
            if f.point == point and f.remaining > 0 and \
                    f.matches(pid=pid, batch=batch, chunk=chunk):
                hit = f
                break
        if hit is None:
            return False
        hit.remaining -= 1
        _fired_total += 1
        ARMED = any(f.remaining > 0 for f in _plan)
        cbs = list(_callbacks.get(point, ()))
    # side effects OUTSIDE the plan lock: the flight recorder and the
    # metrics registry take their own (leaf) locks, and callbacks may
    # stop servers / join threads
    data = {k: v for k, v in
            (("pid", pid), ("batch", batch), ("chunk", chunk))
            if v is not None}
    from ..service.telemetry import MetricsRegistry, flight_record
    flight_record("fault", point, data or None)
    try:
        MetricsRegistry.get().counter(
            "tpu_faults_injected_total",
            "deterministic chaos-harness firings").inc()
    except Exception:
        pass                      # telemetry must never change the fault
    for cb in cbs:
        try:
            cb()
        except Exception:
            import logging
            logging.getLogger("spark_rapids_tpu.faults").exception(
                "faults.on_fire callback for %s failed", point)
    return True
