"""Runtime buffer-lifecycle ledger: the runtime half of the
device-memory ownership discipline (``analysis/ownership.py`` is the
static half).

Four subsystems transfer buffer ownership without a common audit trail:
fused-program donation (the consumed batch's arrays are dead after the
call), the 3-tier spill store (register/acquire/tier-move/remove), the
durable-shuffle disk pin, and the staging arena. When a hand-off goes
wrong the failure is silent — a leaked device buffer just narrows the
HBM budget until some innocent query pays the spill cascade, and a
freed buffer read back through jax surfaces as a bare "Array has been
deleted" with no owner, no site, no query. This ledger tags every
lifecycle event with the ambient query id and a compact allocation
site, so the failure modes become typed, attributed diagnoses — the
ASAN discipline applied to HBM residency.

Mechanism: ``exec/spill.py`` calls :func:`note_register` /
:func:`note_access` / :func:`note_tier` / :func:`note_free` at its
register/acquire/tier-flip/remove boundaries; donated fused calls mark
the consumed batch via :func:`mark_donated` and the batch's array
funnels call :func:`check_batch_access`. At collect end the driver
calls :func:`end_of_query`: buffers minted by that query and still
DEVICE-resident — excluding cache-priority registrations and
disk-pinned durable outputs, the two deliberate ownership transfers —
are leaks.

Modes (conf ``spark.rapids.tpu.sql.analysis.bufferLedger``):

* ``off`` — no tracking (the default; one module-flag read per hook).
* ``record`` — leaks and dead-buffer accesses are logged,
  flight-recorded and counted (``tpu_buffer_leaks_total``,
  ``tpu_use_after_free_total``); execution continues. The test suite
  and the bench runner run here (the lockdep precedent).
* ``enforce`` — a leak raises :class:`BufferLeakError` at collect end;
  an access to a freed/donated buffer raises
  :class:`UseAfterFreeError` / :class:`UseAfterDonateError` at the
  access site, with the mint/free sites in the message.

The ledger lock is a LEAF: no hook calls the catalog, telemetry or the
flight recorder while holding it (``end_of_query`` snapshots catalog
residency FIRST — the catalog's admission lock may itself be held
around ``note_tier``, so the reverse order would deadlock under
lockdep enforce).
"""

from __future__ import annotations

import logging
import os
import sys
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from .lockdep import named_lock

log = logging.getLogger("spark_rapids_tpu.ledger")

MODES = ("off", "record", "enforce")

#: bounded per-process tables (oldest evicted)
_MAX_QUERIES = 32
_MAX_TOMBSTONES = 4096

#: frames never named in an allocation site (the hook plumbing itself)
_SITE_SKIP = ("analysis/ledger.py", "exec/spill.py")


class BufferLifecycleError(RuntimeError):
    """Base of the ledger's typed diagnoses. Attributes carry what the
    flight-recorder dump scopes on: ``buffer_id``, ``query_id`` (the
    minting query), and ``site`` (the mint site)."""

    def __init__(self, message: str, *, buffer_id: Optional[int] = None,
                 query_id: Optional[str] = None,
                 site: Optional[str] = None):
        super().__init__(message)
        self.buffer_id = buffer_id
        self.query_id = query_id
        self.site = site


class BufferLeakError(BufferLifecycleError):
    """End-of-query residency audit: buffers minted by the finished
    query are still device-resident and not cache/durable-owned."""


class UseAfterFreeError(BufferLifecycleError):
    """A freed (tombstoned) buffer was accessed again."""


class UseAfterDonateError(BufferLifecycleError):
    """A batch whose arrays were donated to a fused program was read
    again — jax would surface this as a bare 'Array has been deleted'
    with no owner attribution."""


class DoubleFreeError(BufferLifecycleError):
    """An already-freed buffer was freed again."""


class _Entry:
    """One tracked buffer's provenance."""

    __slots__ = ("buffer_id", "query_id", "tenant", "site", "nbytes",
                 "priority", "tier", "free_site")

    def __init__(self, buffer_id: int, query_id: Optional[str],
                 tenant: Optional[str], site: str, nbytes: int,
                 priority: float, tier: str):
        self.buffer_id = buffer_id
        self.query_id = query_id
        self.tenant = tenant
        self.site = site
        self.nbytes = nbytes
        self.priority = priority
        self.tier = tier
        self.free_site: Optional[str] = None


# ---------------------------------------------------------------------------
# Process-global mode + tables
# ---------------------------------------------------------------------------

_mu = named_lock("analysis.ledger._mu")
_mode = "off"
#: live tracked buffers: buffer id -> entry (bounded by catalog size —
#: note_free moves entries to the tombstone ring)
_entries: Dict[int, _Entry] = {}
#: freed buffers kept for use-after-free attribution (bounded ring)
_tombstones: "OrderedDict[int, _Entry]" = OrderedDict()
#: per-query device-residency bookkeeping (bounded)
_queries: "OrderedDict[str, Dict[str, int]]" = OrderedDict()
_audits_total = 0
_leaks_total = 0
_uaf_total = 0
_uad_total = 0
_double_free_total = 0
_donations_total = 0
#: lock-free fast-path flag (the faults.ARMED pattern): read per hook on
#: hot paths, written under ``_mu`` only; a stale read costs one no-op
ARMED = False


def install(mode: str) -> None:
    """Set the ledger mode directly (tests; sessions prime via
    :func:`refresh`)."""
    global _mode, ARMED
    m = str(mode or "off").lower()
    if m not in MODES:
        raise ValueError(f"unknown bufferLedger mode {m!r} (want {MODES})")
    with _mu:
        _mode = m
        ARMED = m != "off"


def mode() -> str:
    return _mode


def armed() -> bool:
    return ARMED


def refresh(conf=None) -> None:
    """Prime the mode from a session conf (session bootstrap calls this
    eagerly, the divergence/faults pattern)."""
    from .. import config as cfg
    conf = conf or cfg.TpuConf()
    install(str(conf.get(cfg.ANALYSIS_BUFFER_LEDGER)))


def reset() -> None:
    """Disarm and drop every table + counter (test isolation)."""
    global _mode, ARMED, _audits_total, _leaks_total, _uaf_total
    global _uad_total, _double_free_total, _donations_total
    with _mu:
        _mode = "off"
        ARMED = False
        _entries.clear()
        _tombstones.clear()
        _queries.clear()
        _audits_total = 0
        _leaks_total = 0
        _uaf_total = 0
        _uad_total = 0
        _double_free_total = 0
        _donations_total = 0


def forget_all() -> None:
    """Drop the buffer tables but keep mode + counters: catalog reset is
    test teardown, not a free — tombstoning torn-down buffers would turn
    the next test's stale-handle probe into a false use-after-free."""
    with _mu:
        _entries.clear()
        _tombstones.clear()
        _queries.clear()


def stats() -> Dict[str, Any]:
    """Per-process ledger counters (the bench runner's summary line)."""
    with _mu:
        return {"mode": _mode, "tracked": len(_entries),
                "audits": _audits_total, "leaks": _leaks_total,
                "use_after_free": _uaf_total,
                "use_after_donate": _uad_total,
                "double_free": _double_free_total,
                "donations": _donations_total}


# ---------------------------------------------------------------------------
# Site capture
# ---------------------------------------------------------------------------

def _site(limit: int = 3) -> str:
    """Compact allocation site: the nearest ``limit`` package frames
    outside the hook plumbing, innermost first (``a.py:12 < b.py:88``).
    Cheap frame walk, no traceback object."""
    try:
        f = sys._getframe(2)
    except Exception:
        return ""
    parts: List[str] = []
    marker = "spark_rapids_tpu"
    while f is not None and len(parts) < limit:
        fn = f.f_code.co_filename
        i = fn.rfind(marker)
        if i >= 0:
            rel = fn[i + len(marker) + 1:].replace(os.sep, "/")
            if rel not in _SITE_SKIP:
                parts.append(f"{rel}:{f.f_lineno}")
        f = f.f_back
    return " < ".join(parts)


def _tier_name(tier: Any) -> str:
    return getattr(tier, "name", None) or str(tier)


def _q_locked(query_id: Optional[str]) -> Optional[Dict[str, int]]:
    """This query's bookkeeping row (caller holds ``_mu``)."""
    if not query_id:
        return None
    q = _queries.get(query_id)
    if q is None:
        q = _queries[query_id] = {"minted": 0, "freed": 0,
                                  "live_dev": 0, "peak_dev": 0}
        while len(_queries) > _MAX_QUERIES:
            _queries.popitem(last=False)
    return q


# ---------------------------------------------------------------------------
# Lifecycle hooks (exec/spill.py calls these; all no-ops when disarmed)
# ---------------------------------------------------------------------------

def note_register(buffer_id: int, nbytes: int, priority: float,
                  tenant: Optional[str], tier: Any = "DEVICE") -> None:
    """A buffer entered the catalog: tag it with the ambient query id
    and the registering call site."""
    if not ARMED:
        return
    from ..exec.query_context import current_query_id
    qid = current_query_id()
    site = _site()
    t = _tier_name(tier)
    with _mu:
        _entries[buffer_id] = _Entry(buffer_id, qid, tenant, site,
                                     int(nbytes), priority, t)
        q = _q_locked(qid)
        if q is not None:
            q["minted"] += 1
            if t == "DEVICE":
                q["live_dev"] += int(nbytes)
                q["peak_dev"] = max(q["peak_dev"], q["live_dev"])


def note_tier(buffer_id: int, tier: Any) -> None:
    """A tracked buffer changed storage tier (spill/promote/pin): keep
    the minting query's live/peak device bytes current."""
    if not ARMED:
        return
    t = _tier_name(tier)
    with _mu:
        e = _entries.get(buffer_id)
        if e is None:
            return
        prev, e.tier = e.tier, t
        if prev == t:
            return
        q = _queries.get(e.query_id) if e.query_id else None
        if q is not None:
            if prev == "DEVICE":
                q["live_dev"] -= e.nbytes
            if t == "DEVICE":
                q["live_dev"] += e.nbytes
                q["peak_dev"] = max(q["peak_dev"], q["live_dev"])


def note_access(buffer_id: int) -> None:
    """A buffer is being acquired: a tombstoned id is a use-after-free
    (typed + site-attributed, where jax would raise a bare deleted-array
    error or the catalog a plain KeyError)."""
    global _uaf_total
    if not ARMED:
        return
    with _mu:
        if buffer_id in _entries:
            return
        e = _tombstones.get(buffer_id)
        if e is None:
            return                  # pre-arming registration: unknown id
        _uaf_total += 1
        msg = (f"use-after-free: buffer {buffer_id} "
               f"({e.nbytes} bytes, minted by {e.query_id or '<no query>'} "
               f"at {e.site or '<unknown>'}) was freed at "
               f"{e.free_site or '<unknown>'} and accessed again at "
               f"{_site()}")
        qid, site = e.query_id, e.site
    _observe("use-after-free", f"buffer-{buffer_id}", msg,
             "tpu_use_after_free_total")
    if _mode == "enforce":
        raise UseAfterFreeError(msg, buffer_id=buffer_id, query_id=qid,
                                site=site)
    log.warning("%s (bufferLedger=record: continuing)", msg)


def note_free(buffer_id: int) -> None:
    """A buffer left the catalog: tombstone it so later accesses (and a
    second free) diagnose instead of reading garbage."""
    global _double_free_total
    if not ARMED:
        return
    with _mu:
        e = _entries.pop(buffer_id, None)
        if e is not None:
            if e.tier == "DEVICE":
                q = _queries.get(e.query_id) if e.query_id else None
                if q is not None:
                    q["live_dev"] -= e.nbytes
            if e.query_id:
                q = _queries.get(e.query_id)
                if q is not None:
                    q["freed"] += 1
            e.free_site = _site()
            e.tier = "FREED"
            _tombstones[buffer_id] = e
            while len(_tombstones) > _MAX_TOMBSTONES:
                _tombstones.popitem(last=False)
            return
        e = _tombstones.get(buffer_id)
        if e is None:
            return
        _double_free_total += 1
        msg = (f"double-free: buffer {buffer_id} (minted by "
               f"{e.query_id or '<no query>'} at {e.site or '<unknown>'}) "
               f"was freed at {e.free_site or '<unknown>'} and freed "
               f"again at {_site()}")
        qid, site = e.query_id, e.site
    _observe("double-free", f"buffer-{buffer_id}", msg,
             "tpu_use_after_free_total")
    if _mode == "enforce":
        raise DoubleFreeError(msg, buffer_id=buffer_id, query_id=qid,
                              site=site)
    log.warning("%s (bufferLedger=record: continuing)", msg)


# ---------------------------------------------------------------------------
# Donation tombstones (plan/physical + plan/stage_compiler call these)
# ---------------------------------------------------------------------------

def mark_donated(batch) -> None:
    """A fused program consumed ``batch``'s arrays at donated positions:
    tombstone the batch object so later reads through its array funnels
    diagnose as use-after-donate. Called only after a SUCCESSFUL donated
    invocation — the failure path's ``_donation_consumed`` probe must
    stay silent."""
    global _donations_total
    if not ARMED:
        return
    try:
        batch.donated = _site()
    except Exception:
        return                       # slots-less stand-ins: nothing to mark
    with _mu:
        _donations_total += 1


def check_batch_access(batch) -> None:
    """Array-funnel guard (``ColumnarBatch.flat_arrays``): reading a
    donated batch is a use-after-donate."""
    global _uad_total
    donated = getattr(batch, "donated", None)
    if donated is None or not ARMED:
        return
    with _mu:
        _uad_total += 1
    msg = (f"use-after-donate: batch donated to a fused program at "
           f"{donated} was read again at {_site()} — its device arrays "
           "are dead (donate_argnums)")
    _observe("use-after-donate", "batch", msg, "tpu_use_after_free_total")
    if _mode == "enforce":
        raise UseAfterDonateError(msg, site=donated)
    log.warning("%s (bufferLedger=record: continuing)", msg)


# ---------------------------------------------------------------------------
# End-of-query residency audit
# ---------------------------------------------------------------------------

def end_of_query(query_id: Optional[str],
                 had_error: bool = False) -> Optional[Dict[str, Any]]:
    """Audit the finished query's device residency: buffers it minted
    that are still DEVICE-resident and not deliberately transferred —
    cache-priority registrations (df.cache(), the scan device cache) and
    disk-pinned durable shuffle outputs — are leaks. Returns the
    per-query ledger summary (query log / EXPLAIN ANALYZE / bench
    report), or None when disarmed.

    ``had_error`` downgrades enforce to record for THIS audit: a
    leak report must not mask the exception already propagating."""
    global _audits_total, _leaks_total
    if not ARMED or not query_id:
        return None
    # catalog state first, ledger lock second: note_tier runs under the
    # catalog's admission lock, so the reverse order is a lock cycle
    from ..exec import spill
    try:
        spill.drain_deferred_finalizers()    # pending frees are not leaks
    except Exception:
        pass
    cat = spill.BufferCatalog.peek()
    snap = cat.residency_snapshot() if cat is not None else []
    cache_priority = spill.CACHE_PRIORITY
    with _mu:
        _audits_total += 1
        q = _queries.pop(query_id, None)
        leaks: List[_Entry] = []
        for bid, tier, priority, pinned in snap:
            e = _entries.get(bid)
            if e is None or e.query_id != query_id:
                continue
            e.tier = _tier_name(tier)        # refresh from the catalog
            if e.tier != "DEVICE" or pinned or priority == cache_priority:
                continue
            leaks.append(e)
        result: Dict[str, Any] = {
            "queryId": query_id,
            "leakedBuffers": len(leaks),
            "leakedBytes": sum(e.nbytes for e in leaks),
            "peakDeviceBytes": int(q["peak_dev"]) if q else 0,
            "mintedBuffers": int(q["minted"]) if q else 0,
            "sites": [f"buffer {e.buffer_id} ({e.nbytes} bytes) minted "
                      f"at {e.site or '<unknown>'}" for e in leaks[:8]],
        }
        if leaks:
            _leaks_total += len(leaks)
            # disown: the leak is reported once, not re-flagged against
            # every later query sharing the process
            for e in leaks:
                e.query_id = None
    if not leaks:
        return result
    msg = (f"query {query_id} leaked {result['leakedBuffers']} "
           f"device-resident buffer(s) ({result['leakedBytes']} bytes) "
           "past collect end: " + "; ".join(result["sites"]))
    _observe("buffer-leak", query_id, msg, "tpu_buffer_leaks_total",
             count=len(leaks), data=result)
    if _mode == "enforce" and not had_error:
        raise BufferLeakError(msg, query_id=query_id,
                              site=result["sites"][0] if result["sites"]
                              else None)
    log.warning("%s (bufferLedger=%s: continuing)", msg, _mode)
    return result


# ---------------------------------------------------------------------------
# Observability (never under _mu, never fails the query)
# ---------------------------------------------------------------------------

def _observe(kind: str, name: str, msg: str, counter: str,
             count: int = 1, data: Optional[Dict[str, Any]] = None
             ) -> None:
    try:
        from ..service.telemetry import MetricsRegistry, flight_record
        flight_record(kind, name, data if data is not None else
                      {"message": msg})
        MetricsRegistry.get().counter(
            counter, "buffer-lifecycle ledger diagnoses").inc(count)
    except Exception:
        pass                         # observability must never fail a query
