"""Project linter: AST enforcement of the repo's device-residency and
registry invariants. Run as ``python -m tools.lint`` (tier-1 enforces a
clean run; see docs/analysis.md).

Rules
-----
``host-sync`` (hot-path modules only: ``ops/``, ``exec/``, ``shuffle/``,
``plan/physical.py``): flags constructs that force (or strongly smell of)
a blocking device->host materialization inside an operator hot path —

* ``np.asarray(...)`` — the implicit-readback funnel,
* ``jax.device_get(...)`` / ``.block_until_ready(...)`` outside the
  allowlisted helpers (PipelineWindow's batched resolve, Metrics.resolve),
* ``float()``/``int()``/``bool()`` applied to a ``jnp.``/``jax.`` call
  result, and ``.item()``.

A deliberate sync carries a pragma on the flagged line::

    x = np.asarray(dec)   # lint: host-sync-ok the ONE per-window stats sync

The reason is mandatory (``pragma-reason`` rule) so every exception is
visible and greppable: ``grep -rn 'host-sync-ok' spark_rapids_tpu/``.

``conf-docs``: every non-internal conf key registered in ``config.py``
appears in ``docs/configs.md`` and vice versa (regenerate with
``python tools/gen_docs.py``).

``exec-contract``: every physical exec class (``*Exec`` in the exec
modules) declares a ``CONTRACT`` in its class body — the declaration
``analysis/contracts.py`` validates per plan.

``exec-metrics``: every exec class that declares a ``CONTRACT`` also
declares ``METRICS = exec_metrics(...)`` — its metric-key surface
(``exec/metrics.py``; the GpuExec.additionalMetrics analog).

``metric-key``: every literal metric key the class body emits — the
``metric_key`` argument of a ``trace_span(...)`` call or the first
argument of a ``<x>.metrics.inc("...")`` call — is declared by the
enclosing class's ``METRICS`` (base keys exempt). Keeps the metrics
surface greppable and drift-free, like the contract rule.

``telemetry-key``: every literal registry metric name in the package —
the first argument of a ``<registry>.counter("...")`` /
``.gauge("...")`` / ``.histogram("...")`` call — is declared in
``service/telemetry.py``'s ``TELEMETRY_KEYS`` tuple (the metric-key
rule's analog for the process-lifetime scrape surface).

``querylog-key``: every top-level record field the structured query
log's ``build_record`` emits (``service/query_log.py``) is declared in
its ``QUERY_LOG_FIELDS`` tuple — the metric-key discipline applied to
the artifact surface ``tools/query_report`` reads.

``use-after-donate`` / ``unreleased-acquire`` / ``double-free`` /
``untracked-residency``: the device-memory ownership rules over the
buffer-handling modules (``analysis/ownership.py``, docs/analysis.md
§7) — deliberate exceptions carry ``# lint: ownership-ok <reason>``.

``bare-recover``: an ``except`` clause naming a recoverable-taxonomy
type (ShuffleFetchError and subclasses, BufferLostError,
InjectedTaskFault — the exec/recovery.py domain) outside
``exec/recovery.py`` carries a ``# lint: recover-ok <reason>`` pragma.
Retry/recovery decisions belong to the ONE stage-retry driver; a bare
catch elsewhere is how retry logic quietly forks into second
implementations (docs/resilience.md).

``cancel-point`` (partition-drain / fetch-poll modules:
``exec/tasks.py``, ``shuffle/transport.py``, ``shuffle/exchange.py``):
every ``while`` loop, and every ``for`` loop whose body contains a
blocking dwell (``sleep``/``wait``/``get``/``acquire``/socket calls),
must reach the ambient cancel poll — a ``check_cancel()`` or
``interruptible_sleep()`` call inside the loop — or carry a reasoned
``# lint: cancel-ok <reason>`` pragma. An unpolled unbounded loop is a
query that cannot be cancelled or preempted while it spins
(exec/lifecycle.py, docs/resilience.md §"cancellation").

The linter is pure AST + text: no engine import, no jax import.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# hot-path membership by path relative to the spark_rapids_tpu package
HOT_PATH_PREFIXES = ("ops/", "exec/", "shuffle/")
HOT_PATH_FILES = ("plan/physical.py", "plan/stage_compiler.py",
                  "service/server.py", "exec/compile_pool.py")

# (relative module, enclosing qualname): sanctioned sync helpers — the
# batched readback funnels every other site must go through
HOST_SYNC_ALLOWLIST = {
    ("exec/pipeline.py", "PipelineWindow._resolve"),
    ("exec/metrics.py", "TpuMetrics.resolve"),
}

# modules whose *Exec classes must declare a CONTRACT
EXEC_MODULES = (
    "plan/physical.py", "plan/overrides.py", "plan/window_exec.py",
    "plan/stage_compiler.py", "shuffle/exchange.py", "io/scan.py",
    "io/write.py", "parallel/mesh_exec.py",
)
EXEC_BASE_CLASSES = {"TpuExec"}       # abstract root: no contract of its own

# mirror of exec/metrics.BASE_METRICS (the linter is pure AST and cannot
# import the engine): keys every exec may emit without declaring —
# GpuMetricNames basics plus the attributed cross-cutting keys
BASE_METRIC_KEYS = {"numOutputRows", "numOutputBatches", "opTime",
                    "hostSyncs", "recompiles", "spillBytes",
                    "peakDeviceBytes", "compileSeconds"}

PRAGMA_RE = re.compile(r"#\s*lint:\s*host-sync-ok(.*)$")
NAKED_JIT_PRAGMA_RE = re.compile(r"#\s*lint:\s*naked-jit-ok(.*)$")
RECOVER_PRAGMA_RE = re.compile(r"#\s*lint:\s*recover-ok(.*)$")

# mirror of exec/recovery's taxonomy (the linter is pure AST and cannot
# import the engine): exception names whose `except` clauses are
# recovery decisions — catching one outside the stage-retry driver
# needs a reasoned pragma (bare-recover rule)
RECOVER_TAXONOMY_NAMES = {
    "ShuffleFetchError", "ShuffleWorkerLostError", "ShuffleDesyncError",
    "ShuffleProtocolError", "BufferLostError", "InjectedTaskFault",
    "recoverable_types",          # except recovery.recoverable_types():
}
#: the one module allowed to catch taxonomy types bare
RECOVER_MODULE = "exec/recovery.py"

CANCEL_PRAGMA_RE = re.compile(r"#\s*lint:\s*cancel-ok(.*)$")

#: partition-drain / fetch-poll modules whose loops must reach the
#: ambient cancel poll (exec/lifecycle.check_cancel) — the cooperative
#: cancellation contract's enforcement surface (docs/resilience.md)
CANCEL_POINT_MODULES = ("exec/tasks.py", "shuffle/transport.py",
                        "shuffle/exchange.py")
#: the calls that ARE a poll point
CANCEL_POLL_NAMES = {"check_cancel", "interruptible_sleep"}
#: attribute-call names that make a ``for`` loop a blocking dwell (the
#: loop can park a thread, so a pending cancel must be able to reach
#: it). Deliberately excludes the ambiguous ``get``/``put``/``join``
#: (dict.get, os.path.join, ...) — the queue dwells those would catch
#: are ``while`` loops, which the rule always checks
CANCEL_BLOCKING_ATTRS = {"sleep", "wait", "acquire", "recv",
                         "recv_into", "sendall", "connect", "select"}


@dataclass
class LintViolation:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _is_hot(rel: str) -> bool:
    return rel.startswith(HOT_PATH_PREFIXES) or rel in HOT_PATH_FILES


def _pragmas(source: str) -> Dict[int, str]:
    """line number -> pragma reason ('' when missing)."""
    out: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = PRAGMA_RE.search(line)
        if m:
            out[i] = m.group(1).strip()
    return out


class _HostSyncVisitor(ast.NodeVisitor):
    """Collects host-sync smells with their enclosing qualname."""

    def __init__(self) -> None:
        self.hits: List[Tuple[int, str, str]] = []   # (line, qualname, msg)
        self._stack: List[str] = []

    @property
    def _qual(self) -> str:
        return ".".join(self._stack) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "asarray" and isinstance(f.value, ast.Name) and \
                    f.value.id in ("np", "numpy", "_np"):
                self._hit(node, "np.asarray() materializes device values "
                                "on host (use jax.device_get via a batched "
                                "resolve, or pragma with a reason)")
            elif f.attr == "device_get" and isinstance(f.value, ast.Name) \
                    and f.value.id == "jax":
                self._hit(node, "bare jax.device_get outside the batched-"
                                "resolve helpers blocks a full link round "
                                "trip")
            elif f.attr == "block_until_ready":
                self._hit(node, ".block_until_ready() serializes the "
                                "stream on device completion")
            elif f.attr == "item" and not node.args and not node.keywords:
                self._hit(node, ".item() forces a host readback when "
                                "applied to a device value")
        elif isinstance(f, ast.Name) and f.id in ("float", "int", "bool") \
                and len(node.args) == 1 and not node.keywords:
            if self._jaxish(node.args[0]):
                self._hit(node, f"{f.id}() over a jax expression is a "
                                "blocking scalar readback")
        self.generic_visit(node)

    @staticmethod
    def _jaxish(arg: ast.AST) -> bool:
        """The argument is syntactically a jax/jnp call (or np.asarray of
        one) — the conservative subset the AST can prove."""
        if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Attribute):
            v = arg.func.value
            if isinstance(v, ast.Name) and v.id in ("jnp", "jax"):
                return True
            if isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "jax":
                return True
            if arg.func.attr == "asarray" and isinstance(v, ast.Name) and \
                    v.id in ("np", "numpy", "_np"):
                return True
        return False

    def _hit(self, node: ast.AST, msg: str) -> None:
        self.hits.append((node.lineno, self._qual, msg))


def lint_source(source: str, rel: str, path: Optional[str] = None
                ) -> List[LintViolation]:
    """Lint one module's source. ``rel`` is its path relative to the
    package root (decides hot-path membership and exec-module rules)."""
    path = path or rel
    out: List[LintViolation] = []
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [LintViolation(path, e.lineno or 0, "parse", str(e))]
    pragmas = _pragmas(source)

    # pragma-reason: a host-sync-ok pragma without a justification
    for line, reason in pragmas.items():
        if not reason:
            out.append(LintViolation(
                path, line, "pragma-reason",
                "host-sync-ok pragma missing its justification "
                "(format: `# lint: host-sync-ok <reason>`)"))

    if _is_hot(rel):
        v = _HostSyncVisitor()
        v.visit(tree)
        for line, qual, msg in v.hits:
            if (rel, qual) in HOST_SYNC_ALLOWLIST:
                continue
            if any(l in pragmas and pragmas[l] for l in (line, line - 1)):
                continue
            out.append(LintViolation(path, line, "host-sync",
                                     f"{qual}: {msg}"))

    # naked-jit (whole package): every jax.jit( call site must sit inside
    # a _fused_fn builder — the one funnel the recompile audit and the
    # persistent compile cache watch — or carry a reasoned pragma
    out.extend(_check_naked_jit(tree, source, path))

    # bare-recover (whole package): taxonomy catches outside the
    # stage-retry driver carry a reasoned pragma
    out.extend(_check_bare_recover(tree, source, rel, path))

    # cancel-point (partition-drain / fetch-poll modules): every
    # unbounded or blocking loop reaches the cooperative cancel poll
    out.extend(_check_cancel_points(tree, source, rel, path))

    # querylog-key: the structured query log's record fields are a
    # declared surface, like METRICS and TELEMETRY_KEYS
    if rel == QUERY_LOG_MODULE:
        out.extend(check_querylog_keys(source, path))

    if rel in EXEC_MODULES:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name.endswith("Exec") and \
                    node.name not in EXEC_BASE_CLASSES:
                has = any(
                    isinstance(st, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "CONTRACT"
                        for t in st.targets)
                    for st in node.body)
                if not has:
                    out.append(LintViolation(
                        path, node.lineno, "exec-contract",
                        f"exec class {node.name} declares no CONTRACT "
                        "(analysis/contracts.exec_contract)"))
                else:
                    out.extend(_check_exec_metrics(node, path))

    # concurrency rules (raw-lock / unguarded-state / lock-blocking /
    # singleton-guard) over the thread-reachable modules — lazy import:
    # concurrency.py imports LintViolation from here
    from . import concurrency
    out.extend(concurrency.lint_source(source, rel, path=path))
    # determinism rules (nondet-clock / nondet-random / nondet-set-order /
    # nondet-scan / lockstep-id) over the lockstep-reachable modules —
    # same lazy-import shape
    from . import determinism
    out.extend(determinism.lint_source(source, rel, path=path))
    # ownership rules (use-after-donate / unreleased-acquire /
    # double-free / untracked-residency) over the buffer-handling
    # modules — same lazy-import shape
    from . import ownership
    out.extend(ownership.lint_source(source, rel, path=path))
    return out


# ---------------------------------------------------------------------------
# bare-recover: taxonomy catches outside exec/recovery.py need a pragma
# ---------------------------------------------------------------------------

def _handler_exception_names(handler: ast.ExceptHandler) -> List[str]:
    """The taxonomy-relevant names an except clause catches: bare names,
    dotted tails (``transport.ShuffleFetchError``), tuple members, and
    the ``recovery.recoverable_types()`` call form — the whole taxonomy
    at once, which needs the pragma most of all."""
    t = handler.type
    if t is None:
        return []
    nodes = list(t.elts) if isinstance(t, ast.Tuple) else [t]
    names: List[str] = []
    for n in nodes:
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
        elif isinstance(n, ast.Call):
            f = n.func
            fname = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if fname == "recoverable_types":
                names.append("recoverable_types")
    return names


def _check_bare_recover(tree: ast.AST, source: str, rel: str, path: str
                        ) -> List[LintViolation]:
    """``bare-recover``: an except clause naming a recoverable-taxonomy
    type outside exec/recovery.py without a reasoned recover-ok pragma —
    a recovery decision made outside the one stage-retry driver
    (docs/resilience.md)."""
    out: List[LintViolation] = []
    pragmas: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = RECOVER_PRAGMA_RE.search(line)
        if m:
            reason = m.group(1).strip()
            if not reason:
                out.append(LintViolation(
                    path, i, "pragma-reason",
                    "recover-ok pragma missing its justification "
                    "(format: `# lint: recover-ok <reason>`)"))
            pragmas[i] = reason
    if rel == RECOVER_MODULE:
        return out                         # the driver's own domain
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        caught = [n for n in _handler_exception_names(node)
                  if n in RECOVER_TAXONOMY_NAMES]
        if not caught:
            continue
        if any(l in pragmas and pragmas[l]
               for l in (node.lineno, node.lineno - 1)):
            continue
        out.append(LintViolation(
            path, node.lineno, "bare-recover",
            f"except of recoverable-taxonomy type(s) {sorted(caught)} "
            "outside exec/recovery.py — route the decision through the "
            "stage-retry driver (exec/recovery.retry_stage / "
            "StageRetryState) or pragma with "
            "`# lint: recover-ok <reason>`"))
    return out


# ---------------------------------------------------------------------------
# cancel-point: drain/poll loops must reach the cooperative cancel poll
# ---------------------------------------------------------------------------

def _loop_polls_cancel(loop: ast.AST) -> bool:
    """The loop (or anything nested in it) calls a poll-point function —
    ``check_cancel()`` / ``interruptible_sleep()``, bare or dotted."""
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in CANCEL_POLL_NAMES:
                return True
    return False


def _loop_blocks(loop: ast.For) -> bool:
    """The for loop's body contains a blocking dwell (a call whose
    attribute name is in CANCEL_BLOCKING_ATTRS) — the subset of ``for``
    loops that can park a thread and therefore must be pollable."""
    for node in ast.walk(loop):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in CANCEL_BLOCKING_ATTRS:
            return True
    return False


def _check_cancel_points(tree: ast.AST, source: str, rel: str, path: str
                         ) -> List[LintViolation]:
    """``cancel-point``: in the partition-drain / fetch-poll modules,
    every ``while`` loop and every blocking ``for`` loop either reaches
    the ambient cancel poll or carries a reasoned cancel-ok pragma — an
    unpolled unbounded loop is a query that cannot be cancelled or
    preempted while it spins (exec/lifecycle.py)."""
    out: List[LintViolation] = []
    pragmas: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = CANCEL_PRAGMA_RE.search(line)
        if m:
            reason = m.group(1).strip()
            if not reason:
                out.append(LintViolation(
                    path, i, "pragma-reason",
                    "cancel-ok pragma missing its justification "
                    "(format: `# lint: cancel-ok <reason>`)"))
            pragmas[i] = reason
    if rel not in CANCEL_POINT_MODULES:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.While):
            kind = "while"
        elif isinstance(node, ast.For) and _loop_blocks(node):
            kind = "blocking-for"
        else:
            continue
        if _loop_polls_cancel(node):
            continue
        if any(l in pragmas and pragmas[l]
               for l in (node.lineno, node.lineno - 1)):
            continue
        out.append(LintViolation(
            path, node.lineno, "cancel-point",
            f"{kind} loop in a partition-drain/fetch-poll module never "
            "polls the ambient cancel token — call "
            "exec/lifecycle.check_cancel() (or interruptible_sleep) "
            "inside the loop, or pragma with "
            "`# lint: cancel-ok <reason>`"))
    return out


# ---------------------------------------------------------------------------
# naked-jit: every jax.jit( call inside a _fused_fn builder or pragma'd
# ---------------------------------------------------------------------------

class _JitVisitor(ast.NodeVisitor):
    """Collects ``jax.jit(`` call sites with their enclosing function-name
    stack (the builder-funnel membership check is name-based)."""

    def __init__(self) -> None:
        self.hits: List[Tuple[int, Tuple[str, ...]]] = []
        self._stack: List[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "jit" and \
                isinstance(f.value, ast.Name) and f.value.id == "jax":
            self.hits.append((node.lineno, tuple(self._stack)))
        self.generic_visit(node)


def _fused_builder_names(tree: ast.AST) -> set:
    """Function names passed (directly, as a bound method, or wrapped in
    a lambda) as the builder argument of a ``_fused_fn(key, builder)``
    call: a jax.jit inside one of these IS inside the audit funnel."""
    names: set = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and len(node.args) >= 2):
            continue
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if fname != "_fused_fn":
            continue
        for sub in ast.walk(node.args[1]):
            if isinstance(sub, ast.Name):
                names.add(sub.id)
            elif isinstance(sub, ast.Attribute):
                names.add(sub.attr)
    return names


def _check_naked_jit(tree: ast.AST, source: str, path: str
                     ) -> List[LintViolation]:
    """``naked-jit``: a ``jax.jit(`` call site outside every _fused_fn
    builder and without a ``# lint: naked-jit-ok <reason>`` pragma — a
    compile the recompile audit and the persistent compile cache would
    never see."""
    out: List[LintViolation] = []
    sanctioned = _fused_builder_names(tree)
    pragmas: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = NAKED_JIT_PRAGMA_RE.search(line)
        if m:
            reason = m.group(1).strip()
            if not reason:
                out.append(LintViolation(
                    path, i, "pragma-reason",
                    "naked-jit-ok pragma missing its justification "
                    "(format: `# lint: naked-jit-ok <reason>`)"))
            pragmas[i] = reason
    v = _JitVisitor()
    v.visit(tree)
    for line, stack in v.hits:
        if any(name in sanctioned for name in stack):
            continue
        if any(l in pragmas and pragmas[l] for l in (line, line - 1)):
            continue
        out.append(LintViolation(
            path, line, "naked-jit",
            "jax.jit( outside a _fused_fn builder: this compile escapes "
            "the recompile audit and the persistent compile cache — "
            "route it through plan/physical._fused_fn (or a cache that "
            "calls exec/compile_cache.note_build) or pragma with "
            "`# lint: naked-jit-ok <reason>`"))
    return out


# ---------------------------------------------------------------------------
# exec METRICS declarations (exec-metrics / metric-key rules)
# ---------------------------------------------------------------------------

def _declared_metric_keys(cls: ast.ClassDef):
    """The string keys of this class's ``METRICS = exec_metrics(...)``
    assignment, or None when no METRICS is declared."""
    for st in cls.body:
        if isinstance(st, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "METRICS"
                for t in st.targets):
            keys = {n.value for n in ast.walk(st.value)
                    if isinstance(n, ast.Constant) and
                    isinstance(n.value, str)}
            return keys
    return None


def _used_metric_keys(cls: ast.ClassDef):
    """(line, key, kind) for every literal metric key the class body
    emits: trace_span's metric_key argument and
    ``<x>.metrics.inc("...")`` calls."""
    out = []
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if fname == "trace_span":
            key = None
            if len(node.args) >= 3 and isinstance(node.args[2],
                                                  ast.Constant):
                key = node.args[2].value
            for kw in node.keywords:
                if kw.arg == "metric_key" and \
                        isinstance(kw.value, ast.Constant):
                    key = kw.value.value
            if isinstance(key, str):
                out.append((node.lineno, key, "trace_span metric_key"))
        elif fname == "inc" and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Attribute) and \
                f.value.attr == "metrics" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.lineno, node.args[0].value, "metrics.inc"))
    return out


def _check_exec_metrics(cls: ast.ClassDef, path: str
                        ) -> List[LintViolation]:
    """exec-metrics: a CONTRACT-declaring exec class must declare METRICS;
    metric-key: every literal key it emits must be declared (base keys
    exempt)."""
    out: List[LintViolation] = []
    declared = _declared_metric_keys(cls)
    if declared is None:
        out.append(LintViolation(
            path, cls.lineno, "exec-metrics",
            f"exec class {cls.name} declares a CONTRACT but no METRICS "
            "(exec/metrics.exec_metrics: its metric-key surface)"))
        declared = set()
    allowed = declared | BASE_METRIC_KEYS
    for line, key, kind in _used_metric_keys(cls):
        if key not in allowed:
            out.append(LintViolation(
                path, line, "metric-key",
                f"{cls.name} emits metric key {key!r} ({kind}) not "
                "declared in its METRICS = exec_metrics(...) — declare "
                "it so the metrics surface stays greppable"))
    return out


# ---------------------------------------------------------------------------
# telemetry registry names (telemetry-key rule)
# ---------------------------------------------------------------------------

#: module declaring the registry name surface (relative to the package)
TELEMETRY_MODULE = "service/telemetry.py"
_TELEMETRY_CALLS = {"counter", "gauge", "histogram"}


def telemetry_declared_keys(source: str):
    """The string names in ``TELEMETRY_KEYS = (...)``, or None when the
    module declares no such tuple."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):   # TELEMETRY_KEYS: Tuple = (...)
            targets = [node.target]
        else:
            continue
        if node.value is not None and any(
                isinstance(t, ast.Name) and t.id == "TELEMETRY_KEYS"
                for t in targets):
            return {n.value for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant) and
                    isinstance(n.value, str)}
    return None


def telemetry_usages(source: str):
    """(line, name) for every ``<x>.counter/gauge/histogram("...")``
    literal registry-metric name in a module."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _TELEMETRY_CALLS and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.lineno, node.args[0].value))
    return out


def check_telemetry_keys(sources: Dict[str, Tuple[str, str]]
                         ) -> List[LintViolation]:
    """``telemetry-key``: every literal registry metric name used
    anywhere in the package is declared in TELEMETRY_KEYS
    (``sources``: rel -> (path, source) for every package module)."""
    decl_entry = sources.get(TELEMETRY_MODULE)
    if decl_entry is None:
        return []                          # no telemetry subsystem yet
    decl_path, decl_src = decl_entry
    declared = telemetry_declared_keys(decl_src)
    if declared is None:
        return [LintViolation(
            decl_path, 0, "telemetry-key",
            "service/telemetry.py declares no TELEMETRY_KEYS tuple — the "
            "registry name surface must be declared")]
    out: List[LintViolation] = []
    for rel, (path, src) in sorted(sources.items()):
        for line, name in telemetry_usages(src):
            if name not in declared:
                out.append(LintViolation(
                    path, line, "telemetry-key",
                    f"registry metric name {name!r} is not declared in "
                    "service/telemetry.TELEMETRY_KEYS — declare it so "
                    "the scrape surface stays greppable"))
    return out


# ---------------------------------------------------------------------------
# query-log record fields (querylog-key rule)
# ---------------------------------------------------------------------------

#: module declaring the structured query-log field surface
QUERY_LOG_MODULE = "service/query_log.py"


def querylog_declared_keys(source: str):
    """The string names in ``QUERY_LOG_FIELDS = (...)``, or None when the
    module declares no such tuple."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if node.value is not None and any(
                isinstance(t, ast.Name) and t.id == "QUERY_LOG_FIELDS"
                for t in targets):
            return {n.value for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant) and
                    isinstance(n.value, str)}
    return None


def querylog_usages(source: str):
    """(line, key) for every top-level record field ``build_record``
    emits: the string keys of the dict literal assigned to ``rec`` and
    ``rec["..."] = ...`` subscript assignments."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = []
    for fn in ast.walk(tree):
        if not (isinstance(fn, ast.FunctionDef) and
                fn.name == "build_record"):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "rec" and \
                        isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            out.append((k.lineno, k.value))
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "rec" and \
                        isinstance(t.slice, ast.Constant) and \
                        isinstance(t.slice.value, str):
                    out.append((t.lineno, t.slice.value))
    return out


def check_querylog_keys(source: str, path: str) -> List[LintViolation]:
    """``querylog-key``: every top-level record field the query-log
    writer emits is declared in ``QUERY_LOG_FIELDS`` — the metric-key /
    telemetry-key discipline applied to the artifact surface consumers
    (tools/query_report) read."""
    declared = querylog_declared_keys(source)
    if declared is None:
        return [LintViolation(
            path, 0, "querylog-key",
            "service/query_log.py declares no QUERY_LOG_FIELDS tuple — "
            "the query-log record surface must be declared")]
    out: List[LintViolation] = []
    for line, key in querylog_usages(source):
        if key not in declared:
            out.append(LintViolation(
                path, line, "querylog-key",
                f"query-log record field {key!r} is not declared in "
                "service/query_log.QUERY_LOG_FIELDS — declare it so the "
                "artifact surface stays greppable"))
    return out


# ---------------------------------------------------------------------------
# adaptive-execution decision rules (aqe-decision rule)
# ---------------------------------------------------------------------------

#: module declaring the adaptive-execution rule surface
AQE_MODULE = "plan/aqe.py"


def aqe_declared_rules(source: str):
    """The string names in ``AQE_RULES = (...)``, or None when the
    module declares no such tuple."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if node.value is not None and any(
                isinstance(t, ast.Name) and t.id == "AQE_RULES"
                for t in targets):
            return {n.value for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant) and
                    isinstance(n.value, str)}
    return None


def aqe_rule_usages(source: str):
    """(line, rule) for every ``record_decision(node, "...")`` call with
    a literal rule name, whether called bare or as ``aqe.record_decision``."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        named = (isinstance(fn, ast.Name) and fn.id == "record_decision") \
            or (isinstance(fn, ast.Attribute) and
                fn.attr == "record_decision")
        if named and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            out.append((node.lineno, node.args[1].value))
    return out


def check_aqe_rules(sources: Dict[str, Tuple[str, str]]
                    ) -> List[LintViolation]:
    """``aqe-decision``: every literal rule name passed to
    ``plan/aqe.record_decision`` anywhere in the package is declared in
    ``AQE_RULES`` — the telemetry-key discipline applied to the
    adaptive-execution decision surface (EXPLAIN ANALYZE, query log,
    ``tpu_aqe_decisions_total{rule}``)."""
    decl_entry = sources.get(AQE_MODULE)
    if decl_entry is None:
        return []                          # no adaptive subsystem yet
    decl_path, decl_src = decl_entry
    declared = aqe_declared_rules(decl_src)
    if declared is None:
        return [LintViolation(
            decl_path, 0, "aqe-decision",
            "plan/aqe.py declares no AQE_RULES tuple — the adaptive "
            "decision-rule surface must be declared")]
    out: List[LintViolation] = []
    for rel, (path, src) in sorted(sources.items()):
        for line, rule in aqe_rule_usages(src):
            if rule not in declared:
                out.append(LintViolation(
                    path, line, "aqe-decision",
                    f"AQE decision rule {rule!r} is not declared in "
                    "plan/aqe.AQE_RULES — declare it so the decision "
                    "surface stays greppable"))
    return out


# ---------------------------------------------------------------------------
# conf <-> docs agreement
# ---------------------------------------------------------------------------

def _registered_conf_keys(config_source: str) -> Dict[str, bool]:
    """key -> internal flag, parsed from config.py's builder-chain AST."""
    tree = ast.parse(config_source)
    keys: Dict[str, bool] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr == "create_with_default"):
            continue
        cur: ast.AST = node.func.value
        internal = False
        key: Optional[str] = None
        while cur is not None:
            if isinstance(cur, ast.Attribute):
                cur = cur.value
            elif isinstance(cur, ast.Call):
                f = cur.func
                if isinstance(f, ast.Name):          # _conf("key")
                    if cur.args and isinstance(cur.args[0], ast.Constant):
                        key = cur.args[0].value
                    break
                if isinstance(f, ast.Attribute):
                    if f.attr == "internal":
                        internal = True
                    elif f.attr == "conf" and cur.args and \
                            isinstance(cur.args[0], ast.Constant):
                        key = cur.args[0].value
                        break
                    cur = f.value
                else:
                    break
            else:
                break
        if key:
            keys[key] = internal
    return keys


def _documented_conf_keys(docs_text: str) -> List[str]:
    out = []
    for line in docs_text.splitlines():
        m = re.match(r"\|\s*(spark\.[\w.]+)\s*\|", line)
        if m:
            out.append(m.group(1))
    return out


def check_conf_docs(config_source: str, docs_text: str,
                    config_path: str = "config.py",
                    docs_path: str = "docs/configs.md"
                    ) -> List[LintViolation]:
    registered = _registered_conf_keys(config_source)
    public = {k for k, internal in registered.items() if not internal}
    documented = set(_documented_conf_keys(docs_text))
    out: List[LintViolation] = []
    for k in sorted(public - documented):
        out.append(LintViolation(
            config_path, 0, "conf-docs",
            f"conf key {k} is registered but missing from {docs_path} "
            "(run: python tools/gen_docs.py)"))
    for k in sorted(documented - public):
        out.append(LintViolation(
            docs_path, 0, "conf-docs",
            f"{docs_path} documents {k} which is not registered in "
            f"{config_path}"))
    return out


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def run(package_dir: str, docs_dir: Optional[str] = None
        ) -> List[LintViolation]:
    """Lint every .py under ``package_dir`` (the spark_rapids_tpu package)
    plus the conf/docs agreement check."""
    out: List[LintViolation] = []
    sources: Dict[str, Tuple[str, str]] = {}
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, package_dir).replace(os.sep, "/")
            with open(full, "r") as f:
                src = f.read()
            sources[rel] = (full, src)
            out.extend(lint_source(src, rel, path=full))
    # cross-module: registry metric names vs the TELEMETRY_KEYS surface
    out.extend(check_telemetry_keys(sources))
    # cross-module: adaptive decision rules vs the AQE_RULES surface
    out.extend(check_aqe_rules(sources))
    config_path = os.path.join(package_dir, "config.py")
    if docs_dir is None:
        docs_dir = os.path.join(os.path.dirname(package_dir), "docs")
    docs_path = os.path.join(docs_dir, "configs.md")
    if os.path.exists(config_path) and os.path.exists(docs_path):
        with open(config_path) as f:
            cfg_src = f.read()
        with open(docs_path) as f:
            docs_text = f.read()
        out.extend(check_conf_docs(cfg_src, docs_text,
                                   config_path=config_path,
                                   docs_path=docs_path))
    # cross-module concurrency check: duplicate lockdep names alias
    # runtime order edges
    from . import concurrency
    out.extend(concurrency.check_registry(
        concurrency.lock_registry(package_dir)))
    # cross-module determinism check: a LOCKSTEP_IDS entry whose mint
    # site vanished is a stale registry (the other direction — an
    # undeclared mint site — is flagged per module)
    from . import determinism
    out.extend(determinism.check_registry(
        determinism.id_registry(package_dir)))
    # cross-module ownership check: an OWNERSHIP_SINKS entry whose def
    # site vanished is a stale registry (the rules themselves flag the
    # per-module direction)
    from . import ownership
    out.extend(ownership.check_registry(
        ownership.sink_registry(package_dir)))
    return out


_ANY_PRAGMA_RE = re.compile(r"#\s*lint:\s*([a-z-]+)-ok(.*)$")


def collect_pragmas(package_dir: str) -> List[Dict[str, object]]:
    """Every ``# lint: <rule>-ok`` suppression pragma in the package,
    with its rule tag, reason, and validity (reason-less pragmas do not
    suppress) — the machine-readable half of ``--json`` output, so CI
    can audit what the tree suppresses and why."""
    out: List[Dict[str, object]] = []
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, package_dir).replace(os.sep, "/")
            with open(full, "r") as f:
                for i, line in enumerate(f, start=1):
                    m = _ANY_PRAGMA_RE.search(line)
                    if m:
                        reason = m.group(2).strip()
                        out.append({"path": rel, "line": i,
                                    "rule": m.group(1),
                                    "reason": reason,
                                    "suppresses": bool(reason)})
    return out


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    show_locks = "--locks" in argv
    argv = [a for a in argv if not a.startswith("--")]
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    package_dir = argv[0] if argv else here
    if show_locks:
        from . import concurrency
        sites = concurrency.lock_registry(package_dir)
        for s in sites:
            print(f"{s.rel}:{s.line}: {s.canonical} ({s.kind})")
        print(f"{len(sites)} lock site(s)")
        return 0
    violations = run(package_dir)
    if as_json:
        # machine-readable findings + pragma status: what fired, and
        # what the tree suppresses (with each suppression's reason)
        print(json.dumps({
            "violations": [vars(v) for v in violations],
            "pragmas": collect_pragmas(package_dir)}, indent=2))
    else:
        for v in violations:
            print(v)
        print(f"{len(violations)} violation(s)" if violations
              else "lint OK")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
