"""Runtime lock-order tracking (lockdep) for the threaded engine.

The engine is concurrency-first: partition drains run on a
``ThreadPoolExecutor`` (exec/tasks.py), the shuffle transport spawns
accept/handler threads, and the spill catalog / device manager / conf
registry are process singletons coordinating those threads through locks.
``analysis/concurrency.py`` checks the *lexical* discipline at lint time;
this module checks the *dynamic* discipline at run time — which locks are
actually taken while which others are held, in what order, and for how
long.

Every engine lock is created through :func:`named_lock` /
:func:`named_rlock` instead of ``threading.Lock()`` (the static
``raw-lock`` rule enforces this), which re-homes it onto a process-wide
registry. When armed (``spark.rapids.tpu.sql.analysis.lockdep`` =
``record`` | ``enforce``; default ``off``), each acquisition

* records the edge ``held -> acquired`` into a global lock-order graph,
  capturing BOTH acquisition stacks the first time an edge is seen, so an
  order-inversion report names the two code paths that disagree;
* detects order-inversion cycles (``A`` taken under ``B`` somewhere after
  ``B`` was taken under ``A`` elsewhere — a potential deadlock even if it
  never deadlocked in this run): logged once per cycle in ``record``,
  raised as :class:`LockOrderInversionError` in ``enforce`` (the wrapped
  lock is released first so the raise cannot itself leak a held lock);
* accumulates per-lock wait/hold seconds attributed to the innermost open
  trace span (the same attribution ``SyncCounter`` uses for readbacks),
  surfaced per query by ``benchmarks/runner.py`` next to the semaphore
  wait/hold split;
* flags host transfers performed while holding any registry lock:
  ``sync_audit.allowed_host_transfer`` calls :func:`note_host_transfer`,
  so a spill/wire crossing that sneaks under a lock is recorded
  (``record``) or raised (``enforce``) unless the holding code path
  sanctioned it with :func:`allowed_while_locked`.

Mode is primed EAGERLY (session bootstrap calls :func:`refresh_mode`
with the session conf; tests call it directly) rather than lazily at
first acquire — a lazy read would recurse through the very conf-registry
lock it is instrumenting. Unprimed processes run with lockdep off and
the wrappers degrade to one mode check per acquire.

When ``off``, a named lock is a plain lock plus one string-compare per
acquire; ``record`` adds two perf_counter reads, a thread-local list
push/pop, and (only on a never-seen graph edge) one stack capture.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

MODES = ("off", "record", "enforce")

log = logging.getLogger("spark_rapids_tpu.lockdep")

_MAX_FINDINGS = 200          # cap on stored transfer findings (record mode)
_STACK_LIMIT = 18            # frames captured per acquisition stack


class LockOrderInversionError(RuntimeError):
    """Two code paths acquire the same two locks in opposite orders — a
    potential deadlock. The message carries both acquisition stacks."""


class LockHeldAcrossTransferError(RuntimeError):
    """A host transfer ran while this thread held a registry lock, and no
    enclosing :func:`allowed_while_locked` sanctioned it."""


class _State:
    """Global lockdep state. The internal ``_mu`` is a RAW lock by design
    (it is the instrumentation's own leaf lock: nothing blocking ever
    runs under it, and wrapping it would recurse)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (held_name, acquired_name) -> {"count": int, "stack": str}
        self.edges: Dict[Tuple[str, str], Dict] = {}
        self.succ: Dict[str, set] = {}          # name -> set of successors
        self.stats: Dict[str, Dict] = {}        # name -> wait/hold/spans
        self.cycles: List[Dict] = []            # inversion reports
        self.transfers: List[Dict] = []         # held-across-transfer finds
        self.registered: Dict[str, int] = {}    # name -> creation count
        self._reported: set = set()             # cycle pairs already logged


_state = _State()
_mode = "off"
_tls = threading.local()     # .held: List[[name, t_acq, reentrant, span, id]]
                             # .allow: int (allowed_while_locked depth)
                             # .busy: int (bookkeeping re-entry shield)


class _mu_section:
    """``_state._mu`` with a thread-local re-entry shield.

    A GC weakref finalizer (e.g. the scan-cache eviction closing a
    spillable buffer) can fire at ANY bytecode — including while this
    thread is inside a ``with _mu_section():`` bookkeeping section — and the
    finalizer's own named-lock acquisition would then re-enter lockdep
    and deadlock on the non-reentrant state mutex its interrupted frame
    already holds (observed: ``_evict_table -> BufferCatalog.free``
    firing inside ``_note_acquired``). While ``_tls.busy`` is set,
    :meth:`NamedLock.acquire`/`release` bypass bookkeeping (raw lock
    only), so the finalizer runs untracked instead of hanging the
    process. ``busy`` is raised BEFORE the mutex acquire so a finalizer
    interrupting the wait is shielded too."""

    __slots__ = ("_m",)

    def __enter__(self):
        _tls.busy = getattr(_tls, "busy", 0) + 1
        try:
            # pin the mutex object: reset_state() may swap _state between
            # enter and exit, and releasing the NEW state's unheld mutex
            # would raise out of the exit path
            self._m = _state._mu
            self._m.acquire()
        except BaseException:
            # a KeyboardInterrupt while blocked on the mutex must not
            # leak busy>0 (that thread would silently bypass lockdep
            # forever)
            _tls.busy -= 1
            raise
        return self

    def __exit__(self, *exc):
        self._m.release()
        _tls.busy -= 1
        return False


def _bookkeeping_busy() -> bool:
    return getattr(_tls, "busy", 0) > 0


# ---------------------------------------------------------------------------
# Mode management (eager priming — see module docstring)
# ---------------------------------------------------------------------------

def lockdep_mode() -> str:
    return _mode


def refresh_mode(conf=None) -> str:
    """Prime the mode from ``conf`` (a TpuConf or a literal mode string),
    else from the active session's conf, else process defaults + env.
    Called by session bootstrap; safe to call any time."""
    global _mode
    if isinstance(conf, str):
        _mode = conf if conf in MODES else "off"
        return _mode
    try:
        from .. import config as cfg
        if conf is None:
            try:
                from ..api.session import TpuSession
                # deliberate lock-free read: taking the session lock here
                # would recurse into the instrumentation being configured
                conf = TpuSession._active.conf  # type: ignore[union-attr]
            except Exception:
                conf = None
        if conf is None:
            conf = cfg.TpuConf()
        mode = str(conf.get(cfg.ANALYSIS_LOCKDEP)).lower()
        _mode = mode if mode in MODES else "off"
    except Exception:
        _mode = "off"
    return _mode


def reset_state() -> None:
    """Drop the order graph, stats, and findings (tests)."""
    global _state
    _state = _State()


# ---------------------------------------------------------------------------
# Named lock wrappers
# ---------------------------------------------------------------------------

def _held() -> List[list]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _current_span() -> Optional[str]:
    try:
        from ..exec.tracing import SpanRecorder
        rec = SpanRecorder.active
        return rec.current_span() if rec is not None else None
    except Exception:
        return None


def _stack() -> str:
    return "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-2])


def _stat(name: str) -> Dict:
    return _state.stats.setdefault(
        name, {"waitS": 0.0, "holdS": 0.0, "acquires": 0, "spans": {}})


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS over the order graph; a path src -> ... -> dst."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _state.succ.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquired(name: str, held: List[list]) -> None:
    """Record order edges held->name; detect inversion cycles. Raises in
    enforce mode (caller releases the raw lock first)."""
    # NOTE: a held lock with the SAME name but a different object (two
    # instances of one lock class, e.g. two SpillableBuffer._lock) is NOT
    # filtered out: it records the self-edge name -> name, which closes a
    # cycle immediately — same-class nesting is indistinguishable from an
    # ABBA deadlock when instances share a canonical name, so (kernel-
    # lockdep style) it is reported unless the design removes the nesting.
    held_names = [e[0] for e in held if not e[2]]
    if not held_names:
        return
    stack_now = None
    raise_report = None
    flight_report = None
    with _mu_section():
        for h in dict.fromkeys(held_names):        # de-dup, keep order
            edge = (h, name)
            ent = _state.edges.get(edge)
            if ent is None:
                if stack_now is None:
                    stack_now = _stack()
                ent = _state.edges[edge] = {"count": 0, "stack": stack_now}
                _state.succ.setdefault(h, set()).add(name)
                # a NEW edge is the only thing that can close a cycle
                path = _find_path(name, h)
                if path is not None:
                    pair = frozenset((h, name))
                    report = {
                        "cycle": [h] + path,       # h -> name -> ... -> h
                        "edge": f"{h} -> {name}",
                        "edgeStack": stack_now,
                        "reverse": " -> ".join(path),
                        "reverseStacks": {
                            f"{a} -> {b}":
                                _state.edges.get((a, b), {}).get("stack", "")
                            for a, b in zip(path, path[1:])},
                    }
                    _state.cycles.append(report)
                    flight_report = report
                    if pair not in _state._reported:
                        _state._reported.add(pair)
                        if _mode == "enforce":
                            raise_report = report
                        else:
                            log.warning(
                                "lock-order inversion: %s while the reverse "
                                "order %s was recorded\n-- this acquisition:"
                                "\n%s-- first reverse acquisition:\n%s",
                                report["edge"], report["reverse"],
                                report["edgeStack"],
                                next(iter(report["reverseStacks"].values()),
                                     ""))
            ent["count"] += 1
    if flight_report is not None:
        # flight-recorder incident, recorded OUTSIDE _state._mu: the
        # recorder's first conf read acquires the (lockdep-instrumented)
        # conf-registry lock, which would re-enter this module
        try:
            from ..service.telemetry import flight_record
            flight_record("lock-cycle", flight_report["edge"],
                          {"reverse": flight_report["reverse"]})
        except Exception:
            pass
    if raise_report is not None:
        rev = next(iter(raise_report["reverseStacks"].values()), "")
        raise LockOrderInversionError(
            f"lock-order inversion: acquiring {name} while holding "
            f"{held_names} contradicts the recorded order "
            f"{raise_report['reverse']}\n-- this acquisition:\n"
            f"{raise_report['edgeStack']}-- first reverse acquisition:\n"
            f"{rev}")


class NamedLock:
    """``threading.Lock`` re-homed onto the lockdep registry."""

    _factory = staticmethod(threading.Lock)
    reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._raw = self._factory()
        if _bookkeeping_busy():
            return            # created by a finalizer mid-bookkeeping:
        with _mu_section():   # skip the registry, never re-enter the mutex
            _state.registered[name] = _state.registered.get(name, 0) + 1

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _mode == "off" or _bookkeeping_busy():
            # busy: this thread is INSIDE lockdep bookkeeping (a GC
            # finalizer interrupted it) — track nothing, never re-enter
            return self._raw.acquire(blocking, timeout)
        held = _held()
        # re-entrancy is judged by lock OBJECT, not name: two instances of
        # a shared-name lock class nested in one thread are a real order
        # edge (and a same-class nesting finding), not a re-entry
        my_id = id(self)
        reentrant = self.reentrant and any(
            e[4] == my_id for e in held)
        t0 = time.perf_counter()
        ok = self._raw.acquire(blocking, timeout)
        if not ok:
            return False
        now = time.perf_counter()
        if not reentrant:
            try:
                _note_acquired(self.name, held)
            except LockOrderInversionError:
                # never leak a held lock out of a refused acquisition
                self._raw.release()
                raise
        span = _current_span()
        held.append([self.name, now, reentrant, span, my_id])
        if not reentrant:
            with _mu_section():
                st = _stat(self.name)
                st["waitS"] += now - t0
                st["acquires"] += 1
                if span:
                    sp = st["spans"].setdefault(
                        span, {"waitS": 0.0, "holdS": 0.0})
                    sp["waitS"] += now - t0
        return True

    def release(self) -> None:
        held = getattr(_tls, "held", None)
        entry = None
        if held:
            my_id = id(self)
            for i in range(len(held) - 1, -1, -1):
                if held[i][4] == my_id:
                    entry = held.pop(i)
                    break
        self._raw.release()
        if entry is not None and not entry[2] and not _bookkeeping_busy():
            held_for = time.perf_counter() - entry[1]
            with _mu_section():
                st = _stat(self.name)
                st["holdS"] += held_for
                if entry[3]:
                    sp = st["spans"].setdefault(
                        entry[3], {"waitS": 0.0, "holdS": 0.0})
                    sp["holdS"] += held_for

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class NamedRLock(NamedLock):
    """``threading.RLock`` on the registry: re-entrant acquisitions are
    tracked (so release stays symmetric) but contribute no order edges
    and no double-counted hold time."""

    _factory = staticmethod(threading.RLock)
    reentrant = True

    def locked(self) -> bool:          # RLock has no .locked(); best effort
        acquired = self._raw.acquire(blocking=False)
        if acquired:
            self._raw.release()
        return not acquired


def named_lock(name: str) -> NamedLock:
    return NamedLock(name)


def named_rlock(name: str) -> NamedRLock:
    return NamedRLock(name)


# ---------------------------------------------------------------------------
# Host-transfer integration (sync_audit calls in here)
# ---------------------------------------------------------------------------

def held_locks() -> List[str]:
    """Names of registry locks this thread currently holds (outermost
    first, re-entrant acquisitions collapsed)."""
    return list(dict.fromkeys(
        e[0] for e in getattr(_tls, "held", ()) if not e[2]))


@contextmanager
def allowed_while_locked(reason: str):
    """Sanction host transfers under a held registry lock for this block
    (the synchronous-spill path: the admission lock MUST serialize tier
    moves, so the readback under it is the design, not an accident).
    ``reason`` is mandatory so every sanction documents itself — grep:
    ``grep -rn 'allowed_while_locked' spark_rapids_tpu/``."""
    assert reason, "allowed_while_locked requires a reason"
    _tls.allow = getattr(_tls, "allow", 0) + 1
    try:
        yield
    finally:
        _tls.allow -= 1


def note_host_transfer(reason: str) -> None:
    """Called by ``sync_audit.allowed_host_transfer`` at every sanctioned
    host crossing: records (or, in enforce, raises on) crossings made
    while this thread holds a registry lock without an enclosing
    :func:`allowed_while_locked`."""
    if _mode == "off":
        return
    if getattr(_tls, "allow", 0):
        return
    held = held_locks()
    if not held:
        return
    finding = {"locks": held, "transfer": reason, "stack": _stack()}
    if _mode == "enforce":
        raise LockHeldAcrossTransferError(
            f"host transfer ({reason}) while holding {held} — narrow the "
            "critical section or sanction it with "
            f"lockdep.allowed_while_locked(<reason>)\n{finding['stack']}")
    with _mu_section():
        if len(_state.transfers) < _MAX_FINDINGS:
            _state.transfers.append(finding)


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------

def stats() -> Dict[str, Dict]:
    """Per-lock cumulative wait/hold seconds, acquire counts, and the
    per-span attribution (bench runner reads deltas of this)."""
    with _mu_section():
        out = {}
        for name, st in sorted(_state.stats.items()):
            out[name] = {
                "waitS": round(st["waitS"], 4),
                "holdS": round(st["holdS"], 4),
                "acquires": st["acquires"],
                "spans": {s: {"waitS": round(v["waitS"], 4),
                              "holdS": round(v["holdS"], 4)}
                          for s, v in sorted(st["spans"].items())},
            }
        return out


def stats_delta(before: Dict, after: Optional[Dict] = None) -> Dict:
    """Per-lock growth of wait/hold/acquires (and per-span attribution)
    between two :func:`stats` snapshots, dropping untouched locks — the
    per-query lock report (bench runner, query listeners)."""
    if after is None:
        after = stats()
    out: Dict = {}
    for name, now in after.items():
        was = before.get(name, {"waitS": 0.0, "holdS": 0.0, "acquires": 0,
                                "spans": {}})
        d = {"waitS": round(now["waitS"] - was["waitS"], 4),
             "holdS": round(now["holdS"] - was["holdS"], 4),
             "acquires": now["acquires"] - was["acquires"]}
        # acquires counts at acquire but holdS accrues at release, so a
        # lock taken before the window and released inside it shows
        # acquires == 0 with nonzero holdS — exactly the long-hold stall
        # the metric exists to expose
        if not (d["acquires"] or d["waitS"] or d["holdS"]):
            continue
        spans = {}
        for s, v in now["spans"].items():
            w = was["spans"].get(s, {"waitS": 0.0, "holdS": 0.0})
            ds = {"waitS": round(v["waitS"] - w["waitS"], 4),
                  "holdS": round(v["holdS"] - w["holdS"], 4)}
            if ds["waitS"] or ds["holdS"]:
                spans[s] = ds
        if spans:
            d["spans"] = spans
        out[name] = d
    return out


def report() -> Dict:
    """Full lockdep report: mode, per-lock stats, the order graph, every
    inversion (with both stacks), and held-across-transfer findings."""
    with _mu_section():
        edges = [{"edge": f"{a} -> {b}", "count": e["count"]}
                 for (a, b), e in sorted(_state.edges.items())]
        cycles = list(_state.cycles)
        transfers = list(_state.transfers)
    return {"mode": _mode, "locks": stats(), "edges": edges,
            "cycles": cycles, "heldAcrossTransfer": transfers}
