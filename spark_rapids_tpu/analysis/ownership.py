"""Static ownership/escape analyzer: the lexical half of the engine's
device-memory ownership discipline (``analysis/ledger.py`` is the
runtime half).

Device buffers change owners at a handful of declared boundaries —
fused-program donation (``_donate_argnums``), the spill catalog's
register/acquire/remove, the spillable-handle ``close``, the staging
arena's acquire/release, tier flips and the deferred-finalizer queue.
Every one of those is an *ownership sink*: after the call, somebody
else (or nobody) owns the bytes. The bugs this analyzer targets are the
lexical shapes of getting that wrong: reading a batch after its arrays
were donated, acquiring a spillable handle and forgetting to close it,
freeing the same handle twice, and parking device values in a
module-global container the :class:`~..exec.spill.BufferCatalog` never
sees. The runtime ledger catches the survivors per query; these rules
catch the pattern at lint time, before it ships.

Scope — the buffer-handling modules: ``exec/``, ``io/``, ``shuffle/``,
``columnar/``, plus ``plan/physical.py`` and ``plan/stage_compiler.py``
(where donation lives). Pure AST + text; no engine import.

Rules (wired into ``python -m tools.lint``, tier-1-enforced):

``use-after-donate``
    A function computes ``donate = _donate_argnums(batch, ...)``,
    invokes a ``_fused_fn(...)(...)`` program over ``batch``'s arrays,
    and then reads ``batch`` again on the straight-line path. The
    donated invocation consumed the arrays — a later read is jax's bare
    "Array has been deleted", with no owner attribution. Reads inside
    ``except`` handlers are exempt (the documented failure-path idiom
    probes ``_donation_consumed`` and re-reads only when the program
    never ran), as are the probe/mark calls themselves.

``unreleased-acquire``
    A function binds an owning acquire (``SpillableColumnarBatch(...)``,
    ``_staging_acquire(...)``, ``_StagingTracker(...)``) to a local name
    and neither releases it (``.close()`` / ``.free()`` /
    ``.release_all()`` / ``_staging_release(x)``), escapes it (returns /
    yields / stores / passes it on — ownership moved with it), nor binds
    it in a ``with`` statement. The handle's device bytes stay
    registered forever: the static shape of a leak.

``double-free``
    Two straight-line free calls (``.close()`` / ``.free()``) on the
    same acquire-bound local with no rebinding between them, or two
    catalog ``.remove(id)`` calls with the same argument. Frees inside
    ``except``/``finally`` bodies are exempt (cleanup paths legitimately
    re-close; the handles are idempotent there by contract).

``untracked-residency``
    A module-level container receives a device-ish value (a ``jnp.*``
    call, ``jax.device_put``, a ``ColumnarBatch``/``from_flat_arrays``
    construction, or ``.flat_arrays()`` output) via subscript-assign /
    ``append`` / ``add`` / ``setdefault``. Process-global device
    residency outside the BufferCatalog is invisible to the spill
    cascade, the budget, and the ledger's audit.

Suppression mirrors the other family linters — ONE pragma tag, reason
mandatory, on the flagged line or the line above::

    _IDX_CACHE[key] = idx   # lint: ownership-ok bounded per-shape cache

Reason-less pragmas are themselves flagged (``pragma-reason``) and do
not suppress.

The declared sink surface is :data:`OWNERSHIP_SINKS`; the cross-module
registry check (``ownership-registry``) fails when a declared sink's
definition vanishes from the tree — the registry must describe the code
that exists.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .lint import LintViolation

SCOPE_PREFIXES = ("exec/", "io/", "shuffle/", "columnar/")
SCOPE_FILES = ("plan/physical.py", "plan/stage_compiler.py")

#: Every ownership-consuming/transferring call boundary, as
#: ``(kind, canonical)`` — canonical is ``<module>.<Class>.<def>`` with
#: ``/`` -> ``.`` and the class omitted for module-level defs. The
#: rules below key off the terminal names; the registry check verifies
#: each canonical still has a definition in the tree.
OWNERSHIP_SINKS: Tuple[Tuple[str, str], ...] = (
    # fused-program donation (docs/analysis.md §7): the argnums builder,
    # the failure-path consumption probe, and the success-path marker
    ("donate", "plan.physical._donate_argnums"),
    ("donate-probe", "plan.physical._donation_consumed"),
    ("donate-mark", "plan.physical._note_donated"),
    # owning acquires: the caller holds device bytes until release
    ("acquire", "exec.spill.SpillableColumnarBatch"),
    ("acquire", "io.scan._staging_acquire"),
    ("acquire", "io.scan._StagingTracker"),
    # borrow: the catalog keeps ownership; no release obligation
    ("borrow", "exec.spill.BufferCatalog.acquire_batch"),
    # frees: after the call the bytes are gone (or tombstoned)
    ("free", "exec.spill.BufferCatalog.remove"),
    ("free", "exec.spill.SpillableColumnarBatch.close"),
    ("free", "exec.spill.SpillableBuffer.free"),
    ("release", "io.scan._staging_release"),
    ("release", "io.scan._StagingTracker.release_all"),
    # tier flips: ownership stays put, residency moves (the ledger's
    # note_tier hooks live inside these)
    ("tier", "exec.spill.SpillableBuffer.spill_to_host"),
    ("tier", "exec.spill.SpillableBuffer.spill_to_disk"),
    ("tier", "exec.spill.SpillableBuffer.promote_to_device"),
    ("tier", "exec.spill.SpillableBuffer.demote_to_pinned_disk"),
    ("tier", "exec.spill.BufferCatalog.pin_to_disk"),
    # deferred free: ownership parks on the finalizer queue until the
    # next drain (end_of_query drains before auditing)
    ("defer", "exec.spill.defer_finalizer"),
)

#: terminal names of the OWNING acquire sinks (unreleased-acquire /
#: double-free track locals bound from these)
OWNING_ACQUIRES = {c.rsplit(".", 1)[-1] for k, c in OWNERSHIP_SINKS
                   if k == "acquire"}
#: method names that release an owning acquire
RELEASE_METHODS = {"close", "free", "release_all"}
#: module-level functions that release when passed the handle
RELEASE_FUNCS = {"_staging_release"}
#: calls a donated batch may still legally flow into
DONATE_EXEMPT_CALLS = {"_donation_consumed", "_note_donated",
                       "mark_donated", "check_batch_access"}
#: batch attributes that touch the (donated, hence dead) device arrays —
#: metadata reads (.num_rows/.schema/.capacity) survive donation
ARRAY_ATTRS = {"flat_arrays", "columns", "fetch_to_host", "rows",
               "to_arrow", "to_pandas", "arrays", "select"}

PRAGMA_RE = re.compile(r"#\s*lint:\s*(ownership)-ok(.*)$")

#: container factory callables recognized at module level
_CONTAINER_FACTORIES = {"dict", "list", "set", "OrderedDict",
                        "defaultdict", "WeakValueDictionary"}
#: mutators that insert a value into a container
_INSERT_METHODS = {"append", "add", "setdefault"}


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in SCOPE_FILES


def _pragmas(source: str) -> Dict[int, str]:
    """line -> reason (possibly empty) for ownership-ok pragmas."""
    out: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = PRAGMA_RE.search(line)
        if m:
            out[i] = m.group(2).strip()
    return out


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _deviceish(node: ast.AST) -> bool:
    """The expression syntactically produces device memory: a jnp call,
    jax.device_put, a ColumnarBatch construction (incl. from_flat_arrays)
    or a .flat_arrays() read — the conservative subset the AST proves."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "jnp":
                return True
            if isinstance(base, ast.Name) and base.id == "jax" and \
                    f.attr == "device_put":
                return True
            if f.attr in ("from_flat_arrays", "flat_arrays"):
                return True
        elif isinstance(f, ast.Name) and f.id == "ColumnarBatch":
            return True
    return False


# ---------------------------------------------------------------------------
# Per-function ownership walk (use-after-donate / unreleased-acquire /
# double-free share one traversal)
# ---------------------------------------------------------------------------

class _CleanupTagger(ast.NodeVisitor):
    """Tags every node reachable inside an ``except`` handler or a
    ``finally`` body — the cleanup paths the straight-line rules
    exempt."""

    def __init__(self) -> None:
        self.cleanup: Set[ast.AST] = set()

    def _mark(self, stmts) -> None:
        for st in stmts:
            for sub in ast.walk(st):
                self.cleanup.add(sub)

    def visit_Try(self, node: ast.Try) -> None:
        for h in node.handlers:
            self._mark(h.body)
        self._mark(node.finalbody)
        self.generic_visit(node)


def _function_findings(fn: ast.AST, pragmas: Dict[int, str], path: str
                       ) -> List[LintViolation]:
    out: List[LintViolation] = []
    tagger = _CleanupTagger()
    tagger.visit(fn)
    cleanup = tagger.cleanup

    def suppressed(line: int) -> bool:
        return any(l in pragmas and pragmas[l] for l in (line, line - 1))

    # ---- collect per-function facts --------------------------------------
    donated: Dict[str, int] = {}        # batch name -> _donate_argnums line
    invocation: Dict[str, int] = {}     # batch name -> fused-invocation line
    # pre-pass: locals bound to a _fused_fn(...) result — `fn = _fused_fn
    # (sig, build)` then `fn(...)` is the dominant invocation idiom
    fused_names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                _callee_name(node.value.func) == "_fused_fn":
            fused_names.add(node.targets[0].id)
    acquires: Dict[str, int] = {}       # local name -> owning-acquire line
    released: Set[str] = set()
    escaped: Set[str] = set()
    rebinds: Dict[str, List[int]] = {}  # name -> later assignment lines
    frees: Dict[str, List[int]] = {}    # name -> straight-line free lines
    removes: Dict[str, List[int]] = {}  # remove-arg repr -> call lines
    with_bound: Set[str] = set()

    for node in ast.walk(fn):
        if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call) and \
                        _callee_name(ce.func) in OWNING_ACQUIRES:
                    if isinstance(item.optional_vars, ast.Name):
                        with_bound.add(item.optional_vars.id)
        elif isinstance(node, ast.Assign):
            tgt = node.targets[0] if len(node.targets) == 1 else None
            # _donate_argnums(X, ...) bound anywhere in the value (the
            # `if owned else ()` conditional form included)
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call) and \
                        _callee_name(sub.func) == "_donate_argnums" and \
                        sub.args and isinstance(sub.args[0], ast.Name):
                    donated.setdefault(sub.args[0].id, node.lineno)
            if isinstance(tgt, ast.Name):
                name = tgt.id
                if name in acquires or name in donated:
                    rebinds.setdefault(name, []).append(node.lineno)
                if isinstance(node.value, ast.Call) and \
                        _callee_name(node.value.func) in OWNING_ACQUIRES:
                    acquires.setdefault(name, node.lineno)
            else:
                # tuple / attribute / subscript targets: the acquire (if
                # any) is stored somewhere longer-lived — an escape
                if isinstance(node.value, ast.Call) and \
                        _callee_name(node.value.func) in OWNING_ACQUIRES:
                    pass                     # never tracked, never flagged
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = getattr(node, "value", None)
            if v is not None:
                escaped |= _names_in(v)
        elif isinstance(node, ast.Call):
            callee = _callee_name(node.func)
            # fused invocation over a donated batch:
            # _fused_fn(sig, build)(..., *batch.flat_arrays()) or the
            # bound form fn = _fused_fn(...); fn(..., *batch...)
            if (isinstance(node.func, ast.Call) and
                _callee_name(node.func.func) == "_fused_fn") or \
                    (isinstance(node.func, ast.Name) and
                     node.func.id in fused_names):
                for name in donated:
                    arg_names: Set[str] = set()
                    for a in list(node.args) + [kw.value
                                                for kw in node.keywords]:
                        arg_names |= _names_in(a)
                    if name in arg_names:
                        invocation.setdefault(
                            name,
                            getattr(node, "end_lineno", None)
                            or node.lineno)
            # releases: x.close() / x.free() / x.release_all()
            if callee in RELEASE_METHODS and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name):
                recv = node.func.value.id
                released.add(recv)
                if node not in cleanup:
                    frees.setdefault(recv, []).append(node.lineno)
            elif callee in RELEASE_FUNCS:
                for a in node.args:
                    released |= _names_in(a)
            # catalog remove: two straight-line calls with the same arg
            if callee == "remove" and node.args and \
                    isinstance(node.func, ast.Attribute) and \
                    "catalog" in ast.dump(node.func.value).lower() and \
                    node not in cleanup:
                key = ast.dump(node.args[0])
                removes.setdefault(key, []).append(node.lineno)
            # an acquire handed to any other call escapes (ownership
            # moved with it — the callee's problem now)
            if callee not in RELEASE_METHODS and \
                    callee not in RELEASE_FUNCS:
                for a in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    escaped |= _names_in(a)
        elif isinstance(node, (ast.Dict, ast.List, ast.Set, ast.Tuple)):
            escaped |= _names_in(node)

    # ---- use-after-donate ------------------------------------------------
    # donation kills the batch's FLAT ARRAYS, not its metadata: reading
    # .num_rows/.schema/.capacity after the invocation is fine (only the
    # donated argnums are consumed). Flag array-touching uses — an
    # ARRAY_ATTRS access, or the bare batch handed to another call — on
    # the straight-line path between the invocation and the branch's
    # first return/raise (code past that barrier belongs to a sibling
    # branch the donated invocation never reaches).
    returns = sorted(
        (n.lineno, getattr(n, "end_lineno", None) or n.lineno)
        for n in ast.walk(fn)
        if isinstance(n, (ast.Return, ast.Raise)) and n not in cleanup)
    for name, inv_end in invocation.items():
        barrier = next((e for l, e in returns if l > inv_end), 10 ** 9)
        for sub in ast.walk(fn):
            ln = getattr(sub, "lineno", None)
            if ln is None or not (inv_end < ln <= barrier) or \
                    sub in cleanup:
                continue
            use = None
            if isinstance(sub, ast.Attribute) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id == name and sub.attr in ARRAY_ATTRS:
                use = f".{sub.attr} read"
            elif isinstance(sub, ast.Call):
                callee = _callee_name(sub.func)
                if callee in DONATE_EXEMPT_CALLS:
                    continue
                if (isinstance(sub.func, ast.Call) and
                    _callee_name(sub.func.func) == "_fused_fn") or \
                        (isinstance(sub.func, ast.Name) and
                         sub.func.id in fused_names):
                    continue             # the invocation itself
                for a in list(sub.args) + [kw.value
                                           for kw in sub.keywords]:
                    if isinstance(a, ast.Starred):
                        a = a.value
                    if isinstance(a, ast.Name) and a.id == name:
                        use = f"handed to {callee}()"
                        break
            if use is None or suppressed(ln):
                continue
            out.append(LintViolation(
                path, ln, "use-after-donate",
                f"{name!r} was donated to a fused program (donate_argnums"
                f" from line {donated[name]}, invoked by line {inv_end}) "
                f"and its arrays are {use} again on the straight-line "
                "path — the donated arrays are dead; restructure, or "
                "pragma with `# lint: ownership-ok <reason>`"))
            break                        # one diagnosis per name

    # ---- unreleased-acquire ----------------------------------------------
    for name, line in acquires.items():
        if name in with_bound or name in released or name in escaped:
            continue
        if suppressed(line):
            continue
        out.append(LintViolation(
            path, line, "unreleased-acquire",
            f"{name!r} binds an owning acquire that is never released "
            "(close/free/release_all), never escapes, and is not a "
            "`with` binding — its device bytes stay registered forever; "
            "release it in a finally, or pragma with "
            "`# lint: ownership-ok <reason>`"))

    # ---- double-free -----------------------------------------------------
    for name, lines in frees.items():
        if name not in acquires and name not in with_bound:
            continue                     # only tracked handles (no noise
            #                              from file.close() etc.)
        lines = sorted(lines)
        rb = sorted(rebinds.get(name, ()))
        for a, b in zip(lines, lines[1:]):
            if any(a < r <= b for r in rb):
                continue                 # rebound between frees: fine
            if suppressed(b):
                continue
            out.append(LintViolation(
                path, b, "double-free",
                f"{name!r} is freed here and was already freed at line "
                f"{a} with no rebinding between — the second free "
                "tombstones an id someone else may now own; drop it, "
                "or pragma with `# lint: ownership-ok <reason>`"))
    for key, lines in removes.items():
        lines = sorted(lines)
        for a, b in zip(lines, lines[1:]):
            if suppressed(b):
                continue
            out.append(LintViolation(
                path, b, "double-free",
                f"catalog .remove() of the same buffer id here and at "
                f"line {a} — the second remove is a double-free; drop "
                "it, or pragma with `# lint: ownership-ok <reason>`"))
    return out


def _in_exempt_call(fn: ast.AST, name_node: ast.Name) -> bool:
    """``name_node`` is an argument of a donate-probe/mark call — the
    calls a donated batch may still legally flow into."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                _callee_name(node.func) in DONATE_EXEMPT_CALLS:
            for a in node.args:
                if name_node in ast.walk(a):
                    return True
    return False


# ---------------------------------------------------------------------------
# untracked-residency (module-level containers holding device values)
# ---------------------------------------------------------------------------

def _module_containers(tree: ast.Module) -> Set[str]:
    """Names bound at module level to a mutable-container literal or
    factory call."""
    out: Set[str] = set()
    for st in tree.body:
        tgt = None
        val = None
        if isinstance(st, ast.Assign) and len(st.targets) == 1:
            tgt, val = st.targets[0], st.value
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            tgt, val = st.target, st.value
        if not isinstance(tgt, ast.Name) or val is None:
            continue
        if isinstance(val, (ast.Dict, ast.List, ast.Set)):
            out.add(tgt.id)
        elif isinstance(val, ast.Call) and \
                _callee_name(val.func) in _CONTAINER_FACTORIES:
            out.add(tgt.id)
    return out


def _residency_hits(tree: ast.Module) -> List[Tuple[int, str, str]]:
    """(line, container, how) for every device-ish value inserted into a
    module-level container."""
    containers = _module_containers(tree)
    if not containers:
        return []
    hits: List[Tuple[int, str, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in containers and \
                        _deviceish(node.value):
                    hits.append((node.lineno, t.value.id,
                                 "subscript assignment"))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _INSERT_METHODS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in containers:
            vals = node.args[1:] if node.func.attr == "setdefault" \
                else node.args
            if any(_deviceish(a) for a in vals):
                hits.append((node.lineno, node.func.value.id,
                             f".{node.func.attr}()"))
    return hits


# ---------------------------------------------------------------------------
# Entry points (lint.py wires these)
# ---------------------------------------------------------------------------

def lint_source(source: str, rel: str, path: Optional[str] = None
                ) -> List[LintViolation]:
    """Run the ownership rules over one module's source. ``rel`` decides
    scope membership; pragma-reason findings are emitted for any module
    carrying the tag."""
    path = path or rel
    out: List[LintViolation] = []
    pragmas = _pragmas(source)
    for line, reason in pragmas.items():
        if not reason:
            out.append(LintViolation(
                path, line, "pragma-reason",
                "ownership-ok pragma missing its justification "
                "(format: `# lint: ownership-ok <reason>`)"))
    if not in_scope(rel):
        return out
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return out                       # the parse rule reports it
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.extend(_function_findings(node, pragmas, path))
    for line, container, how in _residency_hits(tree):
        if any(l in pragmas and pragmas[l] for l in (line, line - 1)):
            continue
        out.append(LintViolation(
            path, line, "untracked-residency",
            f"module-level container {container!r} receives a device-ish "
            f"value via {how} — residency outside the BufferCatalog is "
            "invisible to the spill cascade and the ledger audit; "
            "register it, or pragma with `# lint: ownership-ok <reason>`"))
    return out


def sink_registry(package_dir: str) -> Set[str]:
    """Every canonical def/class name the tree defines, in the
    OWNERSHIP_SINKS naming scheme (``module.path.Class.def``) — the
    ground truth the registry check compares against."""
    defined: Set[str] = set()
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, package_dir).replace(os.sep, "/")
            mod = rel[:-3].replace("/", ".")
            try:
                with open(full, "r") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue

            def walk(node, prefix):
                for st in getattr(node, "body", ()):
                    if isinstance(st, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.ClassDef)):
                        q = f"{prefix}.{st.name}"
                        defined.add(q)
                        walk(st, q)

            walk(tree, mod)
    return defined


def check_registry(defined: Set[str]) -> List[LintViolation]:
    """``ownership-registry``: a declared sink whose definition no
    longer exists in the tree — the registry must describe the code
    that exists (the LOCKSTEP_IDS stale-entry discipline)."""
    out: List[LintViolation] = []
    for kind, canonical in OWNERSHIP_SINKS:
        if canonical not in defined:
            out.append(LintViolation(
                "analysis/ownership.py", 0, "ownership-registry",
                f"OWNERSHIP_SINKS declares {kind} sink {canonical!r} "
                "but no such definition exists in the tree — update the "
                "registry to match the code"))
    return out
