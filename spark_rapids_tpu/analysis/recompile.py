"""Recompile audit: distinct compiled shapes per fused kernel.

Whole-stage programs compile per (expression structure, schema, capacity)
signature; the capacity-bucketing discipline (columnar.column.bucket)
exists precisely so a stream of slightly-different batch sizes reuses ONE
compiled program instead of recompiling per shape. A regression there is
invisible in unit tests (everything still returns the right rows) but
catastrophic on real backends where compiles cost seconds — so this audit
counts, per kernel family, how many distinct signatures actually compiled
versus how many calls ran, and flags kernels whose compile count tracks
their call count (the compiling-once-per-batch-shape smell).

Wired into the one funnel every fused program goes through
(``plan/physical._fused_fn`` and per-exec ``FusedStage`` jits); the bench
runner reports per-query deltas (``report``/``snapshot``/``delta``) next
to the sync and semaphore metrics. Gated by
``spark.rapids.tpu.sql.analysis.recompileAudit`` (default on — the cost
is a dict increment per fused-program call).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .lockdep import named_lock

# flag a kernel once it has compiled this many times AND compiles on at
# least half of its calls — a well-bucketed kernel stream compiles a
# handful of shapes then hits the cache forever
FLAG_MIN_COMPILES = 8

_lock = named_lock("analysis.recompile._lock")
# name -> {keys: set, compiles: int, calls: int, coldCompiles: int,
# diskHits: int, compileS: float}. ``compiles`` counts EVERY cache-miss
# build (a same-key recompile after the fused cache evicts is real churn
# and must show), ``keys`` counts distinct shapes. ``coldCompiles`` vs
# ``diskHits`` splits builds by the persistent-cache classification
# (exec/compile_cache.classify): a disk hit loads the executable from
# the on-disk XLA cache instead of recompiling, so a warm restart with
# ``compile.cacheDir`` set should show coldCompiles == 0 for repeated
# shapes. ``compileS`` accumulates first-call (compile-dominated) wall
# seconds per family.
_kernels: Dict[str, Dict[str, Any]] = {}
_enabled_cache: Optional[bool] = None


def _enabled() -> bool:
    global _enabled_cache
    if _enabled_cache is None:
        try:
            from .. import config as cfg
            from .sync_audit import _effective_conf
            enabled = bool(
                _effective_conf().get(cfg.ANALYSIS_RECOMPILE_AUDIT))
        except Exception:
            enabled = True
        with _lock:
            _enabled_cache = enabled
    return _enabled_cache


def reset_cache() -> None:
    global _enabled_cache
    with _lock:
        _enabled_cache = None


def kernel_of(key: Any) -> str:
    """Kernel family of a fused-cache signature: the top-level string
    tags joined (``concat``, ``project``, ``agg/update/partial/dense``,
    ...) — shapes/schemas live in nested tuples and stay out of the
    family name."""
    if isinstance(key, tuple):
        tags = [p for p in key if isinstance(p, str)]
        if tags:
            return "/".join(tags)
    return "anon"


def _ent(kernel: str) -> Dict[str, Any]:
    return _kernels.setdefault(kernel,
                               {"keys": set(), "compiles": 0, "calls": 0,
                                "coldCompiles": 0, "diskHits": 0,
                                "compileS": 0.0})


def note_compile(kernel: str, key: Any, kind: str = "cold") -> None:
    """Record a cache miss: a program built (new shape OR a same-key
    rebuild after eviction — both are paid compile time). ``kind`` is
    the persistent-cache classification (``cold`` build vs ``disk``
    hit, exec/compile_cache.classify)."""
    if not _enabled():
        return
    with _lock:
        ent = _ent(kernel)
        ent["keys"].add(key)
        ent["compiles"] += 1
        ent["calls"] += 1
        ent["diskHits" if kind == "disk" else "coldCompiles"] += 1
    # charge the innermost open exec's metrics bag so EXPLAIN ANALYZE
    # shows which plan node paid the compile (exec/metrics attribution)
    from ..exec.metrics import attribute
    attribute("recompiles")
    # flight-recorder breadcrumb: a compile right before a crash is a
    # prime post-mortem suspect (OOM during build, shape explosion)
    from ..service.telemetry import flight_record
    flight_record("recompile", kernel)


def note_call(kernel: str) -> None:
    """Record a cache hit (a call that reused a compiled program)."""
    if not _enabled():
        return
    with _lock:
        _ent(kernel)["calls"] += 1


def note_compile_time(kernel: str, seconds: float) -> None:
    """Accumulate one built program's first-call (compile-dominated)
    wall seconds onto its family (exec/compile_cache.TimedFirstCall)."""
    if not _enabled():
        return
    with _lock:
        _ent(kernel)["compileS"] += float(seconds)


def report() -> Dict[str, Dict[str, int]]:
    with _lock:
        return {k: {"compiles": v["compiles"],
                    "distinctShapes": len(v["keys"]),
                    "calls": v["calls"],
                    "coldCompiles": v.get("coldCompiles", 0),
                    "diskHits": v.get("diskHits", 0),
                    "compileS": round(v.get("compileS", 0.0), 4)}
                for k, v in sorted(_kernels.items())}


def snapshot() -> Dict[str, Dict[str, int]]:
    """Point-in-time counters for delta reporting (bench runner)."""
    return report()


def delta(base: Dict[str, Dict[str, int]]) -> Dict[str, Dict[str, int]]:
    """Per-kernel counter growth since ``base`` (dropping unchanged
    kernels)."""
    out: Dict[str, Dict[str, int]] = {}
    zero = {"compiles": 0, "distinctShapes": 0, "calls": 0}
    for k, now in report().items():
        was = base.get(k, zero)
        d = {f: now[f] - was.get(f, 0) for f in now}
        if any(d.values()):
            out[k] = d
    return out


def flagged(counters: Optional[Dict[str, Dict[str, int]]] = None
            ) -> Dict[str, str]:
    """Kernels compiling once per call: many compiles AND compiling on >=
    half their calls — missed capacity-bucket padding, or cache-eviction
    churn (same shapes rebuilt after _FUSED_CACHE clears)."""
    counters = report() if counters is None else counters
    out: Dict[str, str] = {}
    leaks = size_class_report()
    for k, c in counters.items():
        n, calls = c["compiles"], max(c["calls"], 1)
        # STRICTLY more than half the calls: the cold+hot two-iteration
        # pattern with perfect cache reuse lands exactly at
        # calls == 2*compiles, which is the healthy baseline the bench
        # runner produces — only compiling beyond it is churn
        if n >= FLAG_MIN_COMPILES and n * 2 > calls:
            msg = (f"{n} compiles ({c.get('distinctShapes', n)} distinct "
                   f"shapes) over {calls} calls — compiling per batch "
                   "shape or churning the fused cache (check capacity "
                   "bucketing)")
            if k in leaks:
                msg += (f"; un-bucketed dimensions in its signatures: "
                        f"{leaks[k]['dims']}")
            out[k] = msg
    return out


# ---------------------------------------------------------------------------
# Size-class audit: trace signatures back to un-bucketed dimensions
# ---------------------------------------------------------------------------

def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def unbucketed_dims(key: Any) -> list:
    """Integer dimensions inside one compiled signature that escaped the
    power-of-two size-class discipline: every shape-bearing int in a
    fused-cache key (capacities, padded string widths, group buckets
    ``Kb``, window frames) is supposed to be a power of two >= its
    class minimum, so a stream of ragged batches reuses ONE program.
    Anything >= 8 and not a power of two is a leak — the dimension that
    made this signature distinct. Small ints (< 8) are op counts and
    flags, not shapes; bools are flags."""
    out = []

    def walk(v):
        if isinstance(v, bool):
            return
        if isinstance(v, int):
            if v >= 8 and not _is_pow2(v):
                out.append(v)
            return
        if isinstance(v, tuple):
            for x in v:
                walk(x)
    walk(key)
    return out


#: families whose signatures legitimately carry non-power-of-two ints:
#: scan_unpack keys hold 8-byte-aligned staging-buffer OFFSETS — sums of
#: bucketed per-column footprints (each pow2-derived, the sum not) — so
#: their distinctness is bounded by #tables x #cap-buckets, never by the
#: per-batch row count the bucket discipline exists to absorb
SIZE_CLASS_EXEMPT = ("scan_unpack",)


def size_class_report() -> Dict[str, Dict[str, Any]]:
    """Per-kernel-family audit of signatures carrying un-bucketed
    dimensions: ``{family: {"dims": [ints], "signatures": n}}`` for every
    family where at least one compiled signature leaked past the bucket
    discipline — the 'which dimension caused this recompile' answer the
    flag message alone cannot give."""
    with _lock:
        snap = {k: list(v["keys"]) for k, v in _kernels.items()}
    out: Dict[str, Dict[str, Any]] = {}
    for kernel, keys in sorted(snap.items()):
        if kernel in SIZE_CLASS_EXEMPT:
            continue
        dims: set = set()
        hit = 0
        for key in keys:
            # unkeyable per-instance builds carry id(self) in their key
            # (FusedStage's note_compile) — a memory address is not a
            # shape dimension
            if isinstance(key, tuple) and "unkeyable" in [
                    p for p in key if isinstance(p, str)]:
                continue
            d = unbucketed_dims(key)
            if d:
                hit += 1
                dims.update(d)
        if hit:
            out[kernel] = {"dims": sorted(dims), "signatures": hit}
    return out


def reset() -> None:
    """Drop all counters (tests)."""
    with _lock:
        _kernels.clear()
