"""Runtime sync auditor: arm ``jax.transfer_guard`` around operator
execute regions.

Two complementary mechanisms guard the device-residency invariant at
runtime (the static linter guards it at review time):

* **Attributed counting** (always available, deterministic on every
  backend): ``exec/tracing.SyncCounter`` hooks the one funnel every
  blocking readback goes through and attributes each to its source site
  AND to the innermost open trace span, so the bench runner reports
  syncs-per-query broken down by span next to the semaphore wait/hold
  split.

* **Transfer-guard arming** (this module; real accelerators only — on the
  CPU backend arrays already live in host memory, so jax never raises):
  when ``spark.rapids.tpu.sql.analysis.syncAudit`` is ``log`` or
  ``disallow``, every partition-drain task body runs under
  ``jax.transfer_guard_device_to_host(mode)``. jax's guard only fires on
  *implicit* transfers (``np.asarray``, ``float()`` on a device value);
  explicit ``jax.device_get`` — which is exactly what the sanctioned
  batched-resolve helpers use — stays legal even under ``disallow``. The
  engine's contract is therefore mechanical: hot paths either keep values
  on device or read them back through an explicit batched resolve; the
  few deliberately-implicit host crossings (the CPU fallback engine's
  pandas materialization) wrap themselves in
  :func:`allowed_host_transfer`, which is the greppable runtime allowlist.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from .lockdep import named_lock

MODES = ("off", "log", "disallow")

_mode_cache: Optional[str] = None
_armed = 0                      # count of live audited regions (any thread)
_lock = named_lock("analysis.sync_audit._lock")


def _effective_conf():
    """The active session's conf when one exists (builder-set keys must
    reach the audit), else process defaults + env overrides."""
    from .. import config as cfg
    try:
        from ..api.session import TpuSession
        with TpuSession._lock:
            active = TpuSession._active
        if active is not None:
            return active.conf
    except Exception:
        pass
    return cfg.TpuConf()


def audit_mode() -> str:
    """Configured audit mode, cached per process (conf reads on the hot
    path would defeat the point). The cache primes from the session
    active at first use; switching modes mid-process needs
    :func:`reset_cache` (session construction calls it)."""
    global _mode_cache
    if _mode_cache is None:
        from .. import config as cfg
        mode = str(_effective_conf().get(cfg.ANALYSIS_SYNC_AUDIT)).lower()
        if mode not in MODES:
            mode = "off"
        with _lock:
            _mode_cache = mode
    return _mode_cache


def reset_cache() -> None:
    global _mode_cache
    with _lock:
        _mode_cache = None


@contextlib.contextmanager
def audited_region():
    """Wrap one operator execute region (a partition-drain task body).
    No-op when the audit is off; otherwise arms the jax device->host
    transfer guard at the configured level for this thread."""
    mode = audit_mode()
    if mode == "off":
        yield
        return
    global _armed
    import jax
    with _lock:
        _armed += 1
    try:
        with jax.transfer_guard_device_to_host(mode):
            yield
    finally:
        with _lock:
            _armed -= 1


@contextlib.contextmanager
def allowed_host_transfer(reason: str):
    """Sanction an implicit device->host crossing inside an audited
    region (the runtime analog of the linter's ``host-sync-ok`` pragma).
    ``reason`` is required purely so call sites document themselves —
    grep: ``grep -rn 'allowed_host_transfer' spark_rapids_tpu/``."""
    assert reason, "allowed_host_transfer requires a reason"
    # lockdep integration: a sanctioned host crossing made while this
    # thread holds a registry lock is a blocking-under-lock hazard —
    # recorded (record) or raised (enforce) unless the holding path
    # wrapped itself in lockdep.allowed_while_locked(<reason>)
    from . import lockdep
    lockdep.note_host_transfer(reason)
    if not _armed:
        yield
        return
    import jax
    with jax.transfer_guard_device_to_host("allow"):
        yield
