"""Column wrapper: operator-overloaded expression builder (pyspark Column
analog). The reference exposes Spark's own API; standalone we provide the same
surface so pyspark-style code ports 1:1.
"""

from __future__ import annotations

from typing import Any, Optional

from ..columnar import dtypes as dt
from ..ops import arithmetic as ar
from ..ops import conditionals as co
from ..ops import expressions as ex
from ..ops import predicates as pr
from ..ops.cast import Cast
from ..plan import logical as lp


def _unwrap(v: Any) -> ex.Expression:
    if isinstance(v, Col):
        return v.expr
    if isinstance(v, ex.Expression):
        return v
    return ex.Literal(v)


class Col:
    def __init__(self, expr: ex.Expression):
        self.expr = expr

    # arithmetic
    def __add__(self, o): return Col(ar.Add(self.expr, _unwrap(o)))
    def __radd__(self, o): return Col(ar.Add(_unwrap(o), self.expr))
    def __sub__(self, o): return Col(ar.Subtract(self.expr, _unwrap(o)))
    def __rsub__(self, o): return Col(ar.Subtract(_unwrap(o), self.expr))
    def __mul__(self, o): return Col(ar.Multiply(self.expr, _unwrap(o)))
    def __rmul__(self, o): return Col(ar.Multiply(_unwrap(o), self.expr))
    def __truediv__(self, o): return Col(ar.Divide(self.expr, _unwrap(o)))
    def __rtruediv__(self, o): return Col(ar.Divide(_unwrap(o), self.expr))
    def __mod__(self, o): return Col(ar.Remainder(self.expr, _unwrap(o)))
    def __neg__(self): return Col(ar.UnaryMinus(self.expr))

    # comparisons
    def __eq__(self, o): return Col(pr.EqualTo(self.expr, _unwrap(o)))  # type: ignore[override]
    def __ne__(self, o): return Col(pr.NotEqual(self.expr, _unwrap(o)))  # type: ignore[override]
    def __lt__(self, o): return Col(pr.LessThan(self.expr, _unwrap(o)))
    def __le__(self, o): return Col(pr.LessThanOrEqual(self.expr, _unwrap(o)))
    def __gt__(self, o): return Col(pr.GreaterThan(self.expr, _unwrap(o)))
    def __ge__(self, o): return Col(pr.GreaterThanOrEqual(self.expr, _unwrap(o)))
    def eqNullSafe(self, o): return Col(pr.EqualNullSafe(self.expr, _unwrap(o)))

    # boolean
    def __and__(self, o): return Col(pr.And(self.expr, _unwrap(o)))
    def __or__(self, o): return Col(pr.Or(self.expr, _unwrap(o)))
    def __invert__(self): return Col(pr.Not(self.expr))

    # null / membership
    def isNull(self): return Col(pr.IsNull(self.expr))
    def isNotNull(self): return Col(pr.IsNotNull(self.expr))
    def isin(self, *values):
        vals = list(values[0]) if len(values) == 1 and \
            isinstance(values[0], (list, tuple, set)) else list(values)
        return Col(pr.In(self.expr, vals))

    # string predicates
    def contains(self, other):
        from ..ops import strings as st
        return Col(st.Contains(self.expr, _unwrap(other)))

    def startswith(self, other):
        from ..ops import strings as st
        return Col(st.StartsWith(self.expr, _unwrap(other)))

    def endswith(self, other):
        from ..ops import strings as st
        return Col(st.EndsWith(self.expr, _unwrap(other)))

    def like(self, pattern: str):
        from ..ops import strings as st
        return Col(st.Like(self.expr, pattern))

    def substr(self, start, length):
        from ..ops import strings as st
        return Col(st.Substring(self.expr, _unwrap(start), _unwrap(length)))

    # misc
    def getField(self, name: str) -> "Col":
        """struct.field access (GetStructField; shredded to a flat scan
        column by the planner when possible)."""
        from ..ops.structs import GetField
        return Col(GetField(self.expr, name))

    def alias(self, name: str) -> "Col":
        return Col(ex.Alias(self.expr, name))

    name = alias

    def cast(self, to) -> "Col":
        return Col(Cast(self.expr, dt.of(to)))

    astype = cast

    def asc(self) -> lp.SortOrder:
        return lp.SortOrder(self.expr, ascending=True)

    def asc_nulls_last(self) -> lp.SortOrder:
        return lp.SortOrder(self.expr, ascending=True, nulls_first=False)

    def desc(self) -> lp.SortOrder:
        return lp.SortOrder(self.expr, ascending=False)

    def desc_nulls_first(self) -> lp.SortOrder:
        return lp.SortOrder(self.expr, ascending=False, nulls_first=True)

    def over(self, spec) -> "Col":
        """Evaluate this aggregate/window function over a window spec
        (pyspark Column.over; DataFrame.select hoists the resulting
        WindowExpression into a Window node)."""
        from ..ops.window import WindowExpression
        return Col(WindowExpression(self.expr, spec._to_spec()))

    def when(self, condition, value):
        raise TypeError("use functions.when(cond, value).otherwise(...)")

    def otherwise(self, value):
        raise TypeError("otherwise() only valid on a when() chain")

    def __repr__(self):
        return f"Col({self.expr!r})"

    __hash__ = None  # type: ignore[assignment]


class WhenChain(Col):
    """functions.when(...).when(...).otherwise(...) builder."""

    def __init__(self, branches, else_value=None):
        self._branches = branches
        self._else = else_value
        super().__init__(self._build())

    def _build(self):
        return co.CaseWhen(self._branches, self._else)

    def when(self, condition, value):
        return WhenChain(self._branches + [(_unwrap(condition), _unwrap(value))],
                         self._else)

    def otherwise(self, value):
        return WhenChain(self._branches, _unwrap(value))
