"""DataFrame + session API (pyspark surface over the logical plan builder).

The reference rides Spark's own DataFrame API; standalone we mirror the
pyspark subset its integration tests exercise (SURVEY.md §4 ring 2: joins,
aggregates, sorts, repartition, IO round-trips) so those test shapes port.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import copy

from ..columnar import dtypes as dt
from ..ops import conditionals as cd
from ..ops import expressions as ex
from ..ops import predicates as pr
from ..plan import logical as lp
from .column import Col, _unwrap
from . import functions as F

ColumnOrName = Union[Col, str]


def _to_expr(c: ColumnOrName) -> ex.Expression:
    if isinstance(c, str):
        return ex.ColumnRef(c)
    return _unwrap(c)


class DataFrame:
    def __init__(self, plan: lp.LogicalPlan, session: "TpuSession"):
        self._plan = plan
        self.session = session

    # -- plan access ---------------------------------------------------------
    @property
    def schema(self) -> dt.Schema:
        return self._analyzed().schema

    @property
    def columns(self) -> List[str]:
        return self.schema.names()

    def logical_plan(self) -> lp.LogicalPlan:
        return self._plan

    def _analyzed(self) -> lp.LogicalPlan:
        import copy
        plan = copy.deepcopy(self._plan)
        return lp.analyze(plan)

    def _df(self, plan: lp.LogicalPlan) -> "DataFrame":
        return DataFrame(plan, self.session)

    # -- transformations -----------------------------------------------------
    def select(self, *cols: ColumnOrName) -> "DataFrame":
        if not cols:
            cols = tuple(self.columns)
        exprs = [_to_expr(c) for c in cols]
        gen = self._lift_generator(exprs)
        if gen is not None:
            return gen
        win = self._lift_windows(exprs)
        if win is not None:
            return win
        return self._df(lp.Project(self._plan, exprs))

    def _lift_windows(self, exprs) -> Optional["DataFrame"]:
        """Col.over() window expressions in a select lift into a Window
        node under the projection (Catalyst's ExtractWindowExpressions
        rule): each WindowExpression becomes a generated column of an
        lp.Window, and the projection references it — so windows compose
        inside arithmetic (e.g. ``col("rev") * 100 / sum("rev").over(w)``)."""
        from ..ops.window import WindowExpression
        hoisted: List = []

        def repl(e):
            if isinstance(e, WindowExpression):
                name = f"__w{len(hoisted)}"
                hoisted.append((name, e))
                return ex.ColumnRef(name)
            return None

        new_exprs = []
        for e in exprs:
            if e.collect(lambda x: isinstance(x, WindowExpression)):
                new_exprs.append(e.transform_down(repl))
            else:
                new_exprs.append(e)
        if not hoisted:
            return None
        w = lp.Window(self._plan, hoisted)
        return self._df(lp.Project(w, new_exprs))

    def _lift_generator(self, exprs) -> Optional["DataFrame"]:
        """explode/posexplode in a select lifts into a Generate node under
        the projection (Catalyst's ExtractGenerator rule)."""
        from ..ops import arrays as ar_ops

        def inner(e):
            return e.children[0] if isinstance(e, ex.Alias) else e

        gen_idx = [i for i, e in enumerate(exprs)
                   if isinstance(inner(e), ar_ops.Explode)]
        if not gen_idx:
            return None
        if len(gen_idx) > 1:
            raise ValueError("only one generator per select (Spark rule)")
        i = gen_idx[0]
        e = exprs[i]
        g_expr = inner(e)
        col_name = e.alias if isinstance(e, ex.Alias) else "col"
        g = lp.Generate(self._plan, g_expr, col_name=col_name)
        out = []
        for j, e2 in enumerate(exprs):
            if j == i:
                if g_expr.pos:
                    out.append(ex.ColumnRef("pos"))
                out.append(ex.ColumnRef(col_name))
            else:
                out.append(e2)
        return self._df(lp.Project(g, out))

    def selectExpr(self, *exprs: str) -> "DataFrame":
        raise NotImplementedError("SQL string expressions need the parser")

    def filter(self, condition: Col) -> "DataFrame":
        return self._df(lp.Filter(self._plan, _unwrap(condition)))

    where = filter

    def withColumn(self, name: str, col: Col) -> "DataFrame":
        exprs: List[ex.Expression] = []
        replaced = False
        for c in self.columns:
            if c == name:
                exprs.append(ex.Alias(_unwrap(col), name))
                replaced = True
            else:
                exprs.append(ex.ColumnRef(c))
        if not replaced:
            exprs.append(ex.Alias(_unwrap(col), name))
        gen = self._lift_generator(exprs)     # explode() works here too
        if gen is not None:
            return gen
        win = self._lift_windows(exprs)       # Col.over() too
        if win is not None:
            return win
        return self._df(lp.Project(self._plan, exprs))

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        exprs = [ex.Alias(ex.ColumnRef(c), new) if c == old else ex.ColumnRef(c)
                 for c in self.columns]
        return self._df(lp.Project(self._plan, exprs))

    def drop(self, *names: str) -> "DataFrame":
        exprs = [ex.ColumnRef(c) for c in self.columns if c not in names]
        return self._df(lp.Project(self._plan, exprs))

    def groupBy(self, *cols: ColumnOrName) -> "GroupedData":
        return GroupedData(self, [_to_expr(c) for c in cols])

    groupby = groupBy

    def rollup(self, *cols: ColumnOrName) -> "GroupedData":
        """Hierarchical grouping sets {(a,b), (a), ()} via an Expand
        under the aggregate (GpuExpandExec path; Spark df.rollup)."""
        return GroupedData(self, [_to_expr(c) for c in cols],
                           sets="rollup")

    def cube(self, *cols: ColumnOrName) -> "GroupedData":
        """All 2^n grouping-set combinations (Spark df.cube)."""
        return GroupedData(self, [_to_expr(c) for c in cols], sets="cube")

    def agg(self, *aggs: Col) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"outer": "full", "full_outer": "full", "leftouter": "left",
               "left_outer": "left", "rightouter": "right",
               "right_outer": "right", "leftsemi": "left_semi",
               "semi": "left_semi", "leftanti": "left_anti",
               "anti": "left_anti"}.get(how, how)
        cond = None
        using = None
        if isinstance(on, Col):
            cond = _unwrap(on)
        elif isinstance(on, str):
            using = [on]
        elif isinstance(on, (list, tuple)) and on:
            if isinstance(on[0], str):
                using = list(on)
            else:
                c = _unwrap(on[0])
                for o in on[1:]:
                    c = pr.And(c, _unwrap(o))
                cond = c
        if using is not None:
            cond = None
            for name in using:
                eq = pr.EqualTo(ex.ColumnRef(name), _UsingRight(name))
                cond = eq if cond is None else pr.And(cond, eq)
            plan = lp.Join(self._plan, other._plan, how, cond, using)
            return self._df(_dedupe_using(plan, using, how, self, other))
        return self._df(lp.Join(self._plan, other._plan, how, cond))

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return self._df(lp.Join(self._plan, other._plan, "cross"))

    def union(self, other: "DataFrame") -> "DataFrame":
        return self._df(lp.Union(self._plan, other._plan))

    unionAll = union

    def distinct(self) -> "DataFrame":
        return self._df(lp.Distinct(self._plan))

    def dropDuplicates(self, subset: Optional[List[str]] = None) -> "DataFrame":
        if subset is None:
            return self.distinct()
        grouping = [ex.ColumnRef(c) for c in subset]
        aggs = []
        for c in self.columns:
            if c in subset:
                aggs.append(ex.ColumnRef(c))
            else:
                aggs.append(ex.Alias(
                    lp.AggregateExpression("first", ex.ColumnRef(c)), c))
        return self._df(lp.Aggregate(self._plan, grouping, aggs))

    def orderBy(self, *cols, ascending: Optional[Any] = None) -> "DataFrame":
        orders = []
        for i, c in enumerate(cols):
            if isinstance(c, lp.SortOrder):
                orders.append(c)
                continue
            e = _to_expr(c)
            asc = True
            if ascending is not None:
                asc = ascending[i] if isinstance(ascending, (list, tuple)) \
                    else bool(ascending)
            orders.append(lp.SortOrder(e, asc))
        return self._df(lp.Sort(self._plan, orders, is_global=True))

    sort = orderBy

    def limit(self, n: int) -> "DataFrame":
        return self._df(lp.Limit(self._plan, n))

    def mapInPandas(self, fn, schema) -> "DataFrame":
        """fn(iterator of pandas DataFrames) -> iterator of DataFrames
        (GpuMapInPandasExec analog)."""
        from ..columnar import dtypes as dtm
        if not isinstance(schema, dtm.Schema):
            schema = dtm.Schema(schema)
        return self._df(lp.MapInPandas(self._plan, fn, schema))

    def repartition(self, n: int, *cols: ColumnOrName) -> "DataFrame":
        by = [_to_expr(c) for c in cols] or None
        return self._df(lp.Repartition(self._plan, n, by))

    def coalesce(self, n: int) -> "DataFrame":
        return self._df(lp.Repartition(self._plan, n))

    def alias(self, name: str) -> "DataFrame":
        return self  # single-session name scoping not needed yet

    # -- actions -------------------------------------------------------------
    def _execute(self):
        """Plan (or serve from the parameterized-plan cache) this
        frame's query. Returns the exec tree, and leaves the serving
        info — plan-cache hit/miss, result-cache key — on the session
        (plan/plan_cache.py, docs/plan_cache.md)."""
        import time
        t0 = time.perf_counter()
        plan = self._analyzed()
        from ..exec.spill import BufferCatalog
        from ..plan import plan_cache as pc
        exec_plan, serving = pc.plan_for(self.session, plan)
        self.session._last_plan_time_s = time.perf_counter() - t0
        self.session._last_exec_plan = exec_plan
        self.session._last_serving = serving
        # the session attr is an observability surface that concurrent
        # service workers clobber; the execution pipeline reads THIS
        # thread's serving info (collect_batch, the prepared capture)
        pc.note_thread_serving(serving)
        # result-cache key read NOW (snapshot = current table tokens /
        # file stats) so the collect can short-circuit or store
        serving["resultKey"] = pc.result_key(self.session, serving, plan)
        # spill counters are process-cumulative; snapshot them so
        # last_query_metrics() can report THIS query's deltas
        cat = BufferCatalog.get()
        self.session._mem_baseline = (cat.spilled_device_bytes,
                                      cat.spilled_host_bytes)
        return exec_plan

    def cache(self) -> "DataFrame":
        """Materialize this DataFrame once into a SPILLABLE device batch
        and serve later queries straight from it, IN PLACE like Spark's
        df.cache() (GpuInMemoryTableScanExec analog): no re-execution, no
        host re-conversion, no re-upload; memory pressure spills the
        cached batch through the normal tiers. Returns self."""
        if isinstance(self._plan, lp.CachedScan):
            return self                     # already cached
        from ..exec.spill import CACHE_PRIORITY, SpillableColumnarBatch
        batch = self.collect_batch()
        handle = SpillableColumnarBatch(batch, CACHE_PRIORITY)
        self._uncached_plan = self._plan
        self._plan = lp.CachedScan(batch.schema, lp._CacheOwner(handle))
        return self

    def persist(self, storageLevel=None) -> "DataFrame":
        """Spark-compat alias of cache(); the storage level is accepted and
        ignored (the spill tiers decide residency here)."""
        return self.cache()

    def unpersist(self) -> "DataFrame":
        """Restore the original plan: later queries on THIS frame
        re-execute it (no-op for frames never cached). The cached batch
        itself is released when its last reference dies — derived frames
        still sharing it keep working, matching Spark's always-safe
        unpersist."""
        orig = getattr(self, "_uncached_plan", None)
        if orig is not None:
            self._plan = orig
            self._uncached_plan = None
        return self

    def collect_batch(self):
        from ..plan import plan_cache as pc
        try:
            exec_plan = self._execute()
        except BaseException:
            # plan_for may have CLAIMED a cache entry before a later
            # step of _execute raised (result-key snapshot, baseline):
            # release it or the entry reads busy forever. A stale
            # serving dict from a previous query is harmless — its
            # planEntry was already popped by that query's release.
            pc.release_plan_entry(pc.thread_serving())
            raise
        serving = pc.thread_serving() or {}
        try:
            hit = pc.serve_result_hit(self.session, serving)
            if hit is not None:
                # exact-repeat short circuit: no execution at all — the
                # stored HOST batch serves (no spans/metrics/listeners
                # for this collect; EXPLAIN ANALYZE marks the hit)
                return hit
            return self._collect_planned(exec_plan, serving)
        finally:
            # the exec tree claimed from the plan cache is free for the
            # next execution (concurrent collects on a busy entry plan
            # fresh trees, plan_cache.PlanEntry.try_begin_execution)
            pc.release_plan_entry(serving)

    def _collect_planned(self, exec_plan, serving):
        import time
        from ..exec import query_context as qc
        from ..exec.tracing import SpanRecorder, SyncCounter
        from ..plan import plan_cache as pc
        listeners = bool(self.session._query_listeners)
        if listeners:
            # snapshots only when someone is listening: the deltas cost a
            # dict copy per query
            from ..analysis import lockdep, recompile
            rc0 = recompile.snapshot()
            lk0 = lockdep.stats()
        # the query-lifecycle identity (docs/observability.md §8): ONE
        # query id minted at collect time, ambient for the execution so
        # spans, flight events, shuffle protocol traffic and exchange
        # stage ids all attribute to this query — lockstep-deterministic,
        # so distributed workers running the same query mint the same id
        # a pre-minted reservation (qc.reserve_query) wins over a fresh
        # mint: concurrent distributed drivers mint their contexts in
        # lockstep program order on the main thread, then collect on
        # worker threads — the racy collect order must not draw from
        # the query-id counter
        ctx = qc.take_reserved()
        if ctx is not None:
            qid = ctx.query_id
        else:
            qid = qc.mint_query_id(exec_plan)
            # the context picks up the ambient tenant hint (the
            # service's tenant_scope on this thread); captured here so
            # the query-log record and session surface carry it after
            # the scope closes
            ctx = qc.QueryContext(qid)
        self.session._last_query_id = qid
        qc.note_thread_query_id(qid)
        self.session._last_tenant = ctx.tenant
        self.session._last_first_row_s = None
        # lifecycle control plane (exec/lifecycle.py): index this query's
        # cancel token by id so cancel/suspend surfaces (QueryService,
        # session.cancel_query, the peer META reply) can reach the
        # running execution; unregistered in the finally below
        from ..exec import lifecycle as _lifecycle
        _lifecycle.register(ctx)
        from ..analysis import faults as _faults
        faults0 = _faults.fired_total()
        # AQE pre-execution hook (plan/aqe.py): clear the prior run's
        # decision records and fold stored observed cardinalities for
        # this fingerprint back into est_rows (drift feedback).
        # Best-effort — adaptive machinery must never fail the query.
        try:
            from ..plan import aqe
            aqe.begin_query(self.session, exec_plan, serving)
        except Exception:
            pass
        t0 = time.perf_counter()
        try:
            with qc.query_scope(ctx):
                try:
                    with SyncCounter() as sc, SpanRecorder() as spans:
                        spans.query_id = qid
                        out = exec_plan.execute_collect()
                except BaseException as e:
                    # post-mortem for failures OUTSIDE task bodies
                    # (planner-side execute, concat, exchange setup): dump
                    # the flight ring INSIDE the query scope so the
                    # artifact is scoped+named to the failing query.
                    # dump_on_error never raises and dedups against the
                    # task-level hook, so the original exception
                    # propagates unmasked.
                    from ..service.telemetry import dump_on_error
                    dump_on_error(e)
                    raise
            self.session._last_execute_time_s = time.perf_counter() - t0
            # a materializing collect serves its first row when it serves
            # its last: firstRowS == executeTimeS, honestly (collect_iter
            # is the path that beats it; docs/observability.md)
            self.session._last_first_row_s = \
                self.session._last_execute_time_s
            try:
                # AQE post-execution hook: store observed cardinalities +
                # exchange bytes under this fingerprint for the NEXT
                # execution (drift feedback, admission cost weighting)
                from ..plan import aqe
                aqe.note_execution(self.session, exec_plan, serving)
            except Exception:
                pass
            try:
                from ..service.telemetry import MetricsRegistry
                MetricsRegistry.get().histogram(
                    "tpu_query_execute_seconds",
                    "collect-action execute wall seconds").observe(
                    self.session._last_execute_time_s)
            except Exception:
                pass           # observability must never fail the query
            self.session._last_sync_report = sc.report()
            self.session._last_span_report = spans.report()
            # the recorder itself stays reachable so the bench runner /
            # tests can export the Chrome-trace timeline of this query
            self.session._last_span_recorder = spans
            if listeners:
                from .session import QueryExecution
                ov = self.session._last_overrides
                self.session._notify_query_listeners(QueryExecution(
                    self.session, exec_plan,
                    self.session._last_sync_report,
                    self.session._last_span_report,
                    recompile.delta(rc0), lockdep.stats_delta(lk0),
                    violations=getattr(ov, "last_violations", ()) if ov
                    else ()))
            rkey = serving.get("resultKey")
            if rkey is not None:
                # store AFTER the sync/span windows closed: the caching
                # fetch must not perturb this query's reported sync counts
                out = pc.store_result(self.session, rkey, out)
            # end-of-query buffer-lifecycle audit (analysis/ledger.py):
            # runs AFTER store_result so a cached result's pinned buffers
            # are owned by the cache, not leaked by this query.
            # BufferLeakError propagates in enforce mode — leak
            # discipline is the point.
            from ..analysis import ledger as _ledger
            self.session._last_ledger = _ledger.end_of_query(qid)
            try:
                # opt-in structured query log (service/query_log.py, conf
                # telemetry.queryLog.dir): one JSONL record per execution.
                # Best-effort — the log must never fail the query.
                from ..service import query_log
                query_log.maybe_log(self.session, exec_plan, serving, qid,
                                    faults_before=faults0,
                                    tenant=ctx.tenant)
            except Exception:
                pass
            return out
        finally:
            import sys as _sys
            if _sys.exc_info()[0] is not None:
                # failed (or cancelled) queries get the residency audit
                # too: a cancellation's cleanup must be ledger-provable,
                # and had_error keeps enforce mode from masking the
                # propagating exception with a leak report
                try:
                    from ..analysis import ledger as _ledger_err
                    self.session._last_ledger = _ledger_err.end_of_query(
                        qid, had_error=True)
                except Exception:
                    pass
            # the token's transition log retires with the query (the
            # query-log record read it above; a late peer META poll still
            # sees the cancelled verdict through the retired map)
            _lifecycle.unregister(qid)

    def collect_iter(self):
        """Streaming collect: yield host-resident batches as partitions
        drain (one batch per partition, in partition order) instead of
        materializing the whole result — the consumer sees first rows in
        first-partition time (docs/observability.md firstRowS). The
        concatenated rows of the yielded batches are IDENTICAL to
        ``collect()``'s, in the same order.

        The generator owns the full query lifecycle: closing it early
        releases the plan-cache entry, cancels undrained partitions,
        waits for running drains so staging arenas release, and still
        writes the query-log record. While the stream is live, cold
        fused-stage builds route to the background compile pool and
        batches flow through the per-op eager path until the compiled
        program swaps in (docs/compile.md §5). Streaming results are
        never stored in the result cache (an exact-repeat hit is still
        SERVED, as a single batch)."""
        from ..plan import plan_cache as pc
        try:
            exec_plan = self._execute()
        except BaseException:
            pc.release_plan_entry(pc.thread_serving())
            raise
        serving = pc.thread_serving() or {}
        try:
            hit = pc.serve_result_hit(self.session, serving)
            if hit is not None:
                self.session._last_first_row_s = 0.0
                yield hit
                return
            for batch in self._collect_iter_planned(exec_plan, serving):
                yield batch
        finally:
            pc.release_plan_entry(serving)

    def _collect_iter_planned(self, exec_plan, serving):
        import time
        from ..exec import query_context as qc
        from ..exec.tracing import SpanRecorder, SyncCounter
        listeners = bool(self.session._query_listeners)
        if listeners:
            from ..analysis import lockdep, recompile
            rc0 = recompile.snapshot()
            lk0 = lockdep.stats()
        # reserved contexts win here too (the materializing collect's
        # adoption rule, above)
        ctx = qc.take_reserved()
        if ctx is not None:
            qid = ctx.query_id
        else:
            qid = qc.mint_query_id(exec_plan)
            ctx = qc.QueryContext(qid)
        self.session._last_query_id = qid
        qc.note_thread_query_id(qid)
        # the streaming marker rides the context to every partition-drain
        # worker thread: cold stage builds route to the compile pool
        # instead of blocking the first batches (compile_pool.routable)
        ctx.streaming = True
        self.session._last_tenant = ctx.tenant
        # lifecycle token index (the materializing collect's rule above);
        # unregistered in the finally
        from ..exec import lifecycle as _lifecycle
        _lifecycle.register(ctx)
        from ..analysis import faults as _faults
        faults0 = _faults.fired_total()
        try:
            from ..plan import aqe
            aqe.begin_query(self.session, exec_plan, serving)
        except Exception:
            pass
        self.session._last_first_row_s = None
        first_row_s = None
        sc = spans = None
        t0 = time.perf_counter()
        try:
            with qc.query_scope(ctx):
                with SyncCounter() as sc, SpanRecorder() as spans:
                    spans.query_id = qid
                    try:
                        for batch in exec_plan.execute_collect_iter():  # lint: cancel-ok body polls check_cancel per delivered batch
                            # streaming delivery is a lifecycle poll
                            # point: a cancelled stream stops between
                            # batches instead of draining to the end
                            _lifecycle.check_cancel()
                            if first_row_s is None:
                                first_row_s = time.perf_counter() - t0
                                self.session._last_first_row_s = \
                                    first_row_s
                            yield batch
                    except BaseException as e:
                        from ..service.telemetry import dump_on_error
                        dump_on_error(e)
                        raise
        finally:
            # runs on exhaustion, failure AND early close: the lifecycle
            # bookkeeping must not depend on the consumer finishing
            self.session._last_execute_time_s = time.perf_counter() - t0
            try:
                from ..plan import aqe
                aqe.note_execution(self.session, exec_plan, serving)
            except Exception:
                pass
            try:
                from ..service.telemetry import MetricsRegistry
                reg = MetricsRegistry.get()
                reg.histogram(
                    "tpu_query_execute_seconds",
                    "collect-action execute wall seconds").observe(
                    self.session._last_execute_time_s)
                if first_row_s is not None:
                    reg.histogram(
                        "tpu_query_first_row_seconds",
                        "wall seconds from streaming collect to its "
                        "first yielded batch").observe(first_row_s)
            except Exception:
                pass
            if spans is not None:
                self.session._last_sync_report = sc.report()
                self.session._last_span_report = spans.report()
                self.session._last_span_recorder = spans
            if listeners:
                try:
                    from .session import QueryExecution
                    ov = self.session._last_overrides
                    self.session._notify_query_listeners(QueryExecution(
                        self.session, exec_plan,
                        self.session._last_sync_report,
                        self.session._last_span_report,
                        recompile.delta(rc0), lockdep.stats_delta(lk0),
                        violations=getattr(ov, "last_violations", ())
                        if ov else ()))
                except Exception:
                    pass
            # end-of-query audit for the streaming path: had_error keeps
            # enforce mode from masking a propagating failure with a
            # leak report (the audit downgrades itself to record)
            import sys as _sys
            from ..analysis import ledger as _ledger
            self.session._last_ledger = _ledger.end_of_query(
                qid, had_error=_sys.exc_info()[0] is not None)
            try:
                from ..service import query_log
                query_log.maybe_log(self.session, exec_plan, serving,
                                    qid, faults_before=faults0,
                                    tenant=ctx.tenant)
            except Exception:
                pass
            _lifecycle.unregister(qid)

    def collect(self) -> List[tuple]:
        return self.collect_batch().rows()

    def toPandas(self):
        return self.collect_batch().to_pandas()

    def to_arrow(self):
        return self.collect_batch().to_arrow()

    def count(self) -> int:
        plan = lp.Aggregate(self._plan, [], [
            ex.Alias(lp.AggregateExpression("count_star", None), "count")])
        df = self._df(plan)
        return df.collect()[0][0]

    def show(self, n: int = 20, truncate: bool = True) -> None:
        print(self.limit(n).toPandas().to_string(index=False))

    def explain(self, extended: bool = False) -> None:
        """Print the physical plan. ``extended=True`` adds the overrides
        explain (fallback reasons + contract diagnostics);
        ``extended="analyze"`` EXECUTES the query (Spark's EXPLAIN
        ANALYZE) and prints the executed tree with each node's runtime
        metrics inline plus the query-level summary."""
        if isinstance(extended, str) and extended.lower() == "analyze":
            self.collect_batch()
            print(self.session.explain_analyze())
            return
        plan = self._analyzed()
        from ..plan.overrides import Overrides
        conf = self.session.conf.with_overrides(
            {"spark.rapids.tpu.sql.explain": "NONE"})
        ov = Overrides(conf)
        exec_plan = ov.apply(plan)
        print(exec_plan)
        if extended and ov.last_explain:
            print(ov.last_explain)

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    def createOrReplaceTempView(self, name: str) -> None:
        self.session._views[name] = self._plan


class _UsingRight(ex.ColumnRef):
    """Marker ref that must resolve against the RIGHT side in a USING join."""


def _dedupe_using(plan: lp.Join, using: List[str], how: str,
                  left: DataFrame, right: DataFrame) -> lp.LogicalPlan:
    """USING-join output keeps one copy of the key columns (Spark semantics)."""
    lnames = left.columns
    rnames = right.columns
    if how in ("left_semi", "left_anti"):
        return plan
    keep: List[ex.Expression] = []
    for c in lnames:
        keep.append(ex.ColumnRef(c))
    for c in rnames:
        if c not in using:
            keep.append(ex.ColumnRef(c))
    return lp.Project(plan, keep)


class GroupedData:
    def __init__(self, df: DataFrame, grouping: List[ex.Expression],
                 sets: Optional[str] = None):
        self.df = df
        self.grouping = grouping
        self.sets = sets          # None | "rollup" | "cube"

    def agg(self, *aggs: Union[Col, Dict[str, str]]) -> DataFrame:
        from ..ops.python_udf import PandasAggUDF
        if len(aggs) == 1 and isinstance(aggs[0], dict):
            aggs = tuple(
                getattr(F, op if op != "mean" else "avg")(F.col(c))
                for c, op in aggs[0].items())
        agg_exprs = [_unwrap(a) for a in aggs]

        def is_pandas_agg(e):
            inner = e.children[0] if isinstance(e, ex.Alias) else e
            return isinstance(inner, PandasAggUDF)
        if any(is_pandas_agg(e) for e in agg_exprs):
            if self.sets:
                raise ValueError(
                    "grouped-agg pandas UDFs do not support rollup/cube")
            if not all(is_pandas_agg(e) for e in agg_exprs):
                raise ValueError(
                    "cannot mix grouped-agg pandas UDFs with built-in "
                    "aggregates in one agg() (pyspark restriction)")
            names = [ex.output_name(g, i)
                     for i, g in enumerate(self.grouping)]
            names += [e.alias if isinstance(e, ex.Alias)
                      else ex.output_name(e, len(names) + i)
                      for i, e in enumerate(agg_exprs)]
            inner = [e.children[0] if isinstance(e, ex.Alias) else e
                     for e in agg_exprs]
            return self.df._df(lp.AggregateInPandas(
                self.df._plan, self.grouping, inner, names))
        if self.sets:
            return self._agg_grouping_sets(agg_exprs)
        out: List[ex.Expression] = list(self.grouping) + agg_exprs
        return self.df._df(lp.Aggregate(self.df._plan, self.grouping, out))

    def applyInPandas(self, fn, schema) -> DataFrame:
        """fn(pandas.DataFrame) -> DataFrame — or fn(key_tuple, pdf) —
        applied once per group (GpuFlatMapGroupsInPandasExec analog)."""
        from ..columnar import dtypes as dtm
        if not isinstance(schema, dtm.Schema):
            schema = dtm.Schema(schema)
        return self.df._df(lp.FlatMapGroupsInPandas(
            self.df._plan, list(self.grouping), fn, schema))

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """Pair this grouping with another frame's grouping for
        cogroup(...).applyInPandas (GpuFlatMapCoGroupsInPandasExec)."""
        return CoGroupedData(self, other)

    def _agg_grouping_sets(self, agg_exprs: List[ex.Expression]) -> DataFrame:
        """rollup/cube: Expand replicates every input row once per grouping
        set, nulling the grouped-out keys and tagging a grouping id; one
        hash aggregate over (keys..., gid) then computes all sets at once
        (the reference's GpuExpandExec + GpuHashAggregateExec pipeline,
        GpuExpandExec.scala)."""
        import itertools
        nk = len(self.grouping)
        if self.sets == "rollup":
            masks = [tuple(i < keep for i in range(nk))
                     for keep in range(nk, -1, -1)]
        else:
            masks = [tuple(bits) for bits in
                     itertools.product((True, False), repeat=nk)]
        child_cols = self.df.columns
        key_names = [ex.output_name(g, i)
                     for i, g in enumerate(self.grouping)]
        out_names = list(child_cols) + \
            [f"_g{i}" for i in range(nk)] + ["_gid"]
        projections: List[List[ex.Expression]] = []
        for mask in masks:
            proj: List[ex.Expression] = [ex.ColumnRef(c)
                                         for c in child_cols]
            gid = 0
            for i, keep in enumerate(mask):
                if keep:
                    proj.append(copy.deepcopy(self.grouping[i]))
                else:
                    # typed NULL of the key's dtype: a never-true branch
                    # keeps the analyzer's coercion rules in charge
                    proj.append(cd.CaseWhen(
                        [(ex.lit(False), copy.deepcopy(self.grouping[i]))],
                        None))
                    gid |= 1 << (nk - 1 - i)
            proj.append(ex.lit(gid))
            projections.append(proj)
        expand = lp.Expand(self.df._plan, projections, out_names)
        grouping = [ex.ColumnRef(f"_g{i}") for i in range(nk)] + \
            [ex.ColumnRef("_gid")]
        outputs = [ex.Alias(ex.ColumnRef(f"_g{i}"), key_names[i])
                   for i in range(nk)] + agg_exprs
        return self.df._df(lp.Aggregate(expand, grouping, outputs))

    def count(self) -> DataFrame:
        return self.agg(Col(ex.Alias(
            lp.AggregateExpression("count_star", None), "count")))

    def sum(self, *cols: str) -> DataFrame:
        return self.agg(*[F.sum(c).alias(f"sum({c})") for c in cols])

    def avg(self, *cols: str) -> DataFrame:
        return self.agg(*[F.avg(c).alias(f"avg({c})") for c in cols])

    mean = avg

    def min(self, *cols: str) -> DataFrame:
        return self.agg(*[F.min(c).alias(f"min({c})") for c in cols])

    def max(self, *cols: str) -> DataFrame:
        return self.agg(*[F.max(c).alias(f"max({c})") for c in cols])


class CoGroupedData:
    def __init__(self, left: "GroupedData", right: "GroupedData"):
        self.left = left
        self.right = right

    def applyInPandas(self, fn, schema) -> DataFrame:
        """fn(left_pdf, right_pdf) -> DataFrame — or fn(key, l, r) —
        applied once per key present on EITHER side (missing side =
        empty frame), matching pyspark cogroup semantics."""
        from ..columnar import dtypes as dtm
        if len(self.left.grouping) != len(self.right.grouping):
            raise ValueError(
                f"cogroup key counts differ: {len(self.left.grouping)} "
                f"vs {len(self.right.grouping)} (pyspark raises too)")
        if not isinstance(schema, dtm.Schema):
            schema = dtm.Schema(schema)
        return self.left.df._df(lp.FlatMapCoGroupsInPandas(
            self.left.df._plan, self.right.df._plan,
            list(self.left.grouping), list(self.right.grouping),
            fn, schema))


class DataFrameWriter:
    def __init__(self, df: DataFrame):
        self.df = df
        self._mode = "error"
        self._options: Dict[str, Any] = {}
        self._partition_by: List[str] = []

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def option(self, k: str, v: Any) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def parquet(self, path: str) -> None:
        self._write("parquet", path)

    def csv(self, path: str) -> None:
        self._write("csv", path)

    def orc(self, path: str) -> None:
        self._write("orc", path)

    def _write(self, fmt: str, path: str) -> None:
        plan = lp.WriteFile(self.df._plan, fmt, path, self._mode,
                            self._options, self._partition_by)
        df = self.df._df(plan)
        from ..plan import plan_cache as pc
        try:
            exec_plan = df._execute()
            for part in exec_plan.execute():
                for _ in part:
                    pass
        finally:
            # writes plan uncacheable today (fingerprint None) so this
            # is a no-op, but the release hook keeps every _execute()
            # caller symmetric if that ever changes
            pc.release_plan_entry(pc.thread_serving())
