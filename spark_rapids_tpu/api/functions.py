"""pyspark.sql.functions analog: the public expression constructors.

Surface mirrors the reference's supported expression set (SURVEY.md §2.3 —
the 138 expr rules of GpuOverrides) for the types this framework implements.
"""

from __future__ import annotations

from typing import Any, Optional

from ..columnar import dtypes as dt
from ..ops import arithmetic as ar
from ..ops import conditionals as co
from ..ops import datetime as dtm
from ..ops import expressions as ex
from ..ops import hashing as hs
from ..ops import math_ops as mo
from ..ops import predicates as pr
from ..ops import strings as st
from ..ops.cast import Cast
from ..plan import logical as lp
from .column import Col, WhenChain, _unwrap


def col(name: str) -> Col:
    return Col(ex.ColumnRef(name))


column = col


def lit(value: Any) -> Col:
    return Col(ex.Literal(value))


def when(condition, value) -> WhenChain:
    return WhenChain([(_unwrap(condition), _unwrap(value))])


def expr_col(e: ex.Expression) -> Col:
    return Col(e)


# -- aggregates ---------------------------------------------------------------

def _agg(op: str, c, **kw) -> Col:
    child = None if c is None else _unwrap(col(c) if isinstance(c, str) else c)
    return Col(lp.AggregateExpression(op, child, **kw))


def count(c="*") -> Col:
    if isinstance(c, str) and c == "*":
        return Col(lp.AggregateExpression("count_star", None))
    return _agg("count", c)


def sum(c) -> Col:  # noqa: A001 - pyspark parity
    return _agg("sum", c)


def avg(c) -> Col:
    return _agg("avg", c)


mean = avg


def min(c) -> Col:  # noqa: A001
    return _agg("min", c)


def max(c) -> Col:  # noqa: A001
    return _agg("max", c)


def first(c, ignorenulls: bool = False) -> Col:
    return _agg("first", c, ignore_nulls=ignorenulls)


def last(c, ignorenulls: bool = False) -> Col:
    return _agg("last", c, ignore_nulls=ignorenulls)


def countDistinct(c) -> Col:
    return _agg("count", c, distinct=True)


def sumDistinct(c) -> Col:
    return _agg("sum", c, distinct=True)


# -- conditionals -------------------------------------------------------------

def coalesce(*cols) -> Col:
    return Col(co.Coalesce(*[_unwrap(c) for c in cols]))


def isnull(c) -> Col:
    return Col(pr.IsNull(_unwrap(c)))


def isnan(c) -> Col:
    return Col(pr.IsNaN(_unwrap(c)))


def nvl(a, b) -> Col:
    return Col(co.Nvl(_unwrap(a), _unwrap(b)))


def nullif(a, b) -> Col:
    return Col(co.NullIf(_unwrap(a), _unwrap(b)))


def greatest(*cols) -> Col:
    return Col(co.Greatest(*[_unwrap(c) for c in cols]))


def least(*cols) -> Col:
    return Col(co.Least(*[_unwrap(c) for c in cols]))


# -- math ---------------------------------------------------------------------

def abs(c) -> Col:  # noqa: A001
    return Col(ar.Abs(_unwrap(c)))


def sqrt(c) -> Col:
    return Col(mo.Sqrt(_unwrap(c)))


def exp(c) -> Col:
    return Col(mo.Exp(_unwrap(c)))


def log(c) -> Col:
    return Col(mo.Log(_unwrap(c)))


def pow(l, r) -> Col:  # noqa: A001
    return Col(mo.Pow(_unwrap(l), _unwrap(r)))


def floor(c) -> Col:
    return Col(mo.Floor(_unwrap(c)))


def ceil(c) -> Col:
    return Col(mo.Ceil(_unwrap(c)))


def round(c, scale: int = 0) -> Col:  # noqa: A001
    return Col(mo.Round(_unwrap(c), scale))


def sin(c) -> Col:
    return Col(mo.Sin(_unwrap(c)))


def cos(c) -> Col:
    return Col(mo.Cos(_unwrap(c)))


def tan(c) -> Col:
    return Col(mo.Tan(_unwrap(c)))


def atan2(y, x) -> Col:
    return Col(mo.Atan2(_unwrap(y), _unwrap(x)))


def pmod(l, r) -> Col:
    return Col(ar.Pmod(_unwrap(l), _unwrap(r)))


# -- strings ------------------------------------------------------------------

def length(c) -> Col:
    return Col(st.Length(_unwrap(c)))


def upper(c) -> Col:
    return Col(st.Upper(_unwrap(c)))


def lower(c) -> Col:
    return Col(st.Lower(_unwrap(c)))


def initcap(c) -> Col:
    return Col(st.InitCap(_unwrap(c)))


def substring(c, pos, length) -> Col:
    return Col(st.Substring(_unwrap(c), ex.Literal(pos), ex.Literal(length)))


def concat(*cols) -> Col:
    return Col(st.ConcatStr(*[_unwrap(c) for c in cols]))


def trim(c) -> Col:
    return Col(st.StringTrim(_unwrap(c)))


def ltrim(c) -> Col:
    return Col(st.StringTrimLeft(_unwrap(c)))


def rtrim(c) -> Col:
    return Col(st.StringTrimRight(_unwrap(c)))


def lpad(c, width: int, pad: str = " ") -> Col:
    return Col(st.StringLPad(_unwrap(c), width, pad))


def rpad(c, width: int, pad: str = " ") -> Col:
    return Col(st.StringRPad(_unwrap(c), width, pad))


def locate(substr: str, c, pos: int = 1) -> Col:
    return Col(st.StringLocate(ex.Literal(substr), _unwrap(c), ex.Literal(pos)))


def instr(c, substr: str) -> Col:
    return Col(st.StringLocate(ex.Literal(substr), _unwrap(c), ex.Literal(1)))


def regexp_extract(c, pattern: str, idx: int = 1) -> Col:
    return Col(st.RegExpExtractHost(_unwrap(c), pattern, idx))


def replace(c, search: str, replacement: str = "") -> Col:
    return Col(st.StringReplace(_unwrap(c), search, replacement))


# -- datetime -----------------------------------------------------------------

def year(c) -> Col:
    return Col(dtm.Year(_unwrap(c)))


def month(c) -> Col:
    return Col(dtm.Month(_unwrap(c)))


def dayofmonth(c) -> Col:
    return Col(dtm.DayOfMonth(_unwrap(c)))


def dayofweek(c) -> Col:
    return Col(dtm.DayOfWeek(_unwrap(c)))


def weekday(c) -> Col:
    return Col(dtm.WeekDay(_unwrap(c)))


def dayofyear(c) -> Col:
    return Col(dtm.DayOfYear(_unwrap(c)))


def quarter(c) -> Col:
    return Col(dtm.Quarter(_unwrap(c)))


def hour(c) -> Col:
    return Col(dtm.Hour(_unwrap(c)))


def minute(c) -> Col:
    return Col(dtm.Minute(_unwrap(c)))


def second(c) -> Col:
    return Col(dtm.Second(_unwrap(c)))


def date_add(c, days) -> Col:
    return Col(dtm.DateAdd(_unwrap(c), _unwrap(days)))


def date_sub(c, days) -> Col:
    return Col(dtm.DateSub(_unwrap(c), _unwrap(days)))


def datediff(end, start) -> Col:
    return Col(dtm.DateDiff(_unwrap(end), _unwrap(start)))


def add_months(c, months) -> Col:
    return Col(dtm.AddMonths(_unwrap(c), _unwrap(months)))


def last_day(c) -> Col:
    return Col(dtm.LastDay(_unwrap(c)))


def unix_timestamp(c) -> Col:
    return Col(dtm.UnixTimestamp(_unwrap(c)))


def from_unixtime(c) -> Col:
    return Col(dtm.FromUnixTime(_unwrap(c)))


def to_date(c) -> Col:
    return Col(dtm.ToDate(_unwrap(c)))


# -- misc ---------------------------------------------------------------------

def hash(*cols) -> Col:  # noqa: A001
    return Col(hs.Murmur3Hash(*[_unwrap(c) for c in cols]))


def md5(c) -> Col:
    return Col(hs.Md5(_unwrap(c)))


def rand(seed: int = 0) -> Col:
    return Col(hs.Rand(seed))


def monotonically_increasing_id() -> Col:
    return Col(hs.MonotonicallyIncreasingID())


def spark_partition_id() -> Col:
    return Col(hs.SparkPartitionID())


def input_file_name() -> Col:
    return Col(hs.InputFileName())


# -- window -------------------------------------------------------------------

def row_number() -> Col:
    from ..ops.window import RowNumber
    return Col(RowNumber())


def rank() -> Col:
    from ..ops.window import Rank
    return Col(Rank())


def dense_rank() -> Col:
    from ..ops.window import DenseRank
    return Col(DenseRank())


def lead(c, offset: int = 1, default=None) -> Col:
    from ..ops.window import Lead
    return Col(Lead(_unwrap(col(c) if isinstance(c, str) else c), offset, default))


def lag(c, offset: int = 1, default=None) -> Col:
    from ..ops.window import Lag
    return Col(Lag(_unwrap(col(c) if isinstance(c, str) else c), offset, default))


# -- arrays / generators (complexTypeExtractors + GpuGenerateExec analogs) ---

def explode(c) -> Col:
    from ..ops import arrays as ar_ops
    return Col(ar_ops.Explode(_unwrap(c)))


def posexplode(c) -> Col:
    from ..ops import arrays as ar_ops
    return Col(ar_ops.Explode(_unwrap(c), pos=True))


def split(c, delimiter: str) -> Col:
    from ..ops import arrays as ar_ops
    return Col(ar_ops.StringSplit(_unwrap(c), delimiter))


def size(c) -> Col:
    from ..ops import arrays as ar_ops
    return Col(ar_ops.Size(_unwrap(c)))


def _key_literal(v) -> "ex.Expression":
    import numpy as np
    if isinstance(v, np.integer):
        v = int(v)
    elif isinstance(v, np.floating):
        v = float(v)
    elif isinstance(v, np.bool_):
        v = bool(v)
    return ex.Literal(v)


def get_item(c, index) -> Col:
    from ..ops import maps as mp_ops
    key = _unwrap(index) if isinstance(index, Col) else _key_literal(index)
    return Col(mp_ops.GetItem(_unwrap(c), key))


def element_at(c, key) -> Col:
    """element_at(map, key) / element_at(array, 1-based index)."""
    from ..ops import maps as mp_ops
    k = _unwrap(key) if isinstance(key, Col) else _key_literal(key)
    return Col(mp_ops.GetItem(_unwrap(c), k, one_based=True))


def create_map(*cols) -> Col:
    """map(k1, v1, k2, v2, ...) — complexTypeCreator.scala CreateMap."""
    from ..ops import maps as mp_ops
    return Col(mp_ops.CreateMap(*[_unwrap(c) for c in cols]))


def map_keys(c) -> Col:
    from ..ops import maps as mp_ops
    return Col(mp_ops.MapKeys(_unwrap(c)))


def map_values(c) -> Col:
    from ..ops import maps as mp_ops
    return Col(mp_ops.MapValues(_unwrap(c)))


# -- python UDFs (§2.9: GpuArrowEvalPythonExec + udf-compiler analogs) -------

def udf(fn=None, returnType="double"):
    """Scalar python UDF. The udf-compiler first tries to translate the
    function's BYTECODE into a native expression tree (the reference's
    udf-compiler module); untranslatable functions fall back to the pandas
    host path — same contract as Plugin.scala:28-94's resolution rule."""
    rt = dt.of(returnType) if not isinstance(returnType, dt.DType) else returnType

    def wrap(f):
        def call(*cols):
            from ..ops.udf_compiler import try_compile_udf
            from ..ops.python_udf import PandasUDF
            args = [_unwrap(c) if isinstance(c, Col) else ex.ColumnRef(c)
                    for c in cols]
            compiled = try_compile_udf(f, args)
            if compiled is not None:
                # unconditional cast: column refs are unresolved pre-analysis,
                # so the result dtype is unknowable here; Cast to self is free
                return Col(Cast(compiled, rt))
            import pandas as pd

            def elementwise(*series):
                # Spark python UDFs receive None inputs as-is (they decide);
                # this matches pyspark, NOT the compiled path's expression
                # null-propagation — the same divergence the reference's
                # udf-compiler has between translated and fallback UDFs
                def norm(v):
                    if not isinstance(v, (list, tuple)) and pd.isna(v):
                        return None
                    return v
                return pd.Series([f(*[norm(v) for v in vals])
                                  for vals in zip(*series)])
            return Col(PandasUDF(elementwise, rt, *args,
                                 name=getattr(f, "__name__", "udf")))
        call.__name__ = getattr(f, "__name__", "udf")
        return call
    return wrap(fn) if fn is not None else wrap


def pandas_udf(fn=None, returnType="double", functionType: str = "scalar"):
    """Vectorized pandas UDF (no bytecode translation attempt; always the
    Arrow round-trip path).

    ``functionType="scalar"``: fn(pandas.Series...) -> Series, row-wise.
    ``functionType="grouped_agg"``: fn(pandas.Series...) -> scalar, one
    call per group inside groupBy(...).agg(...)
    (GpuAggregateInPandasExec path)."""
    rt = dt.of(returnType) if not isinstance(returnType, dt.DType) else returnType
    if functionType not in ("scalar", "grouped_agg"):
        raise ValueError(f"unsupported pandas_udf functionType "
                         f"{functionType!r}")

    def wrap(f):
        def call(*cols):
            from ..ops.python_udf import PandasAggUDF, PandasUDF
            args = [_unwrap(c) if isinstance(c, Col) else ex.ColumnRef(c)
                    for c in cols]
            klass = PandasAggUDF if functionType == "grouped_agg" \
                else PandasUDF
            return Col(klass(f, rt, *args,
                             name=getattr(f, "__name__", "pandas_udf")))
        call.__name__ = getattr(f, "__name__", "pandas_udf")
        return call
    return wrap(fn) if fn is not None else wrap


def regexp_replace(c, pattern: str, replacement: str) -> Col:
    return Col(st.RegExpReplaceHost(_unwrap(c) if isinstance(c, Col)
                                    else ex.ColumnRef(c),
                                    pattern, replacement))
