"""TpuSession: the SparkSession analog + plugin bootstrap.

Reference: ``SQLPlugin.scala`` + ``Plugin.scala:108-154`` (driver/executor
init: conf fixup, device+memory init, semaphore init). Standalone, session
construction performs the executor-side bootstrap directly.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from .. import config as cfg
from ..analysis.lockdep import named_lock
from ..columnar import dtypes as dt
from ..plan import logical as lp
from .dataframe import DataFrame


# guards every session's SQL-text parse cache (leaf: only dict ops run
# under it; concurrent service workers hit sql() from pool threads)
_parse_cache_mu = named_lock("api.session._parse_cache_mu")


class TpuSessionBuilder:
    def __init__(self):
        self._conf: Dict[str, Any] = {}

    def config(self, key: str, value: Any = None) -> "TpuSessionBuilder":
        if isinstance(key, dict):
            self._conf.update(key)
        else:
            self._conf[key] = value
        return self

    def appName(self, name: str) -> "TpuSessionBuilder":
        self._conf["app.name"] = name
        return self

    def master(self, m: str) -> "TpuSessionBuilder":
        return self

    def getOrCreate(self) -> "TpuSession":
        return TpuSession(cfg.TpuConf(self._conf))


class RuntimeConf:
    """session.conf facade (set/get like Spark's RuntimeConfig)."""

    def __init__(self, session: "TpuSession"):
        self._session = session

    def set(self, key: str, value: Any) -> None:
        self._session.conf = self._session.conf.with_overrides({key: value})
        # conf changes are flight-recorder events: a post-mortem on a
        # dead run needs to know which knobs moved right before it died
        from ..service.telemetry import flight_record
        flight_record("conf", key, {"value": str(value)})
        # the audits cache their gates per process (conf reads on hot
        # paths would defeat them); a runtime change to an analysis.* key
        # must re-prime those caches or the first-primed value latches
        # for the rest of the process
        if ".analysis." in key:
            from ..analysis import recompile, sync_audit
            recompile.reset_cache()
            sync_audit.reset_cache()
        # compile.* keys reconfigure the persistent cache + donation gate
        if ".compile." in key:
            from ..exec import compile_cache
            compile_cache.configure(self._session.conf)
        # recovery budget / durable tier / fetch-retry knobs / chaos
        # plan re-prime on their keys (they cache per process like the
        # audits)
        if ".recovery." in key or ".shuffle.durable" in key or \
                ".shuffle.fetch." in key:
            from ..exec import recovery
            recovery.refresh(self._session.conf)
        if ".faults." in key:
            from ..analysis import faults
            faults.refresh(self._session.conf)
        if ".analysis.divergence" in key:
            from ..analysis import divergence
            divergence.refresh(self._session.conf)
        if ".analysis.bufferledger" in key.lower():
            from ..analysis import ledger
            ledger.refresh(self._session.conf)
        # ANY conf change drops the session's serving caches: cached
        # plans were analyzed/optimized/validated under the old conf, and
        # a stored result may have been produced by it (the parse cache
        # is conf-independent, but dropping it keeps one rule)
        self._session._plan_cache = None
        self._session._result_cache = None
        self._session._sql_parse_cache = None

    def get(self, key: str, default: Any = None) -> Any:
        return self._session.conf.get_key(key, default)


class _BuilderAccessor:
    """``TpuSession.builder`` returns a FRESH builder per access — a shared
    mutable builder would leak .config() settings into later sessions."""

    def __get__(self, obj, objtype=None):
        return TpuSessionBuilder()


def _annotated_plan_lines(plan, violations, conf=None) -> List[str]:
    """Executed-plan tree with runtime metrics plus the per-node
    annotations EXPLAIN ANALYZE renders — contract diagnostics keyed by
    validator path, fused-stage membership / decline reasons
    (plan/stage_compiler.fusion_annotations), per-exchange stage-boundary
    statistics (shuffle/exchange.stage_stats_annotations), and the
    estimate-vs-actual row drift per node (plan/estimates). One
    implementation for both the session-level and captured-
    QueryExecution renderings."""
    by_path: Dict[str, List[str]] = {}
    for v in violations:
        by_path.setdefault(v.path, []).append(f"! contract: {v.message}")
    from ..plan.stage_compiler import fusion_annotations
    for path, notes in fusion_annotations(plan).items():
        by_path.setdefault(path, []).extend(notes)
    from ..shuffle.exchange import stage_stats_annotations
    for path, notes in stage_stats_annotations(plan).items():
        by_path.setdefault(path, []).extend(notes)
    from ..plan.estimates import drift_annotations
    for path, notes in drift_annotations(plan, conf=conf).items():
        by_path.setdefault(path, []).extend(notes)
    from ..plan.aqe import aqe_annotations
    for path, notes in aqe_annotations(plan).items():
        by_path.setdefault(path, []).extend(notes)
    return plan.metrics_lines(
        annotate=lambda path: list(by_path.get(path, ())))


class QueryExecution:
    """Everything a query-execution listener receives for ONE executed
    query (the ExecutionPlanCaptureCallback analog, Plugin.scala:211-300,
    widened with the observability reports): the executed physical plan,
    the per-operator metrics tree, and the sync/span/recompile/lock
    reports the bench runner prints. Self-contained: renders from ITS
    OWN captured plan and violations, so a capture for query N stays
    correct after later queries run."""

    def __init__(self, session: "TpuSession", plan, sync: dict,
                 spans: dict, recompiles: dict, locks: dict,
                 violations=()):
        self.session = session
        self.plan = plan                   # executed TpuExec tree
        self.sync = sync                   # SyncCounter.report()
        self.spans = spans                 # SpanRecorder.report()
        self.recompiles = recompiles       # recompile.delta over the query
        self.locks = locks                 # lockdep stats delta
        self.violations = list(violations)  # contract diags at capture
        self._metrics_tree = None

    @property
    def metrics_tree(self):
        """[(depth, operator, metrics)] — materialized LAZILY: resolving
        the bags costs device readbacks, which must not land inside a
        benchmark's timed collect window."""
        if self._metrics_tree is None:
            self._metrics_tree = self.plan.metrics_tree()
        return self._metrics_tree

    def explain_analyze(self) -> str:
        """THIS query's executed plan annotated with runtime metrics,
        its captured contract diagnostics, and fused-stage membership
        (rendered on demand)."""
        lines = ["== Executed Plan (analyzed) =="]
        lines += _annotated_plan_lines(self.plan, self.violations,
                                       conf=self.session.conf)
        lines.append(
            f"query: hostSyncs={self.sync.get('hostSyncs', 0)} "
            f"spanWallS={self.spans.get('wallS', 0.0)} "
            f"concurrency={self.spans.get('concurrency', 0.0)}")
        return "\n".join(lines)


class TpuSession:
    builder = _BuilderAccessor()

    _active: Optional["TpuSession"] = None
    _lock = named_lock("api.session.TpuSession._lock")

    def __init__(self, conf: Optional[cfg.TpuConf] = None):
        self.conf = conf or cfg.TpuConf()
        self._views: Dict[str, lp.LogicalPlan] = {}
        self._last_exec_plan = None
        self._last_overrides = None
        self._last_serving = None
        # serving front door (plan/plan_cache.py): lazily built from the
        # conf; RuntimeConf.set drops them so conf changes replan
        self._plan_cache = None
        self._result_cache = None
        self._serving_stats = None
        self._query_listeners: List = []
        self._bootstrap()
        with TpuSession._lock:
            TpuSession._active = self

    def _bootstrap(self) -> None:
        """Executor-plugin init analog (Plugin.scala:124-154): device, memory
        budget, semaphore, spill catalog."""
        from ..exec.device import DeviceManager, TpuSemaphore
        from ..exec.spill import BufferCatalog
        dm = DeviceManager.get(self.conf)
        TpuSemaphore.initialize(self.conf.concurrent_tpu_tasks)
        cat = BufferCatalog.get()
        cat.device_budget = dm.memory_budget_bytes
        # audit caches prime from the ACTIVE session's conf at first use;
        # a new session (possibly with different analysis.* keys) must
        # re-prime them
        from ..analysis import lockdep, recompile, sync_audit
        from ..exec import metrics as exec_metrics_mod, tracing
        sync_audit.reset_cache()
        recompile.reset_cache()
        # metrics gate primes EAGERLY from THIS conf (like lockdep): a
        # lazy read at first inc could run under the spill catalog's
        # admission lock and recurse into the session lock
        exec_metrics_mod.refresh(self.conf)
        tracing.reset_cache()               # tracing.enabled / .timeline
        # lockdep primes EAGERLY from THIS session's conf (a lazy read at
        # first acquire would recurse through the conf-registry lock)
        lockdep.refresh_mode(self.conf)
        # telemetry primes EAGERLY too (flight-recorder gate/capacity/dir)
        # and starts the scrape endpoint when telemetry.port is set
        from ..service import telemetry
        telemetry.refresh(self.conf)
        # persistent compile cache + donation gate (compile.cacheDir /
        # compile.donate): wires jax's on-disk compilation cache and
        # loads the fused-program signature index; degrades gracefully
        from ..exec import compile_cache
        compile_cache.configure(self.conf)
        # recovery knobs + fault-injection plan prime EAGERLY (the
        # lockdep pattern: a lazy conf read inside a failing partition
        # drain could recurse into the conf-registry lock)
        from ..analysis import faults
        from ..exec import recovery
        recovery.refresh(self.conf)
        faults.refresh(self.conf)
        # lockstep divergence audit mode (analysis/divergence.py): primed
        # eagerly like faults — the mint-site hooks read a lock-free flag
        from ..analysis import divergence
        divergence.refresh(self.conf)
        # buffer-lifecycle ledger mode (analysis/ledger.py): same eager
        # priming — the spill-store hooks read a lock-free flag
        from ..analysis import ledger
        ledger.refresh(self.conf)
        # cold-path killers (docs/compile.md §5): reload the AQE
        # cardinality-feedback checkpoint and prewarm the hottest fused
        # stages from the corpus beside the signature index. Both are
        # best-effort — a torn or missing artifact must not fail
        # bootstrap; prewarm submits to the background pool and returns
        # without blocking.
        try:
            from ..plan import aqe
            aqe.reload_checkpoint(self.conf)
        except Exception:
            pass
        try:
            if bool(self.conf.get(cfg.COMPILE_PREWARM)):
                from ..exec import compile_pool
                compile_pool.prewarm(self.conf)
        except Exception:
            pass

    @classmethod
    def active(cls) -> "TpuSession":
        with cls._lock:
            if cls._active is None:
                cls._active = TpuSession()
            return cls._active

    # -- dataframe creation --------------------------------------------------
    def createDataFrame(self, data, schema=None) -> DataFrame:
        import pandas as pd
        import pyarrow as pa
        if isinstance(data, pd.DataFrame):
            table = pa.Table.from_pandas(data, preserve_index=False)
        elif isinstance(data, pa.Table):
            table = data
        elif isinstance(data, dict):
            table = self._table_from_pydict(data)
        else:
            # rows: list of tuples/dicts (+ schema names)
            if schema is not None and isinstance(schema, (list, tuple)):
                names = list(schema)
                cols = {n: [row[i] for row in data] for i, n in enumerate(names)}
                table = pa.table(cols)
            elif data and isinstance(data[0], dict):
                names = list(data[0].keys())
                cols = {n: [row.get(n) for row in data] for n in names}
                table = pa.table(cols)
            else:
                raise TypeError("provide schema names for row data")
        if isinstance(schema, dt.Schema):
            # cast arrow table to requested types
            import pyarrow as pa
            fields = [pa.field(f.name, dt.to_arrow(f.dtype)) for f in schema]
            table = table.cast(pa.schema(fields))
        return DataFrame(lp.LocalScan(table), self)

    @staticmethod
    def _table_from_pydict(data):
        """pa.table() with MAP columns handled: pyarrow infers python
        dicts as structs (and rejects non-string keys), so columns holding
        dicts get an explicit arrow map type from the inferred SQL type."""
        import pyarrow as pa
        from ..columnar.batch import _infer_dtype
        if not any(isinstance(values, list) and
                   any(isinstance(v, dict) for v in values)
                   for values in data.values()):
            return pa.table(data)       # no map columns: the fast path
        cols, fields = [], []
        for name, values in data.items():
            vals = list(values) if not hasattr(values, "dtype") else values
            has_dict = isinstance(vals, list) and any(
                isinstance(v, dict) for v in vals)
            if has_dict:
                t = dt.to_arrow(_infer_dtype(vals))
                cols.append(pa.array(
                    [None if v is None else list(v.items()) for v in vals],
                    type=t))
                fields.append(pa.field(name, t))
            else:
                arr = pa.array(vals)
                cols.append(arr)
                fields.append(pa.field(name, arr.type))
        return pa.Table.from_arrays(cols, schema=pa.schema(fields))

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              numPartitions: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(lp.Range(start, end, step, numPartitions), self)

    def table(self, name: str) -> DataFrame:
        return DataFrame(self._views[name], self)

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    def sql(self, query: str) -> DataFrame:
        from ..plan import plan_cache as pc
        from .sql import parse_sql
        st = pc.serving_stats(self)
        plan = self._parse_cache_get(query)
        if plan is not None:
            # SQL-text parse cache hit (docs/plan_cache.md §parse): the
            # lexer/parser is skipped entirely; the plan-cache
            # fingerprint downstream still decides plan reuse
            st["parseCacheHits"] += 1
            return DataFrame(plan, self)
        if int(self.conf.get(cfg.PARSE_CACHE_MAX_ENTRIES)) > 0:
            st["parseCacheMisses"] += 1
        st["parses"] += 1
        df = parse_sql(query, self)
        self._parse_cache_put(query, df.logical_plan())
        return df

    # -- SQL-text -> parsed-plan cache (PR 12 follow-up: the layer AHEAD
    # of the plan-cache fingerprint for non-prepared sql() traffic) ------
    def _parse_cache_views_sig(self) -> tuple:
        """Identity snapshot of the session catalog: a parsed plan embeds
        references to the view plan OBJECTS it resolved, so a hit is
        only legal while every registered view is still the same object
        (re-registering a temp view invalidates naturally)."""
        return tuple(sorted((n, id(p)) for n, p in self._views.items()))

    def _parse_cache(self):
        cache = getattr(self, "_sql_parse_cache", None)
        if cache is None:
            from collections import OrderedDict
            cache = self._sql_parse_cache = OrderedDict()  # lint: unguarded-ok every caller holds _parse_cache_mu (module-level helper lock, not the session class lock)
        return cache

    def _parse_cache_get(self, query: str):
        max_entries = int(self.conf.get(cfg.PARSE_CACHE_MAX_ENTRIES))
        if max_entries <= 0:
            return None
        with _parse_cache_mu:
            cache = self._parse_cache()
            hit = cache.get(query)
            if hit is None:
                return None
            views_sig, plan = hit
            if views_sig != self._parse_cache_views_sig():
                del cache[query]     # a referenced view was re-registered
                return None
            cache.move_to_end(query)
            return plan

    def _parse_cache_put(self, query: str, plan) -> None:
        max_entries = int(self.conf.get(cfg.PARSE_CACHE_MAX_ENTRIES))
        if max_entries <= 0:
            return
        with _parse_cache_mu:
            cache = self._parse_cache()
            cache[query] = (self._parse_cache_views_sig(), plan)
            cache.move_to_end(query)
            while len(cache) > max_entries:
                cache.popitem(last=False)

    def prepare(self, query: Union[str, DataFrame]) -> "PreparedStatement":
        """Prepared-statement API (the serving front door,
        docs/plan_cache.md): parse ONCE, plan/contract-validate/
        stage-compile once (through the parameterized-plan cache),
        execute many. SQL text may carry ``:name`` placeholders bound
        per execution::

            stmt = session.prepare(
                "SELECT sum(v) FROM t WHERE d >= :lo AND d < :hi")
            stmt.execute(lo=date(1994, 1, 1), hi=date(1995, 1, 1))
            stmt.execute(lo=date(1995, 1, 1), hi=date(1996, 1, 1))

        A DataFrame works too (its literals auto-parameterize, so later
        frames of the same shape share the plan)."""
        from .sql import PreparedStatement
        return PreparedStatement(self, query)

    def serving_stats(self) -> Dict[str, int]:
        """Counters of the serving front door on THIS session: parses,
        analyzes, plans built, plan/result cache hits and misses,
        binding revalidations (tests and dashboards read these; the
        process-wide analogs are the ``tpu_plan_cache_*`` /
        ``tpu_result_cache_*`` telemetry series)."""
        from ..plan import plan_cache as pc
        return dict(pc.serving_stats(self))

    def stop(self) -> None:
        with TpuSession._lock:
            if TpuSession._active is self:
                TpuSession._active = None

    # -- process telemetry (service/telemetry: the continuous layer) --------
    def metrics_snapshot(self, path: Optional[str] = None) -> dict:
        """Point-in-time snapshot of the PROCESS metrics registry —
        semaphore, lockdep, sync, recompile, spill, shuffle-transport and
        HBM watermark metrics from one surface (the live-Spark-UI
        metrics stream, pulled). With ``path``, one JSONL line is also
        appended there (the scrape-less export)."""
        from ..service.telemetry import MetricsRegistry
        reg = MetricsRegistry.get()
        snap = reg.snapshot()
        if path:
            # the line on disk IS the returned dict (one harvest)
            reg.snapshot_jsonl(path, snap)
        return snap

    def prometheus_metrics(self) -> str:
        """The registry in Prometheus text format (what the scrape
        endpoint at ``spark.rapids.tpu.sql.telemetry.port`` serves)."""
        from ..service.telemetry import MetricsRegistry
        return MetricsRegistry.get().prometheus_text()

    def dump_flight_record(self, path: Optional[str] = None,
                           query_id: Optional[str] = None) -> str:
        """Write the always-on flight ring to a JSON artifact on demand
        (the automatic dump fires when a task body or collect raises);
        returns the artifact path. ``query_id`` scopes the artifact to
        one query: the filename carries the id and another query's
        attributed events are filtered out."""
        from ..service.telemetry import FlightRecorder
        return FlightRecorder.get().dump(path, reason="on-demand",
                                         query_id=query_id)

    # -- query-lifecycle control (exec/lifecycle.py, docs/service.md §4) ----
    def cancel_query(self, query_id: str, reason: str = "cancel") -> bool:
        """Cooperatively cancel a RUNNING query by id (from another
        thread — a collect is synchronous on its own): sets the query's
        cancel flag, and the execution unwinds with a typed
        ``QueryCancelledError`` at its next poll point (partition drain,
        fetch/completion poll, retry backoff, ``collect_iter``
        delivery). Never a thread kill; cleanup runs the normal error
        path (arenas release, the buffer ledger audits residency).
        False when no such query is live."""
        from ..exec import lifecycle
        return lifecycle.cancel_query(query_id, reason)

    def live_queries(self) -> List[str]:
        """Query ids currently registered with the lifecycle control
        plane in this process (running collects; suspended queries stay
        with the service that parked them)."""
        from ..exec import lifecycle
        return lifecycle.live_queries()

    # -- query-lifecycle observability (docs/observability.md §8) -----------
    def last_query_id(self) -> Optional[str]:
        """The query id minted for the last executed collect (None before
        the first execution; shared by every worker of a lockstep
        distributed run)."""
        return getattr(self, "_last_query_id", None)

    def last_stage_stats(self) -> List[dict]:
        """Stage-boundary exchange statistics of the last executed query:
        one entry per exchange node in tree order — stage id, data plane,
        per-partition rows/bytes, p50/max partition bytes and the skew
        factor observed at materialization. This is the AQE feed
        (ROADMAP item 2): coalesce/skew re-planning reads exactly this
        shape."""
        if self._last_exec_plan is None:
            raise RuntimeError("no plan executed yet")
        from ..shuffle.exchange import collect_stage_stats
        return collect_stage_stats(self._last_exec_plan)

    def last_aqe_decisions(self) -> List[dict]:
        """Adaptive-execution decisions of the last executed query, in
        plan-tree order: per record the rule (coalesce / skew-split /
        join-promote / join-demote / drift-feedback), whether it was
        applied or declined, the owning operator + plan path, the
        before/after shapes, and the reason (plan/aqe.py,
        docs/aqe.md)."""
        if self._last_exec_plan is None:
            raise RuntimeError("no plan executed yet")
        from ..plan.aqe import collect_decisions
        return collect_decisions(self._last_exec_plan)

    def last_drift_report(self) -> List[dict]:
        """Estimate-vs-actual row drift of the last executed query, worst
        first: per plan node the planner's estimate, the executed actual,
        the drift ratio, and whether it crossed
        ``observability.driftThreshold`` (the cardinality-feedback
        groundwork, plan/estimates.py)."""
        if self._last_exec_plan is None:
            raise RuntimeError("no plan executed yet")
        from ..plan.estimates import drift_report
        return drift_report(self._last_exec_plan, conf=self.conf)

    def merged_timeline(self, extra=(), query_id: Optional[str] = None,
                        path: Optional[str] = None):
        """ONE Chrome-trace timeline for the last executed query across
        every worker that ran it: this session's recorded spans merged
        with ``extra`` trace documents (dicts or trace.json paths —
        typically the REMOTE workers' dumps), filtered to the shared
        query id, each source under its own process group. Requires the
        timeline conf (``tracing.timeline``) or a trace-recording run.
        Returns the merged trace dict; with ``path``, also writes it
        there and returns the path."""
        rec = getattr(self, "_last_span_recorder", None)
        if rec is None:
            raise RuntimeError("no recorded query timeline (enable "
                               "spark.rapids.tpu.sql.tracing.timeline)")
        from ..exec.tracing import merge_chrome_traces
        qid = query_id or getattr(self, "_last_query_id", None)
        merged = merge_chrome_traces(
            [rec.chrome_trace()] + list(extra), query_id=qid)
        if path:
            import json
            import os
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            with open(path, "w") as f:
                json.dump(merged, f)
            return path
        return merged

    # -- testing hooks (ExecutionPlanCaptureCallback analog) ----------------
    def last_plan(self):
        return self._last_exec_plan

    # -- per-query metrics (SQLMetrics-in-the-UI analog: GpuMetricNames +
    # per-exec additionalMetrics, GpuExec.scala:27-56; spill volume feeds
    # the query summary like TaskMetrics.memoryBytesSpilled) --------------
    def last_query_metrics(self) -> dict:
        """Structured metrics for the last executed query: per-operator
        counters/timers in plan-tree order, spill DELTAS attributable to
        that query (TaskMetrics.memoryBytesSpilled analog), and the
        point-in-time catalog residency gauges."""
        if self._last_exec_plan is None:
            raise RuntimeError("no plan executed yet")
        from ..exec.spill import BufferCatalog
        cat = BufferCatalog.get()
        base_dev, base_host = getattr(self, "_mem_baseline", (0, 0))
        return {
            "operators": [
                {"depth": d, "operator": name, "metrics": m}
                for d, name, m in self._last_exec_plan.metrics_tree()],
            "memory": {
                "deviceBytesHeld": cat.device_bytes,
                "hostBytesHeld": cat.host_bytes,
                "spilledDeviceBytes": cat.spilled_device_bytes - base_dev,
                "spilledHostBytes": cat.spilled_host_bytes - base_host,
            },
            # attributed blocking device->host readbacks during the collect
            # (the dominant end-to-end cost on high-latency links; see
            # exec/tracing.SyncCounter)
            "sync": getattr(self, "_last_sync_report",
                            {"hostSyncs": 0, "syncSites": {}}),
            # per-span wall-clock breakdown (self time, nesting excluded):
            # names where executeTimeS went — concurrent partition tasks
            # can legitimately sum past the wall clock
            "spans": getattr(self, "_last_span_report", {}),
            # driver-side planning (analyze + overrides) wall time and the
            # execute_collect wall (device work + transfers + syncs): with
            # the per-operator timers these account for the query's wall
            # clock end to end
            "planTimeS": round(getattr(self, "_last_plan_time_s", 0.0), 4),
            "executeTimeS": round(
                getattr(self, "_last_execute_time_s", 0.0), 4),
            # wall seconds to the first batch: == executeTimeS for a
            # materializing collect, smaller for collect_iter streams
            "firstRowS": round(
                getattr(self, "_last_first_row_s", 0.0) or 0.0, 4),
        }

    def explain_metrics(self) -> str:
        """The last executed plan annotated with each operator's metrics
        (the explain-with-SQLMetrics view of the Spark UI)."""
        if self._last_exec_plan is None:
            raise RuntimeError("no plan executed yet")
        rep = self.last_query_metrics()
        mem = rep["memory"]
        tail = ("memory: " +
                ", ".join(f"{k}={v}" for k, v in sorted(mem.items())))
        return self._last_exec_plan.metrics_string() + "\n" + tail

    def explain_analyze(self) -> str:
        """EXPLAIN ANALYZE of the last executed query: the executed plan
        tree with each node's runtime metrics inline (rows, batches,
        opTime, attributed hostSyncs/recompiles/spillBytes, ...), the
        plan-contract validator's diagnostics attached to the offending
        node, and the query-level wall/sync/span summary — the Spark-UI
        SQL-tab view, in text. ``df.explain(\"analyze\")`` executes the
        frame and prints this."""
        if self._last_exec_plan is None:
            raise RuntimeError("no plan executed yet")
        # contract violations keyed by root->node path (the same path
        # contracts.validate_plan builds and metrics_tree(with_path=True)
        # reproduces)
        # annotations computed from the EXECUTED tree so runtime fusion
        # fallbacks (stage broken -> per-op eager) show too
        ov = self._last_overrides
        lines: List[str] = ["== Executed Plan (analyzed) =="]
        lines += _annotated_plan_lines(
            self._last_exec_plan,
            getattr(ov, "last_violations", []) if ov else [],
            conf=self.conf)
        rep = self.last_query_metrics()
        sync = rep.get("sync", {})
        spans = rep.get("spans", {})
        qid = getattr(self, "_last_query_id", None)
        lines.append(
            f"query: {'queryId=' + qid + ' ' if qid else ''}"
            f"planTimeS={rep.get('planTimeS')} "
            f"executeTimeS={rep.get('executeTimeS')} "
            f"firstRowS={rep.get('firstRowS')} "
            f"hostSyncs={sync.get('hostSyncs', 0)} "
            f"spanWallS={spans.get('wallS', 0.0)} "
            f"concurrency={spans.get('concurrency', 0.0)}")
        # serving-cache hit/miss per layer (plan/plan_cache.py)
        from ..plan.plan_cache import serving_line
        sl = serving_line(getattr(self, "_last_serving", None))
        if sl:
            lines.append(sl)
        # buffer-lifecycle verdict (analysis/ledger.py end_of_query):
        # present whenever the ledger audited this query
        led = getattr(self, "_last_ledger", None)
        if led:
            lines.append(
                f"ledger: leakedBuffers={led.get('leakedBuffers', 0)} "
                f"leakedBytes={led.get('leakedBytes', 0)} "
                f"peakDeviceBytes={led.get('peakDeviceBytes', 0)} "
                f"mintedBuffers={led.get('mintedBuffers', 0)}")
        return "\n".join(lines)

    # -- query-execution listeners (ExecutionPlanCaptureCallback analog,
    # Plugin.scala:211-300): tests and the bench runner register callbacks
    # receiving a QueryExecution per executed query -----------------------
    def register_query_listener(self, callback) -> None:
        """``callback(QueryExecution)`` fires after every collect-style
        action on this session. Exceptions in listeners are logged and
        swallowed — observability must never fail the query."""
        if callback not in self._query_listeners:
            self._query_listeners.append(callback)

    def unregister_query_listener(self, callback) -> None:
        try:
            self._query_listeners.remove(callback)
        except ValueError:
            pass

    def _notify_query_listeners(self, qe: "QueryExecution") -> None:
        import logging
        for cb in list(self._query_listeners):
            try:
                cb(qe)
            except Exception:
                logging.getLogger("spark_rapids_tpu.listener").exception(
                    "query listener %r failed", cb)

    def assert_on_tpu(self, allowed_fallbacks: Sequence[str] = ()) -> None:
        """assertIsOnTheGpu test mode (GpuTransitionOverrides.scala:311-367)."""
        from ..plan.physical import CpuFallbackExec
        from ..plan.overrides import CpuOpBridgeExec

        def walk(node):
            if isinstance(node, (CpuFallbackExec, CpuOpBridgeExec)):
                name = node.plan.name
                if name not in allowed_fallbacks:
                    raise AssertionError(
                        f"{name} ran on CPU; explain:\n"
                        f"{self._last_overrides.last_explain}")
            for c in node.children:
                walk(c)
        assert self._last_exec_plan is not None, "no plan executed yet"
        walk(self._last_exec_plan)


class DataFrameReader:
    def __init__(self, session: TpuSession):
        self.session = session
        self._options: Dict[str, Any] = {}
        self._schema: Optional[dt.Schema] = None

    def option(self, k: str, v: Any) -> "DataFrameReader":
        self._options[k] = v
        return self

    def options(self, **kw) -> "DataFrameReader":
        self._options.update(kw)
        return self

    def schema(self, s: dt.Schema) -> "DataFrameReader":
        self._schema = s
        return self

    def parquet(self, *paths: str) -> DataFrame:
        return self._scan("parquet", list(paths))

    def csv(self, *paths: str) -> DataFrame:
        return self._scan("csv", list(paths))

    def orc(self, *paths: str) -> DataFrame:
        return self._scan("orc", list(paths))

    def _scan(self, fmt: str, paths: List[str]) -> DataFrame:
        return DataFrame(
            lp.FileScan(fmt, paths, self._schema, self._options), self.session)
