"""Minimal SQL front end for TpuSession.sql().

The reference accelerates SQL text through Spark Catalyst (its whole entry
point: ``SQLExecPlugin`` injecting rules into the session,
sql-plugin/.../Plugin.scala:40-59); standalone, we ship a small SQL
dialect over registered temp views instead of a full Catalyst clone:

    SELECT [DISTINCT] expr [AS alias], ...
    FROM view [alias] [, view ...]
         [ [INNER|LEFT|RIGHT|FULL [OUTER]|CROSS] JOIN ref
           (ON cond | USING (cols)) ]...
    [WHERE cond] [GROUP BY expr|position, ...] [HAVING cond]
    [ORDER BY expr [ASC|DESC], ...] [LIMIT n]

Expressions: arithmetic, comparisons, AND/OR/NOT, BETWEEN, IN (...),
LIKE, IS [NOT] NULL, CASE WHEN, CAST(x AS type), DATE 'yyyy-mm-dd',
INTERVAL 'n' DAY/MONTH/YEAR, aggregate and scalar function calls mapped
onto ``api.functions``, ``*`` and qualified ``t.col`` references
(resolved by name: the single-session catalog has no per-table scoping).

Subqueries (the Catalyst RewritePredicateSubquery /
RewriteCorrelatedScalarSubquery rules, collapsed into the parser):
- FROM ( SELECT ... ) derived tables;
- WITH name AS ( SELECT ... ) prefixes (query-scoped temp views);
- WHERE [NOT] EXISTS ( SELECT ... correlated ) -> decorrelated into a
  left-semi/anti join on the correlated conjuncts;
- expr [NOT] IN ( SELECT ... ) -> semi/anti join (correlated or not);
- scalar subqueries in comparisons/HAVING: uncorrelated execute once and
  fold to a literal; correlated (equality correlation only) decorrelate
  into a grouped aggregate LEFT-joined on the correlation keys.
Correlation is resolved scope-wise while parsing a subquery's WHERE: a
name (or ``alias.name`` with an enclosing FROM's alias) that does not
resolve in the subquery's own FROM but does in an enclosing query's
becomes an outer reference. Subquery predicates must sit in top-level
AND conjuncts. Everything else raises ``SqlParseError`` — the caller
sees a clear message, never a silently wrong plan.
"""
from __future__ import annotations

import re
from typing import List, Optional

from . import functions as F
from .column import Col, _unwrap
from ..analysis.lockdep import named_lock
from ..ops import expressions as ex
from ..ops import predicates as pr
from ..plan import logical as lp


class SqlParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    \s+
  | (?P<comment>--[^\n]*)
  | (?P<param>:[A-Za-z_][A-Za-z_0-9]*)
  | (?P<number>\d+\.\d*([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?|\d+([eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*|`[^`]+`)
  | (?P<op><>|!=|>=|<=|=|<|>|\|\||[+\-*/%(),.])
""", re.VERBOSE)


class _Tok:
    def __init__(self, kind: str, text: str, pos: int):
        self.kind = kind          # number | string | ident | op | end
        self.text = text
        self.upper = text.upper() if kind == "ident" else text
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.text!r}"


def _lex(sql: str) -> List[_Tok]:
    out: List[_Tok] = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise SqlParseError(f"cannot tokenize SQL at: {sql[i:i+20]!r}")
        i = m.end()
        if m.lastgroup in (None, "comment"):
            continue
        text = m.group()
        if m.lastgroup == "ident" and text.startswith("`"):
            text = text[1:-1]
        out.append(_Tok(m.lastgroup, text, m.start()))
    out.append(_Tok("end", "", len(sql)))
    return out


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

_AGG_FNS = {"SUM", "COUNT", "AVG", "MEAN", "MIN", "MAX", "FIRST", "LAST"}

# SQL name -> api.functions name, where they differ
_FN_ALIASES = {
    "SUBSTR": "substring", "CHAR_LENGTH": "length", "CHARACTER_LENGTH":
    "length", "LCASE": "lower", "UCASE": "upper", "CEILING": "ceil",
    "POWER": "pow", "MEAN": "avg", "DAY": "dayofmonth",
    "NVL": "nvl", "IFNULL": "nvl",
}

_RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "JOIN", "ON",
    "USING", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "OUTER", "AND",
    "OR", "NOT", "AS", "ASC", "DESC", "THEN", "ELSE", "END", "WHEN",
    "BY", "UNION",
}


class _Scope:
    """Per-SELECT name scope: FROM tables' columns + aliases. ``in_where``
    gates outer-reference resolution — only a subquery's WHERE clause may
    reach enclosing scopes (correlation lives in WHERE; select lists parse
    BEFORE FROM, so outer fallback there would misresolve)."""

    def __init__(self):
        self.all_cols: set = set()
        self.aliases: dict = {}          # alias -> set of columns
        self.in_where = False


class _OuterRef(ex.ColumnRef):
    """A column reference that resolved in an ENCLOSING query's FROM:
    the correlation marker the decorrelation rewrite consumes. Reaching
    eval/planning unconsumed is a bug guard."""
    is_outer = True


class _SubqueryExpr(ex.Expression):
    """Parse-time subquery predicate/value nodes, consumed by the WHERE
    rewrite — escaping into a real plan raises."""

    @property
    def dtype(self):
        raise SqlParseError(
            f"{type(self).__name__} must appear in a top-level AND "
            "conjunct of WHERE (or, for scalar subqueries, inside a "
            "comparison there or in HAVING)")

    def eval(self, batch):
        self.dtype


class _ExistsSQ(_SubqueryExpr):
    def __init__(self, info):
        super().__init__()
        self.info = info


class _InSQ(_SubqueryExpr):
    def __init__(self, value_expr, info, negated):
        super().__init__()
        self.value_expr = value_expr
        self.info = info
        self.negated = negated


class _ScalarSQ(_SubqueryExpr):
    def __init__(self, info):
        super().__init__()
        self.info = info


class _SubqueryInfo:
    """Parsed-but-unfinished subquery: core df (FROM + pure-inner WHERE,
    nested subqueries already applied) plus the deferred clauses and the
    correlated conjuncts pulled out of its WHERE."""

    def __init__(self, parser, df, items, group_exprs, having, distinct,
                 corr, orders, limit, star_cols=None):
        self.parser = parser
        self.df = df
        self.items = items
        self.group_exprs = group_exprs
        self.having = having
        self.distinct = distinct
        self.corr = corr
        self.orders = orders
        self.limit = limit
        self.star_cols = star_cols

    def build_full(self):
        """Finish as a normal derived table (only valid uncorrelated)."""
        assert not self.corr
        return self.parser._finish(self.df, self.items, self.group_exprs,
                                   self.having, self.distinct, self.orders,
                                   self.limit, self.star_cols)


class _Parser:
    def __init__(self, toks: List[_Tok], session):
        self.toks = toks
        self.i = 0
        self.session = session
        # single-namespace resolution safety: qualified refs seen while
        # parsing + the FROM tables' column sets, checked per SELECT
        self._qualified_refs: List[str] = []
        self._from_columns: List[set] = []
        self._has_cross = False
        self._scopes: List[_Scope] = []
        self._sq_counter = 0

    # -- token helpers ------------------------------------------------------
    def peek(self, ahead: int = 0) -> _Tok:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        if t.kind != "end":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in kws

    def take_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.take_kw(kw):
            raise SqlParseError(
                f"expected {kw} near {self.peek().text!r}")

    def take_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.text == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.take_op(op):
            raise SqlParseError(
                f"expected {op!r} near {self.peek().text!r}")

    # -- statement ----------------------------------------------------------
    def parse_select(self, as_subquery: bool = False):
        """Returns a DataFrame — or, with ``as_subquery``, a
        :class:`_SubqueryInfo` whose finishing is deferred so the caller
        can decorrelate."""
        outer_refs = self._qualified_refs
        outer_cols = self._from_columns
        outer_cross = self._has_cross
        self._qualified_refs, self._from_columns = [], []
        self._has_cross = False
        scope = _Scope()
        self._scopes.append(scope)
        self.expect_kw("SELECT")
        distinct = self.take_kw("DISTINCT")
        items = self.parse_select_list()
        self.expect_kw("FROM")
        df = self.parse_from()
        # `*` expands from the PRE-rewrite column list: subquery
        # decorrelation (scalar-subquery LEFT joins) appends internal
        # `__sqN_*` columns to df below, which must never leak into a
        # user-visible star projection
        star_cols = list(df.columns)
        corr: List[ex.Expression] = []
        if self.take_kw("WHERE"):
            scope.in_where = True
            cond = self.parse_expr()
            scope.in_where = False
            df, corr = self._apply_where(df, cond,
                                         allow_correlated=as_subquery)
        group_exprs = None
        if self.take_kw("GROUP"):
            self.expect_kw("BY")
            group_exprs = self.parse_group_by(items)
        having = self.parse_expr() if self.take_kw("HAVING") else None
        orders = None
        if self.take_kw("ORDER"):
            self.expect_kw("BY")
            orders = self.parse_order_by(items)
        limit = None
        if self.take_kw("LIMIT"):
            t = self.next()
            if t.kind != "number":
                raise SqlParseError(f"LIMIT expects a number, got {t.text!r}")
            limit = int(t.text)
        # after EVERY clause parsed (GROUP BY / HAVING / ORDER BY refs
        # included), then restore the enclosing query's scope
        self._check_qualified_refs()
        self._qualified_refs, self._from_columns = outer_refs, outer_cols
        self._has_cross = outer_cross
        self._scopes.pop()
        if as_subquery:
            return _SubqueryInfo(self, df, items, group_exprs, having,
                                 distinct, corr, orders, limit, star_cols)
        return self._finish(df, items, group_exprs, having, distinct,
                            orders, limit, star_cols)

    def _finish(self, df, items, group_exprs, having, distinct, orders,
                limit, star_cols=None):
        having = self._fold_scalar_subqueries(having)
        df = self.build_projection(df, items, group_exprs, having,
                                   star_cols)
        if distinct:
            df = df.distinct()
        if orders:
            df = df.orderBy(*orders)
        if limit is not None:
            df = df.limit(limit)
        return df

    # -- WHERE rewriting (predicate-subquery decorrelation) ------------------
    def _apply_where(self, df, cond, allow_correlated: bool):
        """Split the WHERE into top-level AND conjuncts; subquery
        predicates turn into semi/anti/left joins on ``df``, correlated
        conjuncts (containing outer refs) are pulled out for the
        enclosing decorrelation, the rest filter."""
        plain: List[ex.Expression] = []
        corr: List[ex.Expression] = []
        for c in _split_and(cond):
            if c.collect(lambda x: isinstance(x, _OuterRef)):
                if not allow_correlated:
                    raise SqlParseError(
                        "correlated column reference outside a subquery")
                if c.collect(lambda x: isinstance(x, _SubqueryExpr)):
                    raise SqlParseError(
                        "a correlated conjunct cannot also contain a "
                        "subquery")
                corr.append(c)
                continue
            df, keep = self._rewrite_conjunct(df, c)
            if keep is not None:
                plain.append(keep)
        if plain:
            out = plain[0]
            for p in plain[1:]:
                out = pr.And(out, p)
            df = df.filter(Col(out))
        return df, corr

    def _rewrite_conjunct(self, df, c):
        """One WHERE conjunct: EXISTS/IN subqueries consume it into a
        join; scalar subqueries fold into literals (uncorrelated) or a
        grouped-aggregate LEFT join (correlated); plain conjuncts pass
        through."""
        neg = False
        inner = c
        if isinstance(inner, pr.Not) and isinstance(inner.children[0],
                                                    _ExistsSQ):
            neg, inner = True, inner.children[0]
        if isinstance(inner, _ExistsSQ):
            return self._apply_exists(df, inner.info, neg), None
        if isinstance(inner, _InSQ):
            return self._apply_in(df, inner), None
        if c.collect(lambda x: isinstance(x, (_ExistsSQ, _InSQ))):
            raise SqlParseError(
                "EXISTS / IN-subquery predicates must stand alone in a "
                "top-level AND conjunct (not under OR or expressions)")
        scalars = c.collect(lambda x: isinstance(x, _ScalarSQ))
        for sq in scalars:
            df, repl = self._resolve_scalar(df, sq)
            c = c.transform_down(
                lambda n, _sq=sq, _r=repl: _r if n is _sq else None)
        return df, c

    def _prefix(self) -> str:
        self._sq_counter += 1
        return f"__sq{self._sq_counter}_"

    def _rename_sub(self, sub_df, prefix):
        return sub_df.select(*[Col(ex.Alias(ex.ColumnRef(c), prefix + c))
                               for c in sub_df.columns])

    @staticmethod
    def _rewrite_corr(e, prefix, inner_cols):
        """Correlated conjunct -> join condition: outer refs become bare
        outer columns, inner refs get the subquery's rename prefix."""
        def fn(n):
            if isinstance(n, _OuterRef):
                return ex.ColumnRef(n.col_name)
            if isinstance(n, ex.ColumnRef) and n.col_name in inner_cols:
                return ex.ColumnRef(prefix + n.col_name)
            return None
        return e.transform_down(fn)

    def _apply_exists(self, df, info, neg):
        """[NOT] EXISTS -> left-semi/anti join on the correlated
        conjuncts (RewritePredicateSubquery)."""
        if not info.corr:
            raise SqlParseError(
                "EXISTS requires a correlated subquery in this dialect")
        if info.orders or info.limit is not None or info.group_exprs:
            raise SqlParseError(
                "EXISTS subqueries cannot use GROUP BY/ORDER BY/LIMIT")
        prefix = self._prefix()
        inner_cols = set(info.df.columns)
        renamed = self._rename_sub(info.df, prefix)
        cond = None
        for e in info.corr:
            e = self._rewrite_corr(e, prefix, inner_cols)
            cond = e if cond is None else pr.And(cond, e)
        return df.join(renamed, on=Col(cond),
                       how="left_anti" if neg else "left_semi")

    def _apply_in(self, df, node):
        """expr [NOT] IN (SELECT ...) -> semi/anti join on the value
        equality (+ correlated conjuncts)."""
        info = node.info
        prefix = self._prefix()
        if info.corr:
            if info.group_exprs or info.distinct or info.having or \
                    info.orders or info.limit is not None:
                raise SqlParseError(
                    "correlated IN subqueries cannot use GROUP BY/"
                    "DISTINCT/HAVING/ORDER BY/LIMIT")
            if node.negated:
                # a null-aware anti join against a correlated subquery
                # needs per-outer-row null accounting — refuse rather
                # than silently dropping three-valued semantics
                raise SqlParseError(
                    "correlated NOT IN subqueries are not supported; "
                    "rewrite as NOT EXISTS")
            (sel, _alias), = info.items if len(info.items) == 1 else (
                (None, None),)
            if sel is None or sel == "*":
                raise SqlParseError(
                    "IN subquery must select exactly one expression")
            inner_cols = set(info.df.columns)
            renamed = self._rename_sub(info.df, prefix)
            cond = pr.EqualTo(node.value_expr,
                              self._rewrite_corr(sel, prefix, inner_cols))
            for e in info.corr:
                cond = pr.And(cond,
                              self._rewrite_corr(e, prefix, inner_cols))
            return df.join(renamed, on=Col(cond), how="left_semi")
        full = info.build_full()
        if len(full.columns) != 1:
            raise SqlParseError(
                "IN subquery must select exactly one column")
        out = prefix + full.columns[0]
        renamed = full.select(
            Col(ex.Alias(ex.ColumnRef(full.columns[0]), out)))
        cond = pr.EqualTo(node.value_expr, ex.ColumnRef(out))
        if not node.negated:
            return df.join(renamed, on=Col(cond), how="left_semi")
        # NOT IN: SQL three-valued semantics (Spark's null-aware anti
        # join). A row qualifies iff the subquery is EMPTY, or (its value
        # is non-null AND the subquery output has no NULLs AND the value
        # matches none of them). Plain left_anti alone would wrongly keep
        # rows whenever the subquery contains a NULL.
        n_total = prefix + "ntotal"
        n_nonnull = prefix + "nnonnull"
        stats = full.agg(
            Col(ex.Alias(lp.AggregateExpression("count_star", None),
                         n_total)),
            Col(ex.Alias(lp.AggregateExpression(
                "count", ex.ColumnRef(full.columns[0])), n_nonnull)))
        anti = df.join(renamed, on=Col(cond), how="left_anti") \
                 .crossJoin(stats)
        keep = pr.Or(
            pr.EqualTo(ex.ColumnRef(n_total), ex.lit(0)),
            pr.And(pr.EqualTo(ex.ColumnRef(n_total),
                              ex.ColumnRef(n_nonnull)),
                   pr.IsNotNull(node.value_expr)))
        kept = anti.filter(Col(keep))
        return kept._df(lp.Project(
            kept._plan, [ex.ColumnRef(c) for c in df.columns]))

    def _resolve_scalar(self, df, sq):
        """Scalar subquery -> (df', replacement expr). Uncorrelated:
        execute once, fold to a literal (Spark runs uncorrelated scalar
        subqueries exactly once before the main query). Correlated:
        grouped aggregate over the equality-correlation keys LEFT-joined
        back (RewriteCorrelatedScalarSubquery) — empty groups yield NULL
        through the left join, matching SQL's empty-scalar-subquery."""
        info = sq.info
        if not info.corr:
            full = info.build_full()
            rows = full.collect()
            if len(rows) > 1 or (rows and len(rows[0]) != 1):
                raise SqlParseError(
                    "scalar subquery must produce at most one value")
            return df, ex.lit(rows[0][0] if rows else None)
        if info.group_exprs or info.having or info.distinct or \
                info.orders or info.limit is not None:
            raise SqlParseError(
                "correlated scalar subqueries support a bare aggregate "
                "select only")
        (sel, _alias), = info.items if len(info.items) == 1 else (
            (None, None),)
        if sel is None or sel == "*" or not _has_agg(sel):
            raise SqlParseError(
                "correlated scalar subquery must select one aggregate")
        prefix = self._prefix()
        inner_keys, outer_exprs = [], []
        for e in info.corr:
            if not isinstance(e, pr.EqualTo):
                raise SqlParseError(
                    "correlated scalar subqueries support equality "
                    "correlation only")
            a, b = e.children
            a_outer = bool(a.collect(lambda x: isinstance(x, _OuterRef)))
            b_outer = bool(b.collect(lambda x: isinstance(x, _OuterRef)))
            if a_outer == b_outer:
                raise SqlParseError(
                    "correlation equality must compare an inner "
                    "expression to an outer one")
            inner, outer = (b, a) if a_outer else (a, b)
            inner_keys.append(inner)
            outer_exprs.append(outer.transform_down(
                lambda n: ex.ColumnRef(n.col_name)
                if isinstance(n, _OuterRef) else None))
        key_cols = [Col(ex.Alias(k, f"{prefix}k{i}"))
                    for i, k in enumerate(inner_keys)]
        val = f"{prefix}val"
        agg_df = info.df.groupBy(*key_cols).agg(Col(ex.Alias(sel, val)))
        cond = None
        for i, o in enumerate(outer_exprs):
            e = pr.EqualTo(o, ex.ColumnRef(f"{prefix}k{i}"))
            cond = e if cond is None else pr.And(cond, e)
        joined = df.join(agg_df, on=Col(cond), how="left")
        keep = [ex.ColumnRef(c) for c in df.columns] + [ex.ColumnRef(val)]
        repl: ex.Expression = ex.ColumnRef(val)
        counts = sel.collect(
            lambda x: isinstance(x, lp.AggregateExpression) and
            x.op in ("count", "count_star"))
        if counts:
            # a COUNT over an empty group is 0, but the grouped rewrite
            # has no group to join -> NULL through the left join. Spark's
            # RewriteCorrelatedScalarSubquery substitutes the aggregate's
            # empty-input default; a bare count folds to coalesce(val, 0),
            # anything mixing count into a wider expression would need
            # per-aggregate defaults — refuse loudly instead.
            if isinstance(sel, lp.AggregateExpression):
                from ..ops.conditionals import Coalesce
                repl = Coalesce(ex.ColumnRef(val), ex.lit(0))
            else:
                raise SqlParseError(
                    "correlated scalar subqueries mixing COUNT into a "
                    "larger expression are not supported (empty-group "
                    "default would be wrong)")
        return joined._df(lp.Project(joined._plan, keep)), repl

    def _fold_scalar_subqueries(self, e):
        """HAVING may hold UNcorrelated scalar subqueries (TPC-H q11):
        fold them eagerly; correlated ones have no join target here."""
        if e is None:
            return None
        scalars = e.collect(lambda x: isinstance(x, _ScalarSQ))
        for sq in scalars:
            if sq.info.corr:
                raise SqlParseError(
                    "correlated scalar subqueries are not supported in "
                    "HAVING")
            _df, repl = self._resolve_scalar(None, sq)
            e = e.transform_down(
                lambda n, _sq=sq, _r=repl: _r if n is _sq else None)
        return e

    def parse_select_list(self):
        items: List[tuple] = []   # (expr | "*", alias | None)
        while True:
            if self.take_op("*"):
                items.append(("*", None))
            elif (self.peek().kind == "ident"
                  and self.peek(1).text == "."
                  and self.peek(2).text == "*"):
                self.next(); self.next(); self.next()
                items.append(("*", None))   # t.*: single-namespace catalog
            else:
                e = self.parse_expr()
                alias = None
                if self.take_kw("AS"):
                    alias = self.next().text
                elif (self.peek().kind == "ident"
                      and self.peek().upper not in _RESERVED_STOP):
                    alias = self.next().text
                items.append((e, alias))
            if not self.take_op(","):
                return items

    # -- FROM / joins -------------------------------------------------------
    def parse_table_ref(self):
        if self.take_op("("):
            df = self.parse_select()
            self.expect_op(")")
        else:
            t = self.next()
            if t.kind != "ident":
                raise SqlParseError(f"expected table name, got {t.text!r}")
            try:
                df = self.session.table(t.text)
            except KeyError:
                raise SqlParseError(f"unknown table or view: {t.text!r}")
        alias = None
        if self.take_kw("AS"):
            alias = self.next().text
        elif (self.peek().kind == "ident"
              and self.peek().upper not in _RESERVED_STOP):
            alias = self.next().text
        self._from_columns.append(set(df.columns))
        if self._scopes:
            scope = self._scopes[-1]
            scope.all_cols.update(df.columns)
            if alias:
                scope.aliases[alias] = set(df.columns)
        return df

    def parse_from(self):
        df = self.parse_table_ref()
        while True:
            if self.take_op(","):             # comma = cross join + WHERE
                self._has_cross = True
                df = df.crossJoin(self.parse_table_ref())
                continue
            how = None
            if self.at_kw("JOIN"):
                how = "inner"
            elif self.at_kw("INNER", "LEFT", "RIGHT", "FULL", "CROSS"):
                kw = self.next().upper
                self.take_kw("OUTER")
                how = {"INNER": "inner", "LEFT": "left", "RIGHT": "right",
                       "FULL": "full", "CROSS": "cross"}[kw]
            if how is None:
                return df
            self.expect_kw("JOIN")
            other = self.parse_table_ref()
            if how == "cross":
                self._has_cross = True
                df = df.crossJoin(other)
            elif self.take_kw("ON"):
                # ON conditions resolve left/right by the planner's
                # equi-key extraction — qualified refs there are sound,
                # so drop them from the ambiguity check
                mark = len(self._qualified_refs)
                cond = self.parse_expr()
                del self._qualified_refs[mark:]
                df = df.join(other, on=Col(cond), how=how)
            elif self.take_kw("USING"):
                self.expect_op("(")
                cols = [self.next().text]
                while self.take_op(","):
                    cols.append(self.next().text)
                self.expect_op(")")
                df = df.join(other, on=cols, how=how)
            else:
                raise SqlParseError("JOIN requires ON or USING")

    # -- GROUP BY / projection ---------------------------------------------
    def parse_group_by(self, items) -> List[ex.Expression]:
        out: List[ex.Expression] = []
        while True:
            t = self.peek()
            if t.kind == "number" and "." not in t.text:
                self.next()                   # positional: GROUP BY 1
                pos = int(t.text)
                if not (1 <= pos <= len(items)) or items[pos - 1][0] == "*":
                    raise SqlParseError(f"GROUP BY position {pos} invalid")
                out.append(items[pos - 1][0])
            else:
                out.append(self.parse_expr())
            if not self.take_op(","):
                return out

    def build_projection(self, df, items, group_exprs, having,
                         star_cols=None):
        has_star = any(e == "*" for e, _ in items)
        exprs: List[ex.Expression] = []
        for e, alias in items:
            if e == "*":
                continue
            exprs.append(ex.Alias(e, alias) if alias else e)
        is_agg = group_exprs is not None or any(
            _has_agg(e) for e in exprs)
        if not is_agg:
            # star expands from the pre-rewrite column list: WHERE-clause
            # subquery decorrelation appends internal __sqN_* columns that
            # must not surface in the user-visible schema
            base = [c for c in (star_cols if star_cols is not None
                                else df.columns) if c in df.columns]
            if has_star and len(items) == 1:
                if list(df.columns) == base:
                    return df
                return df._df(lp.Project(
                    df._plan, [ex.ColumnRef(c) for c in base]))
            if has_star:
                cols = [ex.ColumnRef(c) for c in base]
                return df._df(lp.Project(df._plan, cols + exprs))
            return df.select(*[Col(e) for e in exprs])
        if has_star:
            raise SqlParseError("SELECT * cannot mix with aggregation")
        grouping = group_exprs or []
        out = df._df(lp.Aggregate(df._plan, grouping, list(exprs)))
        if having is not None:
            # HAVING may reference select aliases or re-state aggregates;
            # re-stated aggregates must be computed IN the aggregation, so
            # fold them in as hidden columns, filter, then drop
            extra, cond = _extract_having(having, exprs)
            if extra:
                out = df._df(lp.Aggregate(
                    df._plan, grouping, list(exprs) + extra))
                keep = [ex.ColumnRef(ex.output_name(e, i))
                        for i, e in enumerate(exprs)]
                return out.filter(Col(cond)).select(*[Col(k) for k in keep])
            return out.filter(Col(cond))
        return out

    def parse_order_by(self, items):
        orders = []
        while True:
            t = self.peek()
            if t.kind == "number" and "." not in t.text:
                self.next()
                pos = int(t.text)
                if not (1 <= pos <= len(items)) or items[pos - 1][0] == "*":
                    raise SqlParseError(f"ORDER BY position {pos} invalid")
                e, alias = items[pos - 1]
                e = ex.ColumnRef(alias) if alias \
                    else ex.ColumnRef(ex.output_name(e, pos - 1))
            else:
                e = self.parse_expr()
            asc = True
            if self.take_kw("DESC"):
                asc = False
            else:
                self.take_kw("ASC")
            orders.append(lp.SortOrder(e, asc))
            if not self.take_op(","):
                return orders

    # -- expressions (precedence climbing) ----------------------------------
    def parse_expr(self) -> ex.Expression:
        return self.parse_or()

    def parse_or(self) -> ex.Expression:
        e = self.parse_and()
        while self.take_kw("OR"):
            e = pr.Or(e, self.parse_and())
        return e

    def parse_and(self) -> ex.Expression:
        e = self.parse_not()
        while self.take_kw("AND"):
            e = pr.And(e, self.parse_not())
        return e

    def parse_not(self) -> ex.Expression:
        if self.take_kw("NOT"):
            return pr.Not(self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> ex.Expression:
        e = self.parse_additive()
        neg = False
        if self.at_kw("NOT") and self.peek(1).upper in (
                "BETWEEN", "IN", "LIKE"):
            self.next()
            neg = True
        if self.take_kw("BETWEEN"):
            lo = self.parse_additive()
            self.expect_kw("AND")
            hi = self.parse_additive()
            out = pr.And(pr.GreaterThanOrEqual(e, lo),
                         pr.LessThanOrEqual(e, hi))
            return pr.Not(out) if neg else out
        if self.take_kw("IN"):
            self.expect_op("(")
            if self.at_kw("SELECT"):
                info = self.parse_select(as_subquery=True)
                self.expect_op(")")
                return _InSQ(e, info, neg)
            vals = [self.parse_expr()]
            while self.take_op(","):
                vals.append(self.parse_expr())
            self.expect_op(")")
            lits = []
            for v in vals:
                if not isinstance(v, ex.Literal) or \
                        isinstance(v, ex.Parameter):
                    raise SqlParseError(
                        "IN list must be literals (:name placeholders "
                        "are supported in comparisons, not IN lists)")
                lits.append(v.value)
            out = _unwrap(Col(e).isin(*lits))
            return pr.Not(out) if neg else out
        if self.take_kw("LIKE"):
            p = self.parse_additive()
            if not isinstance(p, ex.Literal) or isinstance(p, ex.Parameter):
                raise SqlParseError(
                    "LIKE pattern must be a string literal (:name "
                    "placeholders are not supported there)")
            out = _unwrap(Col(e).like(p.value))
            return pr.Not(out) if neg else out
        if self.take_kw("IS"):
            isnot = self.take_kw("NOT")
            self.expect_kw("NULL")
            return pr.IsNotNull(e) if isnot else pr.IsNull(e)
        t = self.peek()
        if t.kind == "op" and t.text in ("=", "<>", "!=", "<", "<=", ">",
                                         ">="):
            self.next()
            r = self.parse_additive()
            cls = {"=": pr.EqualTo, "<>": pr.NotEqual, "!=": pr.NotEqual,
                   "<": pr.LessThan, "<=": pr.LessThanOrEqual,
                   ">": pr.GreaterThan, ">=": pr.GreaterThanOrEqual}[t.text]
            return cls(e, r)
        return e

    def parse_additive(self) -> ex.Expression:
        e = self.parse_multiplicative()
        while True:
            if self.take_op("+"):
                r = self.parse_multiplicative()
                if isinstance(e, _Interval):       # INTERVAL + date
                    e, r = r, e
                e = _date_arith(e, r, +1) if isinstance(r, _Interval) \
                    else _unwrap(Col(e) + Col(r))
            elif self.take_op("-"):
                r = self.parse_multiplicative()
                if isinstance(e, _Interval):
                    raise SqlParseError("INTERVAL - <expr> is not valid")
                e = _date_arith(e, r, -1) if isinstance(r, _Interval) \
                    else _unwrap(Col(e) - Col(r))
            elif self.take_op("||"):
                e = _unwrap(F.concat(Col(e),
                                     Col(self.parse_multiplicative())))
            else:
                return e

    def parse_multiplicative(self) -> ex.Expression:
        e = self.parse_unary()
        while True:
            if self.take_op("*"):
                e = _unwrap(Col(e) * Col(self.parse_unary()))
            elif self.take_op("/"):
                e = _unwrap(Col(e) / Col(self.parse_unary()))
            elif self.take_op("%"):
                e = _unwrap(Col(e) % Col(self.parse_unary()))
            else:
                return e

    def parse_unary(self) -> ex.Expression:
        if self.take_op("-"):
            e = self.parse_unary()
            if isinstance(e, ex.Literal) and isinstance(
                    e.value, (int, float)) and not isinstance(e.value, bool):
                return ex.lit(-e.value)       # fold: IN lists need literals
            return _unwrap(-Col(e))
        if self.take_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> ex.Expression:
        t = self.peek()
        if t.kind == "param":
            # :name placeholder (prepared statements, docs/plan_cache.md):
            # dtype resolves from the first execute()'s bound value
            self.next()
            return ex.Parameter(name=t.text[1:])
        if t.kind == "number":
            self.next()
            if "." in t.text or "e" in t.text or "E" in t.text:
                return ex.lit(float(t.text))
            return ex.lit(int(t.text))
        if t.kind == "string":
            self.next()
            return ex.lit(t.text[1:-1].replace("''", "'"))
        if self.take_op("("):
            if self.at_kw("SELECT"):
                info = self.parse_select(as_subquery=True)
                self.expect_op(")")
                return _ScalarSQ(info)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind != "ident":
            raise SqlParseError(f"unexpected token {t.text!r}")
        up = t.upper
        if up == "EXISTS" and self.peek(1).text == "(":
            self.next()
            self.expect_op("(")
            info = self.parse_select(as_subquery=True)
            self.expect_op(")")
            return _ExistsSQ(info)
        if up == "NULL":
            self.next()
            return ex.lit(None)
        if up in ("TRUE", "FALSE"):
            self.next()
            return ex.lit(up == "TRUE")
        if up == "DATE" and self.peek(1).kind == "string":
            self.next()
            s = self.next().text[1:-1]
            return _unwrap(F.lit(s).cast("date"))
        if up == "INTERVAL":
            return self.parse_interval()
        if up == "CASE":
            return self.parse_case()
        if up == "CAST":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            ty = self.next().text.lower()
            self.expect_op(")")
            return _unwrap(Col(e).cast(ty))
        if self.peek(1).text == "(" and up not in _RESERVED_STOP:
            return self.parse_call()
        # [qualifier.]column — single-namespace resolution: the qualifier
        # is dropped, which is only sound when the bare name is unambiguous
        # across the FROM tables (checked after FROM parses). Inside a
        # subquery's WHERE, names/aliases that resolve only in an
        # ENCLOSING query's FROM become outer (correlation) references.
        self.next()
        qualifier = None
        name = t.text
        if self.take_op("."):
            qualifier = name
            name = self.next().text
            self._qualified_refs.append(name)
        return self._resolve_ref(qualifier, name)

    def _resolve_ref(self, qualifier, name) -> ex.ColumnRef:
        scope = self._scopes[-1] if self._scopes else None
        if scope is None or not scope.in_where or len(self._scopes) < 2:
            return ex.ColumnRef(name)
        if qualifier is not None:
            if qualifier in scope.aliases:
                return ex.ColumnRef(name)
            for outer in reversed(self._scopes[:-1]):
                if qualifier in outer.aliases:
                    if name not in outer.aliases[qualifier]:
                        raise SqlParseError(
                            f"column {name!r} not found in table aliased "
                            f"{qualifier!r}")
                    return _OuterRef(name)
            return ex.ColumnRef(name)
        if name in scope.all_cols:
            return ex.ColumnRef(name)
        for outer in reversed(self._scopes[:-1]):
            if name in outer.all_cols:
                return _OuterRef(name)
        return ex.ColumnRef(name)

    def _check_qualified_refs(self):
        """Comma/CROSS joins have no equi-key resolution to save a
        same-named column: a dropped qualifier would silently compare a
        column to itself (full cross product), so refuse instead."""
        if not self._has_cross or not self._qualified_refs or \
                len(self._from_columns) < 2:
            return
        for name in self._qualified_refs:
            if sum(1 for cols in self._from_columns if name in cols) > 1:
                raise SqlParseError(
                    f"qualified reference to column {name!r} is ambiguous: "
                    f"{name!r} exists in multiple FROM tables and this "
                    "dialect resolves by bare name. Use JOIN ... ON / "
                    "USING (...) or rename the columns.")

    def parse_interval(self) -> ex.Expression:
        """INTERVAL '3' MONTH / INTERVAL 1 DAY -> day count literal
        (date arithmetic adds days; month/year go through add_months)."""
        self.next()
        t = self.next()
        if t.kind == "string":
            n = int(t.text[1:-1])
        elif t.kind == "number":
            n = int(t.text)
        else:
            raise SqlParseError(f"bad INTERVAL quantity {t.text!r}")
        unit = self.next().upper.rstrip("S") if self.peek() else ""
        if unit not in ("DAY", "MONTH", "YEAR"):
            raise SqlParseError(f"unsupported INTERVAL unit {unit!r}")
        return _Interval(n, unit)

    def parse_case(self) -> ex.Expression:
        self.next()                           # CASE
        if not self.at_kw("WHEN"):            # CASE expr WHEN v THEN ...
            base = self.parse_expr()
            chain = None
            while self.take_kw("WHEN"):
                v = self.parse_expr()
                self.expect_kw("THEN")
                r = self.parse_expr()
                cond = Col(pr.EqualTo(base, v))
                chain = F.when(cond, Col(r)) if chain is None \
                    else chain.when(cond, Col(r))
        else:
            chain = None
            while self.take_kw("WHEN"):
                c = self.parse_expr()
                self.expect_kw("THEN")
                r = self.parse_expr()
                chain = F.when(Col(c), Col(r)) if chain is None \
                    else chain.when(Col(c), Col(r))
        if chain is None:
            raise SqlParseError("CASE needs at least one WHEN")
        if self.take_kw("ELSE"):
            chain = chain.otherwise(Col(self.parse_expr()))
        self.expect_kw("END")
        return _unwrap(chain)

    def parse_call(self) -> ex.Expression:
        name = self.next().upper
        self.expect_op("(")
        if name == "COUNT":
            if self.take_op("*"):
                self.expect_op(")")
                return _unwrap(F.count("*"))
            if self.take_kw("DISTINCT"):
                e = self.parse_expr()
                self.expect_op(")")
                return _unwrap(F.countDistinct(Col(e)))
        if name == "SUM" and self.take_kw("DISTINCT"):
            e = self.parse_expr()
            self.expect_op(")")
            return _unwrap(F.sumDistinct(Col(e)))
        args: List[ex.Expression] = []
        if not self.take_op(")"):
            args.append(self.parse_expr())
            while self.take_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
        fname = _FN_ALIASES.get(name, name.lower())
        fn = getattr(F, fname, None)
        if fn is None:
            raise SqlParseError(f"unknown function {name}")
        call_args = [a.value if isinstance(a, ex.Literal)
                     and not isinstance(a, ex.Parameter)
                     and fname in ("substring", "lpad", "rpad", "round",
                                   "locate", "instr", "regexp_extract",
                                   "regexp_replace", "replace", "lead",
                                   "lag")
                     and i > 0 else Col(a)
                     for i, a in enumerate(args)]
        try:
            return _unwrap(fn(*call_args))
        except TypeError as e:
            raise SqlParseError(f"bad arguments to {name}: {e}")


class _Interval(ex.Literal):
    """Day/month/year interval literal; only valid next to +/- against a
    date expression, where it folds into date_add/add_months. Escaping
    that fold raises (never a silently wrong plan): any attempt to type
    or evaluate an unfolded interval fails parse/analysis."""

    def __init__(self, n: int, unit: str):
        super().__init__(n if unit == "DAY" else 0)
        self.n = n
        self.unit = unit

    @property
    def dtype(self):
        raise SqlParseError(
            f"INTERVAL '{self.n}' {self.unit} is only supported as the "
            "right operand of date +/- arithmetic")

    def eval(self, batch):
        self.dtype    # raises


def _date_arith(e: ex.Expression, iv: "_Interval", sign: int):
    n = sign * iv.n
    if iv.unit == "DAY":
        return _unwrap(F.date_add(Col(e), n))
    months = n * (12 if iv.unit == "YEAR" else 1)
    return _unwrap(F.add_months(Col(e), months))


def _split_and(e: ex.Expression) -> List[ex.Expression]:
    if isinstance(e, pr.And):
        return _split_and(e.children[0]) + _split_and(e.children[1])
    return [e]


def _has_agg(e) -> bool:
    if isinstance(e, lp.AggregateExpression):
        return True
    return any(_has_agg(c) for c in getattr(e, "children", []))


def _extract_having(cond: ex.Expression, select_exprs):
    """Replace aggregate subtrees in a HAVING condition with refs to
    (possibly hidden) aggregation output columns. Matching against the
    select list uses the faithful structural key (physical's
    _expr_cache_key — reprs omit load-bearing attributes like LIKE
    patterns); unkeyable aggregates always get their own hidden column."""
    from ..plan.physical import _expr_cache_key
    extra: List[ex.Expression] = []
    named = {}
    for i, e in enumerate(select_exprs):
        inner = e.children[0] if isinstance(e, ex.Alias) else e
        k = _expr_cache_key(inner)
        if k is not None:
            named[k] = ex.ColumnRef(ex.output_name(e, i))

    def walk(e):
        if isinstance(e, lp.AggregateExpression):
            key = _expr_cache_key(e)
            if key is not None and key in named:
                return named[key]
            name = f"_having_{len(extra)}"
            extra.append(ex.Alias(e, name))
            ref = ex.ColumnRef(name)
            if key is not None:
                named[key] = ref
            return ref
        kids = getattr(e, "children", [])
        for i, c in enumerate(kids):
            kids[i] = walk(c)
        return e

    import copy
    cond = copy.deepcopy(cond)
    return extra, walk(cond)


class PreparedStatement:
    """``session.prepare(sql) -> stmt.execute(**params)``: parse ONCE,
    plan/contract-validate/stage-compile once (through the
    parameterized-plan cache), execute many (docs/plan_cache.md).

    SQL text may carry ``:name`` placeholders in WHERE conditions and
    SELECT expressions; each ``execute()`` binds them (python
    int/float/bool, ``datetime.date``/``datetime.datetime``, ISO
    ``yyyy-mm-dd`` strings, plain strings). The first execute resolves
    placeholder dtypes, analyzes, plans and caches; later executes with
    the same value dtypes skip parse AND analysis and go straight to the
    cached entry — rebind, cheap binding validation, run. Changing a
    value's dtype replans (new fingerprint) and re-validates.

    A DataFrame works in place of SQL: its literals auto-parameterize,
    so repeated frames of the same shape share one plan."""

    def __init__(self, session, query):
        from ..plan import plan_cache as pc
        self.session = session
        self.sql = query if isinstance(query, str) else None
        if isinstance(query, str):
            pc.serving_stats(session)["parses"] += 1
            self._df = parse_sql(query, session)
        else:
            self._df = query
        self._named = self._collect_named(self._df.logical_plan())
        # after the first planned execute: (fingerprint, value template,
        # {name: slot}, placeholder dtype signature)
        self._fast = None

    @staticmethod
    def _collect_named(plan):
        named: dict = {}

        def walk(p):
            for e in p.expressions():
                for n in e.collect(lambda x: isinstance(x, ex.Parameter)
                                   and x.param_name is not None):
                    named.setdefault(n.param_name, []).append(n)
            for c in p.children:
                walk(c)
        walk(plan)
        return named

    @property
    def parameter_names(self):
        return sorted(self._named)

    @staticmethod
    def _coerce(name, value):
        """python value -> (engine value, dtype) for a placeholder."""
        import calendar
        import datetime
        from ..columnar import dtypes as dtm
        if isinstance(value, bool):
            return value, dtm.BOOL
        if isinstance(value, datetime.datetime):
            micros = calendar.timegm(value.utctimetuple()) * 1_000_000 \
                + value.microsecond
            return micros, dtm.TIMESTAMP
        if isinstance(value, datetime.date):
            return (value - datetime.date(1970, 1, 1)).days, dtm.DATE
        if isinstance(value, int):
            return value, dtm.INT64
        if isinstance(value, float):
            return value, dtm.FLOAT64
        if isinstance(value, str):
            if re.fullmatch(r"\d{4}-\d{2}-\d{2}", value):
                d = datetime.date.fromisoformat(value)
                return (d - datetime.date(1970, 1, 1)).days, dtm.DATE
            return value, dtm.STRING
        raise TypeError(
            f"unsupported parameter type for :{name}: {type(value).__name__}")

    def _bind_named(self, kw) -> None:
        missing = sorted(set(self._named) - set(kw))
        extra = sorted(set(kw) - set(self._named))
        if missing or extra:
            raise ValueError(
                f"prepared-statement parameters mismatch: missing="
                f"{missing} unexpected={extra} (declared: "
                f"{self.parameter_names})")
        for name, value in kw.items():
            if value is None:
                raise ValueError(
                    f"parameter :{name} cannot bind NULL (write a "
                    "literal NULL in the statement instead)")
            v, t = self._coerce(name, value)
            for node in self._named[name]:
                node.bind(v, t, retype=True)

    def _dtype_sig(self) -> tuple:
        return tuple(sorted(
            (name, nodes[0].dtype.name)
            for name, nodes in self._named.items()))

    def execute(self, **params):
        """Bind + run; returns the collected ColumnarBatch (call
        ``.rows()`` / ``.to_pandas()`` on it, or use :meth:`collect`)."""
        self._bind_named(params)
        out = self._serve_fast()
        if out is not None:
            return out
        batch = self._df.collect_batch()
        self._capture_fast()
        return batch

    def collect(self, **params):
        return self.execute(**params).rows()

    # -- the plan-once / execute-many fast path -----------------------------
    def _capture_fast(self) -> None:
        from ..plan import plan_cache as pc
        # THIS thread's serving info, never the session attr: concurrent
        # service workers clobber session._last_serving, and capturing
        # another query's fingerprint here would bind this statement's
        # parameters into a foreign plan (docs/service.md §5)
        serving = pc.thread_serving()
        if not serving or not serving.get("cacheable"):
            return
        cache, _rc = pc.session_caches(self.session)
        entry = cache.peek(serving["fingerprint"])
        if entry is None:
            return
        if any(not p.traceable() for p in entry.params):
            # a value-baked (string) parameter's value is part of the
            # plan fingerprint AND of every compiled program in the
            # entry's frozen exec tree (whole-stage _fns memoize it) —
            # the fast path's in-place rebind would serve the stale
            # baked program. The full path gives each distinct value
            # its own cache entry, which still plan-cache-hits on
            # repeats of the same value.
            return
        named_slots: dict = {}
        for p in entry.params:
            if p.param_name is not None:
                # one :name may occupy several slots (used twice)
                named_slots.setdefault(p.param_name, []).append(p.slot)
        self._fast = (serving["fingerprint"], list(serving["values"]),
                      named_slots, self._dtype_sig())

    def _serve_fast(self):
        """Skip parse AND analysis: rebind the cached entry and execute
        it through the normal collect machinery. None -> full path."""
        if self._fast is None:
            return None
        fingerprint, template, named_slots, dsig = self._fast
        if self._dtype_sig() != dsig:
            self._fast = None          # dtype change: replan + revalidate
            return None
        from ..exec.spill import BufferCatalog
        from ..plan import plan_cache as pc
        cache, _rc = pc.session_caches(self.session)
        entry = cache.get(fingerprint)
        if entry is None:
            self._fast = None
            return None
        # claim the tree before binding (the service's concurrent
        # executes share one statement's cache entry): busy -> the full
        # path plans a fresh tree for this execution
        if not entry.try_begin_execution():
            pc.serving_stats(self.session)["planBusy"] += 1
            return None
        values = list(template)
        for name, slots in named_slots.items():
            for slot in slots:
                values[slot] = self._named[name][0].value
        try:
            revalidated, violations = entry.bind(values)
        except Exception:
            # tainted entry: drop it so a clean retry replans
            entry.end_execution()
            cache.discard(fingerprint)
            self._fast = None
            raise
        if revalidated and violations:
            entry.end_execution()
            cache.discard(fingerprint)
            self._fast = None
            return None
        serving = {
            "planCache": "hit", "resultCache": "off",
            "params": len(values), "fingerprint": fingerprint,
            "values": tuple(values), "snapshot": None,
            "cacheable": True, "revalidated": revalidated,
            "prepared": True, "planEntry": entry,
        }
        # from here the claim is released through the serving dict —
        # every exit (incl. reset_metrics/baseline raising) runs the
        # release, or the entry would read busy forever
        try:
            entry.reset_metrics()
            sess = self.session
            st = pc.serving_stats(sess)
            st["planHits"] += 1
            pc._inc("tpu_plan_cache_hits_total",
                    "parameterized-plan cache hits (analyze/optimize/"
                    "validate/stage-compile skipped)")
            sess._last_plan_time_s = 0.0
            sess._last_exec_plan = entry.exec_plan
            sess._last_overrides = pc._CachedOverrides(entry.overrides,
                                                       violations)
            sess._last_serving = serving
            cat = BufferCatalog.get()
            sess._mem_baseline = (cat.spilled_device_bytes,
                                  cat.spilled_host_bytes)
            serving["resultKey"] = pc.result_key(sess, serving,
                                                 entry.logical_plan)
            hit = pc.serve_result_hit(sess, serving)
            if hit is not None:
                return hit
            return self._df._collect_planned(entry.exec_plan, serving)
        finally:
            pc.release_plan_entry(serving)


#: serializes parses that mutate the session catalog: CTE registration
#: writes query-scoped temp views into the SHARED ``session._views`` and
#: restores it afterwards — two concurrent service workers interleaving
#: that save/mutate/restore would leak one parse's CTEs into the session
#: (or delete the other's mid-parse), so the whole parse runs under one
#: leaf lock (parsing takes no engine locks; docs/service.md §5)
_parse_views_mu = named_lock("api.sql._parse_views_mu")


def parse_sql(query: str, session):
    p = _Parser(_lex(query), session)
    with _parse_views_mu:
        saved_views = dict(session._views)
        try:
            first = True
            while p.at_kw("WITH") or (not first and p.take_op(",")):
                # WITH name AS (SELECT ...) [, name2 AS (SELECT ...)]...
                # registered as query-scoped temp views (Catalyst CTEs);
                # the session catalog is restored after the parse
                if p.at_kw("WITH"):
                    p.next()
                name = p.next().text
                p.expect_kw("AS")
                p.expect_op("(")
                sub = p.parse_select()
                p.expect_op(")")
                sub.createOrReplaceTempView(name)
                first = False
            df = p.parse_select()
            if p.peek().kind != "end":
                raise SqlParseError(
                    f"trailing input near {p.peek().text!r}")
            return df
        finally:
            session._views = saved_views
