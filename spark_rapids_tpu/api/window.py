"""pyspark-parity window spec builder: ``Window.partitionBy(...).orderBy(
...).rowsBetween(...)`` consumed by ``Col.over`` (the user-facing surface
of GpuWindowExec; the reference accepts Spark's WindowSpec through
Catalyst, SURVEY.md §2.3 window expressions).

Frame semantics: no ``orderBy`` -> whole-partition aggregate; with
``orderBy`` and no explicit frame -> rows UNBOUNDED PRECEDING..CURRENT ROW
(Spark defaults to the RANGE form, which differs only on order-key ties —
use ``rangeBetween`` explicitly when tie-peer inclusion matters).

Because any order key can carry ties, applying this implicit ROWS default
emits a :class:`DefaultRowsFrameWarning` (once per process): running
aggregates over tied keys differ from Spark's peer-inclusive RANGE default
— tied rows each see only the rows physically before them. Silence it by
stating the frame explicitly (``rowsBetween``/``rangeBetween``) or with
the standard ``warnings`` machinery."""

from __future__ import annotations

import sys
import warnings
from typing import List, Optional

from ..ops import window as W
from ..plan import logical as lp
from .column import Col, _unwrap


class DefaultRowsFrameWarning(UserWarning):
    """An ordered window spec fell back to the implicit ROWS
    UNBOUNDED PRECEDING..CURRENT ROW frame; Spark's default is the RANGE
    (peer-inclusive) form, which differs on tied order keys."""


class WindowSpec:
    """Immutable builder; each method returns a new spec."""

    def __init__(self, partition=None, order=None,
                 frame: Optional[W.WindowFrame] = None):
        self._partition = list(partition or [])
        self._order = list(order or [])
        self._frame = frame

    def partitionBy(self, *cols) -> "WindowSpec":
        return WindowSpec(self._partition + [_to_expr(c) for c in cols],
                          self._order, self._frame)

    def orderBy(self, *cols) -> "WindowSpec":
        return WindowSpec(self._partition,
                          self._order + [_to_order(c) for c in cols],
                          self._frame)

    def rowsBetween(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self._partition, self._order,
                          W.WindowFrame(_bound(start), _bound(end),
                                        is_range=False))

    def rangeBetween(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self._partition, self._order,
                          W.WindowFrame(_bound(start), _bound(end),
                                        is_range=True))

    def _to_spec(self) -> W.WindowSpec:
        frame = self._frame
        if frame is None and self._order:
            # Spark's default frame when ordered (rows form; see module
            # doc). Order keys may carry ties, where the ROWS form
            # diverges from Spark's peer-inclusive RANGE default — warn
            # through the standard machinery (its once-per-location
            # default dedups, while 'always'/'error' filters still let
            # users audit every implicit-frame call site)
            warnings.warn(
                "ordered window spec without an explicit frame uses "
                "ROWS UNBOUNDED PRECEDING..CURRENT ROW; Spark's "
                "default is the RANGE (peer-inclusive) form, which "
                "differs on tied order keys — state the frame with "
                "rowsBetween()/rangeBetween() to silence this",
                DefaultRowsFrameWarning, stacklevel=3)
            frame = W.WindowFrame(None, 0, is_range=False)
        return W.WindowSpec(list(self._partition), list(self._order), frame)


class Window:
    """Entry points mirroring pyspark.sql.window.Window."""

    unboundedPreceding = -sys.maxsize
    unboundedFollowing = sys.maxsize
    currentRow = 0

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)


def _bound(v: int) -> Optional[int]:
    if v <= Window.unboundedPreceding or v >= Window.unboundedFollowing:
        return None
    return int(v)


def _to_expr(c):
    from ..ops import expressions as ex
    if isinstance(c, str):
        return ex.ColumnRef(c)
    return _unwrap(c)


def _to_order(c) -> lp.SortOrder:
    if isinstance(c, lp.SortOrder):
        return c
    return lp.SortOrder(_to_expr(c), ascending=True)
