"""ColumnarBatch: a set of equal-capacity device columns + host-known row count.

Analog of Spark's ``ColumnarBatch`` carrying ``GpuColumnVector``s
(``GpuColumnVector.java:40-535``; batch<->Table converters). The TPU twist
(DESIGN.md §1): all columns share a bucketed capacity, rows beyond ``num_rows``
are zeroed+invalid padding, and kernels carry counts as device scalars until a
host boundary reads them.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from . import dtypes as dt
from .column import Column, Scalar, bucket


class ColumnarBatch:
    __slots__ = ("schema", "columns", "num_rows")

    def __init__(self, schema: dt.Schema, columns: List[Column], num_rows: int):
        assert len(schema) == len(columns), "schema/column arity mismatch"
        caps = {c.capacity for c in columns}
        assert len(caps) <= 1, f"mixed capacities in batch: {caps}"
        self.schema = schema
        self.columns = columns
        try:
            self.num_rows = int(num_rows)
        except Exception:
            # traced device scalar: batches built inside fused (jitted)
            # stages carry their row count as a tracer until the stage's
            # host boundary syncs it
            self.num_rows = num_rows

    # -- shape ---------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else bucket(self.num_rows)

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def device_size_bytes(self) -> int:
        return sum(c.device_size_bytes() for c in self.columns)

    def row_mask(self) -> jnp.ndarray:
        """Bool[capacity] mask of live rows (True for rows < num_rows)."""
        return jnp.arange(self.capacity) < self.num_rows

    def column(self, name_or_idx) -> Column:
        if isinstance(name_or_idx, int):
            return self.columns[name_or_idx]
        return self.columns[self.schema.index_of(name_or_idx)]

    def with_columns(self, schema: dt.Schema, columns: List[Column],
                     num_rows: Optional[int] = None) -> "ColumnarBatch":
        return ColumnarBatch(schema, columns, self.num_rows if num_rows is None else num_rows)

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_pydict(data: Dict[str, Sequence[Any]],
                    schema: Optional[dt.Schema] = None,
                    capacity: Optional[int] = None) -> "ColumnarBatch":
        # build in schema order when one is given so fields and columns line up
        names = schema.names() if schema is not None else list(data.keys())
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity or bucket(n)
        cols: List[Column] = []
        fields: List[dt.Field] = []
        for name in names:
            values = data[name]
            if len(values) != n:
                raise ValueError(
                    f"column {name!r} has {len(values)} rows, expected {n}")
            if schema is not None:
                dtype = schema[name].dtype
            else:
                dtype = _infer_dtype(values)
            if isinstance(values, np.ndarray) and dtype != dt.STRING:
                col = Column.from_numpy(values, dtype, capacity=cap)
            else:
                col = Column.from_pylist(list(values), dtype, capacity=cap)
            cols.append(col)
            fields.append(dt.Field(name, dtype))
        return ColumnarBatch(schema or dt.Schema(fields), cols, n)

    @staticmethod
    def from_arrow(table, capacity: Optional[int] = None) -> "ColumnarBatch":
        """pyarrow Table/RecordBatch -> device batch (the HostColumnarToGpu analog,
        ref HostColumnarToGpu.scala:30-235)."""
        n = table.num_rows
        cap = capacity or bucket(n)
        cols = [Column.from_arrow(table.column(i), capacity=cap)
                for i in range(table.num_columns)]
        fields = [dt.Field(table.schema.names[i], dt.from_arrow(table.schema.types[i]))
                  for i in range(table.num_columns)]
        return ColumnarBatch(dt.Schema(fields), cols, n)

    @staticmethod
    def empty(schema: dt.Schema, capacity: int = 128) -> "ColumnarBatch":
        cols = [Column.full_null(f.dtype, capacity) for f in schema]
        return ColumnarBatch(schema, cols, 0)

    # -- flat array form (fused stages / spill / wire share this layout) -----
    def flat_arrays(self) -> List[jnp.ndarray]:
        """All underlying arrays in schema order: [data, validity(, lengths)]
        per column — the jit-boundary form of a batch."""
        out: List[jnp.ndarray] = []
        for c in self.columns:
            out.extend(c.arrays())
        return out

    @staticmethod
    def from_flat_arrays(schema: dt.Schema, arrays: Sequence[jnp.ndarray],
                         num_rows) -> "ColumnarBatch":
        """Inverse of flat_arrays; num_rows may be a traced scalar inside
        fused stages."""
        cols: List[Column] = []
        i = 0
        for f in schema:
            if f.dtype.var_width:
                cols.append(Column(f.dtype, arrays[i], arrays[i + 1],
                                   arrays[i + 2]))
                i += 3
            else:
                cols.append(Column(f.dtype, arrays[i], arrays[i + 1]))
                i += 2
        return ColumnarBatch(schema, cols, num_rows)

    # -- host extraction -----------------------------------------------------
    def to_pydict(self) -> Dict[str, List[Any]]:
        return {f.name: c.to_pylist(self.num_rows)
                for f, c in zip(self.schema, self.columns)}

    def to_arrow(self):
        import pyarrow as pa
        arrays = [c.to_arrow(self.num_rows) for c in self.columns]
        return pa.table(arrays, names=self.schema.names())

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def rows(self) -> List[tuple]:
        """Materialize host rows (GpuColumnarToRowExec analog for small results)."""
        cols = [c.to_pylist(self.num_rows) for c in self.columns]
        return list(zip(*cols)) if cols else [()] * self.num_rows

    def __repr__(self):
        return (f"ColumnarBatch(rows={self.num_rows}, cap={self.capacity}, "
                f"schema={self.schema})")


def _infer_dtype(values: Sequence[Any]) -> dt.DType:
    if isinstance(values, np.ndarray):
        return dt.of(values.dtype)
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return dt.BOOL
        if isinstance(v, int):
            return dt.INT64
        if isinstance(v, float):
            return dt.FLOAT64
        if isinstance(v, (str, bytes)):
            return dt.STRING
    return dt.STRING
