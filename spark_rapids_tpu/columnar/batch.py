"""ColumnarBatch: a set of equal-capacity device columns + host-known row count.

Analog of Spark's ``ColumnarBatch`` carrying ``GpuColumnVector``s
(``GpuColumnVector.java:40-535``; batch<->Table converters). The TPU twist
(DESIGN.md §1): all columns share a bucketed capacity, rows beyond ``num_rows``
are zeroed+invalid padding, and kernels carry counts as device scalars until a
host boundary reads them.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from . import dtypes as dt
from .column import Column, Scalar, bucket


class ColumnarBatch:
    # ``origin``: the open catalog registration (SpillableColumnarBatch)
    # that already OWNS this batch's device arrays — set by the scan device
    # cache so downstream spillable-drain layers borrow that registration
    # instead of double-counting the same HBM under a second buffer id.
    # ``shared``: the arrays are owned by a live catalog entry that may
    # re-read them (set by BufferCatalog.acquire_batch) — such a batch
    # must NEVER have its buffers donated to a fused program
    # (exec/compile_cache donation gate; docs/compile.md)
    # ``params``: traced query-parameter scalars riding INSIDE a fused
    # program only (plan cache parameterization, docs/plan_cache.md):
    # ``from_flat_arrays`` attaches any arguments beyond the schema's
    # arity here, and ``ops.expressions.Parameter`` reads them by its
    # stamped trace position. Host-side batches always carry ().
    # ``donated``: non-None once a fused program consumed this batch's
    # arrays at donated positions (analysis/ledger.mark_donated stamps
    # the donation site) — the arrays are DEAD and any further read
    # through the funnels below diagnoses as use-after-donate instead of
    # surfacing jax's bare "Array has been deleted"
    __slots__ = ("schema", "columns", "_num_rows", "origin", "shared",
                 "params", "donated")

    def __init__(self, schema: dt.Schema, columns: List[Column], num_rows: int):
        assert len(schema) == len(columns), "schema/column arity mismatch"
        caps = {c.capacity for c in columns}
        assert len(caps) <= 1, f"mixed capacities in batch: {caps}"
        self.schema = schema
        self.columns = columns
        self.origin = None
        self.shared = False
        self.params = ()
        self.donated = None
        if isinstance(num_rows, (int, np.integer)):
            self._num_rows = int(num_rows)
        else:
            # Traced tracer (batches built inside fused/jitted stages) or a
            # CONCRETE device scalar: the count stays device-resident until a
            # host consumer reads `num_rows` — so a streaming pipeline can
            # dispatch batch after batch without a blocking readback per
            # batch (the dominant engine cost on high-latency links).
            self._num_rows = num_rows

    # -- shape ---------------------------------------------------------------
    @property
    def num_rows(self):
        """Host row count. Lazily syncs a device-resident count on first
        access (cross host boundaries with ``resolve_counts`` to batch the
        readbacks); returns the tracer unchanged inside traced code."""
        nr = self._num_rows
        if isinstance(nr, int):
            return nr
        import jax
        if isinstance(nr, jax.core.Tracer):
            return nr
        nr = int(nr)                       # device->host sync
        self._num_rows = nr
        return nr

    @property
    def num_rows_raw(self):
        """The count in whatever form it currently has (int / device scalar /
        tracer) — no sync."""
        return self._num_rows

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else bucket(self.num_rows)

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def device_size_bytes(self) -> int:
        return sum(c.device_size_bytes() for c in self.columns)

    def row_mask(self) -> jnp.ndarray:
        """Bool[capacity] mask of live rows (True for rows < num_rows)."""
        return jnp.arange(self.capacity) < self.num_rows

    def row_mask_raw(self) -> jnp.ndarray:
        """row_mask built from the count in whatever form it has — never
        forces a device-resident count to host (sync-free hot paths)."""
        return jnp.arange(self.capacity) < self._num_rows

    def column(self, name_or_idx) -> Column:
        if isinstance(name_or_idx, int):
            return self.columns[name_or_idx]
        return self.columns[self.schema.index_of(name_or_idx)]

    def with_columns(self, schema: dt.Schema, columns: List[Column],
                     num_rows: Optional[int] = None) -> "ColumnarBatch":
        return ColumnarBatch(
            schema, columns,
            self._num_rows if num_rows is None else num_rows)

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_pydict(data: Dict[str, Sequence[Any]],
                    schema: Optional[dt.Schema] = None,
                    capacity: Optional[int] = None) -> "ColumnarBatch":
        # build in schema order when one is given so fields and columns line up
        names = schema.names() if schema is not None else list(data.keys())
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity or bucket(n)
        cols: List[Column] = []
        fields: List[dt.Field] = []
        for name in names:
            values = data[name]
            if len(values) != n:
                raise ValueError(
                    f"column {name!r} has {len(values)} rows, expected {n}")
            if schema is not None:
                dtype = schema[name].dtype
            else:
                dtype = _infer_dtype(values)
            if isinstance(values, np.ndarray) and dtype != dt.STRING:
                col = Column.from_numpy(values, dtype, capacity=cap)
            else:
                col = Column.from_pylist(list(values), dtype, capacity=cap)
            cols.append(col)
            fields.append(dt.Field(name, dtype))
        return ColumnarBatch(schema or dt.Schema(fields), cols, n)

    @staticmethod
    def from_arrow(table, capacity: Optional[int] = None) -> "ColumnarBatch":
        """pyarrow Table/RecordBatch -> device batch (the HostColumnarToGpu analog,
        ref HostColumnarToGpu.scala:30-235).

        All columns ride ONE staging-buffer upload + one cached unpack
        program (per-array transfer overhead would otherwise dominate scan
        streams on high-latency links — the bounce-buffer idea from the
        reference's shuffle, applied at the scan boundary)."""
        return ColumnarBatch.upload_prepped(
            ColumnarBatch.prep_from_arrow(table, capacity))

    @staticmethod
    def prep_from_arrow(table, capacity: Optional[int] = None):
        """Host-only half of ``from_arrow``: arrow -> padded numpy arrays,
        NO device work — safe to run on a prefetch thread before the task
        holds the semaphore or has reserved memory. Feed the result to
        ``upload_prepped`` (on the task thread, after admission)."""
        n = table.num_rows
        cap = capacity or bucket(n)
        fields = [dt.Field(table.schema.names[i],
                           dt.from_arrow(table.schema.types[i]))
                  for i in range(table.num_columns)]
        schema = dt.Schema(fields)
        # ARRAY<...> columns need the python-list path (device-building):
        # decide from the schema BEFORE converting anything twice
        if n == 0 or any(dt.is_array(f.dtype) or dt.is_map(f.dtype) or
                         dt.is_struct(f.dtype)
                         for f in fields):
            return ("fallback", schema, table, cap, n)
        hosts = [Column.host_from_arrow(table.column(i), capacity=cap)
                 for i in range(table.num_columns)]
        nbytes = sum(a.nbytes for _d, arrs in hosts for a in arrs)
        return ("packed", schema, hosts, cap, n, nbytes)

    @staticmethod
    def stage_prepped(prep, acquire=None):
        """Optional host half 2 of ``from_arrow``: PACK a 'packed' prep
        into its one contiguous staging buffer on the CALLING thread — a
        scan prefetch thread pays the memcpy so the task thread only
        uploads. ``acquire(nbytes)`` may return a writable window from a
        pinned bounce-buffer arena (exec/native_alloc); the returned prep
        then carries the window and ``upload_prepped`` force-copies to
        device so the caller can release the window right after upload.
        Non-'packed' preps pass through unchanged."""
        if prep[0] != "packed":
            return prep
        _tag, schema, hosts, cap, n, nbytes = prep
        spec, total, buf, window = _pack_staging(hosts, acquire)
        layout = [(dtype, len(arrs)) for dtype, arrs in hosts]
        return ("staged", schema, layout, spec, total, buf, window, cap, n,
                nbytes)

    @staticmethod
    def upload_prepped(prep) -> "ColumnarBatch":
        """Device half of ``from_arrow``: one packed staging upload + one
        cached unpack program (or the per-column fallback path)."""
        if prep[0] == "fallback":
            _tag, schema, table, cap, n = prep
            cols = [Column.from_arrow(table.column(i), capacity=cap)
                    for i in range(table.num_columns)]
            return ColumnarBatch(schema, cols, n)
        if prep[0] == "staged":
            (_tag, schema, layout, spec, total, buf, window, _cap, n,
             _nbytes) = prep
            # arena-windowed buffers force a device-owned copy: the window
            # is released (and reused) as soon as this returns
            cols = _unpack_staged(layout, spec, total, buf,
                                  force_copy=window is not None)
            return ColumnarBatch(schema, cols, n)
        _tag, schema, hosts, _cap, n, _nbytes = prep
        return ColumnarBatch(schema, _upload_packed(hosts), n)

    @staticmethod
    def prepped_size_bytes(prep) -> int:
        """Approximate device bytes ``upload_prepped`` will allocate (for
        admission before the upload)."""
        if prep[0] == "packed":
            return prep[5]
        if prep[0] == "staged":
            return prep[9]
        table = prep[2]
        return int(getattr(table, "nbytes", 0)) * 2

    @staticmethod
    def staged_window(prep):
        """The arena window a 'staged' prep holds (None otherwise) — the
        scan releases it after ``upload_prepped``."""
        return prep[6] if prep[0] == "staged" else None

    @staticmethod
    def empty(schema: dt.Schema, capacity: int = 128) -> "ColumnarBatch":
        cols = [Column.full_null(f.dtype, capacity) for f in schema]
        return ColumnarBatch(schema, cols, 0)

    # -- flat array form (fused stages / spill / wire share this layout) -----
    def flat_arrays(self) -> List[jnp.ndarray]:
        """All underlying arrays in schema order: [data, validity(, lengths)]
        per column — the jit-boundary form of a batch."""
        if self.donated is not None:
            from ..analysis import ledger
            ledger.check_batch_access(self)
        out: List[jnp.ndarray] = []
        for c in self.columns:
            out.extend(c.arrays())
        return out

    @staticmethod
    def from_flat_arrays(schema: dt.Schema, arrays: Sequence[jnp.ndarray],
                         num_rows) -> "ColumnarBatch":
        """Inverse of flat_arrays; num_rows may be a traced scalar inside
        fused stages. Per-column arity is a pure function of the dtype
        (column_arity), so arrays/structs reconstruct consistently at
        every site (fused stages, spill, shuffle wire)."""
        from .column import build_column
        cols: List[Column] = []
        i = 0
        for f in schema:
            c, i = build_column(f.dtype, arrays, i)
            cols.append(c)
        out = ColumnarBatch(schema, cols, num_rows)
        if i < len(arrays):
            # arguments beyond the schema's arity are appended query
            # parameters (traced 0-d scalars inside a fused program)
            out.params = tuple(arrays[i:])
        return out

    # -- host extraction -----------------------------------------------------
    def fetch_to_host(self) -> "ColumnarBatch":
        """Materialize every column on host in ONE batched transfer
        (GpuColumnarToRowExec's single device->host copy, vs a blocking
        round-trip per array — which dominates on high-latency links).
        Returns a batch whose columns are numpy-backed, sliced to
        ``num_rows``."""
        import jax
        if self.donated is not None:
            from ..analysis import ledger
            ledger.check_batch_access(self)
        if not self.columns:
            self.num_rows                     # resolve the count
            return self
        if all(isinstance(c.data, np.ndarray) for c in self.columns):
            self.num_rows
            return self
        if not isinstance(self.num_rows_raw, int) and \
                self.capacity <= (1 << 14):
            # device-resident count + small batch: ONE transfer carries the
            # count along with the data (a separate count sync would cost a
            # full extra RTT on tunnel links)
            flat = self.flat_arrays() + [self.num_rows_raw]
            host = jax.device_get(flat)
            n = int(host[-1])
            self._num_rows = n
            return ColumnarBatch.from_flat_arrays(self.schema, host[:-1], n)
        n = self.num_rows                     # the one count sync
        # slice to a BUCKETED length before the transfer: padding beyond
        # bucket(n) stays on device, while the power-of-two slice shapes
        # keep the compile cache bounded (vs one slice program per n)
        from .column import ObjectColumn
        cap = self.capacity
        m = cap if cap <= (1 << 14) else min(bucket(max(n, 1)), cap)
        sliced: List[Any] = []
        obj_cols = {}
        for ci, c in enumerate(self.columns):
            if isinstance(c, ObjectColumn):   # host python payload already
                obj_cols[ci] = c
                continue
            for a in c.arrays():              # rows are always axis 0
                sliced.append(a if m == cap else a[:m])
        host = jax.device_get(sliced)         # one round trip for the batch
        if not obj_cols:
            return ColumnarBatch.from_flat_arrays(self.schema, host, n)
        from .column import build_column
        cols: List[Column] = []
        i = 0
        for ci, f in enumerate(self.schema):
            if ci in obj_cols:
                cols.append(obj_cols[ci])
                continue
            c, i = build_column(f.dtype, host, i)
            cols.append(c)
        return ColumnarBatch(self.schema, cols, n)

    def to_pydict(self) -> Dict[str, List[Any]]:
        host = self.fetch_to_host()
        return {f.name: c.to_pylist(host.num_rows)
                for f, c in zip(host.schema, host.columns)}

    def to_arrow(self):
        import pyarrow as pa
        host = self.fetch_to_host()
        arrays = [c.to_arrow(host.num_rows) for c in host.columns]
        return pa.table(arrays, names=host.schema.names())

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def rows(self) -> List[tuple]:
        """Materialize host rows (GpuColumnarToRowExec analog for small results)."""
        host = self.fetch_to_host()
        cols = [c.to_pylist(host.num_rows) for c in host.columns]
        return list(zip(*cols)) if cols else [()] * host.num_rows

    def __repr__(self):
        return (f"ColumnarBatch(rows={self.num_rows}, cap={self.capacity}, "
                f"schema={self.schema})")


_UNPACK_CACHE: Dict[tuple, Any] = {}

# registered with the JIT map-pressure relief valve: each cached unpack
# program pins a loaded executable (exec/compile_cache.jit_map_guard)
from ..exec.compile_cache import register_program_cache as _rpc  # noqa: E402
_rpc(_UNPACK_CACHE.clear)
del _rpc


def _pack_staging(hosts, acquire=None):
    """Pack every column's padded host arrays into one aligned uint8
    staging buffer. ``acquire(nbytes)`` may hand back a writable window
    from the pinned bounce-buffer arena (exec/native_alloc) — the staging
    tier of the streaming scan; None (or an exhausted arena) falls back
    to a transient numpy buffer. Returns (spec, total, buf, window)."""
    arrays: List[np.ndarray] = []
    spec: List[tuple] = []        # (np dtype str, shape, offset, nbytes)
    pos = 0
    for _dtype, arrs in hosts:
        for a in arrs:
            a = np.ascontiguousarray(a)
            nbytes = a.nbytes
            spec.append((a.dtype.str, a.shape, pos, nbytes))
            arrays.append(a)
            pos += (nbytes + 7) & ~7          # 8-byte aligned segments
    window = acquire(pos) if acquire is not None else None
    if window is not None:
        buf = np.frombuffer(window, dtype=np.uint8, count=pos)
        buf[:] = 0
    else:
        buf = np.zeros(pos, dtype=np.uint8)
    for a, (_d, _s, off, nbytes) in zip(arrays, spec):
        buf[off:off + nbytes] = a.view(np.uint8).ravel()
    return tuple(spec), pos, buf, window


def _unpack_program(spec, pos):
    """The cached jitted unpack (slice + bitcast) for one staging layout."""
    import jax
    import jax.lax as lax
    from ..exec import compile_cache as _cc
    # donate the staging buffer: the unpack is its only consumer, and at
    # one full batch of bytes it is exactly the transient the HBM
    # watermark blames on scans (baked into the program -> keyed)
    donate = (0,) if _cc.donate_enabled() else ()
    key = (tuple(spec), pos, bool(donate))
    fn = _UNPACK_CACHE.get(key)
    if fn is None:
        if len(_UNPACK_CACHE) > 256:
            _UNPACK_CACHE.clear()

        def unpack(b):
            outs = []
            for dstr, shape, off, nbytes in spec:
                seg = lax.slice(b, (off,), (off + nbytes,))
                npdt = np.dtype(dstr)
                if npdt == np.uint8:
                    outs.append(seg.reshape(shape))
                elif npdt == np.bool_:
                    outs.append((seg != 0).reshape(shape))
                else:
                    flat = lax.bitcast_convert_type(
                        seg.reshape(-1, npdt.itemsize), jnp.dtype(npdt))
                    outs.append(flat.reshape(shape))
            return tuple(outs)
        # audited + persisted like every _fused_fn program (the naked-jit
        # rule: no compile escapes the recompile/compile-cache funnel)
        _kind, wrap = _cc.note_build(("scan_unpack",) + key, "scan_unpack")
        fn = _UNPACK_CACHE[key] = wrap(
            jax.jit(unpack, donate_argnums=donate))  # lint: naked-jit-ok scan unpack cache: audited via compile_cache.note_build above
    else:
        from ..analysis import recompile as _recompile
        _recompile.note_call("scan_unpack")
    return fn


def _unpack_staged(layout, spec, pos, buf, force_copy: bool) -> List[Column]:
    """Upload one pre-packed staging buffer and carve the device columns
    out (the device half shared by _upload_packed and 'staged' preps).
    ``force_copy`` guarantees a device-OWNED buffer when ``buf`` views a
    reusable arena window (jnp.asarray may alias host memory on the CPU
    backend — an aliased window would be clobbered on reuse)."""
    fn = _unpack_program(spec, pos)
    src = jnp.array(buf) if force_copy else jnp.asarray(buf)
    dev = fn(src)                            # ONE upload + ONE dispatch
    cols: List[Column] = []
    i = 0
    for dtype, arity in layout:
        cols.append(Column(dtype, *dev[i:i + arity]))
        i += arity
    return cols


def _upload_packed(hosts) -> List[Column]:
    """Pack every column's padded host arrays into one aligned uint8
    staging buffer, upload it in a single transfer, and carve the device
    arrays back out with one cached jitted unpack (slice + bitcast)."""
    spec, pos, buf, _window = _pack_staging(hosts)
    layout = [(dtype, len(arrs)) for dtype, arrs in hosts]
    return _unpack_staged(layout, spec, pos, buf, force_copy=False)


def resolve_counts(batches: Sequence["ColumnarBatch"]) -> None:
    """Materialize every device-resident row count in ONE batched
    device_get (a single host round-trip) instead of one blocking readback
    per batch — the cheap way to cross a host boundary after a lazily
    counted stream."""
    lazy = [(b, b.num_rows_raw) for b in batches
            if not isinstance(b.num_rows_raw, int)]
    if not lazy:
        return
    import jax
    vals = jax.device_get([r for _, r in lazy])
    for (b, _), v in zip(lazy, vals):
        b._num_rows = int(v)


def _infer_dtype(values: Sequence[Any]) -> dt.DType:
    if isinstance(values, np.ndarray):
        return dt.of(values.dtype)
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return dt.BOOL
        if isinstance(v, int):
            return dt.INT64
        if isinstance(v, float):
            return dt.FLOAT64
        if isinstance(v, (str, bytes)):
            return dt.STRING
        if isinstance(v, dict):
            # widen across EVERY dict in the column (a single-sample
            # inference mistyped e.g. int-then-float value columns and the
            # encoding silently truncated); empty-map-only columns default
            # to map<bigint,bigint>
            ks: list = []
            vs: list = []
            for d in values:
                if isinstance(d, dict):
                    ks.extend(d.keys())
                    vs.extend(x for x in d.values() if x is not None)
            if not ks:
                return dt.MAP(dt.INT64, dt.INT64)
            return dt.MAP(_widen_across(ks), _widen_across(vs or [0]))
        if isinstance(v, (list, tuple)) and v:
            elems = [x for lst in values
                     if isinstance(lst, (list, tuple))
                     for x in lst if x is not None]
            if any(isinstance(x, str) for x in elems):
                return dt.ARRAY_STRING
            return dt.ARRAY(_widen_across(elems or [0]))
    return dt.STRING


def _widen_across(values: Sequence[Any]) -> dt.DType:
    """Widest primitive dtype across observed python values: any float
    promotes int to float64, any string wins outright (mixed map columns
    must not truncate later-row values)."""
    out: dt.DType = None
    for v in values:
        t = (dt.BOOL if isinstance(v, bool) else
             dt.INT64 if isinstance(v, int) else
             dt.FLOAT64 if isinstance(v, float) else dt.STRING)
        if out is None or out == t:
            out = t
        elif {out, t} == {dt.INT64, dt.FLOAT64}:
            out = dt.FLOAT64
        else:
            out = dt.STRING if dt.STRING in (out, t) else dt.FLOAT64
    return out or dt.INT64
