"""Device columnar containers: the ``GpuColumnVector`` / ``ColumnarBatch`` analog.

Reference: ``GpuColumnVector.java:40-535`` (Spark ColumnVector over a cuDF column) and
``SURVEY.md`` §2.7. TPU-first differences (DESIGN.md §1, §4):

* every column lives in a *bucketed capacity* (next power of two, min 128) so XLA's
  compile cache stays bounded; the batch tracks the logical ``num_rows``
* NULLs are a dense bool validity vector (True = valid), not a bitmask
* strings are fixed-width padded byte matrices ``uint8[cap, byte_cap]`` plus an
  ``int32[cap]`` length vector — vectorizable on the VPU — instead of Arrow offsets
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt

MIN_CAPACITY = 128
MIN_STRING_WIDTH = 8


def _bits_from_values(vals, dtype: dt.DType) -> np.ndarray:
    """Logical values -> int64 bitpatterns for the MAP layout: integral /
    bool / date / timestamp store the int64 VALUE; floats store the
    float64 bitpattern (f32 widens exactly)."""
    if dtype.is_floating:
        return np.asarray(vals, np.float64).view(np.int64)
    return np.asarray([int(v) for v in vals], np.int64)


def _values_from_bits(bits: np.ndarray, dtype: dt.DType) -> np.ndarray:
    if dtype.is_floating:
        return bits.view(np.float64).astype(dtype.numpy_dtype)
    return bits.astype(dtype.numpy_dtype)


def bucket(n: int, minimum: int = MIN_CAPACITY) -> int:
    """Smallest power of two >= max(n, minimum). Bounds XLA recompiles per DESIGN.md §1."""
    n = max(int(n), minimum)
    return 1 << (n - 1).bit_length()


def string_width_bucket(max_len: int) -> int:
    return bucket(max_len, MIN_STRING_WIDTH)


@dataclass(frozen=True)
class Scalar:
    """Device-free scalar value paired with its SQL type (cuDF ``Scalar`` analog,
    used by ``GpuLiteral``/``GpuScalar`` — literals.scala in the reference)."""
    value: Any                      # python value; None = null scalar
    dtype: dt.DType

    @property
    def is_null(self) -> bool:
        return self.value is None


class Column:
    """A device column: storage arrays sized to a capacity >= the batch's num_rows.

    numeric/bool/date/timestamp: ``data[cap]`` with the type's numpy dtype
    string:                      ``data[cap, byte_cap] uint8`` + ``lengths[cap] int32``
    All carry ``validity[cap] bool`` (True = valid). Padding rows must be invalid and
    their data zeroed (zeroed padding keeps kernels free of NaN/garbage hazards).
    """

    __slots__ = ("dtype", "data", "validity", "lengths", "elem_validity")

    def __init__(self, dtype: dt.DType, data, validity, lengths=None,
                 elem_validity=None):
        self.dtype = dtype
        self.data = data
        self.validity = validity
        self.lengths = lengths
        # ARRAY<primitive> element nullability: bool[cap, W] aligned with
        # the element matrix (True = element valid). MANDATORY for device
        # arrays so the flat-array protocol's arity is a function of the
        # dtype (column_arity), never of the instance.
        self.elem_validity = elem_validity
        if dtype.var_width:
            assert lengths is not None and data.ndim == 2, \
                "var-width (string/array) column needs lengths + 2D data"
            if dt.is_array(dtype) and dtype.numpy_dtype is not None:
                assert elem_validity is not None, \
                    "device ARRAY column needs an element-validity matrix"
        else:
            assert data.ndim == 1, f"fixed-width column must be 1D, got {data.ndim}D"

    # -- capacity / shape ----------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def byte_width(self) -> int:
        """Padded width for var-width columns; storage width for fixed types."""
        if self.dtype.var_width:
            return int(self.data.shape[1])
        return self.dtype.byte_width

    def device_size_bytes(self) -> int:
        total = self.data.size * self.data.dtype.itemsize
        total += self.validity.size * 1
        if self.lengths is not None:
            total += self.lengths.size * 4
        if self.elem_validity is not None:
            total += self.elem_validity.size * 1
        return int(total)

    def arrays(self) -> List[jnp.ndarray]:
        out = [self.data, self.validity]
        if self.lengths is not None:
            out.append(self.lengths)
        if self.elem_validity is not None:
            out.append(self.elem_validity)
        return out

    def with_arrays(self, data, validity, lengths=None) -> "Column":
        return Column(self.dtype, data, validity,
                      lengths if lengths is not None else
                      (self.lengths if self.dtype.var_width else None),
                      self.elem_validity)

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, dtype: Optional[dt.DType] = None,
                   validity: Optional[np.ndarray] = None,
                   capacity: Optional[int] = None) -> "Column":
        values = np.asarray(values)
        if dtype is None:
            dtype = dt.of(values.dtype)
        n = len(values)
        cap = capacity or bucket(n)
        storage = np.zeros(cap, dtype=dtype.numpy_dtype)
        valid = np.zeros(cap, dtype=np.bool_)
        v = values.astype(dtype.numpy_dtype, copy=False)
        if validity is None:
            validity = np.ones(n, dtype=np.bool_)
            if dtype.is_floating:
                # NaN stays valid (SQL NaN != NULL); nothing to mask here.
                pass
        storage[:n] = np.where(validity, v, np.zeros((), dtype=dtype.numpy_dtype)) \
            if len(v) else v
        valid[:n] = validity
        return Column(dtype, jnp.asarray(storage), jnp.asarray(valid))

    @staticmethod
    def from_pylist(values: Sequence[Any], dtype: dt.DType,
                    capacity: Optional[int] = None,
                    width: Optional[int] = None) -> "Column":
        n = len(values)
        if dt.is_struct(dtype):
            if all(_device_capable(t) for _, t in dtype.fields):
                return StructColumn.from_pylist_struct(values, dtype,
                                                       capacity)
            # a field type with no device layout (e.g. map<string,_>):
            # host objects carry the values across the collect boundary
            return ObjectColumn(dtype, values, capacity)
        if (dt.is_map(dtype) or dt.is_array(dtype)) and \
                dtype.numpy_dtype is None:
            # CPU-engine-only complex dtype (e.g. map<string,_>): these are
            # planner-gated off the device, so the column only exists to
            # carry CpuFallback results across the collect boundary — keep
            # the python objects instead of the device bitpattern layout
            # (which would misencode/crash on string keys)
            return ObjectColumn(dtype, values, capacity)
        valid_np = np.array([v is not None for v in values], dtype=np.bool_)
        if dt.is_map(dtype):
            # MAP<K,V>: int64[cap, 3W] INTERLEAVED bitpattern matrix
            # ([k, v, value-valid] per entry lane — pad-safe, see
            # dtypes.MAP) + entry counts; duplicate keys keep the LAST
            # entry (spark.sql.mapKeyDedupPolicy=LAST_WIN)
            dicts = [dict(v) if v is not None else None for v in values]
            max_len = max((len(d) for d in dicts if d is not None), default=0)
            w = width or bucket(max_len, 4)
            cap = capacity or bucket(n)
            mat = np.zeros((cap, 3 * w), dtype=np.int64)
            lens = np.zeros(cap, dtype=np.int32)
            for i, d in enumerate(dicts):
                if d is None:
                    continue
                ks = list(d.keys())
                vs = list(d.values())
                ln = len(ks)
                vv = np.array([v is not None for v in vs], np.bool_)
                mat[i, 0:3 * ln:3] = _bits_from_values(ks, dtype.key)
                mat[i, 1:3 * ln + 1:3] = np.where(
                    vv, _bits_from_values(
                        [v if v is not None else 0 for v in vs],
                        dtype.element), 0)
                mat[i, 2:3 * ln + 2:3] = vv.astype(np.int64)
                lens[i] = ln
            valid_full = np.zeros(cap, np.bool_)
            valid_full[:n] = valid_np
            return Column(dtype, jnp.asarray(mat), jnp.asarray(valid_full),
                          jnp.asarray(lens))
        if dt.is_array(dtype):
            # ARRAY<primitive>: padded element matrix + per-row lengths +
            # element-validity matrix (NULL elements round-trip)
            max_len = max((len(v) for v in values if v is not None),
                          default=0)
            w = width or bucket(max_len, 4)
            cap = capacity or bucket(n)
            mat = np.zeros((cap, w), dtype=dtype.numpy_dtype)
            lens = np.zeros(cap, dtype=np.int32)
            evalid = np.zeros((cap, w), dtype=np.bool_)
            for i, v in enumerate(values):
                if v is None:
                    continue
                ev = np.array([e is not None for e in v], np.bool_)
                mat[i, :len(v)] = np.asarray(
                    [e if e is not None else 0 for e in v],
                    dtype=dtype.numpy_dtype)
                evalid[i, :len(v)] = ev
                lens[i] = len(v)
            valid_full = np.zeros(cap, np.bool_)
            valid_full[:n] = valid_np
            return Column(dtype, jnp.asarray(mat), jnp.asarray(valid_full),
                          jnp.asarray(lens), jnp.asarray(evalid))
        if dtype == dt.STRING:
            encoded = [v.encode("utf-8") if isinstance(v, str)
                       else (v if isinstance(v, bytes) else b"") for v in values]
            max_len = max((len(b) for b in encoded), default=0)
            w = width or string_width_bucket(max_len)
            if max_len > w:
                raise ValueError(f"string of {max_len} bytes exceeds width {w}")
            cap = capacity or bucket(n)
            mat = np.zeros((cap, w), dtype=np.uint8)
            lens = np.zeros(cap, dtype=np.int32)
            for i, b in enumerate(encoded):
                mat[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
                lens[i] = len(b)
            lens[:n] = np.where(valid_np, lens[:n], 0)
            return Column(dt.STRING, jnp.asarray(mat), jnp.asarray(valid_np if cap == n else
                          np.concatenate([valid_np, np.zeros(cap - n, np.bool_)])),
                          jnp.asarray(lens))
        vals = np.array([v if v is not None else 0 for v in values],
                        dtype=dtype.numpy_dtype)
        return Column.from_numpy(vals, dtype, valid_np, capacity)

    @staticmethod
    def from_arrow(arr, capacity: Optional[int] = None,
                   width: Optional[int] = None) -> "Column":
        """Build a device column from a pyarrow Array/ChunkedArray (host boundary)."""
        host = Column.host_from_arrow(arr, capacity, width)
        if host is None:            # ARRAY/MAP<...>: python-object path
            import pyarrow as pa
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            dtype = dt.from_arrow(arr.type)
            vals = arr.to_pylist()
            if dt.is_map(dtype):
                # pyarrow maps materialize as lists of (k, v) tuples
                vals = [dict(v) if v is not None else None for v in vals]
            return Column.from_pylist(vals, dtype, capacity, width)
        dtype, arrays = host
        return Column(dtype, *[jnp.asarray(a) for a in arrays])

    @staticmethod
    def host_from_arrow(arr, capacity: Optional[int] = None,
                        width: Optional[int] = None):
        """Arrow -> padded host numpy arrays [data, validity(, lengths)]
        WITHOUT the device upload, so a batch-level caller can pack every
        column into one staging buffer and upload once (per-array transfer
        overhead dominates scan streams on high-latency links). Returns
        (dtype, arrays) or None for types that need the pylist path."""
        import pyarrow as pa
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        dtype = dt.from_arrow(arr.type)
        if dtype == dt.STRING:
            # vectorized offsets+values -> padded byte matrix: the python
            # per-row loop in from_pylist costs ~0.7s per 131k-row batch,
            # which dominated scan-heavy queries end to end
            import pyarrow as pa
            sa = arr
            n = len(sa)
            off_t = np.int64 if pa.types.is_large_string(sa.type) else np.int32
            off_buf = sa.buffers()[1]
            offs = (np.frombuffer(off_buf, dtype=off_t)
                    [sa.offset:sa.offset + n + 1].astype(np.int64)
                    if off_buf is not None else np.zeros(n + 1, np.int64))
            data_buf = sa.buffers()[2]
            vals = (np.frombuffer(data_buf, dtype=np.uint8)
                    if data_buf is not None else np.zeros(0, np.uint8))
            lens = (offs[1:] - offs[:-1]).astype(np.int32)
            valid = np.ones(n, np.bool_) if sa.null_count == 0 else \
                np.asarray(sa.is_valid())
            lens = np.where(valid, lens, 0).astype(np.int32)
            max_len = int(lens.max()) if n else 0
            w = width or string_width_bucket(max_len)
            if max_len > w:
                raise ValueError(
                    f"string of {max_len} bytes exceeds width {w}")
            cap = capacity or bucket(n)
            mat = np.zeros((cap, w), dtype=np.uint8)
            if n:
                mask = np.arange(w)[None, :] < lens[:, None]
                src = offs[:-1, None] + np.arange(w)[None, :]
                mat[:n][mask] = vals[src[mask]]
            lens_full = np.zeros(cap, np.int32)
            lens_full[:n] = lens
            valid_full = np.zeros(cap, np.bool_)
            valid_full[:n] = valid
            return (dt.STRING, [mat, valid_full, lens_full])
        if dt.is_array(dtype) or dt.is_map(dtype) or dt.is_struct(dtype):
            return None
        np_valid = np.ones(len(arr), dtype=np.bool_) if arr.null_count == 0 else \
            np.asarray(arr.is_valid())
        if dtype == dt.TIMESTAMP:
            values = np.asarray(arr.cast(pa.timestamp("us")).view(pa.int64())
                                .fill_null(0)).astype(np.int64)
        elif dtype == dt.DATE:
            values = np.asarray(arr.view(pa.int32()).fill_null(0)).astype(np.int32)
        elif dtype == dt.BOOL:
            values = np.asarray(arr.fill_null(False))
        else:
            values = np.asarray(arr.fill_null(0)).astype(dtype.numpy_dtype)
        n = len(values)
        cap = capacity or bucket(n)
        storage = np.zeros(cap, dtype=dtype.numpy_dtype)
        valid = np.zeros(cap, dtype=np.bool_)
        storage[:n] = np.where(np_valid, values,
                               np.zeros((), dtype=dtype.numpy_dtype)) \
            if n else values
        valid[:n] = np_valid
        return (dtype, [storage, valid])

    @staticmethod
    def full_null(dtype: dt.DType, capacity: int, width: int = MIN_STRING_WIDTH) -> "Column":
        valid = jnp.zeros(capacity, dtype=jnp.bool_)
        if dt.is_struct(dtype):
            return StructColumn(
                dtype, [Column.full_null(t, capacity) for _, t in
                        dtype.fields], valid)
        if dtype == dt.STRING:
            return Column(dtype, jnp.zeros((capacity, width), dtype=jnp.uint8), valid,
                          jnp.zeros(capacity, dtype=jnp.int32))
        if dt.is_array(dtype) and dtype.numpy_dtype is not None:
            return Column(dtype,
                          jnp.zeros((capacity, width), dtype=dtype.numpy_dtype),
                          valid, jnp.zeros(capacity, dtype=jnp.int32),
                          jnp.zeros((capacity, width), dtype=jnp.bool_))
        if dtype.var_width:              # MAP bitpattern matrix
            return Column(dtype,
                          jnp.zeros((capacity, width),
                                    dtype=dtype.numpy_dtype),
                          valid, jnp.zeros(capacity, dtype=jnp.int32))
        return Column(dtype, jnp.zeros(capacity, dtype=dtype.numpy_dtype), valid)

    @staticmethod
    def from_scalar(scalar: Scalar, num_rows: int, capacity: Optional[int] = None) -> "Column":
        cap = capacity or bucket(num_rows)
        if scalar.is_null:
            return Column.full_null(scalar.dtype, cap)
        valid = jnp.arange(cap) < num_rows
        if scalar.dtype == dt.STRING:
            # trace-safe broadcast: the byte row is STATIC (the literal),
            # only the live mask depends on num_rows — a pylist build
            # would do `[value] * tracer` and break whole-stage fusion
            b = scalar.value.encode("utf-8") if isinstance(
                scalar.value, str) else bytes(scalar.value)
            w = string_width_bucket(len(b))
            row = np.zeros(w, dtype=np.uint8)
            row[:len(b)] = np.frombuffer(b, dtype=np.uint8)
            data = jnp.where(valid[:, None],
                             jnp.broadcast_to(jnp.asarray(row), (cap, w)),
                             jnp.zeros((), jnp.uint8))
            lengths = jnp.where(valid, jnp.int32(len(b)), 0)
            return Column(dt.STRING, data, valid, lengths)
        data = jnp.full(cap, scalar.value, dtype=scalar.dtype.numpy_dtype)
        data = jnp.where(valid, data, jnp.zeros((), dtype=scalar.dtype.numpy_dtype))
        return Column(scalar.dtype, data, valid)

    # -- host extraction -----------------------------------------------------
    def to_numpy(self, num_rows: int) -> np.ndarray:
        """Host values for the first num_rows rows; NULLs as masked array fill."""
        if self.dtype == dt.STRING:
            raise TypeError("use to_pylist for string columns")
        return np.asarray(self.data[:num_rows])

    def to_pylist(self, num_rows: int) -> List[Any]:
        valid = np.asarray(self.validity[:num_rows])
        if dt.is_map(self.dtype):
            mat = np.asarray(self.data[:num_rows])
            lens = np.asarray(self.lengths[:num_rows])
            kt, vt = self.dtype.key, self.dtype.element
            kconv = (float if kt.is_floating else
                     bool if kt == dt.BOOL else int)
            vconv = (float if vt.is_floating else
                     bool if vt == dt.BOOL else int)
            out: List[Any] = []
            for i in range(num_rows):
                if not valid[i]:
                    out.append(None)
                    continue
                ln = int(lens[i])
                ks = _values_from_bits(mat[i, 0:3 * ln:3], kt)
                vs = _values_from_bits(mat[i, 1:3 * ln + 1:3], vt)
                vv = mat[i, 2:3 * ln + 2:3] != 0
                out.append({kconv(k): (vconv(v) if ok else None)
                            for k, v, ok in zip(ks, vs, vv)})
            return out
        if dt.is_array(self.dtype):
            mat = np.asarray(self.data[:num_rows])
            lens = np.asarray(self.lengths[:num_rows])
            ev = (np.asarray(self.elem_validity[:num_rows])
                  if self.elem_validity is not None else None)
            elem = self.dtype.element
            conv = (int if elem.is_integral or elem in (dt.DATE, dt.TIMESTAMP)
                    else bool if elem == dt.BOOL else float)
            return [[conv(x) if ev is None or ev[i, j] else None
                     for j, x in enumerate(mat[i, :lens[i]])]
                    if valid[i] else None
                    for i in range(num_rows)]
        if self.dtype == dt.STRING:
            mat = np.asarray(self.data[:num_rows])
            lens = np.asarray(self.lengths[:num_rows])
            out: List[Any] = []
            for i in range(num_rows):
                if not valid[i]:
                    out.append(None)
                else:
                    out.append(bytes(mat[i, :lens[i]]).decode("utf-8", errors="replace"))
            return out
        data = np.asarray(self.data[:num_rows])
        if self.dtype == dt.BOOL:
            return [bool(v) if ok else None for v, ok in zip(data, valid)]
        if self.dtype.is_integral or self.dtype in (dt.DATE, dt.TIMESTAMP):
            return [int(v) if ok else None for v, ok in zip(data, valid)]
        return [float(v) if ok else None for v, ok in zip(data, valid)]

    def to_arrow(self, num_rows: int):
        import pyarrow as pa
        valid = np.asarray(self.validity[:num_rows])
        if self.dtype == dt.STRING or dt.is_array(self.dtype) or \
                dt.is_map(self.dtype):
            return pa.array(self.to_pylist(num_rows),
                            type=dt.to_arrow(self.dtype))
        data = np.asarray(self.data[:num_rows])
        mask = ~valid  # pyarrow mask semantics: True = null
        if self.dtype == dt.DATE:
            return pa.array(data, type=pa.date32(), mask=mask)
        if self.dtype == dt.TIMESTAMP:
            return pa.array(data, type=pa.timestamp("us"), mask=mask)
        return pa.array(data, type=dt.to_arrow(self.dtype), mask=mask)

    def __repr__(self):
        extra = f", width={self.data.shape[1]}" if self.dtype.var_width else ""
        return f"Column({self.dtype}, cap={self.capacity}{extra})"


class ObjectColumn(Column):
    """Host-only python-object column for CPU-engine-only dtypes (maps with
    string keys/values, array<string>). The planner's type gate keeps these
    off the device (overrides type check, like the reference's unsupported
    nested types in GpuColumnVector.java's matrix), so an ObjectColumn only
    carries CpuFallback results across the host collect boundary — any
    device op touching it is a planner bug and raises."""

    __slots__ = ("values",)

    def __init__(self, dtype: dt.DType, values: Sequence[Any],
                 capacity: Optional[int] = None):
        n = len(values)
        cap = capacity or bucket(n)
        vals = list(values) + [None] * (cap - n)
        if dt.is_map(dtype):
            # normalize list-of-pairs (arrow's map rendering) to dicts
            vals = [dict(v) if isinstance(v, (list, tuple)) else v
                    for v in vals]
        self.dtype = dtype
        self.values = vals
        self.data = np.empty((cap, 0), dtype=np.uint8)
        self.validity = np.array([v is not None for v in vals], np.bool_)
        self.lengths = np.zeros(cap, np.int32)
        self.elem_validity = None

    @property
    def capacity(self) -> int:
        return len(self.values)

    def device_size_bytes(self) -> int:
        return 0

    def arrays(self) -> List[jnp.ndarray]:
        raise TypeError(
            f"{self.dtype} columns are host-only (CPU-engine dtype); "
            "no device arrays exist")

    def with_arrays(self, data, validity, lengths=None) -> "Column":
        raise TypeError(f"{self.dtype} columns are host-only")

    def to_pylist(self, num_rows: int) -> List[Any]:
        return self.values[:num_rows]

    def to_arrow(self, num_rows: int):
        import pyarrow as pa
        vals = self.values[:num_rows]
        if dt.is_map(self.dtype):
            vals = [None if v is None else list(v.items()) for v in vals]
        return pa.array(vals, type=dt.to_arrow(self.dtype))

    def __repr__(self):
        return f"ObjectColumn({self.dtype}, cap={self.capacity})"


class StructColumn(Column):
    """Device STRUCT layout: struct-of-columns + a struct-level validity
    vector (the GpuColumnVector struct form, GpuColumnVector.java:40-535).
    Scans still SHRED field accesses into flat columns (the fast path);
    this layout is for WHOLE-struct values flowing through joins, sorts,
    exchanges, and collects without the host ObjectColumn crawl: the
    row-reorder kernels (gather/concat) recurse into the children, and
    the flat-array protocol flattens [validity, *children...] with an
    arity that is a pure function of the dtype (column_arity)."""

    def __init__(self, dtype: dt.DType, children: List[Column], validity):
        self.dtype = dtype
        self.children = children
        self.validity = validity
        self.data = None
        self.lengths = None
        self.elem_validity = None

    @staticmethod
    def from_pylist_struct(values: Sequence[Any], dtype: dt.DType,
                           capacity: Optional[int] = None) -> "StructColumn":
        n = len(values)
        cap = capacity or bucket(n)
        valid = np.zeros(cap, np.bool_)
        valid[:n] = [v is not None for v in values]
        children = []
        for fname, ftype in dtype.fields:
            fvals = [None if v is None else
                     (v.get(fname) if isinstance(v, dict)
                      else getattr(v, fname)) for v in values]
            children.append(Column.from_pylist(fvals, ftype, capacity=cap))
        return StructColumn(dtype, children, jnp.asarray(valid))

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def byte_width(self) -> int:
        return sum(c.byte_width for c in self.children)

    def device_size_bytes(self) -> int:
        return int(self.validity.size) + \
            sum(c.device_size_bytes() for c in self.children)

    def arrays(self) -> List[jnp.ndarray]:
        out = [self.validity]
        for c in self.children:
            out.extend(c.arrays())
        return out

    def with_arrays(self, data, validity, lengths=None) -> "Column":
        raise TypeError("use build_column to reconstruct struct columns")

    def to_pylist(self, num_rows: int) -> List[Any]:
        valid = np.asarray(self.validity[:num_rows])
        kids = [c.to_pylist(num_rows) for c in self.children]
        names = [n for n, _ in self.dtype.fields]
        return [dict(zip(names, vals)) if ok else None
                for ok, vals in zip(valid, zip(*kids))] if kids else \
            [None] * num_rows

    def to_arrow(self, num_rows: int):
        import pyarrow as pa
        return pa.array(self.to_pylist(num_rows),
                        type=dt.to_arrow(self.dtype))

    def __repr__(self):
        return f"StructColumn({self.dtype}, cap={self.capacity})"


def _device_capable(t: dt.DType) -> bool:
    """Types with a device layout (vs host-only ObjectColumn types)."""
    if dt.is_struct(t):
        return all(_device_capable(ft) for _, ft in t.fields)
    if dt.is_array(t) or dt.is_map(t):
        return t.numpy_dtype is not None
    return True


def column_arity(t: dt.DType) -> int:
    """Number of flat storage arrays a device column of type ``t``
    contributes — a pure function of the dtype, shared by every
    reconstruction site (fused stages, spill, shuffle wire)."""
    if dt.is_struct(t):
        return 1 + sum(column_arity(ft) for _, ft in t.fields)
    if dt.is_array(t) and t.numpy_dtype is not None:
        return 4                      # data, validity, lengths, elem_valid
    if t.var_width:
        return 3                      # data, validity, lengths
    return 2                          # data, validity


def build_column(t: dt.DType, arrays: Sequence[Any], i: int = 0):
    """(column, next_index): rebuild one column from the flat-array form
    starting at ``arrays[i]`` (inverse of ``Column.arrays()``)."""
    if dt.is_struct(t):
        validity = arrays[i]
        i += 1
        children = []
        for _, ft in t.fields:
            c, i = build_column(ft, arrays, i)
            children.append(c)
        return StructColumn(t, children, validity), i
    if dt.is_array(t) and t.numpy_dtype is not None:
        return Column(t, arrays[i], arrays[i + 1], arrays[i + 2],
                      arrays[i + 3]), i + 4
    if t.var_width:
        return Column(t, arrays[i], arrays[i + 1], arrays[i + 2]), i + 3
    return Column(t, arrays[i], arrays[i + 1]), i + 2
