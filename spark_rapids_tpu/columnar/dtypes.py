"""SQL type system for spark-rapids-tpu columnar data.

The reference piggybacks on Spark's Catalyst ``DataType`` and cuDF ``DType``
(type mapping in ``GpuColumnVector.java:40-535``). Here we define a small standalone
type lattice with mappings to jax/numpy dtypes and Arrow types.

Timestamps are int64 microseconds since epoch (Spark semantics); dates are int32 days
since epoch — both match the reference's cuDF TIMESTAMP_MICROSECONDS / TIMESTAMP_DAYS
choices (GpuColumnVector.java type mapping).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np


@dataclass(frozen=True)
class DType:
    name: str
    numpy_dtype: Optional[np.dtype]   # physical storage dtype (None for STRING: uint8 matrix)
    is_numeric: bool = False
    is_integral: bool = False
    is_floating: bool = False
    byte_width: int = 0               # fixed-width storage bytes (0 for string)
    var_width: bool = False           # 2-D padded data + lengths (string/array)
    element: Optional["DType"] = None  # ARRAY element type / MAP value type
    key: Optional["DType"] = None      # MAP key type
    fields: Optional[tuple] = None     # STRUCT (name, DType) pairs

    def __repr__(self) -> str:
        return self.name


BOOL = DType("boolean", np.dtype(np.bool_), byte_width=1)
INT8 = DType("tinyint", np.dtype(np.int8), True, True, byte_width=1)
INT16 = DType("smallint", np.dtype(np.int16), True, True, byte_width=2)
INT32 = DType("int", np.dtype(np.int32), True, True, byte_width=4)
INT64 = DType("bigint", np.dtype(np.int64), True, True, byte_width=8)
FLOAT32 = DType("float", np.dtype(np.float32), True, is_floating=True, byte_width=4)
FLOAT64 = DType("double", np.dtype(np.float64), True, is_floating=True, byte_width=8)
STRING = DType("string", None, byte_width=0, var_width=True)
DATE = DType("date", np.dtype(np.int32), byte_width=4)            # days since epoch
TIMESTAMP = DType("timestamp", np.dtype(np.int64), byte_width=8)  # micros since epoch
NULLTYPE = DType("null", np.dtype(np.bool_), byte_width=1)

ALL_TYPES = [BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, STRING, DATE, TIMESTAMP]
_BY_NAME = {t.name: t for t in ALL_TYPES}
_ALIASES = {
    "long": INT64, "integer": INT32, "short": INT16, "byte": INT8,
    "bool": BOOL, "str": STRING, "float32": FLOAT32, "float64": FLOAT64,
    "real": FLOAT32,
}

INTEGRAL_TYPES = [INT8, INT16, INT32, INT64]
NUMERIC_TYPES = INTEGRAL_TYPES + [FLOAT32, FLOAT64]
ORDERABLE_TYPES = NUMERIC_TYPES + [BOOL, STRING, DATE, TIMESTAMP]

_ARRAY_CACHE: dict = {}


def ARRAY(element: DType) -> DType:
    """ARRAY<element> of a fixed-width primitive: stored like strings —
    padded element matrix ``elem_dtype[cap, W]`` + per-row lengths
    (complexTypeExtractors.scala's list scope, TPU-first layout)."""
    if element.var_width:
        raise TypeError(f"nested var-width array element {element} "
                        "not supported")
    t = _ARRAY_CACHE.get(element.name)
    if t is None:
        t = DType(f"array<{element.name}>", element.numpy_dtype,
                  var_width=True, element=element)
        _ARRAY_CACHE[element.name] = t
        _BY_NAME[t.name] = t
    return t


# array<string>: only flows through the CPU engine / explode fusion —
# the padded-matrix device layout is primitive-element only
ARRAY_STRING = DType("array<string>", None, var_width=True, element=STRING)
_BY_NAME[ARRAY_STRING.name] = ARRAY_STRING

_MAP_CACHE: dict = {}


def MAP(key: DType, value: DType) -> DType:
    """MAP<key, value> of fixed-width primitives. Physical layout (DESIGN
    stance: keep every transport/spill path ignorant of maps): ONE
    ``int64[cap, 3W]`` matrix of INTERLEAVED per-entry lanes —
    ``[k0, v0, ok0, k1, v1, ok1, ...]`` (key bitpattern, value bitpattern,
    value-validity flag) — plus per-row entry counts in ``lengths``.
    Interleaving makes the layout safe under the var-width width
    harmonization every concat/join/conditional path performs: right-
    padding appends whole empty lanes, which the entry count already
    masks. Map ops bitcast the strided planes back to the logical dtypes
    (complexTypeExtractors.scala's GetMapValue scope, TPU-first layout).
    String keys/values take the CPU path."""
    name = f"map<{key.name},{value.name}>"
    t = _MAP_CACHE.get(name)
    if t is None:
        if key.var_width or value.var_width or key.numpy_dtype is None or \
                value.numpy_dtype is None:
            # string/nested keys or values: CPU-engine-only dtype (the
            # planner's type gate tags it off the device, like ARRAY_STRING)
            t = DType(name, None, var_width=True, element=value, key=key)
        else:
            t = DType(name, np.dtype(np.int64), var_width=True,
                      element=value, key=key)
        _MAP_CACHE[name] = t
        _BY_NAME[name] = t
    return t


_STRUCT_CACHE: dict = {}


def STRUCT(fields) -> DType:
    """STRUCT<name:type,...>. No device layout of its own: the planner
    SHREDS referenced fields into flat child columns at the scan (the
    columnar-storage move — parquet stores structs shredded anyway), and a
    whole-struct value only materializes host-side through the
    python-object column path (like map<string,_>). The reference's analog
    is GpuColumnVector's nested-type matrix + complexTypeExtractors."""
    fields = tuple((n, t) for n, t in fields)
    name = "struct<" + ",".join(f"{n}:{t.name}" for n, t in fields) + ">"
    t = _STRUCT_CACHE.get(name)
    if t is None:
        t = DType(name, None, var_width=True, fields=fields)
        _STRUCT_CACHE[name] = t
        _BY_NAME[name] = t
    return t


def is_array(t: DType) -> bool:
    return t.element is not None and t.key is None


def is_map(t: DType) -> bool:
    return t.key is not None


def is_struct(t: DType) -> bool:
    return t.fields is not None


def of(name_or_dtype: Any) -> DType:
    """Resolve a DType from a name, numpy dtype, or python type."""
    if isinstance(name_or_dtype, DType):
        return name_or_dtype
    if isinstance(name_or_dtype, str):
        t = _BY_NAME.get(name_or_dtype) or _ALIASES.get(name_or_dtype)
        if t is None and name_or_dtype.startswith("array<") and \
                name_or_dtype.endswith(">"):
            return ARRAY(of(name_or_dtype[6:-1]))
        if t is None and name_or_dtype.startswith("map<") and \
                name_or_dtype.endswith(">"):
            inner = name_or_dtype[4:-1]
            depth = 0
            for i, ch in enumerate(inner):
                if ch == "<":
                    depth += 1
                elif ch == ">":
                    depth -= 1
                elif ch == "," and depth == 0:
                    return MAP(of(inner[:i].strip()),
                               of(inner[i + 1:].strip()))
        if t is None and name_or_dtype.startswith("struct<") and \
                name_or_dtype.endswith(">"):
            inner = name_or_dtype[7:-1]
            fields = []
            depth = 0
            start = 0
            for i, ch in enumerate(inner + ","):
                if ch == "<":
                    depth += 1
                elif ch == ">":
                    depth -= 1
                elif ch == "," and depth == 0:
                    part = inner[start:i].strip()
                    if part:
                        fname, _, ftype = part.partition(":")
                        fields.append((fname, of(ftype)))
                    start = i + 1
            return STRUCT(fields)
        if t is None:
            raise ValueError(f"unknown SQL type name {name_or_dtype!r}")
        return t
    if name_or_dtype is int:
        return INT64
    if name_or_dtype is float:
        return FLOAT64
    if name_or_dtype is bool:
        return BOOL
    if name_or_dtype is str:
        return STRING
    npdt = np.dtype(name_or_dtype)
    for t in ALL_TYPES:
        if t.numpy_dtype == npdt and t not in (DATE, TIMESTAMP):
            return t
    raise ValueError(f"cannot map {name_or_dtype!r} to a SQL type")


def from_arrow(arrow_type) -> DType:
    import pyarrow as pa
    if pa.types.is_boolean(arrow_type): return BOOL
    if pa.types.is_int8(arrow_type): return INT8
    if pa.types.is_int16(arrow_type): return INT16
    if pa.types.is_int32(arrow_type): return INT32
    if pa.types.is_int64(arrow_type): return INT64
    if pa.types.is_float32(arrow_type): return FLOAT32
    if pa.types.is_float64(arrow_type): return FLOAT64
    if pa.types.is_string(arrow_type) or pa.types.is_large_string(arrow_type): return STRING
    if pa.types.is_date32(arrow_type): return DATE
    if pa.types.is_timestamp(arrow_type): return TIMESTAMP
    if pa.types.is_list(arrow_type) or pa.types.is_large_list(arrow_type):
        return ARRAY(from_arrow(arrow_type.value_type))
    if pa.types.is_map(arrow_type):
        return MAP(from_arrow(arrow_type.key_type),
                   from_arrow(arrow_type.item_type))
    if pa.types.is_struct(arrow_type):
        return STRUCT([(arrow_type.field(i).name,
                        from_arrow(arrow_type.field(i).type))
                       for i in range(arrow_type.num_fields)])
    raise ValueError(f"unsupported arrow type {arrow_type}")


def to_arrow(t: DType):
    import pyarrow as pa
    mapping = {
        BOOL: pa.bool_(), INT8: pa.int8(), INT16: pa.int16(), INT32: pa.int32(),
        INT64: pa.int64(), FLOAT32: pa.float32(), FLOAT64: pa.float64(),
        STRING: pa.string(), DATE: pa.date32(), TIMESTAMP: pa.timestamp("us"),
    }
    if is_map(t):
        return pa.map_(to_arrow(t.key), to_arrow(t.element))
    if is_array(t):
        return pa.list_(to_arrow(t.element))
    if is_struct(t):
        return pa.struct([(n, to_arrow(ft)) for n, ft in t.fields])
    return mapping[t]


_NUMERIC_PRECEDENCE = [BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64]


def promote(a: DType, b: DType) -> DType:
    """Numeric widening per Spark's TypeCoercion precedence
    (Byte < Short < Int < Long < Float < Double): the result is the higher-
    precedence type, so e.g. long + float -> float, int + smallint -> int."""
    if a == b:
        return a
    try:
        ia, ib = _NUMERIC_PRECEDENCE.index(a), _NUMERIC_PRECEDENCE.index(b)
    except ValueError:
        raise ValueError(f"cannot promote {a} and {b}") from None
    return _NUMERIC_PRECEDENCE[max(ia, ib)]


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DType
    nullable: bool = True


class Schema:
    def __init__(self, fields):
        self.fields = [f if isinstance(f, Field) else Field(f[0], of(f[1])) for f in fields]
        # first occurrence wins on duplicate names (post-join schemas carry
        # both sides; USING-join dedup keeps the left copy, Spark semantics)
        self._index = {}
        for i, f in enumerate(self.fields):
            self._index.setdefault(f.name, i)

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, key):
        if isinstance(key, int):
            return self.fields[key]
        return self.fields[self._index[key]]

    def index_of(self, name: str) -> int:
        return self._index[name]

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def names(self):
        return [f.name for f in self.fields]

    def __eq__(self, other):
        return isinstance(other, Schema) and self.fields == other.fields

    def __repr__(self):
        inner = ", ".join(f"{f.name}: {f.dtype}" for f in self.fields)
        return f"Schema({inner})"
