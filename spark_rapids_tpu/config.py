"""Typed configuration registry for spark-rapids-tpu.

TPU-native analog of the reference's ``RapidsConf`` (see
``/root/reference/sql-plugin/src/main/scala/com/nvidia/spark/rapids/RapidsConf.scala:116-278``
for the builder DSL and ``:282-762`` for the key registry). Mirrors its shape:

* a self-documenting builder DSL (``conf("spark.rapids.tpu...").doc(...).integerConf
  .createWithDefault(...)``)
* byte-unit parsing for memory sizes
* ``internal()`` keys hidden from docs
* per-operator auto-generated enable/disable keys (``spark.rapids.tpu.sql.expression.<Name>``,
  cf. GpuOverrides.scala:129-137) are registered dynamically by the rule registry in
  ``plan/overrides.py``
* ``help_text()`` generates docs/configs.md like RapidsConf.confHelp (RapidsConf.scala:133-168)
"""

from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_BYTE_SUFFIXES = {
    "b": 1,
    "k": 1 << 10, "kb": 1 << 10, "kib": 1 << 10,
    "m": 1 << 20, "mb": 1 << 20, "mib": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "gib": 1 << 30,
    "t": 1 << 40, "tb": 1 << 40, "tib": 1 << 40,
}


def parse_bytes(value: Any) -> int:
    """Parse '2g', '512m', '1024' etc. into a byte count (Spark byte-string semantics)."""
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip().lower()
    # negative values pass through (sentinels like autoBroadcastJoinThreshold=-1)
    m = re.fullmatch(r"(-?\d+(?:\.\d+)?)\s*([a-z]*)", s)
    if not m:
        raise ValueError(f"cannot parse byte value: {value!r}")
    num, suffix = m.groups()
    mult = _BYTE_SUFFIXES.get(suffix or "b")
    if mult is None:
        raise ValueError(f"unknown byte suffix in {value!r}")
    return int(float(num) * mult)


def _parse_bool(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    s = str(value).strip().lower()
    if s in ("true", "1", "yes", "on"):
        return True
    if s in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"cannot parse boolean value: {value!r}")


@dataclass
class ConfEntry:
    key: str
    doc: str
    default: Any
    converter: Callable[[Any], Any]
    type_name: str
    internal: bool = False
    validator: Optional[Callable[[Any], bool]] = None

    def convert(self, raw: Any) -> Any:
        v = self.converter(raw)
        if self.validator is not None and not self.validator(v):
            raise ValueError(f"invalid value {raw!r} for {self.key}")
        return v


class _ConfBuilder:
    """Builder DSL: conf(key).doc(...).internal().booleanConf.create_with_default(...)."""

    def __init__(self, registry: "ConfRegistry", key: str):
        self._registry = registry
        self._key = key
        self._doc = ""
        self._internal = False
        self._validator: Optional[Callable[[Any], bool]] = None
        self._converter: Optional[Callable[[Any], Any]] = None
        self._type_name = "string"

    def doc(self, text: str) -> "_ConfBuilder":
        self._doc = text
        return self

    def internal(self) -> "_ConfBuilder":
        self._internal = True
        return self

    def check(self, validator: Callable[[Any], bool]) -> "_ConfBuilder":
        self._validator = validator
        return self

    @property
    def boolean_conf(self) -> "_ConfBuilder":
        self._converter, self._type_name = _parse_bool, "boolean"
        return self

    @property
    def integer_conf(self) -> "_ConfBuilder":
        self._converter, self._type_name = int, "integer"
        return self

    @property
    def double_conf(self) -> "_ConfBuilder":
        self._converter, self._type_name = float, "double"
        return self

    @property
    def string_conf(self) -> "_ConfBuilder":
        self._converter, self._type_name = str, "string"
        return self

    @property
    def bytes_conf(self) -> "_ConfBuilder":
        self._converter, self._type_name = parse_bytes, "byteSize"
        return self

    def create_with_default(self, default: Any) -> ConfEntry:
        entry = ConfEntry(
            key=self._key,
            doc=self._doc,
            default=default,
            converter=self._converter or str,
            type_name=self._type_name,
            internal=self._internal,
            validator=self._validator,
        )
        self._registry.register(entry)
        return entry


class ConfRegistry:
    def __init__(self) -> None:
        from .analysis.lockdep import named_lock
        self._entries: Dict[str, ConfEntry] = {}
        self._lock = named_lock("config.ConfRegistry._lock")

    def conf(self, key: str) -> _ConfBuilder:
        return _ConfBuilder(self, key)

    def register(self, entry: ConfEntry) -> None:
        with self._lock:
            if entry.key in self._entries:
                raise ValueError(f"duplicate conf key {entry.key}")
            self._entries[entry.key] = entry

    def register_dynamic(self, key: str, doc: str, default: bool) -> ConfEntry:
        """Per-operator enable keys; idempotent (re-registration returns existing)."""
        with self._lock:
            if key in self._entries:
                return self._entries[key]
            entry = ConfEntry(key=key, doc=doc, default=default,
                              converter=_parse_bool, type_name="boolean")
            self._entries[key] = entry
            return entry

    def get_entry(self, key: str) -> Optional[ConfEntry]:
        return self._entries.get(key)

    def entries(self) -> List[ConfEntry]:
        return sorted(self._entries.values(), key=lambda e: e.key)

    def help_text(self, include_internal: bool = False) -> str:
        lines = [
            "# spark-rapids-tpu Configuration",
            "",
            "| Name | Description | Default |",
            "|---|---|---|",
        ]
        for e in self.entries():
            if e.internal and not include_internal:
                continue
            lines.append(f"| {e.key} | {e.doc} | {e.default} |")
        return "\n".join(lines) + "\n"


REGISTRY = ConfRegistry()
_conf = REGISTRY.conf

# ---------------------------------------------------------------------------
# Core keys (mirroring RapidsConf.scala where the concept transfers; citations
# point at the reference key this replaces).
# ---------------------------------------------------------------------------

SQL_ENABLED = _conf("spark.rapids.tpu.sql.enabled").doc(
    "Master enable for columnar TPU acceleration (ref: spark.rapids.sql.enabled, "
    "RapidsConf.scala:744 area)").boolean_conf.create_with_default(True)

EXPLAIN = _conf("spark.rapids.tpu.sql.explain").doc(
    "Explain why parts of a query did or did not run on TPU: NONE, NOT_ON_GPU, ALL "
    "(ref: spark.rapids.sql.explain)").string_conf.check(
        lambda v: v in ("NONE", "NOT_ON_GPU", "ALL")).create_with_default("NONE")

INCOMPATIBLE_OPS = _conf("spark.rapids.tpu.sql.incompatibleOps.enabled").doc(
    "Enable ops whose TPU results differ from CPU in corner cases "
    "(ref: spark.rapids.sql.incompatibleOps.enabled)").boolean_conf.create_with_default(False)

HAS_NANS = _conf("spark.rapids.tpu.sql.hasNans").doc(
    "Assume floating point data may contain NaNs; gates some agg/join key paths "
    "(ref: spark.rapids.sql.hasNans)").boolean_conf.create_with_default(True)

VARIABLE_FLOAT_AGG = _conf("spark.rapids.tpu.sql.variableFloatAgg.enabled").doc(
    "Allow float aggregations whose result may differ from CPU due to reduction order "
    "(ref: spark.rapids.sql.variableFloatAgg.enabled)").boolean_conf.create_with_default(True)

BATCH_SIZE_BYTES = _conf("spark.rapids.tpu.sql.batchSizeBytes").doc(
    "Target coalesced columnar batch size in bytes "
    "(ref: spark.rapids.sql.batchSizeBytes default 2g, RapidsConf.scala:282-377)"
).bytes_conf.create_with_default(512 * 1024 * 1024)

MAX_READER_BATCH_SIZE_ROWS = _conf("spark.rapids.tpu.sql.reader.batchSizeRows").doc(
    "Cap on rows per scan/coalesced batch (ref: spark.rapids.sql.reader."
    "batchSizeRows). Whole-stage programs compile per batch capacity; 1M "
    "rows amortizes per-dispatch link latency ~8x vs 128k while the "
    "persistent compile cache absorbs the one-time larger-shape compile"
).integer_conf.create_with_default(1 << 20)

CONCURRENT_TPU_TASKS = _conf("spark.rapids.tpu.sql.concurrentTpuTasks").doc(
    "Number of tasks that may hold the device concurrently "
    "(ref: spark.rapids.sql.concurrentGpuTasks, RapidsConf.scala:351)"
).integer_conf.create_with_default(2)

TASK_POOL_THREADS = _conf("spark.rapids.tpu.sql.taskPoolThreads").doc(
    "Threads draining partitions concurrently (Spark's executor task slots; "
    "the TpuSemaphore still bounds how many hold the device at once)"
).integer_conf.create_with_default(4)

ALLOC_FRACTION = _conf("spark.rapids.tpu.memory.allocFraction").doc(
    "Fraction of device HBM the pool may use (ref: spark.rapids.memory.gpu.allocFraction)"
).double_conf.check(lambda v: 0.0 < v <= 1.0).create_with_default(0.9)

HOST_SPILL_STORAGE_SIZE = _conf("spark.rapids.tpu.memory.host.spillStorageSize").doc(
    "Bound on host-memory spill tier before cascading to disk "
    "(ref: spark.rapids.memory.host.spillStorageSize, RapidsConf.scala:330)"
).bytes_conf.create_with_default(4 * 1024 * 1024 * 1024)

SPILL_DIR = _conf("spark.rapids.tpu.memory.spillDir").doc(
    "Directory for the disk spill tier (ref: Spark local dirs via RapidsDiskBlockManager)"
).string_conf.create_with_default("/tmp/spark_rapids_tpu_spill")

SHUFFLE_PARTITIONS = _conf("spark.rapids.tpu.sql.shuffle.partitions").doc(
    "Default number of shuffle partitions (ref: spark.sql.shuffle.partitions)"
).integer_conf.create_with_default(8)

SHUFFLE_PLANE = _conf("spark.rapids.tpu.sql.shuffle.plane").doc(
    "Shuffle exchange data plane: 'auto' (device->device ICI collectives "
    "over the active mesh when one exists, the host/DCN path otherwise), "
    "'ici' (force collectives; planning fails without a mesh), 'dcn' "
    "(force the host-staged transport path). The ICI plane moves "
    "uncompressed device buffers through all_to_all (SURVEY.md §5: the "
    "UCX/RDMA -> ICI re-design); the DCN plane keeps the TCP transfer "
    "server, elastic retry, and the wire compression codec "
    "(see docs/shuffle.md)").string_conf.check(
        lambda v: str(v).lower() in ("auto", "ici", "dcn")
).create_with_default("auto")

SHUFFLE_PIPELINE_DEPTH = _conf("spark.rapids.tpu.sql.shuffle.pipelineDepth").doc(
    "Map-side split batches kept in flight before the oldest batch's "
    "slice-sizing readback lands: batch k+1's fused split (hash -> stable "
    "sort by partition id -> counts) dispatches before batch k's packed "
    "sizing resolves, so the map phase pays O(1) host syncs instead of "
    "one per batch. 1 degenerates to read-per-batch. Device residency "
    "grows by one sorted batch per slot"
).integer_conf.check(lambda v: int(v) >= 1).create_with_default(8)

SHUFFLE_DURABLE = _conf("spark.rapids.tpu.sql.shuffle.durable").doc(
    "Durable shuffle outputs (docs/resilience.md): map outputs stay "
    "registered (re-fetchable) until the exchange releases them and are "
    "pinned through the spill store's host/disk tiers — a consumer-side "
    "stage retry re-fetches instead of re-running the map stage, and a "
    "multi-process worker that dies and rejoins re-serves its outputs "
    "from the durable .npz tier (the reference's checkpoint/resume "
    "trade, SURVEY §5). Off keeps the memory-only fast path"
).boolean_conf.create_with_default(False)

SHUFFLE_DURABLE_MAX_BYTES = _conf(
    "spark.rapids.tpu.sql.shuffle.durable.maxBytes").doc(
    "Disk budget for the durable shuffle tier's .npz write-through "
    "(docs/shuffle.md): once the durable files exceed this many bytes, "
    "the OLDEST COMPLETED shuffle's durable files are evicted (the "
    "in-memory outputs keep serving this process; only the dead-worker "
    "rejoin re-serve for that old shuffle is given up), metered into "
    "tpu_durable_evicted_bytes_total — a long-lived session with "
    "shuffle.durable on cannot fill the disk. The newest completed "
    "shuffle is never evicted. 0 disables the budget"
).bytes_conf.create_with_default(2 * 1024 * 1024 * 1024)

SHUFFLE_FETCH_MAX_RETRIES = _conf(
    "spark.rapids.tpu.sql.shuffle.fetch.maxRetries").doc(
    "Transport-level retries per shuffle fetch before the failure "
    "escalates to the stage-retry taxonomy (exec/recovery.py): each "
    "retry uses a fresh connection; CRC mismatches and connection "
    "failures retry, desyncs never do (ShuffleClient; attempts are "
    "metered into tpu_shuffle_retries_total)"
).integer_conf.check(lambda v: int(v) >= 0).create_with_default(3)

SHUFFLE_FETCH_RETRY_BACKOFF = _conf(
    "spark.rapids.tpu.sql.shuffle.fetch.retryBackoff").doc(
    "Linear backoff (seconds x attempt) between transport-level fetch "
    "retries").double_conf.check(
        lambda v: float(v) >= 0).create_with_default(0.05)

RECOVERY_MAX_STAGE_RETRIES = _conf(
    "spark.rapids.tpu.sql.recovery.maxStageRetries").doc(
    "Stage re-executions a recoverable failure (lost shuffle buffer, "
    "fetch give-up, dead worker, injected task fault) may consume "
    "before the query fails — the standalone analog of Spark's "
    "spark.stage.maxConsecutiveAttempts driving FetchFailed map-stage "
    "retries (docs/resilience.md). 0 propagates every failure"
).integer_conf.check(lambda v: int(v) >= 0).create_with_default(2)

RECOVERY_RETRY_BACKOFF = _conf(
    "spark.rapids.tpu.sql.recovery.retryBackoff").doc(
    "Linear backoff (seconds x attempt) between stage retries "
    "(dead-worker liveness probes pace on their own exponential "
    "window, one fetch timeout per budget attempt)"
).double_conf.check(lambda v: float(v) >= 0).create_with_default(0.1)

FAULTS_SPEC = _conf("spark.rapids.tpu.sql.faults.spec").doc(
    "Deterministic fault-injection spec for the chaos harness "
    "(analysis/faults.py, docs/resilience.md): semicolon-separated "
    "point[:count][@selector] clauses over fetch.fail, conn.kill, "
    "task.poison, worker.die, mesh.drop, desync.inject — each fires a "
    "bounded number of times, flight-recorded and counted in "
    "tpu_faults_injected_total. Empty disables injection"
).string_conf.create_with_default("")

SHUFFLE_COMPRESSION_CODEC = _conf("spark.rapids.tpu.shuffle.compression.codec").doc(
    "Codec for shuffle transfer payloads: none, zlib (ref: spark.rapids."
    "shuffle.compression.codec / NvcompLZ4CompressionCodec, "
    "RapidsConf.scala:729; host-side here — no TPU decompression engine)"
).string_conf.check(
        lambda v: v in ("none", "zlib")).create_with_default("none")

SPILL_COMPRESSION_CODEC = _conf("spark.rapids.tpu.memory.spill.compression.codec").doc(
    "Codec for the disk spill tier: none, zlib").string_conf.check(
        lambda v: v in ("none", "zlib")).create_with_default("none")

ADAPTIVE_ENABLED = _conf("spark.rapids.tpu.sql.adaptive.enabled").doc(
    "Adaptive execution: coalesce small shuffle partitions at runtime from "
    "observed map-side sizes (ref: AQE + GpuCustomShuffleReaderExec, "
    "GpuOverrides.scala:1920)").boolean_conf.create_with_default(True)

ADAPTIVE_MIN_PARTITION_BYTES = _conf(
    "spark.rapids.tpu.sql.adaptive.coalescePartitions.minPartitionSize").doc(
    "Target minimum bytes per post-shuffle partition when adaptive "
    "coalescing merges small ones (ref: spark.sql.adaptive."
    "coalescePartitions.minPartitionSize)"
).bytes_conf.create_with_default(8 * 1024 * 1024)

SKEW_JOIN_THRESHOLD = _conf(
    "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionThreshold").doc(
    "A shuffled join's stream-side reduce partition larger than this many "
    "observed bytes splits into mapper-subset tasks, each joined against "
    "the same (shared) build partition (ref: spark.sql.adaptive.skewJoin."
    "skewedPartitionThresholdInBytes + partial-mapper partition specs, "
    "ShuffledBatchRDD.scala:202). 0 disables skew splitting."
).bytes_conf.create_with_default(256 * 1024 * 1024)

ADAPTIVE_COALESCE_ENABLED = _conf(
    "spark.rapids.tpu.sql.adaptive.coalescePartitions.enabled").doc(
    "AQE rule toggle (plan/aqe.py, docs/aqe.md): merge small post-shuffle "
    "partitions up to coalescePartitions.minPartitionSize from observed "
    "map-side sizes. Subordinate to adaptive.enabled (ref: spark.sql."
    "adaptive.coalescePartitions.enabled)"
).boolean_conf.create_with_default(True)

ADAPTIVE_SKEW_JOIN_ENABLED = _conf(
    "spark.rapids.tpu.sql.adaptive.skewJoin.enabled").doc(
    "AQE rule toggle (plan/aqe.py, docs/aqe.md): split a shuffled join's "
    "oversized stream partitions into mapper-subset tasks at runtime. "
    "Subordinate to adaptive.enabled (ref: spark.sql.adaptive.skewJoin."
    "enabled)").boolean_conf.create_with_default(True)

ADAPTIVE_SKEW_FACTOR = _conf(
    "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor").doc(
    "A partition is skewed when its observed bytes exceed BOTH "
    "skewedPartitionThreshold and this factor times the median partition "
    "bytes of its exchange — the relative half of the skew test, so one "
    "uniformly-large shuffle does not split everything (ref: spark.sql."
    "adaptive.skewJoin.skewedPartitionFactor)").double_conf.check(
        lambda v: float(v) >= 1.0).create_with_default(5.0)

ADAPTIVE_JOIN_SWITCH_ENABLED = _conf(
    "spark.rapids.tpu.sql.adaptive.joinSwitch.enabled").doc(
    "AQE rule toggle (plan/aqe.py, docs/aqe.md): switch join strategy from "
    "observed build-side size — promote shuffled->broadcast when the "
    "materialized build lands at or under autoBroadcastJoinThreshold, "
    "demote broadcast->shuffled when it lands over threshold x "
    "joinSwitch.demoteFactor. Subordinate to adaptive.enabled"
).boolean_conf.create_with_default(True)

ADAPTIVE_JOIN_DEMOTE_FACTOR = _conf(
    "spark.rapids.tpu.sql.adaptive.joinSwitch.demoteFactor").doc(
    "Hysteresis band of the AQE join-strategy switch: a planned broadcast "
    "only demotes to a shuffled join when its observed device bytes exceed "
    "autoBroadcastJoinThreshold times this factor, and a shuffled join "
    "only promotes at or under the bare threshold — observed sizes inside "
    "(threshold, threshold*factor] change nothing, so a borderline build "
    "side cannot flap between strategies across repeat executions"
).double_conf.check(lambda v: float(v) >= 1.0).create_with_default(2.0)

ADAPTIVE_FEEDBACK_ENABLED = _conf(
    "spark.rapids.tpu.sql.adaptive.feedback.enabled").doc(
    "AQE rule toggle (plan/aqe.py, docs/aqe.md): fold observed per-node "
    "actual row counts back into est_rows on the next execution of the "
    "same plan fingerprint, so plan-cache repeat queries estimate from "
    "observed cardinalities instead of the static selectivity heuristics "
    "(the cardinality-feedback loop over plan/estimates.py drift). "
    "Subordinate to adaptive.enabled").boolean_conf.create_with_default(True)

AUTO_BROADCAST_JOIN_THRESHOLD = _conf(
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold").doc(
    "Build sides at or under this many bytes broadcast (materialize once, "
    "reused across stream partitions); larger builds co-partition both sides "
    "through a hash exchange (ref: spark.sql.autoBroadcastJoinThreshold + "
    "GpuBroadcastExchangeExec.scala:47). -1 disables broadcast."
).bytes_conf.create_with_default(10 * 1024 * 1024)

REPLACE_SORT_MERGE_JOIN = _conf("spark.rapids.tpu.sql.replaceHashJoin.enabled").doc(
    "Replace hash joins with TPU sort-merge joins (inverse of the reference's "
    "spark.rapids.sql.replaceSortMergeJoin.enabled, RapidsConf.scala:450 — TPU prefers "
    "sort-based joins)").boolean_conf.create_with_default(True)

IMPROVED_TIME_OPS = _conf("spark.rapids.tpu.sql.improvedTimeOps.enabled").doc(
    "Enable full-range timestamp parsing ops that may differ from CPU "
    "(ref: spark.rapids.sql.improvedTimeOps.enabled)").boolean_conf.create_with_default(False)

CAST_FLOAT_TO_STRING = _conf("spark.rapids.tpu.sql.castFloatToString.enabled").doc(
    "Enable float->string casts (formatting differs in corner cases; "
    "ref: spark.rapids.sql.castFloatToString.enabled)").boolean_conf.create_with_default(False)

CAST_STRING_TO_FLOAT = _conf("spark.rapids.tpu.sql.castStringToFloat.enabled").doc(
    "Enable string->float casts (ref: spark.rapids.sql.castStringToFloat.enabled)"
).boolean_conf.create_with_default(False)

CAST_STRING_TO_TIMESTAMP = _conf("spark.rapids.tpu.sql.castStringToTimestamp.enabled").doc(
    "Enable string->timestamp casts (ref: spark.rapids.sql.castStringToTimestamp.enabled)"
).boolean_conf.create_with_default(False)

MAX_STRING_BYTES = _conf("spark.rapids.tpu.sql.maxStringBytes").doc(
    "Maximum padded width of a device string column; wider data falls back to CPU "
    "(TPU-specific: strings are fixed-width padded byte matrices, see DESIGN.md §4)"
).integer_conf.create_with_default(1024)

WHOLESTAGE_FUSION = _conf("spark.rapids.tpu.sql.wholeStageFusion.enabled").doc(
    "MASTER fusion switch: per-operator fused programs (FusedStage and "
    "the fused aggregate phases) into single XLA computations "
    "(TPU-specific; see DESIGN.md §2). Off also disables the stage-level "
    "compiler gated by fusion.wholeStage"
).boolean_conf.create_with_default(True)

FUSION_WHOLE_STAGE = _conf("spark.rapids.tpu.sql.fusion.wholeStage").doc(
    "STAGE-level fusion (plan/stage_compiler.py, docs/fusion.md): compile "
    "a pipeline-breaker-free operator CHAIN (scan-unpack -> filter -> "
    "project -> partial-agg) into ONE fused program per stage instead of "
    "one per operator — the whole-stage-codegen analog (SURVEY §3.3). "
    "Off falls back to the per-OPERATOR fused path, which stays governed "
    "by the master switch wholeStageFusion.enabled; per-node decline "
    "reasons surface in EXPLAIN ANALYZE either way"
).boolean_conf.create_with_default(True)

SCAN_PREFETCH_THREADS = _conf("spark.rapids.tpu.sql.scan.prefetchThreads").doc(
    "CPU decode/prefetch threads for the streaming file scan "
    "(io/scan.py): background threads named tpu-scan-prefetch-N read, "
    "decode and stage batches ahead of device upload, overlapping host "
    "decode with device compute; joined with a bounded timeout on "
    "shutdown (the transport-thread discipline)"
).integer_conf.check(lambda v: int(v) >= 1).create_with_default(4)

BATCH_AUTOTUNE = _conf("spark.rapids.tpu.sql.batch.autotune").doc(
    "Autotune the scan/coalesce target batch rows from the device HBM "
    "budget and the live device watermark (service/telemetry): fused "
    "stages run at the largest safe batch — "
    "min(batchSizeBytes, available-HBM share) / row bytes, quantized to "
    "a power of two (plan/stage_compiler.tuned_batch_rows, "
    "docs/fusion.md §4). An explicitly-set reader.batchSizeRows stays a "
    "hard cap; off reproduces the legacy bytes-derived target"
).boolean_conf.create_with_default(True)

BATCH_AUTOTUNE_MAX_ROWS = _conf(
    "spark.rapids.tpu.sql.batch.autotuneMaxRows").doc(
    "Ceiling on the autotuned rows-per-batch pick (fused programs "
    "compile per capacity bucket; this bounds worst-case compile shapes "
    "and per-batch HBM)"
).integer_conf.check(lambda v: int(v) >= (1 << 14)
                     ).create_with_default(1 << 23)

TEST_CONF = _conf("spark.rapids.tpu.sql.test.enabled").doc(
    "Test mode: assert everything that should be on TPU is on TPU "
    "(ref: spark.rapids.sql.test.enabled / assertIsOnTheGpu, "
    "GpuTransitionOverrides.scala:311-367)").internal().boolean_conf.create_with_default(False)

TEST_ALLOWED_NON_TPU = _conf("spark.rapids.tpu.sql.test.allowedNonTpu").doc(
    "Comma-separated exec/expr class names allowed on CPU in test mode "
    "(ref: spark.rapids.sql.test.allowedNonGpu)").internal().string_conf.create_with_default("")

METRICS_ENABLED = _conf("spark.rapids.tpu.sql.metrics.enabled").doc(
    "Collect per-operator metrics (ref: SQLMetrics/GpuMetricNames, GpuExec.scala:27-56)"
).boolean_conf.create_with_default(True)

TRACING_ENABLED = _conf("spark.rapids.tpu.sql.tracing.enabled").doc(
    "Wrap hot regions in jax profiler TraceAnnotations (ref: NVTX ranges, "
    "NvtxWithMetrics.scala:27)").boolean_conf.create_with_default(False)

TRACING_TIMELINE = _conf("spark.rapids.tpu.sql.tracing.timeline").doc(
    "Record every trace span's begin/end with its thread and export a "
    "Chrome-trace/Perfetto timeline per query "
    "(SpanRecorder.chrome_trace; the bench runner dumps trace.json per "
    "query — open in chrome://tracing or ui.perfetto.dev, see "
    "docs/observability.md)").boolean_conf.create_with_default(False)

READER_TYPE = _conf("spark.rapids.tpu.sql.format.parquet.reader.type").doc(
    "Parquet reader strategy: PERFILE, COALESCING, MULTITHREADED "
    "(ref: spark.rapids.sql.format.parquet.reader.type, RapidsConf.scala:510)"
).string_conf.check(lambda v: v in ("PERFILE", "COALESCING", "MULTITHREADED")
                    ).create_with_default("COALESCING")

MESH_ENABLED = _conf("spark.rapids.tpu.sql.mesh.enabled").doc(
    "SPMD execution over a jax device mesh: 'auto' (multi-device accelerator "
    "platforms), 'true' (force, incl. virtual CPU meshes for tests), 'false'. "
    "Routes supported group-by/join/sort plans through fused all_to_all "
    "pipelines (parallel/mesh.py) instead of the host exchange"
).string_conf.check(
    lambda v: str(v).lower() in ("auto", "true", "false", "1", "0")
).create_with_default("auto")

MESH_MAX_STAGE_BYTES = _conf("spark.rapids.tpu.sql.mesh.maxStageBytes").doc(
    "Upper bound on the estimated input size of a SINGLE-SHOT mesh stage "
    "(whole input staged at once, receive windows workers*cap). "
    "Fixed-width group-bys above this stream in bounded multi-round "
    "windows instead (mesh.streamWindowRows); var-width stages keep the "
    "spillable host exchange path"
).bytes_conf.create_with_default(2 * 1024 * 1024 * 1024)

MESH_STREAM_WINDOW_ROWS = _conf(
    "spark.rapids.tpu.sql.mesh.streamWindowRows").doc(
    "Rows per worker per round for the STREAMING mesh group-by (stages "
    "above mesh.maxStageBytes): per-round residency is "
    "O(workers x window) input plus the group accumulator, the analog of "
    "the reference's windowed shuffle transfers "
    "(WindowedBlockIterator.scala)"
).integer_conf.check(lambda v: int(v) >= 1024).create_with_default(1 << 17)

MATMUL_AGG = _conf("spark.rapids.tpu.sql.agg.matmul.enabled").doc(
    "MXU one-hot-matmul segment reductions for group-by sum/count/avg: "
    "'auto' (accelerator only), 'true', or 'false'. Float sums differ from "
    "sequential order at ~1e-5 rel — the variableFloatAgg trade "
    "(ref: RapidsConf.scala variableFloatAgg)").string_conf.create_with_default("auto")

HASH_OPTIMIZE_SORT = _conf("spark.rapids.tpu.sql.hashOptimizeSort.enabled").doc(
    "Insert a per-partition sort on hash-aggregate/join outputs so "
    "downstream file writes compress better (ref: "
    "spark.rapids.sql.hashOptimizeSort.enabled, "
    "GpuTransitionOverrides.scala:268-304)"
).boolean_conf.create_with_default(False)

AGG_PIPELINE_DEPTH = _conf("spark.rapids.tpu.sql.agg.pipelineDepth").doc(
    "Input batches kept in flight by the streaming aggregation before the "
    "oldest batch's partial result is landed: probe-stat readbacks overlap "
    "device compute across this window, hiding dispatch/link latency "
    "(dominant on tunneled or remote devices). The oldest half of the "
    "window lands when it fills, so stat transfers get half a window of "
    "dispatch work to hide behind. Device residency grows by one input "
    "batch per slot"
).integer_conf.check(lambda v: int(v) >= 1).create_with_default(48)

JOIN_PIPELINE_DEPTH = _conf("spark.rapids.tpu.sql.join.pipelineDepth").doc(
    "Stream batches whose join-output sizing scalars are kept in flight "
    "before the oldest batch's gather is dispatched: the per-batch "
    "device->host size readback (a full link round trip) resolves in ONE "
    "batched read per half-window instead of one blocking read per batch, "
    "making join-path host syncs O(1) per stage. 1 degenerates to "
    "read-per-batch. Device residency grows by one stream batch's match "
    "state per slot"
).integer_conf.check(lambda v: int(v) >= 1).create_with_default(16)

READER_THREADS = _conf("spark.rapids.tpu.sql.format.parquet.multiThreadedRead.numThreads").doc(
    "Background decode threads for the MULTITHREADED reader "
    "(ref: RapidsConf.scala:548)").integer_conf.create_with_default(4)

ANALYSIS_VALIDATE_PLAN = _conf("spark.rapids.tpu.sql.analysis.validatePlan").doc(
    "Plan-contract validation mode: off, warn (default; violations append "
    "to the explain output and log once), error (reject the plan with a "
    "diagnostic). Runs after conversion, before execution: parent/child "
    "schema+dtype agreement, exchange distribution invariants, and "
    "conversion-vs-tagging consistency (analysis/contracts.py; see "
    "docs/analysis.md)").string_conf.check(
        lambda v: str(v).lower() in ("off", "warn", "error")
).create_with_default("warn")

ANALYSIS_SYNC_AUDIT = _conf("spark.rapids.tpu.sql.analysis.syncAudit").doc(
    "Runtime sync audit: off, log, disallow — arms jax.transfer_guard "
    "(device->host) around partition-drain task regions so implicit host "
    "materializations in operator hot paths are logged or rejected on "
    "real accelerators; explicit batched resolves (jax.device_get) stay "
    "legal (analysis/sync_audit.py)").string_conf.check(
        lambda v: str(v).lower() in ("off", "log", "disallow")
).create_with_default("off")

ANALYSIS_DIVERGENCE = _conf("spark.rapids.tpu.sql.analysis.divergence").doc(
    "Cross-worker lockstep divergence audit: off, record, enforce. Each "
    "worker folds its lockstep-relevant event stream (shuffle-id mints, "
    "exchange fingerprints, stage-id draws, AQE decisions) into a "
    "per-query rolling digest carried on the shuffle metadata round "
    "trip; a mismatch names the FIRST divergent event. record logs, "
    "flight-records and counts (tpu_desync_total); enforce raises a "
    "typed DesyncError the recovery ladder maps to fail-query — a "
    "desync is never retried (analysis/divergence.py, docs/analysis.md "
    "§6)").string_conf.check(
        lambda v: str(v).lower() in ("off", "record", "enforce")
).create_with_default("off")

ANALYSIS_BUFFER_LEDGER = _conf(
    "spark.rapids.tpu.sql.analysis.bufferLedger").doc(
    "Runtime buffer-lifecycle ledger: off, record, enforce. Tags every "
    "catalog register/acquire/tier-move/donate/free with the ambient "
    "query id + allocation site; an end-of-query residency audit flags "
    "buffers the query minted that are still device-resident and not "
    "cache/durable-owned as leaks, and freed/donated buffers are "
    "tombstoned so later access diagnoses instead of reading garbage. "
    "record logs, flight-records and counts (tpu_buffer_leaks_total, "
    "tpu_use_after_free_total); enforce raises typed BufferLeakError / "
    "UseAfterFreeError / UseAfterDonateError with mint/free sites "
    "(analysis/ledger.py, docs/analysis.md §7)").string_conf.check(
        lambda v: str(v).lower() in ("off", "record", "enforce")
).create_with_default("off")

ANALYSIS_RECOMPILE_AUDIT = _conf(
    "spark.rapids.tpu.sql.analysis.recompileAudit").doc(
    "Track distinct compiled signatures per fused kernel and flag "
    "operators compiling once per batch shape (missed capacity-bucket "
    "padding); the bench runner reports per-query deltas "
    "(analysis/recompile.py)").boolean_conf.create_with_default(True)

COMPILE_CACHE_DIR = _conf("spark.rapids.tpu.sql.compile.cacheDir").doc(
    "Directory for the persistent (on-disk) XLA compilation cache plus "
    "the engine's fused-program signature index: a fresh process serving "
    "query shapes it has served before loads compiled executables from "
    "disk instead of paying seconds of cold compile per shape (session "
    "bootstrap wires jax.config.jax_compilation_cache_dir; the recompile "
    "audit then splits builds into cold builds vs disk hits with compile "
    "seconds per kernel family). Empty disables; an unusable directory "
    "logs a loud warning and degrades to in-memory caching, never a "
    "query failure (exec/compile_cache.py, docs/compile.md)"
).string_conf.create_with_default("")

COMPILE_DONATE = _conf("spark.rapids.tpu.sql.compile.donate").doc(
    "Donate consumed batch columns to the fused programs that ingest "
    "them (jax donate_argnums): XLA may reuse the input HBM for outputs "
    "and frees donated buffers the moment the program consumes them, "
    "lowering peak device bytes on multi-operator pipelines by ~one "
    "batch per stage. Spill-store-registered and scan-cache-served "
    "batches are never donated — their arrays are re-read through the "
    "catalog (docs/compile.md)").boolean_conf.create_with_default(True)

COMPILE_ASYNC = _conf("spark.rapids.tpu.sql.compile.async.enabled").doc(
    "Background compilation of fused-stage programs (exec/compile_pool.py, "
    "docs/compile.md §5): a cold stage build requested from a latency-"
    "sensitive context (a streaming collect_iter, or a service query whose "
    "deadline cannot absorb the build — see compile.async.deadlineSlackS) "
    "is submitted to a bounded worker pool and the stage serves batches "
    "through its per-op eager path until the compiled program is ready, "
    "swapping in at the next batch boundary. Plain batch collects keep "
    "the synchronous build path unchanged").boolean_conf.create_with_default(True)

COMPILE_ASYNC_WORKERS = _conf("spark.rapids.tpu.sql.compile.async.workers").doc(
    "Compile-pool worker threads shared by async stage builds and "
    "prewarm (query-triggered builds always outrank prewarm in the "
    "pool's priority queue)").integer_conf.check(
        lambda v: int(v) >= 1).create_with_default(2)

COMPILE_ASYNC_DEADLINE_SLACK_S = _conf(
    "spark.rapids.tpu.sql.compile.async.deadlineSlackS").doc(
    "Deadline-aware compile policy (docs/service.md): a query running "
    "under a service deadline keeps a cold stage build OFF its own "
    "thread — routing it to the compile pool and staying on the eager "
    "path — whenever less than this many seconds remain before the "
    "deadline. With more slack than this the query compiles "
    "synchronously (the build amortizes; eager would burn the slack "
    "anyway)").double_conf.check(
        lambda v: float(v) >= 0.0).create_with_default(5.0)

COMPILE_PREWARM = _conf("spark.rapids.tpu.sql.compile.prewarm.enabled").doc(
    "Compile the hottest persisted stage signatures on the compile pool "
    "at session bootstrap, before traffic arrives (docs/compile.md §5): "
    "reads the prewarm corpus recorded beside the signature index in "
    "compile.cacheDir, so a restarted replica serves its first query "
    "warm. No-op without a cache dir. Off by default — enable per "
    "replica, via tools/prewarm, or benchmarks.runner --prewarm"
).boolean_conf.create_with_default(False)

COMPILE_PREWARM_TOP_N = _conf("spark.rapids.tpu.sql.compile.prewarm.topN").doc(
    "How many of the hottest recorded stage signatures prewarm builds "
    "(hotness = times a signature was built or rebuilt across recorded "
    "processes)").integer_conf.check(
        lambda v: int(v) >= 1).create_with_default(32)

ADAPTIVE_FEEDBACK_CHECKPOINT = _conf(
    "spark.rapids.tpu.sql.adaptive.feedback.checkpoint").doc(
    "Persist the AQE cardinality-feedback bank (docs/aqe.md rule 4) as "
    "JSONL beside the compile-cache signature index and reload it at "
    "session bootstrap, so plan-cache repeats in a fresh process plan "
    "from observed actuals instead of re-learning them. No-op without "
    "compile.cacheDir; torn tail lines are skipped on load"
).boolean_conf.create_with_default(True)

PLAN_CACHE_ENABLED = _conf("spark.rapids.tpu.sql.planCache.enabled").doc(
    "Parameterized-plan cache (the serving front door, "
    "docs/plan_cache.md): eligible literals in WHERE/SELECT expressions "
    "extract into runtime parameters, and plans of the same normalized "
    "fingerprint reuse one analyzed/optimized/contract-validated/"
    "stage-compiled exec tree — and the SAME compiled program "
    "signatures — across executions with different literal values. "
    "``session.prepare(sql)`` plans once / executes many; plain "
    "``session.sql()`` hits the cache transparently. Plans carrying "
    "writes, nondeterministic expressions or unkeyable attributes are "
    "served the classic way").boolean_conf.create_with_default(True)

PLAN_CACHE_MAX_ENTRIES = _conf(
    "spark.rapids.tpu.sql.planCache.maxEntries").doc(
    "LRU bound on cached parameterized plans per session (each entry "
    "pins its exec tree and the fused stage programs it references; the "
    "JIT map-pressure relief valve drops all plan caches under mapping "
    "pressure)").integer_conf.check(
        lambda v: int(v) >= 1).create_with_default(64)

RESULT_CACHE_ENABLED = _conf("spark.rapids.tpu.sql.resultCache.enabled").doc(
    "Result cache for exact-repeat queries (docs/plan_cache.md): "
    "executions keyed by (plan fingerprint, parameter values, input "
    "snapshot) short-circuit BEFORE the planner and serve the stored "
    "host-resident result. Snapshots ride the scan data's ownership "
    "tokens (entries invalidate when the base table dies or a file's "
    "mtime/size changes). Off by default: a served result skips "
    "execution, so per-query spans/metrics reflect the original run"
).boolean_conf.create_with_default(False)

RESULT_CACHE_MAX_BYTES = _conf(
    "spark.rapids.tpu.sql.resultCache.maxBytes").doc(
    "Host-memory bound on the per-session result cache (LRU evicts "
    "past it)").bytes_conf.create_with_default(256 * 1024 * 1024)

RESULT_CACHE_MAX_ENTRY_BYTES = _conf(
    "spark.rapids.tpu.sql.resultCache.maxEntryBytes").doc(
    "Largest single result the cache will store; bigger results are "
    "served normally and never cached (serving-shaped results are "
    "small — a huge analytical result would evict everything else)"
).bytes_conf.create_with_default(32 * 1024 * 1024)

ANALYSIS_LOCKDEP = _conf("spark.rapids.tpu.sql.analysis.lockdep").doc(
    "Runtime lock-order tracking over the engine's named locks "
    "(analysis/lockdep.py): off, record (build the lock-order graph, log "
    "order-inversion cycles and lock-held-across-host-transfer findings, "
    "accumulate per-lock wait/hold stats attributed to trace spans — the "
    "tests/bench default), enforce (raise LockOrderInversionError / "
    "LockHeldAcrossTransferError at the offending acquisition, with both "
    "acquisition stacks)").string_conf.check(
        lambda v: str(v).lower() in ("off", "record", "enforce")
).create_with_default("off")

TELEMETRY_PORT = _conf("spark.rapids.tpu.sql.telemetry.port").doc(
    "Port for the background telemetry scrape endpoint serving /metrics "
    "(Prometheus text) and /snapshot (JSON) from the process metrics "
    "registry (service/telemetry.py; the live-Spark-UI metrics-stream "
    "analog). 0 disables the endpoint"
).integer_conf.create_with_default(0)

TELEMETRY_FLIGHT_RECORDER = _conf(
    "spark.rapids.tpu.sql.telemetry.flightRecorder").doc(
    "Always-on flight recorder: a fixed-size ring of recent span ends, "
    "sync/recompile/spill/lock incidents and conf changes, dumped to a "
    "JSON artifact automatically when a task body or collect() raises "
    "(service/telemetry.FlightRecorder; see docs/telemetry.md)"
).boolean_conf.create_with_default(True)

TELEMETRY_FLIGHT_DIR = _conf(
    "spark.rapids.tpu.sql.telemetry.flightRecorderDir").doc(
    "Directory for automatic flight-recorder dump artifacts (created on "
    "demand; a failed dump never masks the query exception)"
).string_conf.create_with_default("/tmp/spark_rapids_tpu_flight")

TELEMETRY_FLIGHT_EVENTS = _conf(
    "spark.rapids.tpu.sql.telemetry.flightRecorderEvents").doc(
    "Capacity of the flight-recorder ring; the newest events win"
).integer_conf.check(lambda v: int(v) >= 16).create_with_default(4096)

TELEMETRY_QUERY_LOG_DIR = _conf(
    "spark.rapids.tpu.sql.telemetry.queryLog.dir").doc(
    "Opt-in structured query log (service/query_log.py, "
    "docs/observability.md §8): one JSONL record per executed query — "
    "query id, plan fingerprint, cache verdicts, per-stage exchange "
    "statistics and wall, stage retries, faults fired, shuffle plane "
    "bytes, HBM peak operator, drift flags, top operators — appended to "
    "<dir>/query_log-<pid>.jsonl (render with python -m "
    "tools.query_report). Empty disables the log"
).string_conf.create_with_default("")

SERVICE_MAX_CONCURRENT = _conf(
    "spark.rapids.tpu.sql.service.maxConcurrentQueries").doc(
    "Worker threads of the multi-tenant query service "
    "(service/server.QueryService): the number of admitted queries "
    "executing concurrently against the shared engine. Layered ABOVE "
    "concurrentTpuTasks — the TpuSemaphore still bounds how many of "
    "those queries' tasks hold the device at once (docs/service.md)"
).integer_conf.check(lambda v: int(v) >= 1).create_with_default(4)

SERVICE_DEFAULT_SLOTS = _conf(
    "spark.rapids.tpu.sql.service.defaultTenantSlots").doc(
    "Concurrent queries ONE tenant may occupy in the service pool when "
    "its TenantSpec does not set slots explicitly (the per-tenant "
    "concurrency bound of docs/service.md §2)"
).integer_conf.check(lambda v: int(v) >= 1).create_with_default(2)

SERVICE_DEFAULT_QUEUE_DEPTH = _conf(
    "spark.rapids.tpu.sql.service.defaultTenantQueueDepth").doc(
    "Queued (not yet running) queries one tenant may hold before the "
    "service load-sheds further submissions with a typed "
    "AdmissionRejected (default for TenantSpecs without an explicit "
    "max_queue_depth; docs/service.md §2)"
).integer_conf.check(lambda v: int(v) >= 1).create_with_default(16)

SERVICE_DEFAULT_MEMORY_BYTES = _conf(
    "spark.rapids.tpu.sql.service.defaultTenantMemoryBytes").doc(
    "Default per-tenant device-byte budget installed at tenant "
    "registration when the TenantSpec does not set one: a tenant "
    "holding more device bytes than its budget spills its OWN buffers "
    "first at reserve/register boundaries, and its buffers are the "
    "global cascade's first victims (docs/service.md §3). 0 = "
    "unbudgeted"
).bytes_conf.create_with_default(0)

SERVICE_ADMISSION_EXPENSIVE_BYTES = _conf(
    "spark.rapids.tpu.sql.service.admission.expensiveBytes").doc(
    "Observed-cost admission weighting (docs/service.md, plan/aqe.py): a "
    "plan fingerprint whose last execution shuffled more than this many "
    "total exchange bytes charges one extra queue-depth unit per multiple "
    "on its tenant's next admit — an observed-expensive repeat query "
    "consumes budget proportional to what it actually cost, not a flat "
    "1 unit. 0 disables cost weighting (every admit charges 1)"
).bytes_conf.create_with_default(0)

SERVICE_SCHEDULER_POLICY = _conf(
    "spark.rapids.tpu.sql.service.scheduler.policy").doc(
    "Queue discipline of the multi-tenant service (docs/service.md §4). "
    "'priority': strict (priority DESC, deadline, arrival) — a "
    "low-priority flood cannot starve a high-priority tenant, the "
    "converse is intended. 'wfq': weighted deficit round-robin over "
    "tenants (TenantSpec.weight shares) with preemption — a "
    "high-priority arrival finding every slot busy suspends the running "
    "query with the largest deficit instead of queueing behind it"
).string_conf.check(
    lambda v: str(v) in ("priority", "wfq")).create_with_default(
    "priority")

SERVICE_DEFAULT_TENANT_WEIGHT = _conf(
    "spark.rapids.tpu.sql.service.defaultTenantWeight").doc(
    "Weighted-fair share for TenantSpecs without an explicit weight "
    "under service.scheduler.policy=wfq: each scheduling round credits "
    "a tenant's deficit counter by its weight, and the eligible tenant "
    "with the largest deficit runs next (docs/service.md §4)"
).double_conf.check(lambda v: float(v) > 0).create_with_default(1.0)

SERVICE_SCHEDULER_PREEMPTION = _conf(
    "spark.rapids.tpu.sql.service.scheduler.preemption").doc(
    "Under the wfq policy, allow a strictly higher-priority arrival "
    "that finds all execution slots busy to SUSPEND the running query "
    "with the largest deficit (working set spilled via the tenant "
    "catalog, stage cursor parked, re-admitted on resume — "
    "docs/service.md §4b). Off: arrivals always queue"
).boolean_conf.create_with_default(True)

PARSE_CACHE_MAX_ENTRIES = _conf(
    "spark.rapids.tpu.sql.service.parseCache.maxEntries").doc(
    "LRU bound on the per-session SQL-text -> parsed-plan cache serving "
    "non-prepared session.sql() traffic ahead of the plan-cache "
    "fingerprint (docs/plan_cache.md): a repeated SQL string skips the "
    "lexer/parser entirely; hits/misses ride serving_stats() as "
    "parseCacheHits/parseCacheMisses. Entries key on the view identity "
    "snapshot, so re-registering a temp view invalidates naturally. "
    "0 disables"
).integer_conf.check(lambda v: int(v) >= 0).create_with_default(256)

OBSERVABILITY_DRIFT_THRESHOLD = _conf(
    "spark.rapids.tpu.sql.observability.driftThreshold").doc(
    "Estimate-vs-actual row drift ratio at which a plan node is flagged "
    "as a misestimate (plan/estimates.py; the cardinality-feedback "
    "groundwork): a node whose actual/estimated output rows ratio is "
    ">= this factor (or <= its inverse) lands in the per-query drift "
    "report (session.last_drift_report) and is marked '! drift' in "
    "EXPLAIN ANALYZE").double_conf.check(
        lambda v: float(v) > 1.0).create_with_default(4.0)


class TpuConf:
    """Immutable-ish view over a key->value dict with typed accessors.

    Analog of ``RapidsConf`` the *instance* (constructed per-session from the config map).
    """

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings: Dict[str, Any] = dict(settings or {})
        # Environment overrides (lower priority than explicit settings):
        # SPARK_RAPIDS_TPU_CONF__<KEY WITH DOTS AS __>, case-insensitive —
        # env names are uppercase so the parsed key is matched against the
        # registry ignoring case (registered keys are camelCase).
        lower_to_key = {e.key.lower(): e.key for e in REGISTRY.entries()}
        for env_key, env_val in os.environ.items():
            if env_key.startswith("SPARK_RAPIDS_TPU_CONF__"):
                raw = env_key[len("SPARK_RAPIDS_TPU_CONF__"):].replace("__", ".").lower()
                key = lower_to_key.get(raw, raw)
                self._settings.setdefault(key, env_val)

    def get(self, entry: ConfEntry) -> Any:
        raw = self._settings.get(entry.key, None)
        if raw is None:
            return entry.default
        return entry.convert(raw)

    def get_key(self, key: str, default: Any = None) -> Any:
        entry = REGISTRY.get_entry(key)
        if entry is not None:
            raw = self._settings.get(key)
            return entry.default if raw is None else entry.convert(raw)
        return self._settings.get(key, default)

    def set(self, key: str, value: Any) -> "TpuConf":
        self._settings[key] = value
        return self

    def with_overrides(self, overrides: Dict[str, Any]) -> "TpuConf":
        merged = dict(self._settings)
        merged.update(overrides)
        return TpuConf(merged)

    def is_operator_enabled(self, key: str, default: bool) -> bool:
        entry = REGISTRY.register_dynamic(key, "(per-operator enable key)", default)
        return self.get(entry)

    # Convenience typed properties used across the codebase ------------------
    @property
    def sql_enabled(self) -> bool: return self.get(SQL_ENABLED)
    @property
    def explain(self) -> str: return self.get(EXPLAIN)
    @property
    def incompatible_ops(self) -> bool: return self.get(INCOMPATIBLE_OPS)
    @property
    def has_nans(self) -> bool: return self.get(HAS_NANS)
    @property
    def batch_size_bytes(self) -> int: return self.get(BATCH_SIZE_BYTES)
    @property
    def concurrent_tpu_tasks(self) -> int: return self.get(CONCURRENT_TPU_TASKS)

    @property
    def task_pool_threads(self) -> int: return self.get(TASK_POOL_THREADS)
    @property
    def host_spill_storage_size(self) -> int: return self.get(HOST_SPILL_STORAGE_SIZE)
    @property
    def spill_dir(self) -> str: return self.get(SPILL_DIR)
    @property
    def shuffle_partitions(self) -> int: return self.get(SHUFFLE_PARTITIONS)
    @property
    def max_string_bytes(self) -> int: return self.get(MAX_STRING_BYTES)
    @property
    def wholestage_fusion(self) -> bool: return self.get(WHOLESTAGE_FUSION)
    @property
    def test_enabled(self) -> bool: return self.get(TEST_CONF)
    @property
    def test_allowed_non_tpu(self) -> List[str]:
        raw = self.get(TEST_ALLOWED_NON_TPU)
        return [s.strip() for s in raw.split(",") if s.strip()]
    @property
    def metrics_enabled(self) -> bool: return self.get(METRICS_ENABLED)
    @property
    def tracing_enabled(self) -> bool: return self.get(TRACING_ENABLED)
