"""CPU reference engine: executes logical plans on pandas.

Role (DESIGN.md §8): this is the "CPU Spark" side of the golden-compare
harness — the reference's correctness strategy runs every query on both CPU
Spark and the GPU plugin and diffs results (SparkQueryCompareTestSuite,
SURVEY.md §4). Being standalone, we supply the CPU side ourselves with an
independent pandas implementation; it doubles as the fallback executor for
operators tagged off the TPU (RapidsMeta.willNotWorkOnGpu analog).

Null model: object-dtype / float-NaN-free representation — every cell is a
python value or None, so SQL three-valued logic is explicit rather than
riding pandas NaN coercion.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from ..columnar import dtypes as dt
from ..ops import expressions as ex
from ..ops import arithmetic as ar
from ..ops import predicates as pr
from ..ops import conditionals as co
from ..ops import math_ops as mo
from ..ops import strings as st
from ..ops import datetime as dtime
from ..ops import hashing as hs
from ..ops.cast import Cast
from ..plan import logical as lp


def _cells(series_or_list) -> List[Any]:
    if isinstance(series_or_list, list):
        return series_or_list
    return list(series_or_list)


class CpuEvaluator:
    """Row-wise expression evaluator with Spark SQL semantics.

    ``schema`` (the plan child's Schema) resolves column refs by ORDINAL —
    post-join frames carry duplicate column names, where pandas ``df[name]``
    would return a frame instead of a series."""

    def __init__(self, df: pd.DataFrame, schema=None):
        self.df = df
        self.schema = schema
        self.n = len(df)

    def _col_by_name(self, name: str):
        if self.schema is not None and name in self.schema:
            return _cells(self.df.iloc[:, self.schema.index_of(name)])
        col = self.df[name]
        if isinstance(col, pd.DataFrame):   # duplicate names: first wins
            col = col.iloc[:, 0]
        return _cells(col)

    def eval(self, e: ex.Expression) -> List[Any]:
        out = self._eval(e)
        if not isinstance(out, list):
            out = [out] * self.n
        return out

    # -- dispatch ------------------------------------------------------------
    def _eval(self, e: ex.Expression):
        if isinstance(e, ex.Literal):
            return [e.value] * self.n
        if isinstance(e, st.RegExpReplaceHost):
            return e.apply_list(self._eval(e.children[0]))
        from ..ops.structs import GetField
        if isinstance(e, GetField):
            vals = self._eval(e.children[0])
            return [None if v is None else
                    (v.get(e.field) if isinstance(v, dict)
                     else getattr(v, e.field, None))
                    for v in vals]
        from ..ops.python_udf import PandasUDF
        if isinstance(e, PandasUDF):
            import pandas as pd
            series = [pd.Series(self._eval(c), dtype=object)
                      for c in e.children]
            out = e.fn(*series)
            if len(out) != self.n:        # same contract as the device path
                raise ValueError(
                    f"pandas UDF {e.udf_name!r} returned {len(out)} rows "
                    f"for {self.n} input rows")
            return [None if pd.isna(v) else v for v in out]
        from ..ops import arrays as ar_ops
        if isinstance(e, ar_ops.StringSplit):
            vals = self._eval(e.children[0])
            return [None if v is None else v.split(e.delimiter)
                    for v in vals]
        if isinstance(e, ar_ops.Size):
            vals = self._eval(e.children[0])
            # Spark 3.0 legacy sizeOfNull: size(NULL) = -1
            return [-1 if v is None else len(v) for v in vals]
        if isinstance(e, ar_ops.GetArrayItem):
            arrs = self._eval(e.children[0])
            idxs = self._eval(e.children[1])
            out = []
            for a, i in zip(arrs, idxs):
                if a is None or i is None:
                    out.append(None)
                    continue
                i = int(i)
                if getattr(e, "one_based", False):
                    if i == 0:
                        out.append(None)
                        continue
                    i = i - 1 if i > 0 else len(a) + i
                out.append(a[i] if 0 <= i < len(a) else None)
            return out
        from ..ops import maps as mp_ops

        def _as_map(o):
            # pandas materializes arrow map cells as lists of (k, v)
            # tuples; dict() also applies LAST_WIN dedup like the device
            return o if o is None or isinstance(o, dict) else dict(o)

        if isinstance(e, mp_ops.CreateMap):
            cols = [self._eval(c) for c in e.children]
            out = []
            for row in zip(*cols):
                ks, vs = row[0::2], row[1::2]
                # NULL key -> NULL map; duplicate keys: LAST_WIN
                out.append(None if any(k is None for k in ks)
                           else dict(zip(ks, vs)))
            return out
        if isinstance(e, mp_ops.GetMapValue):
            ms = [_as_map(m) for m in self._eval(e.children[0])]
            ks = self._eval(e.children[1])
            return [None if m is None or k is None else m.get(k)
                    for m, k in zip(ms, ks)]
        if isinstance(e, mp_ops.GetItem):
            from ..columnar import dtypes as _dt
            objs = self._eval(e.children[0])
            if _dt.is_map(e.children[0].dtype):
                objs = [_as_map(o) for o in objs]
            ks = self._eval(e.children[1])
            out = []
            for o, k in zip(objs, ks):
                if o is None or k is None:
                    out.append(None)
                elif isinstance(o, dict):
                    out.append(o.get(k))
                else:
                    i = int(k)
                    if e.one_based:
                        if i == 0:
                            out.append(None)
                            continue
                        i = i - 1 if i > 0 else len(o) + i
                    out.append(o[i] if 0 <= i < len(o) else None)
            return out
        if isinstance(e, mp_ops.MapKeys):
            ms = [_as_map(m) for m in self._eval(e.children[0])]
            return [None if m is None else list(m.keys()) for m in ms]
        if isinstance(e, mp_ops.MapValues):
            ms = [_as_map(m) for m in self._eval(e.children[0])]
            # NULL map values surface as NULL array elements (the device
            # array layout carries per-element validity)
            return [None if m is None else list(m.values()) for m in ms]
        if isinstance(e, ex.ColumnRef):
            return self._col_by_name(e.col_name)
        if isinstance(e, ex.BoundReference):
            return _cells(self.df.iloc[:, e.ordinal])
        if isinstance(e, ex.Alias):
            return self._eval(e.children[0])
        if isinstance(e, Cast):
            return self._cast(e)
        if isinstance(e, ar.BinaryArithmetic):
            return self._binary_arith(e)
        if isinstance(e, (ar.UnaryMinus, ar.UnaryPositive, ar.Abs)):
            return self._unary_arith(e)
        if isinstance(e, pr.EqualNullSafe):
            l, r = (self._eval(c) for c in e.children)
            return [_null_safe_eq(a, b) for a, b in zip(l, r)]
        if isinstance(e, pr.BinaryComparison):
            return self._comparison(e)
        if isinstance(e, pr.And):
            l, r = (self._eval(c) for c in e.children)
            return [_kleene_and(a, b) for a, b in zip(l, r)]
        if isinstance(e, pr.Or):
            l, r = (self._eval(c) for c in e.children)
            return [_kleene_or(a, b) for a, b in zip(l, r)]
        if isinstance(e, pr.Not):
            return [None if v is None else (not v)
                    for v in self._eval(e.children[0])]
        if isinstance(e, pr.IsNull):
            return [v is None for v in self._eval(e.children[0])]
        if isinstance(e, pr.IsNotNull):
            return [v is not None for v in self._eval(e.children[0])]
        if isinstance(e, pr.IsNaN):
            return [v is not None and isinstance(v, float) and math.isnan(v)
                    for v in self._eval(e.children[0])]
        if isinstance(e, pr.In):
            return self._in(e)
        if isinstance(e, co.If):
            c, t, f = (self._eval(x) for x in e.children)
            return [tv if (cv is True) else fv for cv, tv, fv in zip(c, t, f)]
        if isinstance(e, co.CaseWhen):
            return self._case_when(e)
        if isinstance(e, co.Coalesce):
            cols = [self._eval(c) for c in e.children]
            return [next((v for v in row if v is not None), None)
                    for row in zip(*cols)]
        if isinstance(e, co.NullIf):
            l, r = (self._eval(c) for c in e.children)
            return [None if (a is not None and b is not None and
                             _sql_eq(a, b)) else a for a, b in zip(l, r)]
        if isinstance(e, (co.Least, co.Greatest)):
            cols = [self._eval(c) for c in e.children]
            pick = min if isinstance(e, co.Least) else max
            out = []
            for row in zip(*cols):
                vals = [v for v in row if v is not None]
                out.append(pick(vals, key=_order_key) if vals else None)
            return out
        if isinstance(e, mo.UnaryMath):
            return self._unary_math(e)
        if isinstance(e, (mo.Floor, mo.Ceil)):
            f = math.floor if isinstance(e, mo.Floor) else math.ceil
            return [None if v is None else int(f(v))
                    for v in self._eval(e.children[0])]
        if isinstance(e, mo.Round):
            return self._round(e)
        if isinstance(e, mo.Pow):
            l, r = (self._eval(c) for c in e.children)
            return [None if a is None or b is None else float(a) ** float(b)
                    for a, b in zip(l, r)]
        if isinstance(e, mo.Atan2):
            l, r = (self._eval(c) for c in e.children)
            return [None if a is None or b is None else math.atan2(a, b)
                    for a, b in zip(l, r)]
        handler = _STRING_HANDLERS.get(type(e)) or _DATE_HANDLERS.get(type(e))
        if handler is not None:
            return handler(self, e)
        if isinstance(e, hs.Murmur3Hash):
            return self._murmur3(e)
        raise NotImplementedError(
            f"CPU engine: unsupported expression {type(e).__name__}")

    # -- numeric -------------------------------------------------------------
    def _binary_arith(self, e: ar.BinaryArithmetic):
        l, r = (self._eval(c) for c in e.children)
        t = e.dtype
        out = []
        for a, b in zip(l, r):
            if a is None or b is None:
                out.append(None)
                continue
            out.append(_arith_op(e, a, b, t))
        return out

    def _unary_arith(self, e):
        vals = self._eval(e.children[0])
        if isinstance(e, ar.UnaryPositive):
            return vals
        if isinstance(e, ar.UnaryMinus):
            return [None if v is None else _wrap_int(-v, e.dtype) for v in vals]
        return [None if v is None else _wrap_int(abs(v), e.dtype) for v in vals]

    def _comparison(self, e: pr.BinaryComparison):
        l, r = (self._eval(c) for c in e.children)
        op = type(e).__name__
        out = []
        for a, b in zip(l, r):
            if a is None or b is None:
                out.append(None)
                continue
            ka, kb = _order_key(a), _order_key(b)
            if op == "EqualTo":
                out.append(ka == kb)
            elif op == "NotEqual":
                out.append(ka != kb)
            elif op == "LessThan":
                out.append(ka < kb)
            elif op == "LessThanOrEqual":
                out.append(ka <= kb)
            elif op == "GreaterThan":
                out.append(ka > kb)
            else:
                out.append(ka >= kb)
        return out

    def _in(self, e: pr.In):
        vals = self._eval(e.children[0])
        has_null = any(x is None for x in e.values)
        concrete = [x for x in e.values if x is not None]
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            elif any(_sql_eq(v, x) for x in concrete):
                out.append(True)
            else:
                out.append(None if has_null else False)
        return out

    def _case_when(self, e: co.CaseWhen):
        n = self.n
        result = self._eval(e.children[-1]) if e.has_else else [None] * n
        decided = [False] * n
        out = list(result)
        for i in range(e.num_branches):
            conds = self._eval(e.children[2 * i])
            vals = self._eval(e.children[2 * i + 1])
            for j in range(n):
                if not decided[j] and conds[j] is True:
                    out[j] = vals[j]
                    decided[j] = True
        return out

    def _unary_math(self, e: mo.UnaryMath):
        vals = self._eval(e.children[0])
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            try:
                r = e.pyfn(float(v)) if e.pyfn else None
                if r is None:
                    raise ValueError
            except (ValueError, OverflowError, ZeroDivisionError):
                r = None
            out.append(r)
        return out

    def _round(self, e: mo.Round):
        from decimal import Decimal, ROUND_HALF_UP
        vals = self._eval(e.children[0])
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            elif isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
                out.append(v)
            else:
                q = Decimal(10) ** -e.scale
                r = float(Decimal(str(v)).quantize(q, rounding=ROUND_HALF_UP))
                out.append(r if e.dtype.is_floating else int(r))
        return out

    def _cast(self, e: Cast):
        vals = self._eval(e.children[0])
        src, dst = e.children[0].dtype, e.to
        return [_cast_value(v, src, dst) for v in vals]

    def _murmur3(self, e: hs.Murmur3Hash):
        cols = [self._eval(c) for c in e.children]
        types = [c.dtype for c in e.children]
        out = []
        for row in zip(*cols):
            h = e.seed
            for v, t in zip(row, types):
                h = _murmur3_value(v, t, h)
            out.append(h - (1 << 32) if h >= 1 << 31 else h)
        return out


# -- value helpers -----------------------------------------------------------

def _order_key(v):
    """Total-order key: NaN sorts greater than everything (Spark)."""
    if isinstance(v, float) and math.isnan(v):
        return (1, 0.0)
    if isinstance(v, bool):
        return (0, int(v))
    if isinstance(v, str):
        return (0, v.encode("utf-8"))
    return (0, v)


def _sql_eq(a, b) -> bool:
    if isinstance(a, float) and isinstance(b, float) and \
            math.isnan(a) and math.isnan(b):
        return True
    if isinstance(a, str) != isinstance(b, str):
        return False
    return a == b


def _null_safe_eq(a, b):
    if a is None or b is None:
        return a is None and b is None
    return _sql_eq(a, b)


def _kleene_and(a, b):
    if a is False or b is False:
        return False
    if a is None or b is None:
        return None
    return True


def _kleene_or(a, b):
    if a is True or b is True:
        return True
    if a is None or b is None:
        return None
    return False


_INT_BITS = {dt.INT8: 8, dt.INT16: 16, dt.INT32: 32, dt.INT64: 64}


def _wrap_int(v, t: dt.DType):
    bits = _INT_BITS.get(t)
    if bits is None or not isinstance(v, int):
        return v
    m = 1 << bits
    v &= m - 1
    return v - m if v >= m >> 1 else v


def _arith_op(e, a, b, t: dt.DType):
    if isinstance(e, ar.Add):
        return _wrap_int(a + b, t)
    if isinstance(e, ar.Subtract):
        return _wrap_int(a - b, t)
    if isinstance(e, ar.Multiply):
        return _wrap_int(a * b, t)
    if isinstance(e, ar.Divide):
        if b == 0:
            return None
        return a / b
    if isinstance(e, ar.IntegralDivide):
        if b == 0:
            return None
        return _wrap_int(int(_java_mod_div(a, b)), dt.INT64)
    if isinstance(e, ar.Remainder):
        if b == 0:
            return None
        if t.is_floating:
            return math.fmod(a, b)
        return _wrap_int(int(math.fmod(a, b)), t)
    if isinstance(e, ar.Pmod):
        if b == 0:
            return None
        if t.is_floating:
            r = math.fmod(a, b)
            return r + abs(b) if r < 0 else r
        r = int(math.fmod(a, b))
        return _wrap_int(r + abs(b) if r < 0 else r, t)
    raise NotImplementedError(type(e).__name__)


def _java_mod_div(a, b):
    """Java integer division truncates toward zero."""
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def _cast_value(v, src: dt.DType, dst: dt.DType):
    if v is None:
        return None
    if src == dst:
        return v
    if dst == dt.STRING:
        if src == dt.BOOL:
            return "true" if v else "false"
        if src.is_floating:
            return repr(float(v))
        if src == dt.DATE:
            import datetime
            return (datetime.date(1970, 1, 1) +
                    datetime.timedelta(days=int(v))).isoformat()
        if src == dt.TIMESTAMP:
            import datetime
            base = datetime.datetime(1970, 1, 1) + \
                datetime.timedelta(microseconds=int(v))
            return base.strftime("%Y-%m-%d %H:%M:%S")
        return str(v)
    if src == dt.STRING:
        from ..ops.cast import _parse_value
        return _parse_value(v, dst)
    if dst == dt.BOOL:
        return v != 0
    if dst.is_integral:
        if src == dt.BOOL:
            return int(v)
        if src.is_floating:
            if math.isnan(v):
                return 0
            lo = -(1 << (_INT_BITS[dst] - 1))
            hi = (1 << (_INT_BITS[dst] - 1)) - 1
            return max(lo, min(hi, int(v)))
        return _wrap_int(int(v), dst)
    if dst.is_floating:
        return float(v)
    if dst == dt.DATE and src == dt.TIMESTAMP:
        return int(v // 86_400_000_000) if v >= 0 or v % 86_400_000_000 == 0 \
            else int(v // 86_400_000_000)
    if dst == dt.TIMESTAMP and src == dt.DATE:
        return int(v) * 86_400_000_000
    if dst == dt.TIMESTAMP and src.is_integral:
        return int(v) * 1_000_000
    if dst.is_integral and src == dt.TIMESTAMP:
        return _wrap_int(int(v // 1_000_000), dst)
    if dst == dt.DATE and src.is_integral:
        return _wrap_int(int(v), dt.INT32)   # day-number reinterpret
    if dst.is_integral and src == dt.DATE:
        return _wrap_int(int(v), dst)
    raise NotImplementedError(f"cpu cast {src} -> {dst}")


def _murmur3_value(v, t: dt.DType, seed: int) -> int:
    M = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & M

    def mixk1(k1):
        k1 = (k1 * 0xCC9E2D51) & M
        return (rotl(k1, 15) * 0x1B873593) & M

    def mixh1(h1, k1):
        h1 ^= k1
        return (rotl(h1, 13) * 5 + 0xE6546B64) & M

    def fmix(h1, ln):
        h1 ^= ln
        h1 ^= h1 >> 16
        h1 = (h1 * 0x85EBCA6B) & M
        h1 ^= h1 >> 13
        h1 = (h1 * 0xC2B2AE35) & M
        return h1 ^ (h1 >> 16)

    if v is None:
        return seed
    if t == dt.STRING:
        bs = v.encode("utf-8")
        h1 = seed
        n = len(bs)
        for i in range(0, n // 4 * 4, 4):
            k1 = bs[i] | bs[i + 1] << 8 | bs[i + 2] << 16 | bs[i + 3] << 24
            h1 = mixh1(h1, mixk1(k1))
        for i in range(n // 4 * 4, n):
            b = bs[i] - 256 if bs[i] >= 128 else bs[i]
            h1 = mixh1(h1, mixk1(b & M))
        return fmix(h1, n)
    if t in (dt.INT64, dt.TIMESTAMP):
        lv = int(v) & 0xFFFFFFFFFFFFFFFF
        h1 = mixh1(seed, mixk1(lv & M))
        h1 = mixh1(h1, mixk1((lv >> 32) & M))
        return fmix(h1, 8)
    if t == dt.FLOAT64:
        import struct
        x = 0.0 if v == 0.0 else float(v)
        bits = struct.unpack("<Q", struct.pack("<d", x))[0]
        h1 = mixh1(seed, mixk1(bits & M))
        h1 = mixh1(h1, mixk1((bits >> 32) & M))
        return fmix(h1, 8)
    if t == dt.FLOAT32:
        import struct
        x = 0.0 if v == 0.0 else float(np.float32(v))
        bits = struct.unpack("<I", struct.pack("<f", np.float32(x)))[0]
        return fmix(mixh1(seed, mixk1(bits)), 4)
    iv = int(v) & M
    return fmix(mixh1(seed, mixk1(iv)), 4)


# -- string / datetime handlers ---------------------------------------------

def _h_strings(method):
    def h(ev: CpuEvaluator, e):
        args = [ev._eval(c) for c in e.children]
        return method(ev, e, args)
    return h


def _str1(fn):
    def h(ev, e, args):
        return [None if v is None else fn(e, v) for v in args[0]]
    return h


_STRING_HANDLERS: Dict[type, Callable] = {
    st.Length: _h_strings(_str1(lambda e, v: len(v))),
    st.Upper: _h_strings(_str1(lambda e, v: _ascii_case(v, True))),
    st.Lower: _h_strings(_str1(lambda e, v: _ascii_case(v, False))),
    st.InitCap: _h_strings(_str1(
        lambda e, v: " ".join(w[:1].upper() + w[1:].lower() for w in v.split(" ")))),
    st.StringTrim: _h_strings(_str1(lambda e, v: v.strip(" "))),
    st.StringTrimLeft: _h_strings(_str1(lambda e, v: v.lstrip(" "))),
    st.StringTrimRight: _h_strings(_str1(lambda e, v: v.rstrip(" "))),
}


def _ascii_case(s: str, up: bool) -> str:
    out = []
    for ch in s:
        if up and "a" <= ch <= "z":
            out.append(chr(ord(ch) - 32))
        elif not up and "A" <= ch <= "Z":
            out.append(chr(ord(ch) + 32))
        else:
            out.append(ch)
    return "".join(out)


def _h_substring(ev, e):
    s, p, ln = (ev._eval(c) for c in e.children)
    out = []
    for v, pos, l in zip(s, p, ln):
        if v is None or pos is None or l is None:
            out.append(None)
            continue
        l = max(l, 0)
        if pos > 0:
            start = pos - 1
        elif pos < 0:
            start = max(len(v) + pos, 0)
        else:
            start = 0
        out.append(v[start:start + l])
    return out


def _h_concat(ev, e):
    cols = [ev._eval(c) for c in e.children]
    out = []
    for row in zip(*cols):
        if any(v is None for v in row):
            out.append(None)
        else:
            out.append("".join(str(v) for v in row))
    return out


def _h_pattern(ev, e):
    s = ev._eval(e.children[0])
    p = ev._eval(e.children[1])
    out = []
    for v, pat in zip(s, p):
        if v is None or pat is None:
            out.append(None)
        else:
            out.append(e._py(v, pat))
    return out


def _h_like(ev, e):
    s = ev._eval(e.children[0])
    return [None if v is None else st._like_py(v, e.pattern, e.escape) for v in s]


def _h_locate(ev, e):
    sub = e.children[0]
    s = ev._eval(e.children[1])
    start = ev._eval(e.children[2])
    out = []
    for v, sv in zip(s, start):
        if v is None or sub.value is None:
            out.append(None)
        else:
            sv = sv or 1
            out.append(0 if sv < 1 else v.find(str(sub.value), sv - 1) + 1)
    return out


def _h_replace(ev, e):
    s = ev._eval(e.children[0])
    return [None if v is None else v.replace(e.search, e.replacement) for v in s]


def _h_pad(ev, e):
    s = ev._eval(e.children[0])
    return [None if v is None else st._pad_py(v, e.width, e.pad, e._left)
            for v in s]


def _h_regexp(ev, e):
    import re
    rx = re.compile(e.pattern)
    s = ev._eval(e.children[0])
    out = []
    for v in s:
        if v is None:
            out.append(None)
        else:
            m = rx.search(v)
            out.append(m.group(e.group) if m else "")
    return out


_STRING_HANDLERS.update({
    st.Substring: _h_substring,
    st.ConcatStr: _h_concat,
    st.Contains: _h_pattern,
    st.StartsWith: _h_pattern,
    st.EndsWith: _h_pattern,
    st.Like: _h_like,
    st.StringLocate: _h_locate,
    st.StringReplace: _h_replace,
    st.StringLPad: _h_pad,
    st.StringRPad: _h_pad,
    st.RegExpExtractHost: _h_regexp,
})


def _date_parts(v, t: dt.DType):
    import datetime
    if t == dt.TIMESTAMP:
        days, rem = divmod(int(v), 86_400_000_000)
    else:
        days = int(v)
    return datetime.date(1970, 1, 1) + datetime.timedelta(days=days)


def _h_datepart(fn):
    def h(ev, e):
        t = e.children[0].dtype
        vals = ev._eval(e.children[0])
        return [None if v is None else fn(_date_parts(v, t), v, t) for v in vals]
    return h


def _time_of(v, t):
    sec = int(v) // 1_000_000
    return sec


_DATE_HANDLERS: Dict[type, Callable] = {
    dtime.Year: _h_datepart(lambda d, v, t: d.year),
    dtime.Month: _h_datepart(lambda d, v, t: d.month),
    dtime.DayOfMonth: _h_datepart(lambda d, v, t: d.day),
    dtime.Quarter: _h_datepart(lambda d, v, t: (d.month - 1) // 3 + 1),
    dtime.DayOfWeek: _h_datepart(lambda d, v, t: d.isoweekday() % 7 + 1),
    dtime.WeekDay: _h_datepart(lambda d, v, t: d.weekday()),
    dtime.DayOfYear: _h_datepart(lambda d, v, t: d.timetuple().tm_yday),
    dtime.Hour: _h_datepart(lambda d, v, t: (_time_of(v, t) // 3600) % 24),
    dtime.Minute: _h_datepart(lambda d, v, t: (_time_of(v, t) // 60) % 60),
    dtime.Second: _h_datepart(lambda d, v, t: _time_of(v, t) % 60),
}


def _h_lastday(ev, e):
    import calendar
    t = e.children[0].dtype
    vals = ev._eval(e.children[0])
    out = []
    import datetime
    for v in vals:
        if v is None:
            out.append(None)
            continue
        d = _date_parts(v, t)
        last = calendar.monthrange(d.year, d.month)[1]
        out.append((datetime.date(d.year, d.month, last) -
                    datetime.date(1970, 1, 1)).days)
    return out


def _h_dateadd(ev, e):
    l = ev._eval(e.children[0])
    r = ev._eval(e.children[1])
    sign = e._sign
    return [None if a is None or b is None else int(a) + sign * int(b)
            for a, b in zip(l, r)]


def _h_datediff(ev, e):
    l = ev._eval(e.children[0])
    r = ev._eval(e.children[1])
    return [None if a is None or b is None else int(a) - int(b)
            for a, b in zip(l, r)]


def _h_addmonths(ev, e):
    import datetime
    import calendar
    l = ev._eval(e.children[0])
    r = ev._eval(e.children[1])
    out = []
    for a, b in zip(l, r):
        if a is None or b is None:
            out.append(None)
            continue
        d = _date_parts(a, dt.DATE)
        total = d.year * 12 + (d.month - 1) + int(b)
        y, m = divmod(total, 12)
        m += 1
        day = min(d.day, calendar.monthrange(y, m)[1])
        out.append((datetime.date(y, m, day) - datetime.date(1970, 1, 1)).days)
    return out


def _h_unixts(ev, e):
    t = e.children[0].dtype
    vals = ev._eval(e.children[0])
    if t == dt.DATE:
        return [None if v is None else int(v) * 86_400 for v in vals]
    return [None if v is None else int(v) // 1_000_000 for v in vals]


def _h_fromunix(ev, e):
    vals = ev._eval(e.children[0])
    return [None if v is None else int(v) * 1_000_000 for v in vals]


def _h_todate(ev, e):
    t = e.children[0].dtype
    vals = ev._eval(e.children[0])
    if t == dt.DATE:
        return vals
    return [None if v is None else int(v) // 86_400_000_000 for v in vals]


_DATE_HANDLERS.update({
    dtime.LastDay: _h_lastday,
    dtime.DateAdd: _h_dateadd,
    dtime.DateSub: _h_dateadd,
    dtime.DateDiff: _h_datediff,
    dtime.AddMonths: _h_addmonths,
    dtime.UnixTimestamp: _h_unixts,
    dtime.FromUnixTime: _h_fromunix,
    dtime.ToDate: _h_todate,
})


# ---------------------------------------------------------------------------
# Plan execution
# ---------------------------------------------------------------------------

def execute(plan: lp.LogicalPlan) -> pd.DataFrame:
    """Execute an analyzed logical plan entirely on CPU, returning an
    object-dtype DataFrame (None for NULL)."""
    return _exec(plan)


def _obj_df(columns: Dict[str, List[Any]]) -> pd.DataFrame:
    df = pd.DataFrame()
    for k, v in columns.items():
        df[k] = pd.Series(v, dtype=object)
    if not columns:
        return pd.DataFrame()
    return df


def _from_arrow(table) -> pd.DataFrame:
    cols = {}
    for i, name in enumerate(table.schema.names):
        t = dt.from_arrow(table.schema.types[i])
        arr = table.column(i)
        vals = arr.to_pylist()
        if t == dt.DATE:
            import datetime
            vals = [None if v is None else (v - datetime.date(1970, 1, 1)).days
                    for v in vals]
        elif t == dt.TIMESTAMP:
            import pyarrow as pa
            vals = arr.combine_chunks().cast(pa.timestamp("us")) \
                .cast(pa.int64()).to_pylist() if hasattr(arr, "combine_chunks") \
                else vals
        cols[name] = vals
    return _obj_df(cols)


def _exec(plan: lp.LogicalPlan) -> pd.DataFrame:
    if isinstance(plan, (lp.LocalScan, lp.CachedScan)):
        return _from_arrow(plan.data)
    if isinstance(plan, lp.FileScan):
        from ..io import read_to_arrow
        return _from_arrow(read_to_arrow(plan.fmt, plan.paths, plan.options))
    if isinstance(plan, lp.Range):
        vals = list(range(plan.start, plan.end, plan.step))
        return _obj_df({"id": vals})
    if isinstance(plan, lp.Project):
        child = _exec(plan.children[0])
        ev = CpuEvaluator(child, plan.children[0].schema)
        cols = [ev.eval(e) for e in plan.exprs]
        names = [ex.output_name(e, i) for i, e in enumerate(plan.exprs)]
        out = pd.DataFrame({i: pd.Series(c, dtype=object)
                            for i, c in enumerate(cols)})
        if not len(child):
            out = pd.DataFrame({i: pd.Series([], dtype=object)
                                for i in range(len(cols))})
        out.columns = names
        return out
    if isinstance(plan, lp.Filter):
        child = _exec(plan.children[0])
        mask = CpuEvaluator(child, plan.children[0].schema).eval(plan.condition)
        keep = [m is True for m in mask]
        return child.loc[keep].reset_index(drop=True)
    if isinstance(plan, lp.Aggregate):
        return _exec_aggregate(plan)
    if isinstance(plan, lp.Join):
        return _exec_join(plan)
    if isinstance(plan, lp.Sort):
        return _exec_sort(plan)
    if isinstance(plan, lp.Limit):
        return _exec(plan.children[0]).head(plan.n).reset_index(drop=True)
    if isinstance(plan, lp.Union):
        dfs = [_exec(c) for c in plan.children]
        out = pd.concat(dfs, ignore_index=True)
        out.columns = plan.schema.names()
        return out
    if isinstance(plan, lp.Distinct):
        child = _exec(plan.children[0])
        key = child.apply(lambda r: tuple(
            ("nan" if isinstance(x, float) and math.isnan(x) else x)
            for x in r), axis=1) if len(child) else pd.Series([], dtype=object)
        return child.loc[~key.duplicated()].reset_index(drop=True) \
            if len(child) else child
    if isinstance(plan, lp.Repartition):
        return _exec(plan.children[0])
    if isinstance(plan, lp.Expand):
        child = _exec(plan.children[0])
        frames = []
        for proj in plan.projections:
            ev = CpuEvaluator(child)
            frames.append(_obj_df({
                n: ev.eval(e) for n, e in zip(plan.output_names, proj)}))
        return pd.concat(frames, ignore_index=True) if frames else _obj_df({})
    if isinstance(plan, lp.Window):
        from .window import exec_window_cpu
        return exec_window_cpu(plan, _exec(plan.children[0]))
    if isinstance(plan, lp.MapInPandas):
        child = _exec(plan.children[0])
        frames = list(plan.fn(iter([child])))
        names = plan.out_schema.names()
        if not frames:
            return _obj_df({n: [] for n in names})
        out = pd.concat(frames, ignore_index=True)
        # coerce to the declared schema: order + presence (the TPU path
        # rebuilds through _df_to_batch(out_schema) the same way)
        return out[[n for n in names]]
    if isinstance(plan, lp.FlatMapGroupsInPandas):
        import inspect
        child = _exec(plan.children[0])
        ev = CpuEvaluator(child)
        kf = pd.DataFrame({f"_gk{i}": ev.eval(g)
                           for i, g in enumerate(plan.grouping)})
        try:
            two_arg = len(inspect.signature(plan.fn).parameters) == 2
        except (TypeError, ValueError):
            two_arg = False
        frames = []
        for key, idx in kf.groupby(list(kf.columns), sort=True,
                                   dropna=False).groups.items():
            if not isinstance(key, tuple):
                key = (key,)
            pdf = child.loc[idx].reset_index(drop=True)
            out = plan.fn(key, pdf) if two_arg else plan.fn(pdf)
            if out is not None and len(out):
                frames.append(out)
        names = plan.out_schema.names()
        if not frames:
            return _obj_df({n: [] for n in names})
        return pd.concat(frames, ignore_index=True)[[n for n in names]]
    if isinstance(plan, lp.FlatMapCoGroupsInPandas):
        import inspect
        left = _exec(plan.children[0])
        right = _exec(plan.children[1])

        def side_groups(child, grouping):
            ev = CpuEvaluator(child)
            kf = pd.DataFrame({f"_gk{i}": ev.eval(g)
                               for i, g in enumerate(grouping)})
            out = {}
            if len(child):
                for key, idx in kf.groupby(list(kf.columns), sort=True,
                                           dropna=False).groups.items():
                    if not isinstance(key, tuple):
                        key = (key,)
                    out[key] = child.loc[idx].reset_index(drop=True)
            return out
        lgroups = side_groups(left, plan.left_grouping)
        rgroups = side_groups(right, plan.right_grouping)
        try:
            three_arg = len(inspect.signature(plan.fn).parameters) == 3
        except (TypeError, ValueError):
            three_arg = False
        frames = []
        for key in sorted(set(lgroups) | set(rgroups), key=repr):
            l = lgroups.get(key, left.iloc[0:0])
            r = rgroups.get(key, right.iloc[0:0])
            out = plan.fn(key, l, r) if three_arg else plan.fn(l, r)
            if out is not None and len(out):
                frames.append(out)
        names = plan.out_schema.names()
        if not frames:
            return _obj_df({n: [] for n in names})
        return pd.concat(frames, ignore_index=True)[[n for n in names]]
    if isinstance(plan, lp.AggregateInPandas):
        child = _exec(plan.children[0])
        ev = CpuEvaluator(child)
        kf = pd.DataFrame({f"_gk{i}": ev.eval(g)
                           for i, g in enumerate(plan.grouping)})
        inputs = [[pd.Series(ev.eval(c)) for c in a.children]
                  for a in plan.aggs]
        rows = []
        for key, idx in kf.groupby(list(kf.columns), sort=True,
                                   dropna=False).groups.items():
            if not isinstance(key, tuple):
                key = (key,)
            vals = [a.fn(*[s.loc[idx].reset_index(drop=True)
                           for s in ins])
                    for a, ins in zip(plan.aggs, inputs)]
            rows.append(tuple(key) + tuple(vals))
        names = plan.out_names
        return _obj_df({n: [r[i] for r in rows]
                        for i, n in enumerate(names)})
    if isinstance(plan, lp.Generate):
        child = _exec(plan.children[0])
        ev = CpuEvaluator(child)
        gen = plan.generator
        arrays = ev.eval(gen.children[0])
        rows, poss, elems = [], [], []
        for i, a in enumerate(arrays):
            if a is None:
                continue
            for p_i, v in enumerate(a):
                rows.append(i)
                poss.append(p_i)
                elems.append(v)
        out = child.iloc[rows].reset_index(drop=True) if len(child) else \
            child.iloc[0:0]
        if getattr(gen, "pos", False):
            out[plan.pos_name] = pd.Series(poss, dtype=object)
        out[plan.col_name] = pd.Series(elems, dtype=object)
        return out
    raise NotImplementedError(f"CPU engine: {plan.name}")


def _exec_aggregate(plan: lp.Aggregate) -> pd.DataFrame:
    child = _exec(plan.children[0])
    ev = CpuEvaluator(child)
    n = len(child)

    # evaluate grouping exprs
    gcols = [ev.eval(g) for g in plan.grouping]

    # collect aggregate leaf expressions
    agg_leaves: List[lp.AggregateExpression] = []
    for e in plan.aggregate_exprs:
        agg_leaves.extend(e.collect(lambda x: isinstance(x, lp.AggregateExpression)))
    leaf_inputs = [ev.eval(a.children[0]) if a.children else [1] * n
                   for a in agg_leaves]

    def group_key(i):
        return tuple(_group_cell(c[i]) for c in gcols)

    groups: Dict[tuple, List[int]] = {}
    order: List[tuple] = []
    for i in range(n):
        k = group_key(i)
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(i)
    if not plan.grouping and not order:
        order = [()]
        groups[()] = []

    # compute aggregate values per group per leaf
    leaf_results: List[Dict[tuple, Any]] = []
    for leaf, inputs in zip(agg_leaves, leaf_inputs):
        res = {}
        for k in order:
            rows = groups[k]
            vals = [inputs[i] for i in rows]
            if leaf.distinct:
                seen, dd = set(), []
                for v in vals:
                    kk = _group_cell(v)
                    if kk not in seen:
                        seen.add(kk)
                        dd.append(v)
                vals = dd
            res[k] = _agg_py(leaf.op, vals, leaf.ignore_nulls)
        leaf_results.append(res)

    # assemble output rows: substitute aggregate leaves, then evaluate the
    # result expression per group
    out_cols: Dict[str, List[Any]] = {}
    for i, e in enumerate(plan.aggregate_exprs):
        name = ex.output_name(e, i)
        col_vals = []
        for k in order:
            col_vals.append(_eval_result_expr(e, k, plan, gcols, groups,
                                              agg_leaves, leaf_results))
        out_cols[name] = col_vals
    return _obj_df(out_cols)


def _group_cell(v):
    if isinstance(v, float) and math.isnan(v):
        return ("nan",)
    # struct/array cells surface as dicts/lists (unhashable): canonicalize
    # recursively so CPU-fallback joins/group-bys on them can key a map
    if isinstance(v, dict):
        return ("dict",) + tuple(
            (k, _group_cell(x))
            for k, x in sorted(v.items(), key=lambda kv: repr(kv[0])))
    if isinstance(v, (list, tuple)):
        return ("seq",) + tuple(_group_cell(x) for x in v)
    return v


def _agg_py(op: str, vals: List[Any], ignore_nulls: bool):
    non_null = [v for v in vals if v is not None]
    if op == "count_star":
        return len(vals)
    if op == "count":
        return len(non_null)
    if op == "sum":
        return sum(non_null) if non_null else None
    if op == "avg":
        return sum(non_null) / len(non_null) if non_null else None
    if op == "min":
        return min(non_null, key=_order_key) if non_null else None
    if op == "max":
        return max(non_null, key=_order_key) if non_null else None
    if op == "first":
        pool = non_null if ignore_nulls else vals
        return pool[0] if pool else None
    if op == "last":
        pool = non_null if ignore_nulls else vals
        return pool[-1] if pool else None
    raise NotImplementedError(op)


def _eval_result_expr(e, k, plan, gcols, groups, agg_leaves, leaf_results):
    """Evaluate an output expression for group k: aggregate leaves are looked
    up; grouping expressions take the group's key value; literals fold."""
    # grouping match FIRST (an aliased computed grouping key is the same
    # object in both lists — stripping the alias before comparing would
    # miss it and recurse into unresolvable column refs)
    for gi, g in enumerate(plan.grouping):
        if _same_expr(e, g):
            return k[gi] if not isinstance(k[gi], tuple) else (
                float("nan") if k[gi] == ("nan",) else k[gi])
    if isinstance(e, ex.Alias):
        return _eval_result_expr(e.children[0], k, plan, gcols, groups,
                                 agg_leaves, leaf_results)
    for i, leaf in enumerate(agg_leaves):
        if e is leaf:
            return leaf_results[i][k]
    if isinstance(e, ex.Literal):
        return e.value
    # arithmetic over aggregate results (e.g. sum/count)
    sub = [
        _eval_result_expr(c, k, plan, gcols, groups, agg_leaves, leaf_results)
        for c in e.children]
    df = _obj_df({f"c{i}": [v] for i, v in enumerate(sub)})
    rewired = e.with_children([
        ex.BoundReference(i, c.dtype, True) for i, c in enumerate(e.children)])
    return CpuEvaluator(df).eval(rewired)[0]


def _same_expr(a: ex.Expression, b: ex.Expression) -> bool:
    if a is b:
        return True
    if isinstance(a, ex.Alias):
        return _same_expr(a.children[0], b)
    if isinstance(b, ex.Alias):
        return _same_expr(a, b.children[0])
    if isinstance(a, ex.ColumnRef) and isinstance(b, ex.ColumnRef):
        return a.col_name == b.col_name
    return False


def _exec_join(plan: lp.Join) -> pd.DataFrame:
    from ..ops import predicates as pr_
    left = _exec(plan.children[0])
    right = _exec(plan.children[1])
    how = plan.how
    lnames = plan.children[0].schema.names()
    rnames = plan.children[1].schema.names()

    # extract equi-join keys from the condition (conjunctive EqualTo chains)
    lkeys, rkeys, residual = _extract_equi_keys(plan.condition, lnames, rnames)

    if how == "cross" or (plan.condition is None and not lkeys):
        out = left.merge(right, how="cross") if len(left.columns) and \
            len(right.columns) else left.merge(right, how="cross")
        out.columns = lnames + rnames
        return out

    lev = CpuEvaluator(left)
    rev = CpuEvaluator(right)
    lkc = [lev.eval(e) for e in lkeys]
    rkc = [rev.eval(e) for e in rkeys]

    rmap: Dict[tuple, List[int]] = {}
    for j in range(len(right)):
        kt = tuple(_group_cell(c[j]) for c in rkc)
        if any(c[j] is None for c in rkc):
            continue
        rmap.setdefault(kt, []).append(j)

    pairs: List[tuple] = []
    matched_right = set()
    l_matched = [False] * len(left)
    for i in range(len(left)):
        if any(c[i] is None for c in lkc):
            continue
        kt = tuple(_group_cell(c[i]) for c in lkc)
        for j in rmap.get(kt, []):
            pairs.append((i, j))
            l_matched[i] = True
            matched_right.add(j)

    if residual is not None:
        keep_pairs = []
        for (i, j) in pairs:
            row = {}
            for c in lnames:
                row[c] = [left[c].iloc[i]]
            for c in rnames:
                row[f"__r_{c}"] = [right[c].iloc[j]]
            merged = _obj_df(row)
            cond = _rewire_condition(residual, lnames, rnames)
            v = CpuEvaluator(merged).eval(cond)[0]
            if v is True:
                keep_pairs.append((i, j))
        # recompute matched flags under the residual
        pairs = keep_pairs
        l_matched = [False] * len(left)
        matched_right = set()
        for (i, j) in pairs:
            l_matched[i] = True
            matched_right.add(j)

    if how == "left_semi":
        keep = sorted({i for i, _ in pairs})
        return left.iloc[keep].reset_index(drop=True)
    if how == "left_anti":
        keep = [i for i in range(len(left)) if not l_matched[i]]
        return left.iloc[keep].reset_index(drop=True)

    rows = []
    for (i, j) in pairs:
        rows.append([left[c].iloc[i] for c in lnames] +
                    [right[c].iloc[j] for c in rnames])
    if how in ("left", "full"):
        for i in range(len(left)):
            if not l_matched[i]:
                rows.append([left[c].iloc[i] for c in lnames] +
                            [None] * len(rnames))
    if how in ("right", "full"):
        for j in range(len(right)):
            if j not in matched_right:
                rows.append([None] * len(lnames) +
                            [right[c].iloc[j] for c in rnames])
    # positional build: duplicate column names (self-joins, USING) must not
    # collapse through a dict
    names = lnames + rnames
    out = pd.DataFrame(
        {i: pd.Series([r[i] for r in rows], dtype=object)
         for i in range(len(names))})
    if not len(rows):
        out = pd.DataFrame({i: pd.Series([], dtype=object)
                            for i in range(len(names))})
    out.columns = names
    return out


def _extract_equi_keys(cond, lnames, rnames):
    from ..ops import predicates as pr_
    lkeys, rkeys = [], []
    residual = None
    if cond is None:
        return lkeys, rkeys, None

    def visit(e):
        nonlocal residual
        if isinstance(e, pr_.And):
            visit(e.children[0])
            visit(e.children[1])
            return
        if isinstance(e, pr_.EqualTo):
            l, r = e.children
            lrefs = {c.col_name for c in l.collect(
                lambda x: isinstance(x, ex.ColumnRef))}
            rrefs = {c.col_name for c in r.collect(
                lambda x: isinstance(x, ex.ColumnRef))}
            if lrefs <= set(lnames) and rrefs <= set(rnames):
                lkeys.append(l)
                rkeys.append(r)
                return
            if lrefs <= set(rnames) and rrefs <= set(lnames):
                lkeys.append(r)
                rkeys.append(l)
                return
        residual = e if residual is None else pr_.And(residual, e)

    visit(cond)
    return lkeys, rkeys, residual


def _rewire_condition(cond, lnames, rnames):
    """Rewrite right-side column refs to the prefixed merged frame columns."""
    def fn(node):
        if isinstance(node, ex.ColumnRef) and node.col_name in rnames \
                and node.col_name not in lnames:
            return ex.ColumnRef(f"__r_{node.col_name}")._copy_resolution(node)
        return None
    # ColumnRef lacks _copy_resolution; simpler: rebuild and re-resolve lazily
    def fn2(node):
        if isinstance(node, ex.ColumnRef):
            nn = ex.ColumnRef(f"__r_{node.col_name}"
                              if node.col_name in rnames and
                              node.col_name not in lnames else node.col_name)
            nn._resolved = node._resolved
            return nn
        return None
    return cond.transform(fn2)


def _exec_sort(plan: lp.Sort) -> pd.DataFrame:
    child = _exec(plan.children[0])
    if not len(child):
        return child
    ev = CpuEvaluator(child)
    keys = [ev.eval(o.child) for o in plan.orders]
    idx = list(range(len(child)))

    def key_fn(i):
        parts = []
        for k, o in zip(keys, plan.orders):
            v = k[i]
            null_rank = 0 if (v is None) == o.nulls_first else 1
            if v is None:
                parts.append((null_rank, 0, b"" if False else 0))
                continue
            ok = _order_key(v)
            if not o.ascending:
                parts.append((null_rank, _Neg(ok)))
            else:
                parts.append((null_rank, _Asc(ok)))
        return tuple(parts)

    idx.sort(key=key_fn)
    return child.iloc[idx].reset_index(drop=True)


class _Asc:
    __slots__ = ("k",)

    def __init__(self, k):
        self.k = k

    def __lt__(self, other):
        return self.k < other.k

    def __eq__(self, other):
        return self.k == other.k


class _Neg:
    __slots__ = ("k",)

    def __init__(self, k):
        self.k = k

    def __lt__(self, other):
        return other.k < self.k

    def __eq__(self, other):
        return self.k == other.k
