"""CPU window execution for the pandas engine (golden-compare side)."""

from __future__ import annotations

from typing import List, Tuple

import pandas as pd

from ..ops import window as W
from ..plan import logical as lp


def exec_window_cpu(plan: lp.Window, df: pd.DataFrame) -> pd.DataFrame:
    from .engine import CpuEvaluator, _obj_df, _order_key, _agg_py, _group_cell
    n = len(df)
    out = df.copy()
    for name, w in plan.window_exprs:
        ev = CpuEvaluator(df)
        pkeys = [ev.eval(e) for e in w.spec.partition_by]
        okey_vals = [(ev.eval(o.child), o) for o in w.spec.order_by]

        # sort order: partition keys then order keys (same as device path)
        idx = list(range(n))

        def key_fn(i):
            parts = []
            for col in pkeys:
                v = col[i]
                parts.append((v is None, _order_key(v) if v is not None else 0))
            for col, o in okey_vals:
                v = col[i]
                null_rank = 0 if (v is None) == o.nulls_first else 1
                if v is None:
                    parts.append((null_rank, 0))
                else:
                    k = _order_key(v)
                    from .engine import _Asc, _Neg
                    parts.append((null_rank, _Asc(k) if o.ascending else _Neg(k)))
            return tuple(parts)

        idx.sort(key=key_fn)

        # segment starts
        def pkey_of(i):
            return tuple(_group_cell(c[i]) for c in pkeys)

        def okey_of(i):
            return tuple(_group_cell(c[i]) for c, _ in okey_vals)

        results = [None] * n
        fn = w.function
        seg_start = 0
        for pos in range(n + 1):
            is_boundary = pos == n or (
                pos > 0 and pkey_of(idx[pos]) != pkey_of(idx[pos - 1]))
            if pos > 0 and is_boundary:
                seg = idx[seg_start:pos]
                _compute_segment(fn, w.spec, seg, df, ev, okey_of, results)
                seg_start = pos
        out[name] = pd.Series(results, dtype=object)
    return out


def _compute_segment(fn, spec, seg: List[int], df, ev, okey_of, results):
    from .engine import _agg_py
    if isinstance(fn, W.RowNumber):
        for r, i in enumerate(seg):
            results[i] = r + 1
        return
    if isinstance(fn, (W.Rank, W.DenseRank)):
        dense = isinstance(fn, W.DenseRank)
        rank = 0
        dr = 0
        prev = object()
        for r, i in enumerate(seg):
            k = okey_of(i)
            if k != prev:
                rank = r + 1
                dr += 1
                prev = k
            results[i] = dr if dense else rank
        return
    if isinstance(fn, W.Lead):
        vals = ev.eval(fn.children[0])
        off = fn.offset if not isinstance(fn, W.Lag) else -fn.offset
        for r, i in enumerate(seg):
            src = r + off
            if 0 <= src < len(seg):
                results[i] = vals[seg[src]]
            else:
                results[i] = fn.default
        return
    if isinstance(fn, lp.AggregateExpression):
        vals = ev.eval(fn.children[0]) if fn.children else [1] * len(df)
        frame = spec.frame
        whole = frame is None or frame.is_whole_partition or not spec.order_by
        if whole:
            agg = _agg_py(fn.op, [vals[i] for i in seg], fn.ignore_nulls)
            for i in seg:
                results[i] = agg
            return
        if frame.is_unbounded_to_current:
            for r, i in enumerate(seg):
                window_rows = [vals[j] for j in seg[:r + 1]]
                results[i] = _agg_py(fn.op, window_rows, fn.ignore_nulls)
            return
        if frame.is_range:
            # RANGE frame: window = same-segment rows whose (single, asc)
            # order key lies in [k+lower, k+upper]; NULL-key rows form
            # their own frame group (Spark semantics)
            okey = ev.eval(spec.order_by[0].child)
            for r, i in enumerate(seg):
                k = okey[i]
                if k is None:
                    window = [j for j in seg if okey[j] is None]
                else:
                    lo = None if frame.lower is None else k + frame.lower
                    hi = None if frame.upper is None else k + frame.upper
                    window = [j for j in seg
                              if okey[j] is not None
                              and (lo is None or okey[j] >= lo)
                              and (hi is None or okey[j] <= hi)]
                results[i] = _agg_py(fn.op, [vals[j] for j in window],
                                     fn.ignore_nulls)
            return
        # bounded ROW frame
        for r, i in enumerate(seg):
            lo = 0 if frame.lower is None else max(0, r + frame.lower)
            hi = len(seg) - 1 if frame.upper is None else \
                min(len(seg) - 1, r + frame.upper)
            window_rows = [vals[j] for j in seg[lo:hi + 1]] \
                if lo <= hi else []
            results[i] = _agg_py(fn.op, window_rows, fn.ignore_nulls)
        return
    from ..ops.python_udf import PandasAggUDF
    if isinstance(fn, PandasAggUDF):
        frame = spec.frame
        if frame is not None and not frame.is_whole_partition:
            raise NotImplementedError(
                "pandas window UDFs support whole-partition frames only")
        cols = [ev.eval(c) for c in fn.children]   # once per column
        series = [pd.Series([c[i] for i in seg]) for c in cols]
        val = fn.fn(*series)
        for i in seg:
            results[i] = val
        return
    raise NotImplementedError(f"cpu window fn {type(fn).__name__}")
