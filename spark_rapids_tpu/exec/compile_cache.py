"""Persistent compile cache + buffer donation gate (docs/compile.md).

Compile time and device memory are managed resources at the ``_fused_fn``
funnel (``plan/physical.py``), not side effects:

* **Persistent compile cache** — ``spark.rapids.tpu.sql.compile.cacheDir``
  points JAX's on-disk XLA compilation cache at a directory
  (``jax.config.jax_compilation_cache_dir``) AND keeps an engine-level
  *signature index* (one JSONL line per fused-program cache key ever
  built) beside it. A fresh process serving query shapes it has served
  before classifies each build as a **disk** hit (the executable loads
  from the XLA cache instead of recompiling — the millions-of-users
  restart scenario pays zero cold builds) versus a **cold** build, and
  the recompile audit reports the split per kernel family with compile
  *seconds*, not just counts. An unwritable/unusable cache dir logs a
  loud warning and degrades to in-memory-only caching — never a query
  failure.

* **Buffer donation** — ``spark.rapids.tpu.sql.compile.donate`` (default
  on) lets the fused programs that *consume* a batch take its column
  arrays as donated jit arguments (``donate_argnums``): XLA may reuse
  the input HBM for outputs and frees the rest the moment the program
  ingests them, so peak device residency on multi-operator pipelines
  drops by roughly one batch per pipeline stage. Spill-store-registered
  and scan-cache-served batches are NEVER donated — their arrays are
  owned by a catalog entry that re-reads them (``ColumnarBatch.origin``
  / ``.shared``).

First-call wall time of every freshly-built program is metered
(compile-dominated on every real backend) into the recompile audit, the
``tpu_compile_seconds{kind}`` telemetry histogram, and the innermost
open exec's ``compileSeconds`` metric.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
import warnings
from typing import Any, Optional, Set

from ..analysis.lockdep import named_lock

# Donating a buffer whose shape/layout XLA cannot reuse for an output
# still FREES it the moment the program ingests it — that eager free IS
# the point of the donation discipline, so jax's per-compile "not
# usable" advisory is expected steady state here, not a defect signal.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

log = logging.getLogger("spark_rapids_tpu.compile")

#: file (inside the cache dir) holding one JSON line per fused-program
#: signature ever built against this cache — the engine-level index that
#: lets a fresh process distinguish disk hits from cold builds
INDEX_NAME = "fused_signature_index.jsonl"

_lock = named_lock("exec.compile_cache._lock")
_cache_dir: Optional[str] = None     # active persistent dir (None = off)
_index: Set[str] = set()             # signature hashes known on disk
_index_path: Optional[str] = None
_writable: bool = False
_warned_unwritable: bool = False
_donate_cache: Optional[bool] = None


def configure(conf=None) -> None:
    """Prime the persistent cache + donation gate from a session conf
    (session bootstrap; re-run by ``RuntimeConf.set`` on ``compile.*``
    changes). Degrades gracefully: any failure to use the cache dir logs
    a loud warning and leaves the engine on in-memory caching only."""
    global _cache_dir, _index_path, _writable, _warned_unwritable
    global _donate_cache
    from .. import config as cfg
    if conf is None:
        conf = cfg.TpuConf()
    try:
        # the async compile pool rides the same compile.* conf surface
        # (and the same RuntimeConf.set re-configure trigger)
        from . import compile_pool
        compile_pool.configure(conf)
    except Exception:
        log.debug("compile pool configure failed", exc_info=True)
    try:
        donate = bool(conf.get(cfg.COMPILE_DONATE))
    except Exception:
        donate = True
    with _lock:
        _donate_cache = donate
    try:
        d = str(conf.get(cfg.COMPILE_CACHE_DIR) or "").strip()
    except Exception:
        d = ""
    if not d:
        with _lock:
            _cache_dir = None
            _index_path = None
            _writable = False
            _index.clear()
        return
    d = os.path.abspath(os.path.expanduser(d))
    index_path = os.path.join(d, INDEX_NAME)
    try:
        os.makedirs(d, exist_ok=True)
        # probe writability up front so the first compile is not the one
        # discovering a read-only volume
        with open(index_path, "a"):
            pass
        writable = True
    except OSError as e:
        log.warning(
            "compile.cacheDir %r is not usable (%s): persistent compile "
            "cache DISABLED for this process — queries run correctly but "
            "every restart pays full cold compiles", d, e)
        with _lock:
            _warned_unwritable = True
            _cache_dir = None
            _index_path = None
            _writable = False
        return
    # point XLA's own on-disk compilation cache at the dir; each knob is
    # best-effort (older jax lacks some, CPU backends gained support
    # late) — a missing knob degrades that feature, never the session
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", d)
    except Exception as e:
        log.warning("jax compilation cache unavailable (%s): signature "
                    "index still recorded, executables recompile", e)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            import jax
            jax.config.update(knob, val)
        except Exception:
            pass
    loaded: Set[str] = set()
    try:
        with open(index_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ent = json.loads(line)
                except ValueError:
                    continue     # torn write from a killed process
                sig = ent.get("sig") if isinstance(ent, dict) else None
                if sig:
                    loaded.add(sig)
    except OSError:
        pass
    with _lock:
        _cache_dir = d
        _index_path = index_path
        _writable = writable
        _index.clear()
        _index.update(loaded)


def reset_cache() -> None:
    """Drop the donation-gate prime (tests; session bootstrap calls
    :func:`configure`, which re-primes everything)."""
    global _donate_cache
    with _lock:
        _donate_cache = None


def active_dir() -> Optional[str]:
    return _cache_dir


def donate_enabled() -> bool:
    """Whether consumed-batch donation is on (cached; primed eagerly by
    :func:`configure` at session bootstrap — a lazy conf read here would
    run on the per-batch hot path)."""
    global _donate_cache
    if _donate_cache is None:
        try:
            from .. import config as cfg
            donate = bool(cfg.TpuConf().get(cfg.COMPILE_DONATE))
        except Exception:
            donate = True
        with _lock:
            _donate_cache = donate
    return _donate_cache


def sig_hash(key: Any) -> str:
    """Stable cross-process hash of a fused-program cache key. Keys are
    tuples of strings/ints/structural expression keys (anything carrying
    a memory address is unkeyable and never reaches the cache), so their
    repr is deterministic across processes."""
    return hashlib.sha256(repr(key).encode()).hexdigest()


def classify(key: Any) -> str:
    """``disk`` when this signature was built against the active cache
    dir by a previous process (XLA serves the executable from disk),
    ``cold`` otherwise (including when no cache dir is configured)."""
    if _cache_dir is None:
        return "cold"
    return "disk" if sig_hash(key) in _index else "cold"


def record(key: Any, kernel: str) -> None:
    """Persist one built signature into the index (idempotent; a failed
    write warns once and stops persisting, never raises)."""
    global _writable, _warned_unwritable
    if _cache_dir is None or not _writable:
        return
    h = sig_hash(key)
    with _lock:
        if h in _index:
            return
        _index.add(h)
        path = _index_path
    try:
        with open(path, "a") as f:
            f.write(json.dumps({"sig": h, "kernel": kernel}) + "\n")
    except OSError as e:
        with _lock:
            warn = not _warned_unwritable
            _writable = False
            _warned_unwritable = True
        if warn:
            log.warning("compile signature index %r became unwritable "
                        "(%s): restart-classification degrades to 'cold' "
                        "for new shapes", path, e)


# ---------------------------------------------------------------------------
# JIT map-pressure relief
# ---------------------------------------------------------------------------
#
# Every live XLA CPU executable pins JIT code mappings, and a process has
# a finite mmap budget (vm.max_map_count, default 65530 on Linux): a
# long-lived engine that keeps compiling new shapes runs LLVM's mmap
# into the wall and SEGFAULTS mid-compile — measured at maps=65520 on
# this repo's own tier-1 suite. Bytes are not the binding resource;
# mappings are. The relief valve below counts /proc/self/maps every few
# builds and, past a soft fraction of the limit, clears every registered
# program cache (fused, scan unpack, shuffle split, mesh SPMD) and GCs —
# traffic rebuilds what it still needs (disk hits when cacheDir is set),
# and the recompile audit reports the rebuilds honestly.

#: program caches to drop under map pressure (each registers its clear)
_PROGRAM_CACHE_CLEARS: list = []
_RELIEF_CHECK_EVERY = 32         # builds between /proc/self/maps reads
_RELIEF_FRACTION = 0.7           # relieve past this fraction of the limit
_builds_since_check = 0
_map_limit: Optional[int] = None
_relief_count = 0


def register_program_cache(clear_fn) -> None:
    """Register a compiled-program cache's clear() with the relief valve
    (module import time; the registry is append-only)."""
    _PROGRAM_CACHE_CLEARS.append(clear_fn)


def relief_count() -> int:
    """How many times the valve fired this process (tests use this to
    detect a relief landing inside a timing-sensitive window)."""
    return _relief_count


def _read_map_limit() -> int:
    try:
        with open("/proc/sys/vm/max_map_count") as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return 0                  # non-Linux: valve disabled


def _map_count() -> int:
    try:
        with open("/proc/self/maps") as f:
            return sum(1 for _ in f)
    except OSError:
        return -1


def jit_map_guard() -> None:
    """Pre-compile check (TimedFirstCall first call): every
    ``_RELIEF_CHECK_EVERY`` builds, read the process map count and
    relieve pressure before LLVM hits the hard limit."""
    global _builds_since_check, _map_limit, _relief_count
    _builds_since_check += 1  # lint: unguarded-ok monotone counter; a racing lost increment only delays one check interval
    if _builds_since_check < _RELIEF_CHECK_EVERY:
        return
    _builds_since_check = 0  # lint: unguarded-ok monotone counter; a racing lost increment only delays one check interval
    if _map_limit is None:
        _map_limit = _read_map_limit()  # lint: unguarded-ok idempotent lazy prime; a racing double read stores the same value
    if not _map_limit:
        return
    n = _map_count()
    if n < 0 or n < _RELIEF_FRACTION * _map_limit:
        return
    # cooldown: live plans can pin executables past our caches, so one
    # relief may not get fully below threshold — re-firing every check
    # interval would thrash the caches for no mapping gain
    _builds_since_check = -(_RELIEF_CHECK_EVERY * 7)  # lint: unguarded-ok monotone counter; a racing lost write only shortens one cooldown
    with _lock:
        _relief_count += 1
        count = _relief_count
    log.warning(
        "JIT map pressure: %d/%d process mappings — dropping %d compiled-"
        "program caches before LLVM's mmap fails (relief #%d). Rebuilds "
        "are %s.", n, _map_limit, len(_PROGRAM_CACHE_CLEARS), count,
        "disk hits (compile.cacheDir set)" if _cache_dir
        else "cold (set compile.cacheDir to make them disk hits)")
    for clear in list(_PROGRAM_CACHE_CLEARS):
        try:
            clear()
        except Exception:
            log.exception("program-cache clear failed during map relief")
    import gc
    gc.collect()
    # NOTE: deliberately NOT jax.clear_caches() here — it would also
    # invalidate every LIVE jitted function's traced cache, turning one
    # relief into a process-wide retrace storm. Dropping the program
    # caches + GC releases the executables (and their mappings); the few
    # residual per-program mappings jax's internals keep only matter
    # after many cycles, and the next check fires again if they do.
    try:
        from ..service.telemetry import MetricsRegistry, flight_record
        flight_record("jit_relief", "maps", {"maps": n, "limit": _map_limit})
        MetricsRegistry.get().counter(
            "tpu_jit_map_relief_total",
            "compiled-program cache drops forced by process map-count "
            "pressure").inc()
    except Exception:
        pass


def note_compile_seconds(kernel: str, seconds: float, kind: str) -> None:
    """Meter one program's first-call wall seconds: recompile audit
    (per-family ``compileS``), the ``tpu_compile_seconds{kind}``
    histogram, and the innermost open exec's ``compileSeconds``."""
    from ..analysis import recompile
    recompile.note_compile_time(kernel, seconds)
    from . import metrics as em
    em.attribute("compileSeconds", seconds)
    try:
        from ..service.telemetry import MetricsRegistry
        MetricsRegistry.get().histogram(
            "tpu_compile_seconds",
            "first-call wall seconds of freshly built fused programs "
            "(compile-dominated), by cold build vs persistent-cache disk "
            "hit", kind=kind).observe(seconds)
    except Exception:
        pass         # telemetry must never fail a compile


class TimedFirstCall:
    """Wraps a freshly-built jitted program so its FIRST invocation —
    the one that pays tracing + XLA compilation (or the disk-cache
    load) — is timed and metered. Later calls pay one attribute check."""

    __slots__ = ("_fn", "_kernel", "_kind", "_timed")

    def __init__(self, fn, kernel: str, kind: str):
        self._fn = fn
        self._kernel = kernel
        self._kind = kind
        self._timed = False

    def __call__(self, *args, **kwargs):
        if self._timed:
            return self._fn(*args, **kwargs)
        jit_map_guard()     # relieve map pressure BEFORE the compile
        trace = os.environ.get("SRT_COMPILE_TRACE")
        if trace:
            # crash-forensics breadcrumb: the last line names the program
            # whose first call (the XLA compile) never returned; maps =
            # /proc/self/maps entries (JIT mmap exhaustion shows here)
            try:
                with open("/proc/self/maps") as mf:
                    nmaps = sum(1 for _ in mf)
            except OSError:
                nmaps = -1
            with open(trace, "a") as f:
                f.write(f"BEGIN {self._kind} {self._kernel} maps={nmaps} "
                        f"args={[getattr(a, 'shape', a) for a in args]}\n")
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        self._timed = True
        note_compile_seconds(self._kernel, time.perf_counter() - t0,
                             self._kind)
        if trace:
            with open(trace, "a") as f:
                f.write(f"END {self._kernel}\n")
        return out


def timed(fn, kernel: str, kind: str):
    return TimedFirstCall(fn, kernel, kind)


def note_build(key: Any, kernel: str):
    """One-call integration for program caches OUTSIDE the ``_fused_fn``
    funnel (mesh SPMD stages, the scan unpack cache, the shuffle split
    cache): classify the build against the persistent index, account it
    in the recompile audit, persist the signature, and return
    ``(kind, wrap)`` where ``wrap(fn)`` adds first-call timing."""
    from ..analysis import recompile
    kind = classify(key)
    recompile.note_compile(kernel, key, kind=kind)
    record(key, kernel)
    return kind, (lambda fn: TimedFirstCall(fn, kernel, kind))
