"""Background compile pool: cold fused-stage builds off the query thread
(docs/compile.md §5, the ISSUE 17 tentpole).

BENCH_r03 measured q6 COLD at 20.5s against ~221 Mrows/s warm fused
throughput: first-touch latency is XLA whole-program compilation, paid
synchronously on the thread that owes the user rows. This module moves
that compile OFF the query thread when the caller is latency-sensitive:

* a **streaming collect** (``DataFrame.collect_iter``) must yield its
  first batch in first-batch time, not first-batch-plus-compile time;
* a **service query under a deadline** whose remaining slack cannot
  absorb a cold build (``compile.async.deadlineSlackS``) must not gamble
  the deadline on the compiler.

In either context, :meth:`TpuWholeStageExec._fused` consults this pool
instead of building inline: the build is queued on a bounded worker
pool, the stage serves batches through its per-op eager path while the
build is in flight, and the compiled program swaps in at the next batch
boundary once ready (``consult`` stops answering ``pending`` the moment
the job completes, and the stage's next ``_fused_fn`` consult is a pure
cache hit). Plain batch collects with no deadline keep the synchronous
build path byte-for-byte unchanged — that is what keeps the repeat-
compiles-nothing gates (tests/test_zz_recompile_gate.py) meaningful.

Every pool build goes through the SAME ``_fused_fn`` funnel as a
synchronous build (plan/physical.py): classify cold-vs-disk, recompile
audit, signature-index record, first-call timing. The pool worker then
warm-calls the jitted program with zero-filled dummies captured on the
submitting thread (``jnp.zeros_like`` preserves shape/dtype/weak-type,
so the warm call's jit signature exactly matches the real call) — the
compile genuinely happens on the pool thread, and the query thread's
later call is a traced-cache hit. ``exec.metrics.attribute`` finds no
open exec on pool threads, so ``compileSeconds`` lands on the query's
exec tree ONLY for synchronous builds — that asymmetry is exactly the
async-vs-sync attribution split ``tools/query_report`` reports.

**Prewarm** closes the restart half of the cold path: beside the
persistent signature index, every new stage build appends a *prewarm
corpus* line (the pickled chain + donate tuple + argument avals — what
it takes to rebuild the identical program in a fresh process). At
bootstrap (``compile.prewarm.enabled``, ``tools/prewarm``, ``runner
--prewarm``) the pool replays the top-N hottest signatures as tier-1
jobs — strictly below tier-0 query-triggered builds in the priority
queue — so a restarted replica's first query finds its programs already
in the fused cache and triggers ZERO compiles of its own.

Deadline priority: tier-0 jobs order by the submitting query's
``perf_counter`` deadline (exec/query_context.current_deadline_at),
soonest first; deadline-free submissions sort after every dated one.
"""

from __future__ import annotations

import base64
import heapq
import itertools
import logging
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..analysis.lockdep import named_lock
from . import query_context as qc

log = logging.getLogger("spark_rapids_tpu.compile_pool")

#: file (inside compile.cacheDir) holding one JSON line per stage-program
#: BUILD event: the rebuild recipe + hotness signal prewarm replays from
CORPUS_NAME = "prewarm_corpus.jsonl"

_INF = float("inf")
_FAILED_MAX = 128                  # distinct failing keys remembered
_PREWARM_TIER = 1                  # tier 0 = query-triggered, always first

_mu = named_lock("exec.compile_pool._mu")
_cond = threading.Condition(_mu)  # lint: raw-lock-ok condition OVER the named pool lock; wait/notify not expressible through NamedLock alone

_enabled: bool = True
_workers_target: int = 2
_slack_s: float = 5.0
_shutdown: bool = False
_threads: List[threading.Thread] = []
_queue: List[tuple] = []           # heap: (tier, deadline_at, seq, key)
_jobs: Dict[Any, "_Job"] = {}      # PENDING/RUNNING; DONE jobs drop out
_failed: Dict[Any, BaseException] = {}
_seq = itertools.count(1)
_corpus_recorded: set = set()      # sig hashes already appended this process
_async_built = 0                   # tier-0 programs built by the pool
_prewarm_built = 0                 # tier-1 programs built by the pool

#: test seam: sleep this long in the worker before building, so race
#: tests can hold a build in flight while batches drain eagerly
_test_build_delay_s: float = 0.0


class _Job:
    """One queued build: the ``_fused_fn`` key, the program builder, and
    the dummy arguments whose first call pays the compile."""

    __slots__ = ("key", "builder", "warm_args", "kernel", "tier",
                 "deadline_at", "running")

    def __init__(self, key, builder, warm_args, kernel, tier, deadline_at):
        self.key = key
        self.builder = builder
        self.warm_args = warm_args
        self.kernel = kernel
        self.tier = tier
        self.deadline_at = deadline_at if deadline_at is not None else _INF
        self.running = False


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------

def configure(conf=None) -> None:
    """Prime the pool from a session conf (wired from
    ``compile_cache.configure`` so every ``compile.*`` conf change
    reaches it). Worker threads spawn lazily at first submission."""
    global _enabled, _workers_target, _slack_s
    from .. import config as cfg
    if conf is None:
        conf = cfg.TpuConf()
    try:
        enabled = bool(conf.get(cfg.COMPILE_ASYNC))
        workers = max(1, int(conf.get(cfg.COMPILE_ASYNC_WORKERS)))
        slack = float(conf.get(cfg.COMPILE_ASYNC_DEADLINE_SLACK_S))
    except Exception:
        enabled, workers, slack = True, 2, 5.0
    with _mu:
        _enabled = enabled
        _workers_target = workers
        _slack_s = slack


def enabled() -> bool:
    return _enabled and not _shutdown


def deadline_slack_s() -> float:
    return _slack_s


# ---------------------------------------------------------------------------
# Routing policy (deadline-aware compile scheduling, docs/service.md)
# ---------------------------------------------------------------------------

def routable(key) -> bool:
    """Should a cold build for ``key`` go to the pool instead of the
    query thread? Yes only when the pool is on, the build would be COLD
    (disk-classified builds load from the XLA cache — cheap enough to
    take inline), and the caller is latency-sensitive: a streaming
    collect, or a deadline whose remaining slack is under
    ``compile.async.deadlineSlackS``. Everything else keeps the
    synchronous path unchanged."""
    if not _enabled or _shutdown:
        return False
    from . import compile_cache as _cc
    if _cc.classify(key) != "cold":
        return False
    if qc.streaming_active():
        return True
    deadline_at = qc.current_deadline_at()
    if deadline_at is None:
        return False
    return (deadline_at - time.perf_counter()) < _slack_s


# ---------------------------------------------------------------------------
# Submission / consultation (the stage-compiler handshake)
# ---------------------------------------------------------------------------

def consult(key, builder, warm_args, kernel: str = "") -> str:
    """One stage's build request. Returns:

    * ``"pending"`` — the build is queued or running (possibly submitted
      right now): serve this batch eagerly and ask again next batch;
    * ``"failed"`` — a pool build of this key raised; the stored
      exception (:func:`failure`) lets the caller replicate its
      synchronous failure semantics;
    * ``"go-sync"`` — the pool is off/closing: build inline.

    A completed job is dropped from the table, so the caller's next
    consult never reaches here — ``plan.physical.fused_cached`` turns
    True first and the stage takes the plain cache-hit path (the
    eager -> compiled swap, one batch boundary after the build lands)."""
    # consult is called once per batch boundary while a build is in
    # flight — a named lifecycle poll point: a cancelled query must stop
    # re-asking for a program it will never run
    from .lifecycle import check_cancel
    check_cancel()
    deadline_at = qc.current_deadline_at()
    with _cond:
        if key in _failed:
            return "failed"
        job = _jobs.get(key)
        if job is not None:
            if not job.running and deadline_at is not None and \
                    deadline_at < job.deadline_at:
                # a more urgent query wants the same program: re-push at
                # the tighter deadline (the stale heap entry is skipped)
                job.deadline_at = deadline_at
                job.tier = 0
                heapq.heappush(_queue, (0, deadline_at, next(_seq), key))
                _cond.notify()
            return "pending"
        if _shutdown or not _enabled:
            return "go-sync"
        job = _Job(key, builder, warm_args, kernel, tier=0,
                   deadline_at=deadline_at)
        _jobs[key] = job
        heapq.heappush(_queue, (0, job.deadline_at, next(_seq), key))
        _ensure_workers_locked()
        _cond.notify()
        depth = len(_jobs)
    _publish_depth(depth)
    return "pending"


def status(key) -> Optional[str]:
    """``"pending"`` while a build of ``key`` is queued/running,
    ``"failed"`` when a pool build of it raised, None when the pool is
    not tracking it (never submitted, or completed — completed keys are
    answered by the fused cache itself, not by this table)."""
    with _mu:
        if key in _failed:
            return "failed"
        if key in _jobs:
            return "pending"
    return None


def failure(key) -> Optional[BaseException]:
    """The exception a pool build of ``key`` died with (None when the
    key never failed). Failed keys are remembered — dropping them would
    resubmit the doomed build every batch — bounded to the oldest
    ``_FAILED_MAX`` distinct keys."""
    with _mu:
        return _failed.get(key)


def drain(timeout_s: float = 120.0) -> bool:
    """Block until every queued/running build completes (tests, the
    prewarm CLI, ``runner --prewarm``). True when the pool went idle
    inside the timeout."""
    deadline = time.monotonic() + timeout_s
    with _cond:
        while _jobs:
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            _cond.wait(min(left, 0.2))
    return True


def stats() -> Dict[str, int]:
    with _mu:
        return {"pending": len(_jobs),
                "failed": len(_failed),
                "asyncBuilt": _async_built,
                "prewarmBuilt": _prewarm_built}


# ---------------------------------------------------------------------------
# Worker pool
# ---------------------------------------------------------------------------

def _ensure_workers_locked() -> None:
    while len(_threads) < _workers_target:
        t = threading.Thread(target=_worker_loop, daemon=True,
                             name=f"tpu-compile-{len(_threads)}")
        _threads.append(t)
        t.start()


def _worker_loop() -> None:
    while True:
        with _cond:
            while not _queue and not _shutdown:
                _cond.wait(0.2)
            if _shutdown:
                return
            _tier, _dl, _s, key = heapq.heappop(_queue)
            job = _jobs.get(key)
            if job is None or job.running:
                continue           # stale heap entry (re-push / done)
            job.running = True
        _run_job(job)


def _run_job(job: "_Job") -> None:
    delay = _test_build_delay_s
    if delay:
        time.sleep(delay)
    err: Optional[BaseException] = None
    t0 = time.perf_counter()
    try:
        from ..plan.physical import _fused_fn
        # the SAME funnel as a synchronous build: classify, recompile
        # audit, signature record, first-call timing — then the warm
        # call actually pays the XLA compile here, on the pool thread
        fn = _fused_fn(job.key, job.builder)
        fn(*job.warm_args)
    except BaseException as e:
        err = e
    global _async_built, _prewarm_built
    with _cond:
        _jobs.pop(job.key, None)
        if err is not None:
            if len(_failed) >= _FAILED_MAX:
                _failed.pop(next(iter(_failed)), None)
            _failed[job.key] = err
        elif job.tier == _PREWARM_TIER:
            _prewarm_built += 1
        else:
            _async_built += 1
        depth = len(_jobs)
        prewarm_done = err is None and job.tier == _PREWARM_TIER
        _cond.notify_all()
    _publish_depth(depth)
    if prewarm_done:
        try:
            from ..service.telemetry import MetricsRegistry
            MetricsRegistry.get().counter(
                "tpu_prewarm_compiles_total",
                "fused programs built by bootstrap prewarm (tier-1 pool "
                "jobs, strictly below query-triggered builds)").inc()
        except Exception:
            pass
    if err is not None:
        log.warning(
            "background build of %s failed after %.3fs (%s: %s) — the "
            "requesting stage falls back to per-op eager",
            job.kernel or "program", time.perf_counter() - t0,
            type(err).__name__, err)


def _publish_depth(depth: int) -> None:
    try:
        from ..service.telemetry import MetricsRegistry
        MetricsRegistry.get().gauge(
            "tpu_compile_queue_depth",
            "compile-pool jobs queued or building").set(float(depth))
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Prewarm corpus (record on build, replay at bootstrap)
# ---------------------------------------------------------------------------

def _corpus_path() -> Optional[str]:
    from . import compile_cache as _cc
    d = _cc.active_dir()
    return os.path.join(d, CORPUS_NAME) if d else None


def _arg_specs(warm_args: tuple) -> Optional[List[tuple]]:
    import jax
    import numpy as np
    specs: List[tuple] = []
    for a in warm_args:
        if isinstance(a, jax.Array):
            specs.append(("arr", tuple(a.shape), str(a.dtype),
                          bool(getattr(a, "weak_type", False))))
        elif isinstance(a, np.ndarray):
            # host param arrays (ex.param_arg_values): jit signatures
            # depend only on shape/dtype, so a zeros stand-in replays
            specs.append(("np", tuple(a.shape), str(a.dtype)))
        elif isinstance(a, (int, float, bool)) or a is None:
            specs.append(("py", a))
        else:
            return None            # unreplayable argument kind
    return specs


def _reconstruct_args(specs: List[tuple]) -> tuple:
    import jax.numpy as jnp
    import numpy as np
    args: List[Any] = []
    for spec in specs:
        if spec[0] == "py":
            args.append(spec[1])
            continue
        if spec[0] == "np":
            args.append(np.zeros(spec[1], dtype=spec[2]))
            continue
        _tag, shape, dtype, weak = spec
        if weak and shape == ():
            # weak scalars only arise from python-number arguments:
            # replay one so the jit signature matches
            args.append(jnp.zeros((), dtype).item())  # lint: host-sync-ok prewarm arg replay on the pool thread, not a query hot path
        else:
            args.append(jnp.zeros(shape, dtype))
    return tuple(args)


def note_stage_signature(key, kernel: str, chain, donate: tuple,
                         warm_args: tuple) -> None:
    """Record one stage build into the prewarm corpus (best-effort,
    once per signature per process): the pickled rebuild recipe a fresh
    process replays at bootstrap. Unpicklable chains are skipped with a
    debug note — prewarm is an optimization, never a correctness
    surface."""
    path = _corpus_path()
    if path is None:
        return
    from . import compile_cache as _cc
    sig = _cc.sig_hash(key)
    with _mu:
        if sig in _corpus_recorded:
            return
        _corpus_recorded.add(sig)
    try:
        specs = _arg_specs(warm_args)
        if specs is None:
            return
        payload = pickle.dumps((key, chain, tuple(donate), specs),
                               protocol=pickle.HIGHEST_PROTOCOL)
        import json
        line = json.dumps({"sig": sig, "kernel": kernel,
                           "spec": base64.b64encode(payload).decode()})
        with open(path, "a") as f:
            f.write(line + "\n")
    except Exception as e:
        log.debug("prewarm corpus record skipped for %s: %s", kernel, e)


def _load_corpus(path: str) -> List[Tuple[int, str, dict]]:
    """Corpus entries ranked hottest-first: (build count, signature,
    latest entry) per signature. Torn tail lines are skipped, exactly
    like the signature index load. Ties break on the stable signature
    hash, NOT file position — two corpora with the same content in a
    different append order replay identically (the prewarm order is
    lockstep-observable through compile timing)."""
    import json
    counts: Dict[str, int] = {}
    latest: Dict[str, Tuple[int, dict]] = {}
    try:
        with open(path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    ent = json.loads(line)
                except ValueError:
                    continue       # torn write from a killed process
                sig = ent.get("sig") if isinstance(ent, dict) else None
                if not sig or "spec" not in ent:
                    continue
                counts[sig] = counts.get(sig, 0) + 1
                latest[sig] = (i, ent)
    except OSError:
        return []
    ranked = [(counts[sig], sig, ent) for sig, (_i, ent) in latest.items()]
    ranked.sort(key=lambda t: (-t[0], t[1]))
    return ranked


def prewarm(conf=None) -> int:
    """Queue tier-1 builds for the top-N hottest recorded signatures
    (``compile.prewarm.topN``) and return how many were submitted.
    Non-blocking — callers that must be warm BEFORE serving (the CLI,
    ``runner --prewarm``, the subprocess gate test) follow with
    :func:`drain`. Signatures already in the fused cache are skipped."""
    from .. import config as cfg
    if conf is None:
        conf = cfg.TpuConf()
    path = _corpus_path()
    if path is None:
        return 0
    try:
        top_n = max(1, int(conf.get(cfg.COMPILE_PREWARM_TOP_N)))
    except Exception:
        top_n = 32
    from ..plan import physical as ph
    from ..plan.stage_compiler import build_stage_program
    submitted = 0
    for _count, _ln, ent in _load_corpus(path)[:top_n]:
        try:
            payload = base64.b64decode(ent["spec"])
            key, chain, donate, specs = pickle.loads(payload)
            warm_args = _reconstruct_args(specs)
        except Exception as e:
            log.debug("prewarm entry %s skipped: %s",
                      ent.get("kernel"), e)
            continue
        if ph.fused_cached(key):
            continue
        with _cond:
            if _shutdown or not _enabled or key in _jobs:
                continue
            job = _Job(key, _prewarm_builder(build_stage_program, chain,
                                             donate),
                       warm_args, str(ent.get("kernel") or ""),
                       tier=_PREWARM_TIER, deadline_at=None)
            _jobs[key] = job
            heapq.heappush(_queue,
                           (_PREWARM_TIER, _INF, next(_seq), key))
            _ensure_workers_locked()
            _cond.notify()
            depth = len(_jobs)
        _publish_depth(depth)
        submitted += 1
    if submitted:
        log.info("prewarm: %d stage program(s) queued from %s",
                 submitted, path)
    return submitted


def _prewarm_builder(build_stage_program, chain, donate):
    return lambda: build_stage_program(chain, donate)


# ---------------------------------------------------------------------------
# Test / lifecycle plumbing
# ---------------------------------------------------------------------------

def set_test_build_delay(seconds: float) -> None:
    """Hold every pool build in flight for ``seconds`` (race tests: the
    window in which batches MUST drain eagerly)."""
    global _test_build_delay_s
    _test_build_delay_s = float(seconds)  # lint: unguarded-ok test-only scalar toggle


def reset_for_tests() -> None:
    """Drop queued jobs, failure memory and counters (unit-test
    isolation). Running builds finish on their own; their results land
    in the fused cache harmlessly."""
    global _async_built, _prewarm_built, _test_build_delay_s
    with _cond:
        _queue.clear()
        for key in [k for k, j in _jobs.items() if not j.running]:
            _jobs.pop(key, None)
        _failed.clear()
        _corpus_recorded.clear()
        _async_built = 0
        _prewarm_built = 0
        _test_build_delay_s = 0.0
        _cond.notify_all()
