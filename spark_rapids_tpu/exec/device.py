"""Device manager + task semaphore: the GpuDeviceManager / GpuSemaphore analog.

Reference: ``GpuDeviceManager.scala:31-306`` (one GPU per executor, RMM pool
init, pinned pool) and ``GpuSemaphore.scala:27-161`` (bounds concurrent tasks
on the device; acquire AFTER first batch materialized / IO done).

TPU differences: XLA/PJRT owns the HBM allocator, so the "pool" here is an
accounting budget enforced by the spill framework (spill.py) rather than a
sub-allocator; jax array donation + XLA buffer reuse replace RMM arena blocks.
The semaphore contract transfers unchanged: admission control for host threads
driving device work, sized by ``spark.rapids.tpu.sql.concurrentTpuTasks``.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from .. import config as cfg
from ..analysis.lockdep import named_lock


class DeviceManager:
    """Process-singleton device bootstrap (GpuDeviceManager.initializeGpuAndMemory
    analog, Plugin.scala:124-154 executor init)."""

    _instance: Optional["DeviceManager"] = None
    _lock = named_lock("exec.device.DeviceManager._lock")

    def __init__(self, conf: Optional[cfg.TpuConf] = None):
        import jax
        self.conf = conf or cfg.TpuConf()
        self.devices = jax.devices()
        self.device = self.devices[0]
        self.platform = self.device.platform
        self.memory_budget_bytes = self._compute_budget()

    def _compute_budget(self) -> int:
        """allocFraction * device memory (GpuDeviceManager.scala:159-262)."""
        frac = self.conf.get(cfg.ALLOC_FRACTION)
        stats = None
        try:
            stats = self.device.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"] * frac)
        # CPU backend / no stats: fall back to a conservative fixed budget
        return int(self.conf.get(cfg.BATCH_SIZE_BYTES)) * 8

    @classmethod
    def get(cls, conf: Optional[cfg.TpuConf] = None) -> "DeviceManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceManager(conf)
            return cls._instance

    @classmethod
    def peek(cls) -> Optional["DeviceManager"]:
        """The existing instance or None — never constructs (the
        telemetry harvest must not probe a device as a side effect)."""
        with cls._lock:
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None

    def synchronize(self) -> None:
        """Block until all outstanding device work completes."""
        import jax
        (jax.device_put(0) + 0).block_until_ready()  # lint: host-sync-ok device warmup barrier at init, not a hot path


class TpuSemaphore:
    """Bounds the number of concurrently-executing device tasks
    (GpuSemaphore.scala:27-161). Ordering contract preserved from the
    reference: acquire only after the task's first input batch is ready
    (i.e. after host-side IO/decode), release on task completion.

    Instrumented with a wait-vs-hold split: WAIT is the time a task blocks
    acquiring a permit (admission contention — fixed by raising
    concurrentTpuTasks), HOLD is acquire->release (device occupancy —
    fixed by making the held work faster, e.g. pipelining its readbacks).
    Both feed the per-query span report (``semaphore_wait`` /
    ``semaphore_hold``) and cumulative counters the bench harness reads,
    so the two failure modes are separable in reports instead of one
    undifferentiated ``semaphore_acquire`` bucket."""

    _instance: Optional["TpuSemaphore"] = None
    _lock = named_lock("exec.device.TpuSemaphore._lock")

    def __init__(self, max_concurrent: int):
        self.max_concurrent = max_concurrent
        # deliberately raw: the admission semaphore is HELD across whole
        # device task bodies (transfers included) by contract — it is
        # instrumented separately with the wait/hold split below
        self._sem = threading.Semaphore(max_concurrent)  # lint: raw-lock-ok admission semaphore, held across device work by design; wait/hold instrumented here
        self._held = threading.local()
        self._stats_mu = named_lock("exec.device.TpuSemaphore._stats_mu")
        self.wait_s = 0.0
        self.hold_s = 0.0
        self.acquires = 0
        # threads currently BLOCKED in acquire: the live device-admission
        # queue depth (the multi-tenant service's dashboard shows it next
        # to its own per-tenant queue depth, docs/service.md §1)
        self.waiting = 0

    @classmethod
    def initialize(cls, max_concurrent: int) -> "TpuSemaphore":
        with cls._lock:
            cls._instance = TpuSemaphore(max_concurrent)
            return cls._instance

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                cls._instance = TpuSemaphore(
                    cfg.TpuConf().get(cfg.CONCURRENT_TPU_TASKS))
            return cls._instance

    @classmethod
    def peek(cls) -> Optional["TpuSemaphore"]:
        """The existing instance or None — never constructs (telemetry
        harvest: an idle process contributes no samples)."""
        with cls._lock:
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None

    def stats(self) -> dict:
        """Cumulative wait/hold seconds + acquire count + live blocked
        count (bench harness, the service dashboard)."""
        with self._stats_mu:
            return {"waitS": round(self.wait_s, 4),
                    "holdS": round(self.hold_s, 4),
                    "acquires": self.acquires,
                    "waiting": self.waiting}

    def acquire_if_necessary(self) -> None:
        """Idempotent per-thread acquire (GpuSemaphore.acquireIfNecessary)."""
        import time
        from .tracing import record_span
        if getattr(self._held, "value", False):
            return
        t0 = time.perf_counter()
        with self._stats_mu:
            self.waiting += 1
        try:
            self._sem.acquire()
        finally:
            with self._stats_mu:
                self.waiting -= 1
        now = time.perf_counter()
        waited = now - t0
        self._held.value = True
        self._held.acquired_at = now
        record_span("semaphore_wait", waited)
        with self._stats_mu:
            self.wait_s += waited
            self.acquires += 1

    def release_if_necessary(self) -> None:
        import time
        from .tracing import record_span
        if getattr(self._held, "value", False):
            held_for = time.perf_counter() - getattr(
                self._held, "acquired_at", time.perf_counter())
            self._sem.release()
            self._held.value = False
            record_span("semaphore_hold", held_for)
            with self._stats_mu:
                self.hold_s += held_for

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
        return False
