"""Query lifecycle control plane: cooperative cancel, suspend, resume.

ROADMAP item 2 named the gap: the multi-tenant service could shed QUEUED
work (admission rejection, deadline shed) but a RUNNING query was
uncontrollable — one long low-priority collect held its execution slot
against a high-priority arrival until it finished or died. This module
is the control plane that closes that gap, standing on the substrate the
earlier PRs built: spillable tenant-tagged buffers (exec/spill.py),
bounded stage retries (exec/recovery.py), and the buffer-lifecycle
ledger (analysis/ledger.py) that can *prove* a cancelled or suspended
query released everything.

Three pieces:

* :class:`CancelToken` — a per-query flag pair (cancelled /
  suspend-requested) with lock-free reads, polled cooperatively via
  :func:`check_cancel` at every long-running loop boundary (partition
  drain, shuffle fetch/completion polls, stage-retry backoff dwells,
  compile-pool consult, ``collect_iter`` delivery — the ``cancel-point``
  lint rule keeps the poll set honest). A set flag raises the typed
  :class:`QueryCancelledError` (mapped to FAIL_QUERY by
  ``exec/recovery.classify`` — cancellation is never retried) or
  :class:`QuerySuspendedError` (caught ONLY by the service worker loop,
  which parks the ticket instead of failing it).
* a process-global ``query_id -> token`` registry so external surfaces
  (``QueryService.cancel/suspend/resume``, ``session.cancel_query``,
  the shuffle META reply that propagates cancellation cross-process the
  way divergence snapshots ride it) can reach a running query by id.
* a timestamped transition log per query (``submitted -> running ->
  suspend-requested -> suspended -> resumed -> ...``), flight-recorded
  (kind ``lifecycle``) and surfaced in the query log's ``lifecycle``
  field; transitions of recently finished queries are retained in a
  bounded retired map so the log record written at end-of-query still
  sees them.

Deadline enforcement rides the same poll: a running query whose
admission deadline lapses is cancelled (reason ``deadline``) at its next
poll point — stage boundaries included — instead of running to
completion (the "shed before the deadline lapses" promise, ROADMAP
item 3). The chaos points ``cancel.inject`` / ``preempt.inject``
(analysis/faults.py) fire inside :func:`check_cancel`, so every
lifecycle path is deterministically testable.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from ..analysis.lockdep import named_lock

#: lifecycle states (transition-log vocabulary; the query log and
#: ``tools/query_report`` consume these strings verbatim)
RUNNING = "running"
CANCELLED = "cancelled"
SUSPEND_REQUESTED = "suspend-requested"
SUSPENDED = "suspended"
RESUMED = "resumed"


class QueryCancelledError(RuntimeError):
    """A query observed its cancel flag at a poll point. FAIL_QUERY in
    the recovery taxonomy: retrying cancelled work would resurrect the
    exact execution the caller asked to stop."""

    def __init__(self, query_id: Optional[str] = None,
                 reason: str = "cancel"):
        self.query_id = query_id
        self.reason = reason
        super().__init__(
            f"query {query_id or '<unidentified>'} cancelled ({reason})")


class QuerySuspendedError(RuntimeError):
    """Control-flow signal, not a failure: a query observed its
    suspend-request flag at a poll point and is unwinding so the service
    worker loop can park its ticket (spill the working set, free the
    slot) and later resume it. Only ``service/server._worker_loop``
    catches this; anywhere else it propagates like any unknown error
    (FAIL_QUERY) — a suspend request against a direct caller-owned
    collect has no scheduler to park under."""

    def __init__(self, query_id: Optional[str] = None):
        self.query_id = query_id
        super().__init__(
            f"query {query_id or '<unidentified>'} suspended (preempted)")


class CancelToken:
    """One query's cooperative lifecycle flags. Flag READS are lock-free
    (polled at hot loop boundaries); transitions serialize under the
    token's own lock and append to the timestamped transition log."""

    def __init__(self, query_id: Optional[str] = None):
        self.query_id = query_id
        self._cancelled = False
        self._cancel_reason: Optional[str] = None
        self._suspend_requested = False
        self._state = RUNNING
        #: parked stage cursor (which stage, which partitions completed)
        #: recorded by the poll site that raised the suspension — the
        #: stage-retry driver re-enters the stage on resume, durable
        #: outputs and the plan cache make the re-entry cheap
        self.cursor: Optional[Dict[str, Any]] = None
        self.transitions: List[Dict[str, Any]] = [
            {"state": RUNNING, "tS": round(time.time(), 3)}]
        self._mu = named_lock("exec.lifecycle.CancelToken._mu")

    # -- transitions ---------------------------------------------------------

    def _note_locked(self, state: str,
                     reason: Optional[str] = None) -> None:
        entry: Dict[str, Any] = {"state": state,
                                 "tS": round(time.time(), 3)}
        if reason:
            entry["reason"] = reason
        self.transitions.append(entry)
        self._state = state

    def _flight(self, state: str, reason: Optional[str] = None) -> None:
        # OUTSIDE the token lock: flight_record takes the telemetry
        # singleton lock and must never nest under an engine lock
        try:
            from ..service.telemetry import flight_record
            flight_record("lifecycle", f"{state}-{self.query_id or '?'}",
                          {"reason": reason} if reason else None)
        except Exception:
            pass

    def cancel(self, reason: str = "cancel") -> bool:
        """Set the cancel flag (idempotent; first caller's reason wins).
        The query unwinds at its NEXT poll point — cooperative, never a
        thread kill."""
        with self._mu:
            if self._cancelled:
                return False
            self._cancel_reason = reason
            self._cancelled = True
            self._note_locked(CANCELLED, reason)
        self._flight(CANCELLED, reason)
        _count("tpu_query_cancelled_total")
        return True

    def request_suspend(self, reason: str = "preempt") -> bool:
        """Ask the query to park at its next poll point. No-op when
        already cancelled or already requested."""
        with self._mu:
            if self._cancelled or self._suspend_requested:
                return False
            self._suspend_requested = True
            self._note_locked(SUSPEND_REQUESTED, reason)
        self._flight(SUSPEND_REQUESTED, reason)
        return True

    def mark_suspended(self, cursor: Optional[Dict[str, Any]] = None) \
            -> None:
        """The service worker loop parked the ticket: working set spilled,
        slot freed, stage cursor recorded."""
        with self._mu:
            if cursor is not None:
                self.cursor = cursor
            self._note_locked(SUSPENDED)
        self._flight(SUSPENDED)
        _count("tpu_query_preempted_total")

    def resume(self) -> None:
        """Re-arm for re-admission: clears the suspend request so the
        re-executed thunk runs instead of immediately re-parking."""
        with self._mu:
            self._suspend_requested = False
            self._note_locked(RESUMED)
        self._flight(RESUMED)
        _count("tpu_query_resumed_total")

    def park_cursor(self, stage: Optional[str] = None,
                    partitions_done: Optional[List[int]] = None) -> None:
        """Record WHERE the suspension unwound from (the poll site that
        raised knows its stage and completed partitions)."""
        with self._mu:
            self.cursor = {"stage": stage,
                           "partitionsDone": list(partitions_done or ())}

    # -- lock-free poll surface ----------------------------------------------

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def suspend_requested(self) -> bool:
        return self._suspend_requested

    @property
    def state(self) -> str:
        return self._state

    def check(self) -> None:
        """Raise if a lifecycle flag is set (the poll primitive)."""
        if self._cancelled:
            raise QueryCancelledError(self.query_id, self._cancel_reason
                                      or "cancel")
        if self._suspend_requested:
            raise QuerySuspendedError(self.query_id)


# ---------------------------------------------------------------------------
# process-global registry: query id -> live token
# ---------------------------------------------------------------------------

_mu = named_lock("exec.lifecycle._mu")
_tokens: Dict[str, CancelToken] = {}
#: transitions of recently finished queries (bounded): the query-log
#: record is built AFTER the collect path unregisters, and a late peer
#: META poll may still ask "is qid cancelled?" after local teardown
_retired: "collections.OrderedDict[str, List[Dict[str, Any]]]" = \
    collections.OrderedDict()
_RETIRED_CAP = 128
#: query ids cancelled in THIS process, retained past unregistration so
#: the shuffle META reply keeps answering peers that poll late
_cancelled_qids: "collections.OrderedDict[str, str]" = \
    collections.OrderedDict()


def register(ctx, token: Optional[CancelToken] = None) -> CancelToken:
    """Adopt (or mint) the cancel token for a freshly minted query
    context and index it by query id. Collect paths call this right
    after the context mint; the service worker pre-mints the token and
    hands it down via ``query_context.cancel_token_scope`` so the ticket
    and the execution share one token."""
    tok = token if token is not None else \
        getattr(ctx, "cancel_token", None)
    if tok is None:
        tok = CancelToken(ctx.query_id)
    tok.query_id = ctx.query_id
    ctx.cancel_token = tok
    with _mu:
        _tokens[ctx.query_id] = tok
    return tok


def unregister(query_id: Optional[str]) -> None:
    """End-of-query: drop the live index, retire the transition log."""
    if not query_id:
        return
    with _mu:
        tok = _tokens.pop(query_id, None)
        if tok is not None:
            _retired[query_id] = list(tok.transitions)
            while len(_retired) > _RETIRED_CAP:
                _retired.popitem(last=False)
            if tok.cancelled:
                _cancelled_qids[query_id] = tok._cancel_reason or "cancel"
                while len(_cancelled_qids) > _RETIRED_CAP:
                    _cancelled_qids.popitem(last=False)


def token_for(query_id: Optional[str]) -> Optional[CancelToken]:
    if not query_id:
        return None
    with _mu:
        return _tokens.get(query_id)


def cancel_query(query_id: str, reason: str = "cancel") -> bool:
    """Cancel a running query by id (the external surface —
    ``QueryService.cancel``, ``session.cancel_query``, the META-borne
    peer cancellation). False when no such query is live."""
    tok = token_for(query_id)
    if tok is None:
        return False
    return tok.cancel(reason)


def request_suspend(query_id: str, reason: str = "preempt") -> bool:
    tok = token_for(query_id)
    if tok is None:
        return False
    return tok.request_suspend(reason)


def is_cancelled(query_id: Optional[str]) -> bool:
    """Has ``query_id`` been cancelled in THIS process (live token OR
    retired)? The shuffle META server stamps this into its reply so a
    peer's poll loop learns the cancellation the way it learns
    divergence snapshots — no new round trip."""
    if not query_id:
        return False
    with _mu:
        tok = _tokens.get(query_id)
        if tok is not None:
            return tok.cancelled
        return query_id in _cancelled_qids


def transitions_for(query_id: Optional[str]) -> List[Dict[str, Any]]:
    """The transition log for a query (live or recently retired); empty
    for unknown ids. The query log's ``lifecycle`` field — only
    non-trivial logs (anything past the initial ``running``) are worth
    recording there."""
    if not query_id:
        return []
    with _mu:
        tok = _tokens.get(query_id)
        if tok is not None:
            return list(tok.transitions)
        return list(_retired.get(query_id, ()))


def live_queries() -> List[str]:
    with _mu:
        return sorted(_tokens)


# ---------------------------------------------------------------------------
# the ambient poll
# ---------------------------------------------------------------------------

def check_cancel() -> None:
    """THE cooperative poll: resolve the ambient query's token and raise
    if cancellation/suspension is pending. Called at every long-running
    loop boundary (lint rule ``cancel-point`` enforces the set). Cheap
    on the happy path: one TLS read plus two attribute reads; the fault
    points and the deadline comparison only run when a token exists.

    Side effects, in order:

    * ``cancel.inject`` / ``preempt.inject`` chaos points fire here —
      deterministic lifecycle testing without a second thread racing the
      poll;
    * a lapsed admission deadline cancels the query (reason
      ``deadline``) — running queries now honor the deadline at stage
      boundaries, not only at admission;
    * the token's flags raise :class:`QueryCancelledError` /
      :class:`QuerySuspendedError`.
    """
    from . import query_context as qc
    ctx = qc.current()
    tok: Optional[CancelToken] = getattr(ctx, "cancel_token", None) \
        if ctx is not None else None
    if tok is None:
        return
    from ..analysis import faults
    if faults.fire("cancel.inject"):
        tok.cancel("cancel.inject")
    if faults.fire("preempt.inject"):
        tok.request_suspend("preempt.inject")
    if not tok.cancelled:
        ddl = qc.current_deadline_at()
        if ddl is not None and time.perf_counter() > ddl:
            tok.cancel("deadline")
    tok.check()


def interruptible_sleep(seconds: float, slice_s: float = 0.05) -> None:
    """``time.sleep`` that polls :func:`check_cancel` every ``slice_s``:
    backoff dwells (stage-retry sleeps, fetch-poll delays) must not keep
    a cancelled query alive for the full dwell."""
    check_cancel()
    deadline = time.monotonic() + max(0.0, seconds)
    while True:  # lint: cancel-ok polls check_cancel every slice by construction
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        time.sleep(min(slice_s, remaining))
        check_cancel()


def _count(name: str) -> None:
    """Bump a lifecycle counter, tenant-labelled when ambient (the
    telemetry surface is declared in TELEMETRY_KEYS; never raises)."""
    try:
        from . import query_context as qc
        from ..service.telemetry import MetricsRegistry
        tenant = qc.current_tenant()
        MetricsRegistry.get().counter(
            name, "query lifecycle transitions",
            **({"tenant": tenant} if tenant else {})).inc()
    except Exception:
        pass
