"""Per-operator metrics: the GpuMetric / SQLMetrics layer.

Reference: ``GpuExec.scala:27-56`` — every GpuExec owns a bag of SQLMetrics
(``GpuMetricNames``: numOutputRows, numOutputBatches, opTime, plus
per-operator ``additionalMetrics``) surfaced per operator in the Spark UI.
Here every :class:`~..plan.physical.TpuExec` instance owns a
:class:`TpuMetrics` bag, populated three ways:

* explicitly — ``self.metrics.inc("numOutputRows", n)`` and
  ``trace_span(name, self.metrics, "opTime")`` timer feeds;
* by ATTRIBUTION — while a metered span is open, this module tracks the
  innermost open exec's bag in a thread-local stack (:func:`exec_scope`),
  and cross-cutting instruments route their events to it:
  ``SyncCounter`` adds ``hostSyncs`` per blocking device->host readback,
  the recompile audit adds ``recompiles`` per fused-program build, and the
  spill store adds ``spillBytes`` when a buffer leaves the device tier —
  so EXPLAIN ANALYZE shows which operator paid for what, not just a
  process-wide total;
* lazily — device-resident amounts (lazy batch counts) bank unresolved and
  fold in one batched readback at reporting boundaries (``resolve``).

Every exec class declares its metric-key surface with
``METRICS = exec_metrics(...)`` next to its CONTRACT; the project linter
(``analysis/lint.py`` rules ``exec-metrics`` / ``metric-key``) enforces
that declared set covers every literal key the class emits, keeping the
metrics surface greppable and drift-free.

Collection is gated by ``spark.rapids.tpu.sql.metrics.enabled``
(default on; one cached-bool check per inc when off).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Iterator, Optional, Tuple

# ---------------------------------------------------------------------------
# Declared metric keys
# ---------------------------------------------------------------------------

#: Keys every exec may emit without declaring them: the GpuMetricNames
#: basics plus the cross-cutting attributed keys this module routes.
#: (Mirrored in analysis/lint.py BASE_METRIC_KEYS — the linter is pure
#: AST and cannot import this module.)
BASE_METRICS: Tuple[str, ...] = (
    "numOutputRows", "numOutputBatches", "opTime",
    "hostSyncs", "recompiles", "spillBytes", "peakDeviceBytes",
    "compileSeconds",
)


def exec_metrics(*extras: str) -> frozenset:
    """Declare an exec class's metric-key surface (its ``METRICS`` class
    attribute): the base keys plus the class's additionalMetrics
    (``GpuExec.additionalMetrics`` analog). Keys must be string literals —
    the linter checks usage against the declaration lexically."""
    assert all(isinstance(k, str) and k for k in extras), extras
    return frozenset(BASE_METRICS) | frozenset(extras)


# ---------------------------------------------------------------------------
# Enabled gate (spark.rapids.tpu.sql.metrics.enabled)
# ---------------------------------------------------------------------------

_enabled_cache: Optional[bool] = None


def metrics_enabled() -> bool:
    # primed EAGERLY by session bootstrap (refresh) like lockdep: a lazy
    # read of the ACTIVE session's conf would take TpuSession._lock, and
    # attributed incs can run under the spill catalog's admission lock —
    # a lazy prime there would add a catalog->session lock-order edge
    # opposing bootstrap's session->catalog one
    global _enabled_cache
    if _enabled_cache is None:
        try:
            from .. import config as cfg
            _enabled_cache = bool(cfg.TpuConf().get(cfg.METRICS_ENABLED))
        except Exception:
            _enabled_cache = True
    return _enabled_cache


def refresh(conf) -> None:
    """Prime the enabled gate from a session conf (bootstrap)."""
    global _enabled_cache
    try:
        from .. import config as cfg
        _enabled_cache = bool(conf.get(cfg.METRICS_ENABLED))
    except Exception:
        _enabled_cache = True


def reset_cache() -> None:
    global _enabled_cache
    _enabled_cache = None


# ---------------------------------------------------------------------------
# Innermost-open-exec attribution
# ---------------------------------------------------------------------------
#
# trace_span(metrics=...) pushes the bag for the span's duration; the stack
# is thread-local because partition drains run concurrently on the task
# pool and two execs' spans must not see each other. Cross-cutting
# instruments (SyncCounter, recompile audit, spill store) call
# ``attribute`` to charge the innermost open exec.

_TLS = threading.local()


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


@contextmanager
def exec_scope(metrics: Optional["TpuMetrics"]) -> Iterator[None]:
    """Mark ``metrics`` as the innermost open exec bag on this thread for
    the duration (no-op for None). Entered by ``trace_span`` whenever a
    metered exec span opens, and by ``PipelineWindow`` around its batched
    resolve so deferred readbacks still charge the exec that parked them."""
    if metrics is None:
        yield
        return
    st = _stack()
    st.append(metrics)
    try:
        yield
    finally:
        # remove by identity, not pop(): spans held open across generator
        # yields close out of order (the SpanRecorder._pop lesson), and a
        # bare pop would steal a younger exec's open scope
        for i in range(len(st) - 1, -1, -1):
            if st[i] is metrics:
                del st[i]
                break


def current() -> Optional["TpuMetrics"]:
    """The innermost open exec's metrics bag on THIS thread (None outside
    any metered exec span)."""
    st = _stack()
    return st[-1] if st else None


def attribute(key: str, amount: float = 1) -> None:
    """Charge ``amount`` of ``key`` to the innermost open exec, if any.
    The funnel SyncCounter (hostSyncs), the recompile audit (recompiles)
    and the spill store (spillBytes) route through."""
    m = current()
    if m is not None:
        m.inc(key, amount)


# ---------------------------------------------------------------------------
# The metrics bag
# ---------------------------------------------------------------------------

class TpuMetrics(dict):
    """One exec instance's metric bag (GpuExec.allMetrics analog).

    Plain ``dict`` of key -> number. Device-resident amounts (lazy batch
    counts) bank unresolved and fold in one batched readback at reporting
    boundaries so metric accounting never forces a device sync on the hot
    path."""

    # a RAW leaf lock on purpose: inc runs per batch per operator on every
    # task thread, and a lockdep NamedLock would take the process-global
    # lockdep state mutex up to 3x per inc under record mode (the bench
    # default) — serializing the task pool on the counters the bench
    # exists to measure. The bag lock never nests, so order tracking
    # buys nothing here.
    _lock = threading.Lock()  # lint: raw-lock-ok leaf counter lock on the hottest inc path; lockdep instrumentation would contend the global lockdep state per metric inc

    # keys that are LOAD-BEARING, not just observability: the AQE runtime
    # broadcast switch reads the exchange's observed dataSize
    # (physical._maybe_runtime_broadcast), so it must accumulate even
    # when sql.metrics.enabled is off
    LOAD_BEARING_KEYS = frozenset({"dataSize"})

    # watermark-style keys are SET (max), not summed — publishing their
    # growth into a cumulative registry counter would add peaks together
    WATERMARK_KEYS = frozenset({"peakDeviceBytes"})

    def inc(self, key: str, amount: float = 1) -> None:
        # partitions drain on concurrent task threads; keep counters exact.
        if not metrics_enabled() and key not in TpuMetrics.LOAD_BEARING_KEYS:
            return
        if not isinstance(amount, (int, float)):
            with TpuMetrics._lock:
                if not hasattr(self, "_pending"):
                    self._pending = []
                self._pending.append((key, amount))
                flush = len(self._pending) >= 256
            if flush:          # bound the deferred-scalar backlog
                self.resolve()
            return
        with TpuMetrics._lock:
            self[key] = dict.get(self, key, 0) + amount

    def max(self, key: str, value: float) -> None:
        """Raise ``key`` to at least ``value`` (watermark-style metrics:
        the HBM peak attribution sets, never sums)."""
        if not metrics_enabled():
            return
        with TpuMetrics._lock:
            if value > dict.get(self, key, 0):
                self[key] = value

    def resolve(self) -> "TpuMetrics":
        """Fold deferred device-scalar amounts into the counters in one
        batched readback (reporting boundaries; readers below call it)."""
        with TpuMetrics._lock:
            pend = getattr(self, "_pending", [])
            self._pending = []
        if pend:
            import jax
            try:
                vals = jax.device_get([a for _k, a in pend])
            except Exception:
                # one bad scalar must not zero the whole flush: fall back
                # to per-value reads, dropping only the failed ones
                vals = []
                for _k, a in pend:
                    try:
                        vals.append(jax.device_get(a))
                    except Exception:
                        vals.append(None)
            with TpuMetrics._lock:
                for (key, _a), v in zip(pend, vals):
                    if v is None:
                        continue
                    v = v.item() if hasattr(v, "item") else v  # lint: lock-blocking-ok v is a host numpy value (device_get ran unlocked above); .item() is a cast, not a readback
                    if isinstance(v, float) and v.is_integer():
                        v = int(v)     # row/batch counters stay integral
                    self[key] = dict.get(self, key, 0) + v
        self._publish()
        return self

    def _publish(self) -> None:
        """Fold this bag's growth since the last publish into the
        process metrics registry (``tpu_exec_metric_total{key=...}``) —
        the resolve-boundary publish of the continuous-telemetry layer.
        Resolve runs at reporting boundaries, so the registry never sees
        per-batch (let alone per-row) traffic."""
        if not metrics_enabled():
            return
        with TpuMetrics._lock:
            pub = getattr(self, "_published", None)
            if pub is None:
                pub = self._published = {}
            deltas = []
            for key in dict.keys(self):
                if key in TpuMetrics.WATERMARK_KEYS:
                    continue
                d = dict.get(self, key, 0) - pub.get(key, 0)
                if d > 0:
                    deltas.append((key, d))
                    pub[key] = pub.get(key, 0) + d
        if not deltas:
            return
        try:
            from ..service.telemetry import MetricsRegistry
            reg = MetricsRegistry.get()
            for key, d in deltas:
                reg.counter("tpu_exec_metric_total",
                            "per-exec metric totals folded in at bag "
                            "resolve", key=key).inc(d)
        except Exception:
            pass               # telemetry must never fail a metrics read

    # readers see resolved counters (deferred amounts fold in lazily)
    def __getitem__(self, key):
        self.resolve()
        return dict.__getitem__(self, key)

    def get(self, key, default=None):
        if getattr(self, "_pending", None):
            self.resolve()
        return dict.get(self, key, default)

    def items(self):
        self.resolve()
        return dict.items(self)

    def timer(self, key: str):
        return _Timer(self, key)

    def gbps(self, bytes_keys, seconds_keys) -> Optional[float]:
        """Throughput view over this bag: GB/s of the summed byte
        counters over the summed second counters (None when either side
        is empty — a never-executed operator has no rate). The shuffle
        report reads exchange GB/s through this."""
        b = sum(self.get(k, 0) or 0 for k in bytes_keys)
        s = sum(self.get(k, 0.0) or 0.0 for k in seconds_keys)
        if b <= 0 or s <= 0:
            return None
        return b / s / 1e9


class _Timer:
    def __init__(self, metrics: TpuMetrics, key: str):
        self.metrics = metrics
        self.key = key

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.inc(self.key, time.perf_counter() - self.t0)
        return False


# Back-compat alias: physical.py re-exports this as ``Metrics``
Metrics = TpuMetrics
