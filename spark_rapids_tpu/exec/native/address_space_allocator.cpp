// AddressSpaceAllocator: first-fit sub-allocator over a flat address space.
//
// Reference: sql-plugin AddressSpaceAllocator.scala:22 — the reference
// sub-allocates bounce-buffer pools for the shuffle transport out of one
// large registered allocation (BounceBufferManager.scala:35). This is the
// TPU build's native equivalent, used to carve receive/send staging windows
// out of one pinned host arena without per-buffer malloc churn.
//
// Semantics (mirroring the Scala original):
//   - allocate(size): first-fit over the free list; returns the offset or
//     UINT64_MAX when no block fits. Zero-size allocations fail.
//   - free(offset): releases a previously-allocated block; adjacent free
//     blocks coalesce so fragmentation stays bounded.
//   - counters: allocated bytes, block counts, largest free block (the
//     metric the transport uses to decide whether a send window fits).
//
// Build: g++ -O2 -shared -fPIC (no dependencies). Loaded via ctypes —
// CPython C-API bindings are unnecessary for a pure byte-range manager.

#include <cstdint>
#include <map>
#include <mutex>
#include <new>

namespace {

constexpr uint64_t kFail = ~0ULL;

struct Allocator {
  std::mutex mu;
  uint64_t size;
  // free blocks: offset -> length (ordered => adjacency checks are O(log n))
  std::map<uint64_t, uint64_t> free_blocks;
  // allocated blocks: offset -> length
  std::map<uint64_t, uint64_t> used_blocks;
  uint64_t allocated_bytes = 0;

  explicit Allocator(uint64_t sz) : size(sz) {
    if (sz > 0) free_blocks.emplace(0, sz);
  }

  uint64_t allocate(uint64_t want) {
    if (want == 0) return kFail;
    std::lock_guard<std::mutex> lock(mu);
    for (auto it = free_blocks.begin(); it != free_blocks.end(); ++it) {
      if (it->second < want) continue;
      uint64_t off = it->first;
      uint64_t len = it->second;
      free_blocks.erase(it);
      if (len > want) free_blocks.emplace(off + want, len - want);
      used_blocks.emplace(off, want);
      allocated_bytes += want;
      return off;
    }
    return kFail;
  }

  int free_block(uint64_t off) {
    std::lock_guard<std::mutex> lock(mu);
    auto it = used_blocks.find(off);
    if (it == used_blocks.end()) return -1;
    uint64_t len = it->second;
    used_blocks.erase(it);
    allocated_bytes -= len;

    // insert into the free map, then coalesce with neighbours
    auto ins = free_blocks.emplace(off, len).first;
    if (ins != free_blocks.begin()) {
      auto prev = std::prev(ins);
      if (prev->first + prev->second == ins->first) {
        prev->second += ins->second;
        free_blocks.erase(ins);
        ins = prev;
      }
    }
    auto next = std::next(ins);
    if (next != free_blocks.end() &&
        ins->first + ins->second == next->first) {
      ins->second += next->second;
      free_blocks.erase(next);
    }
    return 0;
  }

  uint64_t largest_free() {
    std::lock_guard<std::mutex> lock(mu);
    uint64_t best = 0;
    for (auto& kv : free_blocks)
      if (kv.second > best) best = kv.second;
    return best;
  }
};

}  // namespace

extern "C" {

void* asa_create(uint64_t size) {
  return new (std::nothrow) Allocator(size);
}

void asa_destroy(void* h) { delete static_cast<Allocator*>(h); }

uint64_t asa_allocate(void* h, uint64_t size) {
  return static_cast<Allocator*>(h)->allocate(size);
}

int asa_free(void* h, uint64_t offset) {
  return static_cast<Allocator*>(h)->free_block(offset);
}

uint64_t asa_allocated_bytes(void* h) {
  std::lock_guard<std::mutex> lock(static_cast<Allocator*>(h)->mu);
  return static_cast<Allocator*>(h)->allocated_bytes;
}

uint64_t asa_free_block_count(void* h) {
  std::lock_guard<std::mutex> lock(static_cast<Allocator*>(h)->mu);
  return static_cast<Allocator*>(h)->free_blocks.size();
}

uint64_t asa_largest_free(void* h) {
  return static_cast<Allocator*>(h)->largest_free();
}

}  // extern "C"
