"""Native AddressSpaceAllocator binding + bounce-buffer manager.

Reference: ``AddressSpaceAllocator.scala:22`` (first-fit sub-allocator over a
long address space) + ``BounceBufferManager.scala:35`` (pool of fixed-size
registered buffers carved from ONE allocation) — the shuffle transport's
staging-memory management (SURVEY.md §2.7/§2.8).

The allocator itself is C++ (exec/native/address_space_allocator.cpp),
compiled on first use with g++ and bound via ctypes (no pybind11 in this
image); a pure-python mirror backs environments without a toolchain. The
BounceBufferManager sub-allocates client receive staging out of one host
bytearray arena, so a fetch of N buffers performs one arena allocation
instead of N transient bytearrays.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

from ..analysis.lockdep import named_lock

_FAIL = (1 << 64) - 1
_lib_lock = named_lock("exec.native_alloc._lib_lock")
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    """Compile the C++ allocator once per interpreter (cached .so)."""
    global _lib, _lib_tried
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        here = os.path.dirname(os.path.abspath(__file__))
        src = os.path.join(here, "native", "address_space_allocator.cpp")
        out = os.path.join(here, "native", "_asa.so")
        try:
            if (not os.path.exists(out) or
                    os.path.getmtime(out) < os.path.getmtime(src)):
                subprocess.run(  # lint: lock-blocking-ok one-time toolchain compile must be serialized; every later call hits the cached .so
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     src, "-o", out],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(out)
            lib.asa_create.restype = ctypes.c_void_p
            lib.asa_create.argtypes = [ctypes.c_uint64]
            lib.asa_destroy.argtypes = [ctypes.c_void_p]
            lib.asa_allocate.restype = ctypes.c_uint64
            lib.asa_allocate.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.asa_free.restype = ctypes.c_int
            lib.asa_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            for f in ("asa_allocated_bytes", "asa_free_block_count",
                      "asa_largest_free"):
                getattr(lib, f).restype = ctypes.c_uint64
                getattr(lib, f).argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


class _PyAllocator:
    """Pure-python mirror of the native allocator (toolchain-less hosts)."""

    def __init__(self, size: int):
        self.size = size
        self._free: Dict[int, int] = {0: size} if size else {}
        self._used: Dict[int, int] = {}
        self.allocated_bytes = 0
        self._mu = named_lock("exec.native_alloc._PyAllocator._mu")

    def allocate(self, want: int) -> Optional[int]:
        if want <= 0:
            return None
        with self._mu:
            for off in sorted(self._free):
                ln = self._free[off]
                if ln < want:
                    continue
                del self._free[off]
                if ln > want:
                    self._free[off + want] = ln - want
                self._used[off] = want
                self.allocated_bytes += want
                return off
            return None

    def free(self, off: int) -> None:
        with self._mu:
            ln = self._used.pop(off)
            self.allocated_bytes -= ln
            self._free[off] = ln
            # coalesce neighbours
            offs = sorted(self._free)
            merged: Dict[int, int] = {}
            for o in offs:
                if merged:
                    lo = max(merged)
                    if lo + merged[lo] == o:
                        merged[lo] += self._free[o]
                        continue
                merged[o] = self._free[o]
            self._free = merged

    @property
    def free_block_count(self) -> int:
        with self._mu:
            return len(self._free)

    @property
    def largest_free(self) -> int:
        with self._mu:
            return max(self._free.values(), default=0)

    def close(self) -> None:
        pass


class AddressSpaceAllocator:
    """First-fit sub-allocator over [0, size) — native-backed when g++ is
    available, python otherwise. Thread-safe."""

    def __init__(self, size: int, force_python: bool = False):
        self.size = size
        lib = None if force_python else _build_and_load()
        self._lib = lib
        if lib is not None:
            self._h = lib.asa_create(size)
            self.native = True
        else:
            self._py = _PyAllocator(size)
            self.native = False

    def allocate(self, size: int) -> Optional[int]:
        if self.native:
            off = self._lib.asa_allocate(self._h, size)
            return None if off == _FAIL else off
        return self._py.allocate(size)

    def free(self, offset: int) -> None:
        if self.native:
            if self._lib.asa_free(self._h, offset) != 0:
                raise ValueError(f"free of unallocated offset {offset}")
        else:
            self._py.free(offset)

    @property
    def allocated_bytes(self) -> int:
        return (self._lib.asa_allocated_bytes(self._h) if self.native
                else self._py.allocated_bytes)

    @property
    def free_block_count(self) -> int:
        return (self._lib.asa_free_block_count(self._h) if self.native
                else self._py.free_block_count)

    @property
    def largest_free(self) -> int:
        return (self._lib.asa_largest_free(self._h) if self.native
                else self._py.largest_free)

    def close(self) -> None:
        if self.native and self._h:
            self._lib.asa_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class BounceBufferManager:
    """One host arena + sub-allocated staging windows
    (BounceBufferManager.scala:35: fixed pools over one allocation).
    The shuffle client stages chunk reassembly here."""

    def __init__(self, arena_bytes: int = 64 << 20,
                 force_python: bool = False):
        self.arena = bytearray(arena_bytes)
        self.allocator = AddressSpaceAllocator(arena_bytes,
                                               force_python=force_python)

    def acquire(self, size: int) -> Optional[memoryview]:
        """A writable window of ``size`` bytes, or None when the arena is
        exhausted (caller falls back to a transient buffer — the
        reference throttles instead; our inflight limit already bounds
        concurrent staging)."""
        off = self.allocator.allocate(size)
        if off is None:
            return None
        mv = memoryview(self.arena)[off:off + size]
        self._offsets = getattr(self, "_offsets", {})
        self._offsets[id(mv)] = off
        self._note_arena()
        return mv

    def release(self, mv: memoryview) -> None:
        off = self._offsets.pop(id(mv), None)
        if off is not None:
            mv.release()
            self.allocator.free(off)
            self._note_arena()

    def _note_arena(self) -> None:
        """Track the staging arena's current + peak occupancy on the
        process watermark (service/telemetry): shuffle receive pressure
        becomes scrapeable next to the HBM stores."""
        from ..service.telemetry import watermark
        watermark("native_arena").update(self.allocator.allocated_bytes)
