"""Deferred-scalar pipeline window: the engine's ONE pipelining primitive.

On tunnel/high-latency links every blocking device->host readback costs a
full round trip (0.1-0.35 s measured), so any operator that sizes its next
dispatch from a device scalar (join output totals, compact counts, group
stats) serializes the stream if it reads that scalar per batch. The
reference never pays this: cuDF's size-returning calls ride one stream
(GpuHashJoin.scala:193-249), and the aggregate hot loop keeps the device
busy across batches (aggregate.scala:427-485).

The window generalizes the streaming aggregate's bespoke in-flight deque
(physical.py round 4) into a reusable primitive:

* operators ``push(continuation, *device_scalars)`` — the continuation is
  the second half of the batch's work, parameterized on the CONCRETE host
  values of the scalars;
* the window holds up to ``depth`` pending entries; when full it lands the
  oldest half, resolving EVERY landing entry's scalars with ONE
  ``jax.device_get([...])`` (a single host round trip, ~8x cheaper than
  sequential gets at depth 16), then runs their continuations in FIFO
  order;
* ``flush()`` lands everything at partition end.

depth=1 degenerates to today's blocking behavior (every push lands
immediately). Entries with NO scalars ride through untouched when nothing
older is pending, so scalar-free operators (semi/anti joins) keep
streaming incrementally instead of buffering a window they don't need.

Failure containment: if the batched ``device_get`` fails (a dispatched
program erroring at execution time), each landing continuation receives
``None`` for its scalars — callers re-read per entry and degrade that one
batch (the aggregate path falls back to eager), so one bad program never
zeroes a whole window.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, List

from .tracing import trace_span


class PipelineWindow:
    """FIFO window of (device scalars, continuation) pairs resolved in
    batched host readbacks. Single-consumer: one window per partition
    drain (partition tasks each build their own)."""

    def __init__(self, depth: int, metrics=None):
        self.depth = max(1, int(depth))
        # owning exec's metrics bag: batched resolves run OUTSIDE the
        # operator's metered span (the push happens after it closes), so
        # the window re-opens the exec scope itself for sync attribution
        self.metrics = metrics
        self._pending: deque = deque()
        # observability: how many batched resolves ran, how many scalars
        # they carried, and how many landings degraded to per-entry reads
        # (exported into span/metric reports by callers that care)
        self.resolves = 0
        self.resolved_scalars = 0
        self.resolve_failures = 0

    def __len__(self) -> int:
        return len(self._pending)

    def push(self, continuation: Callable[..., Any],
             *scalars) -> List[Any]:
        """Enqueue one entry; returns the results of any entries that
        landed as a consequence (possibly empty, FIFO order). The
        continuation is called as ``continuation(*host_values)`` with one
        concrete value per pushed scalar (or ``None`` per scalar when the
        batched readback failed)."""
        if not scalars and not self._pending:
            # scalar-free entry with nothing older in flight: nothing to
            # wait for and no FIFO hazard — run it now so scalar-free
            # streams stay incremental at any depth
            return [continuation()]
        self._pending.append((list(scalars), continuation))
        if len(self._pending) >= self.depth:
            # land the oldest half: the younger half keeps its scalars in
            # flight so their transfers hide behind the continuations'
            # dispatch work (same cadence as the streaming aggregate)
            return self._land(max(self.depth // 2, 1))
        return []

    def flush(self) -> List[Any]:
        """Land every pending entry (partition end)."""
        out: List[Any] = []
        while self._pending:
            out.extend(self._land(max(self.depth // 2, 1)))
        return out

    # -- internal -----------------------------------------------------------
    def _land(self, k: int) -> List[Any]:
        k = min(k, len(self._pending))
        entries = [self._pending.popleft() for _ in range(k)]
        flat = [s for scalars, _cont in entries for s in scalars]
        vals = self._resolve(flat)
        if flat:
            self.resolves += 1
            self.resolved_scalars += len(flat)
        results: List[Any] = []
        pos = 0
        for scalars, cont in entries:
            take = vals[pos:pos + len(scalars)]
            pos += len(scalars)
            results.append(cont(*take))
        return results

    def _resolve(self, flat: List[Any]) -> List[Any]:
        """Materialize every scalar with ONE host readback per distinct
        dtype (typically one): same-dtype scalars pack into a single
        device array via one fused concat dispatch, so k pending scalars
        cost one transfer, not k blocking round trips — and the engine's
        attributed-sync count (the perf metric of record on tunnel links)
        sees O(1) reads per landing, not O(window). No cross-dtype cast:
        int32 counts above 2^24 must not round-trip through a float."""
        if not flat:
            return []
        import numpy as np
        # numpy values are ALREADY host: routing them through the packed
        # device_get would pay an upload + a readback for data the caller
        # could use directly
        device = [(i, s) for i, s in enumerate(flat)
                  if hasattr(s, "dtype") and hasattr(s, "shape")
                  and not isinstance(s, (np.ndarray, np.generic))]
        vals: List[Any] = list(flat)       # host values pass through
        if not device:
            return vals
        import jax
        import jax.numpy as jnp
        from .metrics import exec_scope
        with trace_span("pipeline_resolve"), exec_scope(self.metrics):
            try:
                groups: dict = {}
                for i, s in device:
                    groups.setdefault(np.dtype(s.dtype), []).append((i, s))
                packed = [jnp.concatenate([jnp.ravel(s) for _i, s in grp])
                          if len(grp) > 1 or grp[0][1].shape != ()
                          else grp[0][1]
                          for grp in groups.values()]
                hosts = [np.asarray(h) for h in jax.device_get(packed)]
                for grp, host in zip(groups.values(), hosts):
                    host = np.atleast_1d(host)
                    pos = 0
                    for i, s in grp:
                        n = int(np.prod(s.shape)) if s.shape else 1
                        chunk = host[pos:pos + n]
                        pos += n
                        vals[i] = chunk.reshape(s.shape) if s.shape \
                            else chunk[0]
            except Exception as e:
                # a dispatched program failed at execution time: hand
                # every landing continuation None so each re-reads (and
                # degrades) its OWN batch instead of the whole window.
                # Count + log it — a PERSISTENT failure here silently
                # reverts the engine to per-batch-sync cadence, which must
                # be visible in logs/metrics, not only in CI sync tests
                self.resolve_failures += 1
                import logging
                logging.getLogger("spark_rapids_tpu.pipeline").warning(
                    "pipeline window batched resolve failed (landing "
                    "degrades to per-entry blocking reads): %s", e)
                for i, _s in device:
                    vals[i] = None
        return vals
