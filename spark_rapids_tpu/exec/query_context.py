"""Query-lifecycle context: the per-execution query id and its stage ids.

Spans, flight-recorder events, telemetry counters and shuffle traffic were
process-global with no query identity: a two-worker distributed query
emitted two uncorrelated trace files, and ``dump_on_error`` of one session
interleaved another query's events. This module mints ONE ``query_id``
per collect and makes it ambient for the duration of the execution, so
every cross-cutting instrument (``exec/tracing``, ``service/telemetry``,
the shuffle transport, the mesh exchange) can attribute its events to the
query that paid for them — the substrate the merged multi-worker timeline
and the structured query log stand on (docs/observability.md §8).

Query ids are LOCKSTEP-DETERMINISTIC: a process-global execution counter
plus a structural hash of the executed plan. Multi-process workers run
the same query sequence (the shuffle-id contract, shuffle/manager.py), so
both workers mint the SAME id for the same query — which is exactly what
lets one merged timeline join their spans. Two different concurrent
queries in one process draw different counter values, so their events
never alias.

Stage ids number the exchange boundaries within one query (the
query-stage granularity AQE re-plans at): each shuffle/range exchange
draws ``next_stage_id()`` at execute time, deterministic because exchange
``execute()`` calls run on the single driving thread during plan
construction.

The ambient context uses the SyncCounter pattern (exec/tracing.py): the
entering thread's context is also the process default, so task-pool
worker threads — which do the actual partition drains — inherit it; a
thread entering its own scope overrides the default for itself.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from typing import List, Optional

from ..analysis.lockdep import named_lock

#: process-global execution counter (itertools.count.__next__ is
#: GIL-atomic; workers running the same query sequence draw the same
#: values — the lockstep contract shuffle ids already rely on)
_QUERY_SEQ = itertools.count(1)


def _plan_digest(plan) -> str:
    """Short structural hash of an executed plan tree (exec class names +
    child shape, no data): workers running the same logical query compute
    the same digest, structurally different queries at the same counter
    value do not collide."""

    def desc(node) -> str:
        kids = ";".join(desc(c) for c in getattr(node, "children", ()))
        return f"{type(node).__name__}({kids})"

    return hashlib.sha1(desc(plan).encode()).hexdigest()[:8]


def mint_query_id(plan=None) -> str:
    """A fresh query id: ``q<seq>-<plan digest>`` (digest omitted when no
    plan is given). Minted once per collect, at collect time."""
    seq = next(_QUERY_SEQ)
    if plan is None:
        return f"q{seq:06d}"
    try:
        return f"q{seq:06d}-{_plan_digest(plan)}"
    except Exception:
        return f"q{seq:06d}"


class QueryContext:
    """One query execution's identity: the query id, the TENANT the
    query runs on behalf of (the multi-tenant service's isolation unit,
    service/server.py — None for direct caller-owned sessions), plus the
    stage-id counter exchanges draw from at their boundaries."""

    def __init__(self, query_id: str, tenant: Optional[str] = None):
        self.query_id = query_id
        # the tenant hint is installed by service/tenants.tenant_scope on
        # the SUBMITTING thread before the collect mints this context, so
        # buffer-catalog accounting, flight events and the query log all
        # attribute to the tenant without any API change at collect sites
        self.tenant = tenant if tenant is not None else \
            getattr(_tls, "tenant", None)
        # the deadline hint rides the same pre-collect installation path
        # as the tenant (service/server._worker_loop, deadline_scope);
        # partition-drain workers then see it through thread_scope, which
        # is how the compile pool reads a deadline from a task thread
        self.deadline_at: Optional[float] = getattr(
            _tls, "deadline_at", None)
        #: True while this query drains through a streaming collect
        #: (``DataFrame.collect_iter`` sets it on the minted context)
        self.streaming: bool = bool(getattr(_tls, "streaming", False))
        #: the cooperative lifecycle token (exec/lifecycle.py). The
        #: service worker pre-mints one per ticket and installs it via
        #: :class:`cancel_token_scope` before the thunk collects, so the
        #: ticket and the execution share one token; direct collects
        #: get a fresh token at ``lifecycle.register`` time. None only
        #: for contexts that never reach a collect path.
        self.cancel_token = getattr(_tls, "cancel_token", None)
        self._stage_seq = itertools.count(1)

    def next_stage_id(self) -> int:
        """The next exchange-boundary stage id within this query
        (deterministic: exchanges execute on the driving thread)."""
        return next(self._stage_seq)


def reserve_query(ctx: QueryContext) -> QueryContext:
    """Pre-mint a query identity for the NEXT collect on THIS thread:
    the collect adopts ``ctx`` instead of minting a fresh id (one-shot —
    the reservation clears when taken). This is how a driver runs two
    distributed queries CONCURRENTLY while keeping the mint order
    lockstep: mint both contexts on the main thread in program order
    (every worker draws the same ``q<seq>`` values), then collect each
    on its own thread under its reserved context — the racy per-thread
    collect order no longer touches the query-id counter, and shuffle
    ids stay namespaced consistently across workers (docs/shuffle.md)."""
    _tls.reserved = ctx  # lint: unguarded-ok reserving thread's own TLS field
    return ctx


def take_reserved() -> Optional[QueryContext]:
    """Adopt-and-clear this thread's reserved context (collect paths)."""
    ctx = getattr(_tls, "reserved", None)
    if ctx is not None:
        _tls.reserved = None  # lint: unguarded-ok collecting thread's own TLS field
    return ctx


_tls = threading.local()
_default_stack: List[QueryContext] = []
# guards _default_stack (the SyncCounter._default_stack discipline):
# scopes enter on the driving thread but exits can interleave across
# threads in tests, and bare list mutation racing on the shared stack
# could resurrect a finished context as the lingering default
_stack_mu = named_lock("exec.query_context._stack_mu")


def current() -> Optional[QueryContext]:
    """The innermost active query context on THIS thread, falling back to
    the process default (the driving thread's context, visible to pool
    worker threads). Lock-free read — this runs per flight-recorder event
    on hot paths; the check-then-index window is handled by catching (the
    SyncCounter._get_active rationale)."""
    local = getattr(_tls, "active", None)
    if local is not None:
        return local
    try:
        return _default_stack[-1]
    except IndexError:
        return None


def current_query_id() -> Optional[str]:
    ctx = current()
    return ctx.query_id if ctx is not None else None


def note_thread_query_id(qid: Optional[str]) -> None:
    """Record the query id THIS thread last executed (set at collect,
    cleared by the service before each thunk): the per-ticket id surface
    — ``session._last_query_id`` is last-writer-wins across concurrent
    workers and must not be joined to a specific execution."""
    _tls.last_query_id = qid  # lint: unguarded-ok executing thread's own TLS field


def thread_last_query_id() -> Optional[str]:
    return getattr(_tls, "last_query_id", None)


def current_tenant() -> Optional[str]:
    """The tenant the CURRENT work runs on behalf of: the active query
    context's tenant when one exists (pool worker threads inherit it via
    :class:`thread_scope`), otherwise the thread's tenant hint (the
    service worker thread between submit and collect). None outside any
    tenant scope — single-tenant direct sessions stay untagged."""
    ctx = current()
    if ctx is not None and ctx.tenant is not None:
        return ctx.tenant
    return getattr(_tls, "tenant", None)


class tenant_scope:
    """TLS tenant hint for THIS thread: every query minted while the
    scope is open attributes to ``tenant`` (``None`` is a no-op). The
    multi-tenant service wraps each admitted query's execution in this;
    nests (the inner scope wins, restored on exit)."""

    def __init__(self, tenant: Optional[str]):
        self.tenant = tenant

    def __enter__(self) -> Optional[str]:
        if self.tenant is not None:
            self._prev = getattr(_tls, "tenant", None)  # lint: unguarded-ok worker thread's own TLS field
            _tls.tenant = self.tenant
        return self.tenant

    def __exit__(self, *exc) -> bool:
        if self.tenant is not None:
            _tls.tenant = self._prev
        return False


def current_deadline_at() -> Optional[float]:
    """The ``time.perf_counter`` deadline the CURRENT work must meet, or
    None when no deadline applies. Installed by the service worker loop
    (:class:`deadline_scope`) before the admitted ticket's thunk runs;
    the compile pool reads it to (a) decide whether a cold stage build
    fits the remaining slack and (b) order query-triggered builds by
    urgency (docs/service.md). Reads the active query context first —
    partition-drain worker threads inherit the context, not the
    submitting thread's TLS."""
    ctx = current()
    if ctx is not None and getattr(ctx, "deadline_at", None) is not None:
        return ctx.deadline_at
    return getattr(_tls, "deadline_at", None)


class deadline_scope:
    """TLS deadline hint for THIS thread (the :class:`tenant_scope`
    shape): every compile-pool consult while the scope is open sees
    ``deadline_at`` via :func:`current_deadline_at`. ``None`` is a no-op
    (no deadline — direct sessions and deadline-free tickets)."""

    def __init__(self, deadline_at: Optional[float]):
        self.deadline_at = deadline_at

    def __enter__(self) -> Optional[float]:
        if self.deadline_at is not None:
            self._prev = getattr(_tls, "deadline_at", None)  # lint: unguarded-ok worker thread's own TLS field
            _tls.deadline_at = self.deadline_at
        return self.deadline_at

    def __exit__(self, *exc) -> bool:
        if self.deadline_at is not None:
            _tls.deadline_at = self._prev
        return False


class cancel_token_scope:
    """TLS cancel-token hint for THIS thread (the :class:`deadline_scope`
    shape): the query minted while the scope is open adopts ``token`` as
    its lifecycle token, which is how ``QueryService.cancel/suspend``
    reach an execution they admitted — the ticket holds the same token
    the collect registers. ``None`` is a no-op."""

    def __init__(self, token):
        self.token = token

    def __enter__(self):
        if self.token is not None:
            self._prev = getattr(_tls, "cancel_token", None)  # lint: unguarded-ok worker thread's own TLS field
            _tls.cancel_token = self.token
        return self.token

    def __exit__(self, *exc) -> bool:
        if self.token is not None:
            _tls.cancel_token = self._prev
        return False


def streaming_active() -> bool:
    """True while the CURRENT thread drains a streaming collect
    (``DataFrame.collect_iter``): the latency context in which a cold
    stage build must not block the first batches — the compile pool
    takes it instead while the stage serves rows eagerly
    (docs/compile.md §5). Context first, TLS fallback — same resolution
    order as :func:`current_deadline_at`."""
    ctx = current()
    if ctx is not None and getattr(ctx, "streaming", False):
        return True
    return bool(getattr(_tls, "streaming", False))


class streaming_scope:
    """TLS streaming-collect marker for THIS thread (installed by
    ``collect_iter`` around execution, propagated to partition-drain
    workers by the task funnel alongside the query context)."""

    def __enter__(self) -> "streaming_scope":
        self._prev = getattr(_tls, "streaming", False)  # lint: unguarded-ok entering thread's own TLS field
        _tls.streaming = True
        return self

    def __exit__(self, *exc) -> bool:
        _tls.streaming = self._prev
        return False


class thread_scope:
    """TLS-only activation of ``ctx`` on THIS thread (no default-stack
    push): the task-pool funnel (``exec/tasks.run_partition_tasks``)
    captures the submitting thread's context and installs it on each
    worker thread through this, so two CONCURRENT queries' pool events
    attribute to their own query instead of whichever entered the
    process default last. ``None`` is a no-op (no ambient query)."""

    def __init__(self, ctx: Optional[QueryContext]):
        self.ctx = ctx

    def __enter__(self) -> Optional[QueryContext]:
        if self.ctx is not None:
            self._prev = getattr(_tls, "active", None)  # lint: unguarded-ok worker thread's own TLS field
            _tls.active = self.ctx
        return self.ctx

    def __exit__(self, *exc) -> bool:
        if self.ctx is not None:
            _tls.active = self._prev
        return False


class query_scope:
    """Context manager marking ``ctx`` as the active query on this thread
    AND the process default for the duration. The default is the
    fallback for auxiliary threads (transport handlers, prefetch pools)
    that were not routed explicitly; the partition task pool routes
    explicitly via :class:`thread_scope`, so concurrent queries'
    dominant event traffic never cross-attributes."""

    def __init__(self, ctx: QueryContext):
        self.ctx = ctx

    def __enter__(self) -> QueryContext:
        self._prev = getattr(_tls, "active", None)  # lint: unguarded-ok entering thread's own TLS field, set before the context is shared
        _tls.active = self.ctx
        with _stack_mu:
            _default_stack.append(self.ctx)
        return self.ctx

    def __exit__(self, *exc) -> bool:
        _tls.active = self._prev
        with _stack_mu:
            # remove by identity, not LIFO: interleaved exits across
            # threads must not resurrect a finished context
            for i in range(len(_default_stack) - 1, -1, -1):
                if _default_stack[i] is self.ctx:
                    del _default_stack[i]
                    break
        return False
