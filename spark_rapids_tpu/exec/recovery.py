"""Recoverable-error taxonomy + stage-retry driver.

The reference inherits failure semantics from Spark: a fetch failure
surfaces as FetchFailed and the scheduler re-executes the producing map
stage; executor loss triggers lineage recompute; OOM falls back to spill
(SURVEY.md §3.5, §5 "Failure detection / elastic recovery"). Standalone,
this module is the scheduler's stand-in: the ONE place that decides what
a failure means and drives bounded re-execution.

Taxonomy -> action (:func:`classify`):

=========================  =============  ====================================
error                      action         rationale
=========================  =============  ====================================
QueryCancelledError        FAIL_QUERY     the caller asked this exact
                                          execution to stop (or its deadline
                                          lapsed); retrying would resurrect it
QuerySuspendedError        FAIL_QUERY     only the service worker loop may
                                          park a suspension; anywhere else it
                                          escaped its scheduler — fail loudly
ShuffleDesyncError         FAIL_QUERY     lockstep streams diverged; retrying
                                          would pair wrong data
ShuffleProtocolError       FAIL_QUERY     peer alive but confused (version
                                          skew / unknown buffer); a retry
                                          re-asks the same confused peer
ShuffleWorkerLostError     RETRY_STAGE    the consuming stage re-fetches from
                                          durable outputs once the worker
                                          rejoins (the lost worker is
                                          excluded until a probe readmits it)
ShuffleFetchError (base)   RETRY_STAGE    transport gave up after its own
                                          retries; re-execute the producing
                                          stage from durable inputs
BufferLostError            RETRY_STAGE    a spill-store buffer vanished; the
                                          map refill recomputes it (Spark
                                          FetchFailed -> map-stage retry)
InjectedTaskFault          RETRY_STAGE    chaos-harness poison: recoverable
                                          by construction
ConnectionError/OSError    RETRY_FETCH    transient transport error: the
                                          ShuffleClient retry loop's domain,
                                          below stage granularity
anything else              FAIL_QUERY     unknown failures propagate unmasked
=========================  =============  ====================================

Retry budget and backoff come from ``spark.rapids.tpu.sql.recovery.*``
(primed eagerly at session bootstrap like lockdep/telemetry — a lazy
conf read inside a failing drain could recurse into the conf-registry
lock). Every recovery event bumps the ``tpu_stage_retries_total`` /
``tpu_worker_lost_total`` counters, observes ``tpu_recovery_seconds``
on success, and lands in the flight recorder (kind ``recovery``) so a
post-mortem shows the decision trail.

This module is the only place allowed to catch taxonomy types bare:
everywhere else, lint rule ``bare-recover`` requires a
``# lint: recover-ok <reason>`` pragma so retry logic cannot quietly
fork into second implementations (docs/resilience.md).
"""

from __future__ import annotations

import logging
import time
from enum import Enum
from typing import Callable, Optional, Tuple

from ..analysis.lockdep import named_lock

log = logging.getLogger("spark_rapids_tpu.recovery")


class RecoveryAction(Enum):
    RETRY_FETCH = "retry-fetch"    # below stage granularity (transport)
    RETRY_STAGE = "retry-stage"    # re-execute the producing stage
    FAIL_QUERY = "fail-query"      # propagate unmasked


class InjectedTaskFault(RuntimeError):
    """A chaos-harness task poison (analysis/faults.py ``task.poison``):
    recoverable by construction — the stage retry must absorb it."""


def recoverable_types() -> Tuple[type, ...]:
    """The exception types a stage-retry loop may legally absorb."""
    from ..shuffle.transport import ShuffleFetchError
    from .spill import BufferLostError
    return (ShuffleFetchError, BufferLostError, InjectedTaskFault)


def classify(exc: BaseException) -> RecoveryAction:
    """Map one failure to its recovery action (the table above)."""
    from ..analysis.divergence import DesyncError
    from ..shuffle.transport import (ShuffleDesyncError, ShuffleFetchError,
                                     ShuffleProtocolError,
                                     ShuffleWorkerLostError)
    from .lifecycle import QueryCancelledError, QuerySuspendedError
    from .spill import BufferLostError
    if isinstance(exc, (QueryCancelledError, QuerySuspendedError)):
        # cooperative lifecycle unwinds (exec/lifecycle.py): retrying a
        # cancelled query would resurrect the exact execution the caller
        # asked to stop; a suspension propagating to here escaped the
        # service worker loop (the only legal catcher) and must fail
        # loudly rather than spin in a retry ladder
        return RecoveryAction.FAIL_QUERY
    if isinstance(exc, DesyncError):
        # the digest audit's typed divergence: retrying cannot un-diverge
        # lockstep streams, and the exception already carries the
        # first-divergent-event diagnosis the post-mortem needs
        return RecoveryAction.FAIL_QUERY
    if isinstance(exc, ShuffleDesyncError):
        return RecoveryAction.FAIL_QUERY
    if isinstance(exc, ShuffleProtocolError):
        return RecoveryAction.FAIL_QUERY
    if isinstance(exc, (ShuffleWorkerLostError, ShuffleFetchError,
                        BufferLostError, InjectedTaskFault)):
        return RecoveryAction.RETRY_STAGE
    if isinstance(exc, (ConnectionError, OSError)):
        return RecoveryAction.RETRY_FETCH
    return RecoveryAction.FAIL_QUERY


# ---------------------------------------------------------------------------
# Conf-primed knobs (session bootstrap calls refresh, lockdep pattern)
# ---------------------------------------------------------------------------

_mu = named_lock("exec.recovery._mu")
_max_stage_retries: Optional[int] = None
_backoff_s: Optional[float] = None
_shuffle_durable: Optional[bool] = None
_fetch_max_retries: Optional[int] = None
_fetch_backoff_s: Optional[float] = None
_spill_dir: Optional[str] = None
_durable_max_bytes: Optional[int] = None
_mesh_lost_reason: Optional[str] = None


def refresh(conf=None) -> None:
    """Prime retry budget / backoff / durability / transport fetch-retry
    knobs from a session conf (ShuffleClient reads the fetch knobs from
    here: client construction happens below the session layer, so the
    primed state is how the active session's conf reaches it)."""
    global _max_stage_retries, _backoff_s, _shuffle_durable
    global _fetch_max_retries, _fetch_backoff_s, _spill_dir
    global _durable_max_bytes
    from .. import config as cfg
    conf = conf or cfg.TpuConf()
    with _mu:
        _max_stage_retries = int(conf.get(cfg.RECOVERY_MAX_STAGE_RETRIES))
        _backoff_s = float(conf.get(cfg.RECOVERY_RETRY_BACKOFF))
        _shuffle_durable = bool(conf.get(cfg.SHUFFLE_DURABLE))
        _fetch_max_retries = int(conf.get(cfg.SHUFFLE_FETCH_MAX_RETRIES))
        _fetch_backoff_s = float(
            conf.get(cfg.SHUFFLE_FETCH_RETRY_BACKOFF))
        _spill_dir = str(conf.spill_dir)
        _durable_max_bytes = int(conf.get(cfg.SHUFFLE_DURABLE_MAX_BYTES))


def reset_cache() -> None:
    """Drop the primed knobs (tests / conf mutation re-prime lazily)."""
    global _max_stage_retries, _backoff_s, _shuffle_durable
    global _fetch_max_retries, _fetch_backoff_s, _spill_dir
    global _durable_max_bytes
    with _mu:
        _max_stage_retries = None
        _backoff_s = None
        _shuffle_durable = None
        _fetch_max_retries = None
        _fetch_backoff_s = None
        _spill_dir = None
        _durable_max_bytes = None


def _primed() -> Tuple:
    with _mu:
        knobs = (_max_stage_retries, _backoff_s, _shuffle_durable,
                 _fetch_max_retries, _fetch_backoff_s, _spill_dir)
    if knobs[0] is None:
        refresh(None)
        with _mu:
            knobs = (_max_stage_retries, _backoff_s, _shuffle_durable,
                     _fetch_max_retries, _fetch_backoff_s, _spill_dir)
    return knobs


def max_stage_retries() -> int:
    return _primed()[0]


def retry_backoff_s() -> float:
    return _primed()[1]


def shuffle_durable() -> bool:
    return _primed()[2]


def fetch_max_retries() -> int:
    return _primed()[3]


def fetch_retry_backoff_s() -> float:
    return _primed()[4]


def spill_dir() -> str:
    """The session-primed spill directory (the durable shuffle root
    lives under it; WorkerContext sits below the session layer, so the
    primed state is how the active session's conf reaches it)."""
    return _primed()[5]


def durable_max_bytes() -> int:
    """The durable shuffle tier's disk budget
    (``shuffle.durable.maxBytes``; 0 = unbounded). WorkerContext hands
    it to its ShuffleStore at construction — the store sits below the
    session layer, so the primed state is how the conf reaches it."""
    _primed()
    with _mu:
        return _durable_max_bytes or 0


# ---------------------------------------------------------------------------
# Mesh-participant loss (graceful ICI -> DCN decline)
# ---------------------------------------------------------------------------

def note_mesh_lost(reason: str) -> None:
    """Record that the ICI mesh plane lost a participant: subsequent
    ``auto`` exchanges decline to DCN instead of dispatching a
    collective that would hang on the missing chip."""
    global _mesh_lost_reason
    with _mu:
        already = _mesh_lost_reason is not None
        _mesh_lost_reason = reason
    if not already:
        log.warning("ICI mesh marked lost (%s): exchanges decline to DCN",
                    reason)
        from ..service.telemetry import flight_record
        flight_record("recovery", "mesh-lost", {"reason": reason})


def mesh_lost() -> Optional[str]:
    """The loss reason while the mesh is marked lost, else None."""
    with _mu:
        return _mesh_lost_reason


def clear_mesh_lost() -> None:
    """Re-admit the mesh plane (tests / a topology re-probe)."""
    global _mesh_lost_reason
    with _mu:
        _mesh_lost_reason = None


# ---------------------------------------------------------------------------
# Telemetry funnels (push-style, recovery is a cold path)
# ---------------------------------------------------------------------------

def note_stage_retry(stage: str, exc: BaseException, attempt: int) -> None:
    """One stage re-execution decision: counter + flight record + log."""
    from ..service.telemetry import MetricsRegistry, flight_record
    log.warning("stage %s failed (%s: %s); retry %d/%d",
                stage, type(exc).__name__, exc, attempt,
                max_stage_retries())
    flight_record("recovery", f"stage-retry-{stage}",
                  {"error": f"{type(exc).__name__}: {exc}"[:300],
                   "attempt": attempt})
    try:
        MetricsRegistry.get().counter(
            "tpu_stage_retries_total",
            "stage re-executions absorbed by recovery").inc()
    except Exception:
        pass


def note_worker_lost(worker_id: int, exc: Optional[BaseException] = None
                     ) -> None:
    from ..service.telemetry import MetricsRegistry, flight_record
    log.warning("shuffle worker %d marked lost%s", worker_id,
                f" ({exc})" if exc else "")
    flight_record("recovery", f"worker-lost-{worker_id}",
                  {"error": str(exc)[:300]} if exc else None)
    try:
        MetricsRegistry.get().counter(
            "tpu_worker_lost_total",
            "peer workers observed dead (failed-send detection)").inc()
    except Exception:
        pass


def note_worker_rejoin(worker_id: int) -> None:
    from ..service.telemetry import MetricsRegistry, flight_record
    log.warning("shuffle worker %d rejoined", worker_id)
    flight_record("recovery", f"worker-rejoin-{worker_id}")
    try:
        MetricsRegistry.get().counter(
            "tpu_worker_rejoin_total",
            "peer workers re-admitted after loss").inc()
    except Exception:
        pass


def observe_recovery_seconds(seconds: float) -> None:
    from ..service.telemetry import MetricsRegistry
    try:
        MetricsRegistry.get().histogram(
            "tpu_recovery_seconds",
            "wall seconds from first recoverable failure to recovered "
            "success").observe(seconds)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# The stage-retry driver
# ---------------------------------------------------------------------------

class StageRetryState:
    """Bookkeeping for one stage's bounded retry loop.

    Usage::

        rs = StageRetryState("shuffle-map")
        while True:
            try:
                out = attempt()
                rs.succeeded()
                break
            except recovery.recoverable_types() as e:  # in recovery's
                rs.failed(e)        # re-raises when not retryable    # domain

    ``failed`` classifies the error, counts the attempt against the
    ``recovery.maxStageRetries`` budget, sleeps the linear backoff and
    returns — or re-raises when the action is FAIL_QUERY, the budget is
    exhausted, or the caller's ``retryable`` gate says no. ``succeeded``
    observes ``tpu_recovery_seconds`` when any retry happened."""

    def __init__(self, stage: str,
                 retryable: Optional[Callable[[BaseException], bool]] = None,
                 max_retries: Optional[int] = None,
                 backoff_s: Optional[float] = None):
        self.stage = stage
        self.attempts = 0
        self._retryable = retryable
        self._max = max_stage_retries() if max_retries is None \
            else int(max_retries)
        self._backoff = retry_backoff_s() if backoff_s is None \
            else float(backoff_s)
        self._first_failure_t: Optional[float] = None

    def failed(self, exc: BaseException, sleep: bool = True) -> None:
        """Account one failure; returns to retry, raises to give up.
        ``sleep=False`` defers the backoff to :meth:`sleep_backoff` so
        the caller can discard partial state (a half-written shuffle's
        pinned buffers) BEFORE the dwell instead of holding it through."""
        action = classify(exc)
        if action is RecoveryAction.FAIL_QUERY:
            raise exc
        if self._retryable is not None and not self._retryable(exc):
            raise exc
        if self._first_failure_t is None:
            self._first_failure_t = time.monotonic()
        self.attempts += 1
        if self.attempts > self._max:
            log.error("stage %s: recovery budget exhausted after %d "
                      "retries", self.stage, self._max)
            raise exc
        note_stage_retry(self.stage, exc, self.attempts)
        if sleep:
            self.sleep_backoff()

    def sleep_backoff(self) -> None:
        # the dwell is a named cancel poll point: a cancelled/preempted
        # query must unwind from the backoff, not sleep through it
        from .lifecycle import check_cancel, interruptible_sleep
        if self._backoff > 0:
            interruptible_sleep(self._backoff * self.attempts)
        else:
            check_cancel()

    def succeeded(self) -> None:
        if self.attempts and self._first_failure_t is not None:
            seconds = time.monotonic() - self._first_failure_t
            observe_recovery_seconds(seconds)
            from ..service.telemetry import flight_record
            flight_record("recovery", f"recovered-{self.stage}",
                          {"retries": self.attempts,
                           "seconds": round(seconds, 4)})


def retry_stage(stage: str, attempt: Callable[[], object],
                on_retry: Optional[Callable[[BaseException, int], None]]
                = None, **kw):
    """Run ``attempt()`` under a :class:`StageRetryState` loop.
    ``on_retry(exc, attempt_no)`` runs before each re-execution so the
    caller can discard partial state (a half-written shuffle)."""
    rs = StageRetryState(stage, **kw)
    while True:
        try:
            out = attempt()
        except recoverable_types() as e:
            # discard partial state BEFORE the backoff dwell: a
            # half-written shuffle's buffers must not stay pinned
            # through the sleep
            rs.failed(e, sleep=False)  # re-raises when not retryable
            if on_retry is not None:
                on_retry(e, rs.attempts)
            rs.sleep_backoff()
            continue
        rs.succeeded()
        return out
