"""3-tier spillable buffer store: device (HBM) -> host (RAM) -> disk.

Reference: ``RapidsBufferCatalog.scala:34-211`` (global id->buffer map + spill
chain wiring), ``RapidsBufferStore.scala:30-351`` (tiered store, spill-priority
queue, synchronousSpill), ``RapidsDeviceMemoryStore`` / ``RapidsHostMemoryStore``
/ ``RapidsDiskStore``, ``DeviceMemoryEventHandler.scala:33-95`` (alloc-failure
callback -> spill), ``SpillableColumnarBatch.scala:28-137``, and
``SpillPriorities.scala:26-60``.

TPU mapping: the device tier holds jax arrays (XLA/PJRT HBM buffers); the host
tier numpy arrays; the disk tier .npz files under the spill dir. There is no
RMM alloc-failure hook in PJRT, so the budget is enforced *cooperatively*:
``MemoryAccountant.reserve(nbytes)`` is called before device materialization
and triggers synchronous spill when the accounted device total would exceed
the budget — the same control flow as the RMM event handler, moved from an
allocator callback to an admission check.
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis import ledger as _ledger
from ..analysis import lockdep
from ..analysis.lockdep import named_lock, named_rlock
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column

# Spill priority constants (SpillPriorities.scala:26-60): lower spills first.
OUTPUT_FOR_SHUFFLE_PRIORITY = -100.0   # shuffle outputs idle longest
HOST_MEMORY_BUFFER_PRIORITY = -50.0
CACHE_PRIORITY = -75.0                 # cached tables yield to active work
ACTIVE_ON_DECK_PRIORITY = 100.0        # actively-used batches spill last


class StorageTier(Enum):
    DEVICE = 0
    HOST = 1
    DISK = 2


_id_counter = itertools.count(1)


def next_buffer_id() -> int:
    return next(_id_counter)


class BufferLostError(RuntimeError):
    """A spillable buffer was released or evicted before its read — the
    recoverable 'shuffle block lost' condition (consumers like the shuffle
    exchange re-execute the producing stage, Spark FetchFailed style)."""


# ---------------------------------------------------------------------------
# GC-callback-safe deferred finalization
# ---------------------------------------------------------------------------
#
# A weakref finalizer fires at an ARBITRARY bytecode on an arbitrary
# thread — including inside a frame that already holds the buffer
# catalog / watermark / device-manager locks. Cleanup that re-takes any
# of those locks inline self-deadlocks the thread on its own
# non-reentrant lock (observed: the scan-cache eviction finalizer firing
# inside ``reserve -> watermark`` and blocking on the watermark lock the
# interrupted frame held). Finalizers therefore only ENQUEUE their work
# (``list.append`` is atomic, no lock) and the engine drains the queue
# at safe points: partition-task launch and scan-cache access.

_DEFERRED_FINALIZERS: List[Tuple[Callable, tuple]] = []


def defer_finalizer(fn: Callable, *args) -> None:
    """Enqueue lock-taking cleanup from a GC/weakref callback (run later
    by :func:`drain_deferred_finalizers` from a safe call context)."""
    _DEFERRED_FINALIZERS.append((fn, args))


def drain_deferred_finalizers() -> None:
    """Run enqueued finalizer work. Callers must hold NO engine locks.
    Failures are swallowed — deferred cleanup must never fail the query
    that happened to trigger the drain."""
    while _DEFERRED_FINALIZERS:
        try:
            fn, args = _DEFERRED_FINALIZERS.pop()
        except IndexError:
            break
        try:
            fn(*args)
        except Exception:
            pass


@dataclass
class BufferMeta:
    """Schema + shape info to rebuild a ColumnarBatch from raw arrays
    (MetaUtils TableMeta analog, MetaUtils.scala:33-241)."""
    schema: dt.Schema
    num_rows: int
    capacity: int


class SpillableBuffer:
    """One registered buffer: a columnar batch's arrays at some tier
    (RapidsBufferBase analog with acquire/close refcounting,
    RapidsBufferStore.scala:245-351)."""

    def __init__(self, buffer_id: int, meta: BufferMeta, priority: float,
                 device_arrays: Optional[List[Any]] = None,
                 col_dtypes: Optional[List[dt.DType]] = None,
                 obj_cols: Optional[Dict[int, Column]] = None,
                 tenant: Optional[str] = None):
        self.id = buffer_id
        self.meta = meta
        self.priority = priority
        # the tenant whose query registered this buffer (service
        # multi-tenancy, docs/service.md): device residency is accounted
        # per tenant and an over-budget tenant's buffers are the spill
        # cascade's first victims. None = untenanted (direct sessions,
        # shared cache entries)
        self.tenant = tenant
        self.tier = StorageTier.DEVICE
        self.col_dtypes = col_dtypes or []
        self._device_arrays = device_arrays        # list of jax arrays
        self._host_arrays: Optional[List[np.ndarray]] = None
        self._disk_path: Optional[str] = None
        # CPU-engine-only columns (ObjectColumn: map<string,_> etc.) are
        # python-object payloads that never touch the device; they ride the
        # buffer untiered (already host-resident, nothing to spill)
        self._obj_cols = obj_cols or {}
        # durable-shuffle pin (BufferCatalog.pin_to_disk): a pinned
        # buffer's npz payload is RETAINED across promotion (immutable,
        # write-once) so the post-read re-pin is a tier flip, not a
        # fresh D2H + savez round trip per read
        self.disk_pinned = False
        self._pinned_path: Optional[str] = None
        # every buffer lock shares ONE lockdep name (a lock CLASS, kernel-
        # lockdep style): order edges are per class of lock, not per buffer
        self._lock = named_rlock("exec.spill.SpillableBuffer._lock")
        self.size_bytes = sum(
            a.size * a.dtype.itemsize for a in (device_arrays or []))

    # -- tier movement -------------------------------------------------------
    #
    # Tier moves follow the snapshot/work/publish shape: grab array refs
    # under the lock, do the blocking device readback or disk write
    # UNLOCKED (holding a mutex across a link round trip or an npz write
    # serializes every peer thread behind IO), then re-take the lock and
    # flip the tier only if no concurrent move/free won the race.

    def spill_to_host(self) -> int:
        with self._lock:
            if self.tier != StorageTier.DEVICE or \
                    self._device_arrays is None:
                return 0
            dev = list(self._device_arrays)
        from ..analysis.sync_audit import allowed_host_transfer
        with allowed_host_transfer("spill tier: device->host move"):
            host = [np.asarray(a) for a in dev]  # lint: host-sync-ok spill tier: the device->host move IS the operation
        with self._lock:
            if self.tier != StorageTier.DEVICE or \
                    self._device_arrays is None:
                return 0               # concurrent spill/free won the race
            self._host_arrays = host
            self._device_arrays = None
            self.tier = StorageTier.HOST
        # ledger AFTER the buffer lock releases (its lock is a leaf)
        _ledger.note_tier(self.id, StorageTier.HOST)
        # charge the innermost open exec (exec/metrics attribution): the
        # operator whose pressure pushed this buffer off the device shows
        # spillBytes on its EXPLAIN ANALYZE node
        from .metrics import attribute
        attribute("spillBytes", self.size_bytes)
        from ..service.telemetry import flight_record
        flight_record("spill", f"buffer-{self.id}",
                      {"bytes": self.size_bytes, "to": "host"})
        return self.size_bytes

    def spill_to_disk(self, spill_dir: str) -> int:
        # zero-IO path for disk-pinned buffers already staged on host:
        # the retained npz IS the payload (immutable), so the pressure
        # cascade's host->disk move restores it instead of paying a
        # fresh savez rewrite at the worst possible time. HOST-only:
        # callers' accounting assumes the bytes came off the host tier
        if self.demote_to_pinned_disk(
                only_from=StorageTier.HOST) is not None:
            return self.size_bytes
        self.spill_to_host()           # no-op unless device-resident
        with self._lock:
            if self.tier != StorageTier.HOST or self._host_arrays is None:
                return 0
            host = self._host_arrays
        os.makedirs(spill_dir, exist_ok=True)
        # per-attempt unique path: a racing spill of the same buffer must
        # never clobber (or unlink) the winner's file
        path = os.path.join(
            spill_dir, f"spill-{self.id}-{next(_id_counter)}.npz")
        # codec per spill.compression.codec (TableCompressionCodec
        # analog for the disk tier; zlib = np's deflate container)
        from .. import config as cfg
        codec = str(cfg.TpuConf().get(cfg.SPILL_COMPRESSION_CODEC))
        save = np.savez_compressed if codec == "zlib" else np.savez
        save(path, *host)
        with self._lock:
            if self.tier != StorageTier.HOST or \
                    self._host_arrays is not host:
                won = False            # concurrent move/free won the race
            else:
                self._disk_path = path
                self._host_arrays = None
                self.tier = StorageTier.DISK
                won = True
        if not won:
            try:
                os.unlink(path)
            except OSError:
                pass
            return 0
        _ledger.note_tier(self.id, StorageTier.DISK)
        from ..service.telemetry import flight_record
        flight_record("spill", f"buffer-{self.id}",
                      {"bytes": self.size_bytes, "to": "disk"})
        return self.size_bytes

    def _load_arrays(self) -> List[Any]:
        """Arrays at whatever tier, promoted to device (RapidsBuffer
        .getColumnarBatch re-promotion, RapidsBufferStore.scala:275-301).
        Snapshot under the lock, materialize unlocked (np.load and the
        host->device transfer both block)."""
        import jax.numpy as jnp
        with self._lock:
            tier = self.tier
            dev, host, path = (self._device_arrays, self._host_arrays,
                               self._disk_path)
        if tier == StorageTier.DEVICE:
            if dev is None:
                raise BufferLostError(f"buffer {self.id} was freed")
            return dev
        if tier == StorageTier.HOST:
            if host is None:
                raise BufferLostError(f"buffer {self.id} was freed")
            return [jnp.asarray(a) for a in host]
        try:
            with np.load(path) as z:
                return [jnp.asarray(z[k]) for k in z.files]
        except (FileNotFoundError, TypeError) as e:
            raise BufferLostError(
                f"buffer {self.id} disk payload vanished mid-read "
                f"(concurrent free): {e}") from None

    def get_batch(self, promote: bool = True) -> ColumnarBatch:
        from ..columnar.column import build_column
        arrays = self._load_arrays()
        cols: List[Column] = []
        i = 0
        for ci, f in enumerate(self.meta.schema):
            if ci in self._obj_cols:
                cols.append(self._obj_cols[ci])
            else:
                c, i = build_column(f.dtype, arrays, i)
                cols.append(c)
        return ColumnarBatch(self.meta.schema, cols, self.meta.num_rows)

    def promote_to_device(self, arrays: List[Any]) -> None:
        """Move the buffer back to the device tier (re-promotion on acquire,
        RapidsBufferStore.scala:275-301); caller accounts the bytes. A
        disk-pinned buffer's npz is stashed, not unlinked — the durable
        re-pin restores it without rewriting (buffers are immutable)."""
        with self._lock:
            self._device_arrays = arrays
            self._host_arrays = None
            if self._disk_path:
                if self.disk_pinned:
                    if self._pinned_path and \
                            self._pinned_path != self._disk_path and \
                            os.path.exists(self._pinned_path):
                        os.unlink(self._pinned_path)  # superseded stash
                    self._pinned_path = self._disk_path
                elif os.path.exists(self._disk_path):
                    os.unlink(self._disk_path)
            self._disk_path = None
            self.tier = StorageTier.DEVICE
        _ledger.note_tier(self.id, StorageTier.DEVICE)

    def demote_to_pinned_disk(self, only_from: Optional["StorageTier"]
                              = None) -> Optional["StorageTier"]:
        """Zero-IO demotion for disk-pinned buffers: the retained npz
        payload becomes the buffer again. Returns the tier demoted FROM
        (caller accounts the bytes), or None when there is no retained
        payload / the buffer is already on disk / ``only_from`` names a
        different tier (callers whose accounting assumes a specific
        source tier pass it so a racing move can't skew the books)."""
        with self._lock:
            if self._pinned_path is None or \
                    self.tier == StorageTier.DISK:
                return None
            if only_from is not None and self.tier != only_from:
                return None
            if not os.path.exists(self._pinned_path):
                self._pinned_path = None   # payload vanished; full spill
                return None
            prev = self.tier
            self._device_arrays = None
            self._host_arrays = None
            self._disk_path = self._pinned_path
            self._pinned_path = None
            self.tier = StorageTier.DISK
        _ledger.note_tier(self.id, StorageTier.DISK)
        from ..service.telemetry import flight_record
        flight_record("spill", f"buffer-{self.id}",
                      {"bytes": self.size_bytes, "to": "disk",
                       "pinned": True})
        return prev

    def free(self) -> None:
        with self._lock:
            self._device_arrays = None
            self._host_arrays = None
            if self._disk_path and os.path.exists(self._disk_path):
                os.unlink(self._disk_path)
            self._disk_path = None
            if self._pinned_path and os.path.exists(self._pinned_path):
                os.unlink(self._pinned_path)
            self._pinned_path = None


class BufferCatalog:
    """Global buffer registry + spill orchestration (RapidsBufferCatalog +
    the three RapidsBufferStores collapsed into one coordinator)."""

    _instance: Optional["BufferCatalog"] = None
    _lock = named_lock("exec.spill.BufferCatalog._lock")

    def __init__(self, device_budget: int = 1 << 34,
                 host_budget: int = 1 << 33,
                 spill_dir: str = "/tmp/spark_rapids_tpu_spill"):
        self.device_budget = device_budget
        self.host_budget = host_budget
        self.spill_dir = spill_dir
        self.buffers: Dict[int, SpillableBuffer] = {}
        self.device_bytes = 0
        self.host_bytes = 0
        self.spilled_device_bytes = 0     # metrics: total spilled (task metrics analog)
        self.spilled_host_bytes = 0
        # per-tenant DEVICE residency (service multi-tenancy): bytes held
        # on device by each tenant's buffers, maintained at the same
        # accounting boundaries as device_bytes; entries drop at 0 so an
        # idle tenant's watermark reads exactly zero
        self.tenant_device: Dict[str, int] = {}
        self._mu = named_rlock("exec.spill.BufferCatalog._mu")

    @classmethod
    def get(cls) -> "BufferCatalog":
        # double-checked creation: dependencies are built OUTSIDE the
        # class lock. The old shape called DeviceManager.get() (which
        # takes DeviceManager._lock and can probe the device) while
        # holding BufferCatalog._lock — an undocumented cross-singleton
        # order edge that lockdep flagged on its first clean run
        with cls._lock:
            inst = cls._instance
        if inst is not None:
            return inst
        from .. import config as cfg
        conf = cfg.TpuConf()
        try:
            # real device budget even when no session was built —
            # the 16 GiB constructor default is only a last resort
            from .device import DeviceManager
            device_budget = DeviceManager.get(conf).memory_budget_bytes
        except Exception:
            device_budget = 1 << 34
        candidate = BufferCatalog(
            device_budget=device_budget,
            host_budget=conf.host_spill_storage_size,
            spill_dir=conf.spill_dir)
        with cls._lock:
            if cls._instance is None:
                cls._instance = candidate
            return cls._instance

    @classmethod
    def peek(cls) -> Optional["BufferCatalog"]:
        """The existing instance or None — never constructs (telemetry
        harvest: reading residency must not bootstrap a catalog)."""
        with cls._lock:
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            if cls._instance is not None:
                for b in list(cls._instance.buffers.values()):
                    b.free()
            cls._instance = None
        # catalog reset is test teardown, not a free: drop the ledger's
        # buffer tables instead of tombstoning every torn-down id
        _ledger.forget_all()

    def buffer_count(self) -> int:
        with self._mu:
            return len(self.buffers)

    def residency_snapshot(self) -> List[Tuple[int, "StorageTier",
                                               float, bool]]:
        """(id, tier, priority, disk_pinned) per registered buffer — the
        ledger's end-of-query audit input, taken BEFORE the ledger lock
        (its lock is a leaf under this one)."""
        with self._mu:
            return [(b.id, b.tier, b.priority, b.disk_pinned)
                    for b in self.buffers.values()]

    # -- per-tenant residency (service multi-tenancy, docs/service.md) ------
    def _tenant_device_delta_locked(self, buf: "SpillableBuffer",
                                    delta: int) -> None:
        """Account ``delta`` device bytes to the buffer's tenant (caller
        holds ``self._mu``; untenanted buffers are a no-op). Entries
        drop at <= 0 so per-tenant watermarks return to exactly 0."""
        t = buf.tenant
        if t is None or not delta:
            return
        cur = self.tenant_device.get(t, 0) + delta
        if cur > 0:
            self.tenant_device[t] = cur
        else:
            self.tenant_device.pop(t, None)

    def tenant_device_bytes(self) -> Dict[str, int]:
        """Device bytes currently held per tenant (the
        ``tpu_tenant_device_bytes`` telemetry gauge's source)."""
        with self._mu:
            return dict(self.tenant_device)

    def _note_residency(self) -> None:
        """Update the process HBM/host watermarks after an accounting
        change (service/telemetry): current + peak bytes with
        per-operator peak attribution through the open exec scope.
        Called at admission/registration/free boundaries — never per
        row, never per element."""
        from ..service import telemetry
        telemetry.watermark("device", bag_key="peakDeviceBytes").update(
            self.device_bytes)
        telemetry.watermark("host").update(self.host_bytes)

    # -- registration --------------------------------------------------------
    def register_batch(self, batch: ColumnarBatch,
                       priority: float = ACTIVE_ON_DECK_PRIORITY) -> int:
        from ..columnar.column import ObjectColumn
        arrays: List[Any] = []
        col_dtypes: List[dt.DType] = []
        obj_cols: Dict[int, Column] = {}
        for ci, c in enumerate(batch.columns):
            if isinstance(c, ObjectColumn):
                obj_cols[ci] = c
                continue
            arrays.extend(c.arrays())
            col_dtypes.append(c.dtype)
        # tenant attribution (service multi-tenancy): the ambient query
        # context's tenant owns this buffer's residency. CACHE_PRIORITY
        # registrations (scan device cache, df.cache()) stay UNTENANTED —
        # cached tables are shared infrastructure served to every tenant,
        # and charging them to whichever tenant scanned first would leave
        # that tenant's watermark pinned above zero forever
        tenant = None
        if priority != CACHE_PRIORITY:
            from .query_context import current_tenant
            tenant = current_tenant()
        buf = SpillableBuffer(
            next_buffer_id(),
            BufferMeta(batch.schema, batch.num_rows_raw, batch.capacity),
            priority, arrays, col_dtypes, obj_cols, tenant=tenant)
        with self._mu:
            self.buffers[buf.id] = buf
            self.device_bytes += buf.size_bytes
            self._tenant_device_delta_locked(buf, buf.size_bytes)
            self._maybe_spill_locked()
            # per-tenant budget at the REGISTER boundary: a tenant past
            # its device budget spills its OWN buffers first (never the
            # one just registered — the active batch is not its own
            # victim; it becomes eligible at the next tenant's pressure)
            self._enforce_tenant_budget_locked(tenant, exclude_id=buf.id)
            self._note_residency()
        # ledger AFTER the admission lock releases; the registration
        # cascade may already have spilled this buffer, so pass its tier
        _ledger.note_register(buf.id, buf.size_bytes, priority, tenant,
                              tier=buf.tier)
        return buf.id

    def acquire_batch(self, buffer_id: int) -> ColumnarBatch:
        """Materialize a registered batch on device. A spilled buffer is
        re-promoted to the device tier WITH accounting — admission first
        (possibly spilling lower-priority buffers), then the promotion is
        charged against the device budget, so concurrent acquires cannot
        silently exceed it (RapidsBufferStore.scala:275-301)."""
        _ledger.note_access(buffer_id)
        with self._mu:
            buf = self.buffers[buffer_id]
            if buf.tier != StorageTier.DEVICE:
                target = self.device_budget - buf.size_bytes
                if self.device_bytes > target:
                    self._spill_device_to_locked(max(target, 0))
                prev_tier = buf.tier
                arrays = buf._load_arrays()
                buf.promote_to_device(arrays)
                if prev_tier == StorageTier.HOST:
                    self.host_bytes -= buf.size_bytes
                self.device_bytes += buf.size_bytes
                self._tenant_device_delta_locked(buf, buf.size_bytes)
                # re-promotion is a reserve-like boundary: an over-budget
                # tenant re-admitting a buffer yields its OTHER residents
                self._enforce_tenant_budget_locked(buf.tenant,
                                                   exclude_id=buf.id)
                self._note_residency()
        # device-tier rebuild happens OUTSIDE the catalog lock so concurrent
        # task threads on the (common) unspilled path never serialize here
        batch = buf.get_batch()
        # the catalog still owns (and may re-serve) these arrays: mark
        # the batch so fused programs never take them as donated buffers
        batch.shared = True
        return batch

    def pin_to_disk(self, buffer_id: int) -> int:
        """Push one registered buffer through to the DISK tier now (the
        durable-shuffle checkpoint write, docs/resilience.md) — unlike
        the pressure-driven cascade this is caller-initiated, so durable
        map outputs stop holding device/host memory the moment the map
        phase ends. Returns the buffer's size when it reached disk. The
        buffer stays registered and re-promotes on its next read.

        The npz IO runs OUTSIDE the admission lock (the ShuffleStore
        write-through rule: checkpoint writes must not stall every
        concurrent allocation/spill): the buffer's own lock serializes
        its tier moves, and each move's accounting commits immediately
        after the move lands — a disk write failing halfway must not
        tear the device/host byte counts (the host move already
        happened and stays accounted)."""
        with self._mu:
            buf = self.buffers.get(buffer_id)
        if buf is None:
            return 0
        buf.disk_pinned = True
        # re-pin fast path: a read promoted this pinned buffer and its
        # npz payload was retained — demotion is a tier flip, no IO
        prev = buf.demote_to_pinned_disk()
        if prev is not None:
            with self._mu:
                if prev == StorageTier.DEVICE:
                    self.device_bytes -= buf.size_bytes
                    self._tenant_device_delta_locked(buf, -buf.size_bytes)
                    self.spilled_device_bytes += buf.size_bytes
                elif prev == StorageTier.HOST:
                    self.host_bytes -= buf.size_bytes
                    self.spilled_host_bytes += buf.size_bytes
                self._note_residency()
            return buf.size_bytes
        moved = buf.spill_to_host()
        if moved:
            with self._mu:
                self.device_bytes -= moved
                self._tenant_device_delta_locked(buf, -moved)
                self.host_bytes += moved
                self.spilled_device_bytes += moved
                self._note_residency()
        moved_d = buf.spill_to_disk(self.spill_dir)
        if moved_d:
            with self._mu:
                self.host_bytes -= moved_d
                self.spilled_host_bytes += moved_d
                self._note_residency()
        return buf.size_bytes if buf.tier == StorageTier.DISK else 0

    def pin_working_set(self, tenant: Optional[str]) -> Tuple[int, int]:
        """Spill EVERY device-resident buffer of ``tenant`` to the host
        tier now — the suspend path of the query lifecycle control plane
        (docs/service.md): a preempted query's working set leaves the
        device so the preempting query gets real HBM headroom, not just
        a freed scheduler slot. Unlike the pressure-driven cascade this
        is caller-initiated and unconditional for the tenant; untenanted
        buffers (shared caches, CACHE_PRIORITY) are never victims.
        Returns ``(buffers_moved, bytes_moved)``. The spilled buffers
        stay registered and re-promote lazily on their next read
        (``acquire_batch``) after resume, so resumption pays
        re-promotion only for what it actually re-touches."""
        if tenant is None:
            return (0, 0)
        moved_n = moved_bytes = 0
        with self._mu:
            victims = sorted(
                (b for b in self.buffers.values()
                 if b.tier == StorageTier.DEVICE and b.tenant == tenant),
                key=lambda b: b.priority)
            with lockdep.allowed_while_locked(
                    "suspend working-set spill under the admission lock "
                    "(the synchronous-spill discipline, docs/service.md)"):
                for buf in victims:
                    moved = buf.spill_to_host()
                    if moved:
                        self.device_bytes -= moved
                        self._tenant_device_delta_locked(buf, -moved)
                        self.host_bytes += moved
                        self.spilled_device_bytes += moved
                        moved_n += 1
                        moved_bytes += moved
            self._note_residency()
            if self.host_bytes > self.host_budget:
                self._spill_host_to_locked(self.host_budget)
        return (moved_n, moved_bytes)

    def remove(self, buffer_id: int) -> None:
        with self._mu:
            buf = self.buffers.pop(buffer_id, None)
            if buf is not None:
                if buf.tier == StorageTier.DEVICE:
                    self.device_bytes -= buf.size_bytes
                    self._tenant_device_delta_locked(buf, -buf.size_bytes)
                elif buf.tier == StorageTier.HOST:
                    self.host_bytes -= buf.size_bytes
                buf.free()
                self._note_residency()
        # unconditional (outside the admission lock): a remove of an
        # already-removed id is exactly the double-free the ledger exists
        # to diagnose
        _ledger.note_free(buffer_id)

    # -- spill logic ---------------------------------------------------------
    def reserve(self, nbytes: int) -> None:
        """Admission check before materializing ~nbytes on device
        (DeviceMemoryEventHandler.onAllocFailure analog: spill until the
        allocation fits, DeviceMemoryEventHandler.scala:42-69). Also the
        per-tenant RESERVE boundary: a tenant already past its device
        budget spills its own resident buffers before growing."""
        from .query_context import current_tenant
        tenant = current_tenant()
        with self._mu:
            target = self.device_budget - nbytes
            if self.device_bytes > target:
                self._spill_device_to_locked(max(target, 0))
            self._enforce_tenant_budget_locked(tenant)
            self._note_residency()

    def _maybe_spill_locked(self) -> None:
        if self.device_bytes > self.device_budget:
            self._spill_device_to_locked(self.device_budget)

    def _over_budget_tenants_locked(self) -> set:
        """Tenants currently holding more device bytes than their
        installed budget (service/tenants.py) — the cascade's preferred
        victim class. Caller holds ``self._mu``."""
        from ..service import tenants as tn
        return {t for t, held in self.tenant_device.items()
                if tn.over_budget(t, held)}

    def _spill_device_to_locked(self, target: int) -> None:
        """Pop lowest-priority device buffers and push to host tier
        (RapidsBufferStore.synchronousSpill, RapidsBufferStore.scala:139-201).
        Caller holds ``self._mu`` (the ``_locked`` convention).

        Cross-tenant spill priority (docs/service.md §3): buffers of
        tenants OVER their device budget are cascade victims before any
        under-budget (or untenanted) tenant's, so global pressure caused
        by one tenant's overdraw lands on that tenant first; within a
        class the usual spill priority orders."""
        over = self._over_budget_tenants_locked()
        device_bufs = sorted(
            (b for b in self.buffers.values() if b.tier == StorageTier.DEVICE),
            key=lambda b: (0 if b.tenant in over else 1, b.priority))
        with lockdep.allowed_while_locked(
                "synchronous spill: the admission lock serializes tier "
                "moves by design (DeviceMemoryEventHandler analog)"):
            for buf in device_bufs:
                if self.device_bytes <= target:
                    break
                moved = buf.spill_to_host()
                self.device_bytes -= moved
                self._tenant_device_delta_locked(buf, -moved)
                self.host_bytes += moved
                self.spilled_device_bytes += moved
        self._note_residency()     # host tier may have just peaked
        if self.host_bytes > self.host_budget:
            self._spill_host_to_locked(self.host_budget)

    def _enforce_tenant_budget_locked(self, tenant: Optional[str],
                                      exclude_id: Optional[int] = None
                                      ) -> None:
        """Per-tenant budget enforcement at the reserve/register
        boundaries: while ``tenant`` holds more device bytes than its
        budget (service/tenants.py), its OWN device buffers spill
        lowest-priority-first — an overdrawing tenant pays with its own
        residency before any neighbor does. ``exclude_id`` protects the
        buffer being registered right now (the active batch is never its
        own victim). Caller holds ``self._mu``."""
        from ..service import tenants as tn
        if tenant is None:
            return
        held = self.tenant_device.get(tenant, 0)
        if not tn.over_budget(tenant, held):
            return
        budget = tn.budget_for(tenant)
        victims = sorted(
            (b for b in self.buffers.values()
             if b.tier == StorageTier.DEVICE and b.tenant == tenant and
             b.id != exclude_id),
            key=lambda b: b.priority)
        with lockdep.allowed_while_locked(
                "per-tenant budget spill under the admission lock (the "
                "synchronous-spill discipline, docs/service.md)"):
            for buf in victims:
                if self.tenant_device.get(tenant, 0) <= budget:
                    break
                moved = buf.spill_to_host()
                self.device_bytes -= moved
                self._tenant_device_delta_locked(buf, -moved)
                self.host_bytes += moved
                self.spilled_device_bytes += moved
        self._note_residency()
        if self.host_bytes > self.host_budget:
            self._spill_host_to_locked(self.host_budget)

    def _spill_host_to_locked(self, target: int) -> None:
        host_bufs = sorted(
            (b for b in self.buffers.values() if b.tier == StorageTier.HOST),
            key=lambda b: b.priority)
        with lockdep.allowed_while_locked(
                "synchronous host->disk cascade under the admission lock"):
            for buf in host_bufs:
                if self.host_bytes <= target:
                    break
                moved = buf.spill_to_disk(self.spill_dir)
                self.host_bytes -= moved
                self.spilled_host_bytes += moved


class SpillableColumnarBatch:
    """Handle to a batch that may be spilled and rematerialized on demand
    (SpillableColumnarBatch.scala:28-137)."""

    def __init__(self, batch: ColumnarBatch,
                 priority: float = ACTIVE_ON_DECK_PRIORITY,
                 catalog: Optional[BufferCatalog] = None):
        self.catalog = catalog or BufferCatalog.get()
        # keep a device-resident count lazy: registering a streamed batch
        # must not force a host sync (see ColumnarBatch.num_rows)
        self._num_rows = batch.num_rows_raw
        self.schema = batch.schema
        self.size_bytes = batch.device_size_bytes()
        self._id = self.catalog.register_batch(batch, priority)
        self._closed = False

    @property
    def num_rows(self):
        nr = self._num_rows
        if not isinstance(nr, int):
            nr = int(nr)
            self._num_rows = nr
        return nr

    def get_batch(self) -> ColumnarBatch:
        if self._closed:
            raise BufferLostError(f"buffer {self._id} released")
        try:
            return self.catalog.acquire_batch(self._id)
        except KeyError:
            raise BufferLostError(f"buffer {self._id} missing from the "
                                  "catalog") from None

    def pin_to_disk(self) -> int:
        """Durable pin: push this handle's buffer to the disk tier now
        (see :meth:`BufferCatalog.pin_to_disk`); 0 when already closed."""
        if self._closed:
            return 0
        return self.catalog.pin_to_disk(self._id)

    def close(self) -> None:
        if not self._closed:
            self.catalog.remove(self._id)
            self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class BorrowedSpillableView:
    """Non-owning stand-in for an already-registered batch (a scan
    device-cache entry served straight downstream): re-registering the
    same device arrays would double-count HBM in the catalog, so drain
    layers borrow the owner's registration. ``get_batch`` returns the
    borrowed batch directly (our reference pins the arrays regardless of
    the owner's spill state) and ``close`` is a no-op — lifetime belongs
    to the cache entry."""

    def __init__(self, owner: "SpillableColumnarBatch",
                 batch: ColumnarBatch):
        self._batch = batch
        self.schema = batch.schema
        self.size_bytes = owner.size_bytes
        self._num_rows = batch.num_rows_raw

    @property
    def num_rows(self):
        nr = self._num_rows
        if not isinstance(nr, int):
            nr = int(nr)
            self._num_rows = nr
        return nr

    def get_batch(self) -> ColumnarBatch:
        return self._batch

    def close(self) -> None:
        pass
