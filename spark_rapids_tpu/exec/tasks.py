"""Task execution: thread-pooled partition drains with semaphore discipline.

Reference: Spark executors run N concurrent tasks; ``GpuSemaphore`` bounds how
many of them may hold the device at once (GpuSemaphore.scala:27-161), and a
task-completion listener releases the permit. Here a "task" is the drain of
one partition's batch iterator on a pool thread; ``physical._task_begin``
acquires the semaphore lazily at the first device op inside the drain, and the
runner releases it in a ``finally`` when the partition is exhausted — the
task-completion-listener contract (GpuSemaphore.scala:93) without Spark.

The pool size (``spark.rapids.tpu.sql.taskPoolThreads``) may exceed the
semaphore permits: extra threads block in ``acquire`` exactly like Spark tasks
queueing on the GPU, keeping host-side input preparation overlapped with
device work.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, List, Sequence, TypeVar

T = TypeVar("T")


def _release_semaphore() -> None:
    from .device import TpuSemaphore
    TpuSemaphore.get().release_if_necessary()


def _park_on_suspend(exc: BaseException, ctx, done_pids) -> None:
    """A partition drain unwinding on a suspension request parks its
    stage cursor — which drain, which partitions already completed — on
    the query's lifecycle token. The service worker loop stashes the
    cursor with the suspended ticket; on resume the stage-retry driver's
    re-entry (plan cache + durable shuffle outputs) makes re-running the
    already-done partitions cheap. Never raises."""
    try:
        from .lifecycle import QuerySuspendedError
        if not isinstance(exc, QuerySuspendedError):
            return
        token = getattr(ctx, "cancel_token", None) if ctx is not None \
            else None
        if token is not None:
            token.park_cursor(stage="partition-drain",
                              partitions_done=sorted(done_pids))
    except Exception:
        pass


def _record_swallowed(name: str, exc: BaseException) -> None:
    """A worker exception that will never re-raise on the consumer side
    (early generator close, bounded-join teardown) is LOGGED and
    flight-recorded instead of silently discarded — the teardown
    discipline of docs/resilience.md. Never raises: teardown reporting
    must not replace the (absent) original failure with its own."""
    try:
        import logging
        logging.getLogger("spark_rapids_tpu.tasks").warning(
            "%s teardown swallowed a worker exception: %s: %s",
            name, type(exc).__name__, exc)
        from ..service.telemetry import flight_record
        flight_record("teardown", f"{name}-swallowed",
                      {"error": f"{type(exc).__name__}: {exc}"[:300]})
    except Exception:
        pass


def record_join_timeout(name: str, threads: List[str],
                        logger: str = "spark_rapids_tpu.tasks") -> None:
    """Bounded-join teardown: threads that outlived their join window
    are LOGGED and flight-recorded, not silently abandoned — the wedge
    stays visible in post-mortems (docs/resilience.md). Never raises:
    this runs in finally/teardown paths where a reporting failure must
    not replace the (absent) original error."""
    try:
        import logging
        logging.getLogger(logger).warning(
            "%s: %d thread(s) still alive after bounded join: %s",
            name, len(threads), threads)
        from ..service.telemetry import flight_record
        flight_record("teardown", f"{name}-join-timeout",
                      {"threads": threads})
    except Exception:
        pass


def prefetch_map(items: Iterable[Any], fn: Callable[[Any], T],
                 depth: int = 2,
                 name: str = "spark-rapids-tpu-prefetch") -> Iterable[T]:
    """Map ``fn`` over ``items`` on a background thread, keeping up to
    ``depth`` results ready ahead of the consumer — overlaps host-side
    work (arrow decode/conversion) with downstream device compute, the
    role of the reference's background fetch threads
    (MultiFileCloudParquetPartitionReader, GpuParquetScan.scala:1145)."""
    import queue

    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    sentinel = object()
    stop = threading.Event()
    err: List[BaseException] = []

    def worker() -> None:
        from .lifecycle import check_cancel
        try:
            for it in items:
                check_cancel()          # per-item lifecycle poll
                res = fn(it)
                while not stop.is_set():  # lint: cancel-ok bounded put retry; the per-item poll above covers the drain
                    try:
                        q.put(res, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:          # re-raised on the consumer side
            err.append(e)
        finally:
            while not stop.is_set():  # lint: cancel-ok teardown sentinel delivery must complete even for a cancelled query
                try:
                    q.put(sentinel, timeout=0.2)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=worker, daemon=True, name=name)
    t.start()
    delivered = False
    try:
        from .lifecycle import check_cancel
        while True:
            try:
                v = q.get(timeout=0.2)
            except queue.Empty:
                check_cancel()          # delivery-wait lifecycle poll
                continue
            if v is sentinel:
                if err:
                    delivered = True
                    raise err[0]
                return
            yield v
    finally:
        stop.set()                          # unblock the worker on early exit
        if err and not delivered:
            # the consumer closed early: the worker's exception would be
            # silently discarded — flight-record it so teardown never
            # swallows a real failure (docs/resilience.md)
            _record_swallowed(name, err[0])


def ordered_prefetch(items: Iterable[Any], fn: Callable[[Any], T],
                     threads: int = 2, depth: int = 2,
                     name: str = "tpu-prefetch") -> Iterable[T]:
    """Map ``fn`` over ``items`` on ``threads`` named background threads
    (``<name>-N``), yielding results in INPUT ORDER with at most ``depth``
    completed results buffered ahead of the consumer — the multi-worker
    generalization of :func:`prefetch_map` the streaming scan drains
    batch-by-batch (double-buffered CPU decode overlapping device
    compute; MultiFileCloudParquetPartitionReader's pool role).

    Workers join with a bounded timeout on shutdown (the PR 4
    transport-thread discipline); a worker exception re-raises on the
    consumer side; closing the generator early stops the workers."""
    import queue

    items = list(items)
    if not items:
        return
    threads = max(1, min(threads, len(items)))
    # depth >= threads or in-flight workers for LATER items could hold
    # every result slot while the next-to-yield item's worker starves on
    # acquire (the consumer only frees slots in order)
    depth = max(1, depth, threads)
    idx_q: "queue.SimpleQueue[int]" = queue.SimpleQueue()
    for i in range(len(items)):  # lint: cancel-ok SimpleQueue.put is unbounded and non-blocking — work-list seeding, no dwell
        idx_q.put(i)
    results: dict = {}
    cond = threading.Condition()  # lint: raw-lock-ok per-iterator transient coordination, dies with the generator — not shared engine state
    state = {"next": 0}            # next index the consumer will yield
    stop = threading.Event()
    errs: List[BaseException] = []

    def worker() -> None:
        from .lifecycle import check_cancel
        while not stop.is_set():  # lint: cancel-ok body polls check_cancel per item below
            try:
                i = idx_q.get_nowait()
            except queue.Empty:
                return
            # window admission ordered on the CONSUMER's position: index i
            # may compute only once i < next+depth. The worker holding the
            # next-to-yield index always passes, so (unlike a shared
            # semaphore, whose unfair wakeups let later-index workers
            # starve it — a real deadlock) progress is guaranteed while
            # buffered results stay bounded at `depth`.
            with cond:
                while not stop.is_set() and i >= state["next"] + depth:  # lint: cancel-ok a cancelled consumer sets stop in its finally, releasing this wait
                    cond.wait(0.2)
            if stop.is_set():
                return
            try:
                check_cancel()          # per-item lifecycle poll
                res = fn(items[i])
            except BaseException as e:   # re-raised on the consumer side
                with cond:
                    errs.append(e)
                    stop.set()
                    cond.notify_all()
                return
            with cond:
                results[i] = res
                cond.notify_all()

    workers = [threading.Thread(target=worker, daemon=True,
                                name=f"{name}-{i}")
               for i in range(threads)]
    for t in workers:
        t.start()
    delivered = False
    try:
        from .lifecycle import check_cancel
        for i in range(len(items)):  # lint: cancel-ok the inner delivery wait polls check_cancel
            with cond:
                while i not in results and not errs:
                    check_cancel()  # delivery-wait lifecycle poll
                    cond.wait(0.2)
                if errs:
                    delivered = True     # re-raised, not swallowed
                    raise errs[0]
                res = results.pop(i)
                state["next"] = i + 1
                cond.notify_all()
            yield res
    finally:
        stop.set()
        with cond:
            cond.notify_all()
        for t in workers:                # lint: cancel-ok bounded teardown join; stop is already set so workers exit on their own polls
            t.join(timeout=5.0)
        # bounded-join teardown discipline: a worker that outlived its
        # join window, or an exception captured but never re-raised
        # (consumer closed early), is LOGGED instead of discarded
        alive = [t.name for t in workers if t.is_alive()]
        if alive:
            record_join_timeout(name, alive)
        if not delivered:
            with cond:
                pending_errs = list(errs)
            for e in pending_errs:
                _record_swallowed(name, e)


def stream_partition_tasks(parts: Sequence[Any],
                           fn: Callable[[int, Any], T],
                           max_workers: int = 0) -> Iterable[T]:
    """Generator form of :func:`run_partition_tasks`: yield each
    partition's result IN PARTITION ORDER as soon as it (and every
    earlier partition) completes, instead of materializing the full
    result list — the streaming-collect drain (``DataFrame.collect_iter``,
    docs/observability.md firstRowS). Identical per-task discipline:
    deferred-finalizer drain at launch, query-context propagation,
    audited region, semaphore release, dump-on-error.

    Early close (the consumer abandons the stream) cancels unstarted
    tasks and then waits for RUNNING drains to finish, so every scan's
    ``_drain`` finally fires and staging arenas / prefetch threads
    release (io/scan._StagingTracker); exceptions from tasks that
    completed after the consumer left are logged via the teardown
    discipline, never silently discarded."""
    if max_workers <= 0:
        from .. import config as cfg
        max_workers = cfg.TpuConf().task_pool_threads
    from .spill import drain_deferred_finalizers
    drain_deferred_finalizers()
    from . import query_context as _qc
    from .lifecycle import check_cancel
    _query_ctx = _qc.current()
    done_pids: List[int] = []

    def task(pid_part):
        pid, part = pid_part
        try:
            from ..analysis.sync_audit import audited_region
            with _qc.thread_scope(_query_ctx), audited_region():
                check_cancel()      # partition-drain lifecycle poll
                out = fn(pid, part)
                done_pids.append(pid)   # list.append is GIL-atomic
                return out
        except BaseException as e:
            _park_on_suspend(e, _query_ctx, done_pids)
            from ..service.telemetry import dump_on_error
            dump_on_error(e)
            raise
        finally:
            _release_semaphore()

    parts = list(parts)
    if len(parts) <= 1 or max_workers <= 1:
        for i, p in enumerate(parts):  # lint: cancel-ok serial path; task() polls per partition
            yield task((i, p))
        return
    pool = ThreadPoolExecutor(max_workers=min(max_workers, len(parts)),
                              thread_name_prefix="tpu-task")
    futures = [pool.submit(task, (i, p)) for i, p in enumerate(parts)]
    delivered = -1
    raised = False
    try:
        for i, f in enumerate(futures):  # lint: cancel-ok every task polls; a cancelled task's failure re-raises from f.result()
            try:
                res = f.result()
            except BaseException:  # the task failure re-raises here
                raised = True
                raise
            delivered = i
            yield res
    finally:
        for f in futures:
            f.cancel()
        # wait=True: running drains must complete so their finallys
        # release staging arenas before the consumer moves on
        pool.shutdown(wait=True)
        for i, f in enumerate(futures):
            if i <= delivered or not f.done() or f.cancelled():
                continue
            if raised and i == delivered + 1:
                continue           # this failure re-raised, not swallowed
            e = f.exception()
            if e is not None:
                _record_swallowed("tpu-stream-task", e)


def run_partition_tasks(parts: Sequence[Any],
                        fn: Callable[[int, Any], T],
                        max_workers: int = 0) -> List[T]:
    """Run ``fn(pid, partition)`` for each partition as a task, returning
    results in partition order. Tasks run on a fresh pool (nested calls —
    e.g. an exchange inside a collect — must not share a bounded pool, or
    a parent task waiting on child tasks could starve the pool); each task
    releases the TpuSemaphore on completion regardless of outcome."""
    if max_workers <= 0:
        from .. import config as cfg
        max_workers = cfg.TpuConf().task_pool_threads
    # safe point for GC-deferred cleanup (exec/spill.defer_finalizer):
    # no engine locks are held at task launch
    from .spill import drain_deferred_finalizers
    drain_deferred_finalizers()
    # capture the SUBMITTING thread's query context and install it on
    # each worker thread (TLS-only): with two concurrent queries in one
    # process, pool events must attribute to their own query, not to
    # whichever query entered the process default last
    from . import query_context as _qc
    from .lifecycle import check_cancel
    _query_ctx = _qc.current()
    done_pids: List[int] = []

    def task(pid_part):
        pid, part = pid_part
        try:
            # runtime sync audit (analysis/sync_audit.py): when armed via
            # spark.rapids.tpu.sql.analysis.syncAudit, the partition-drain
            # body — the operator execute region — runs under
            # jax.transfer_guard_device_to_host(log|disallow); sanctioned
            # implicit crossings wrap themselves in allowed_host_transfer
            from ..analysis.sync_audit import audited_region
            with _qc.thread_scope(_query_ctx), audited_region():
                check_cancel()      # partition-drain lifecycle poll
                out = fn(pid, part)
                done_pids.append(pid)   # list.append is GIL-atomic
                return out
        except BaseException as e:
            _park_on_suspend(e, _query_ctx, done_pids)
            # post-mortem: dump the always-on flight ring for a dying
            # task body. dump_on_error never raises and marks the
            # exception, so the collect-level hook will not dump twice
            # and the original error propagates unmasked.
            from ..service.telemetry import dump_on_error
            dump_on_error(e)
            raise
        finally:
            _release_semaphore()

    if len(parts) <= 1 or max_workers <= 1:
        return [task((i, p)) for i, p in enumerate(parts)]
    # named pool threads: lockdep acquisition stacks and teardown reports
    # attribute lock traffic to the drain pool instead of Thread-N
    with ThreadPoolExecutor(max_workers=min(max_workers, len(parts)),
                            thread_name_prefix="tpu-task") as pool:
        return list(pool.map(task, enumerate(parts)))
