"""Tracing spans: named profiler ranges around hot regions.

Reference: ``NvtxWithMetrics.scala:27`` — NVTX ranges (optionally fused with
SQLMetrics timers) wrap every hot region so Nsight shows named spans:
semaphore acquire (GpuSemaphore.scala:107), agg batches (aggregate.scala:435),
shuffle write (RapidsShuffleInternalManager.scala:91).

TPU analog: ``jax.profiler.TraceAnnotation`` spans show up in xprof/
TensorBoard traces; ``start_profiler_server`` exposes the live profiler.
Disabled (no-op, zero overhead beyond one attr check) unless
``spark.rapids.tpu.sql.tracing.enabled`` is on.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

_enabled: Optional[bool] = None


def _tracing_on() -> bool:
    global _enabled
    if _enabled is None:
        from .. import config as cfg
        _enabled = bool(cfg.TpuConf().get(cfg.TRACING_ENABLED))
    return _enabled


def reset_cache() -> None:
    global _enabled
    _enabled = None


@contextmanager
def trace_span(name: str, metrics=None, metric_key: Optional[str] = None):
    """Named profiler span (NvtxWithMetrics: optionally also feeds a
    metrics timer)."""
    if not _tracing_on():
        if metrics is not None and metric_key:
            with metrics.timer(metric_key):
                yield
        else:
            yield
        return
    import jax
    import time
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    if metrics is not None and metric_key:
        metrics.inc(metric_key, time.perf_counter() - t0)


def start_profiler_server(port: int = 9012) -> None:
    """Expose the live jax profiler (xprof capture target)."""
    import jax
    jax.profiler.start_server(port)
