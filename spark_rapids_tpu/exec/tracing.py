"""Tracing spans: named profiler ranges around hot regions.

Reference: ``NvtxWithMetrics.scala:27`` — NVTX ranges (optionally fused with
SQLMetrics timers) wrap every hot region so Nsight shows named spans:
semaphore acquire (GpuSemaphore.scala:107), agg batches (aggregate.scala:435),
shuffle write (RapidsShuffleInternalManager.scala:91).

TPU analog: ``jax.profiler.TraceAnnotation`` spans show up in xprof/
TensorBoard traces; ``start_profiler_server`` exposes the live profiler.
Disabled (no-op, zero overhead beyond one attr check) unless
``spark.rapids.tpu.sql.tracing.enabled`` is on.

Beyond the per-name self-time totals, ``SpanRecorder`` optionally records
every span's begin/end with its thread (conf
``spark.rapids.tpu.sql.tracing.timeline``) and exports a Chrome-trace /
Perfetto ``trace.json`` (:meth:`SpanRecorder.chrome_trace`), turning the
flat self-time map into an actual timeline — open it in chrome://tracing
or ui.perfetto.dev (see docs/observability.md).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from ..analysis.lockdep import named_lock
from .metrics import exec_scope, metrics_enabled

_enabled: Optional[bool] = None
_timeline: Optional[bool] = None


def _effective_conf():
    from ..analysis.sync_audit import _effective_conf as eff
    return eff()


def _tracing_on() -> bool:
    global _enabled
    if _enabled is None:
        from .. import config as cfg
        _enabled = bool(cfg.TpuConf().get(cfg.TRACING_ENABLED))
    return _enabled


def _timeline_on() -> bool:
    global _timeline
    if _timeline is None:
        try:
            from .. import config as cfg
            _timeline = bool(_effective_conf().get(cfg.TRACING_TIMELINE))
        except Exception:
            _timeline = False
    return _timeline


def reset_cache() -> None:
    global _enabled, _timeline
    _enabled = None
    _timeline = None


def _telemetry_span(name: str, begin: float, elapsed: float,
                    err: bool) -> None:
    """Feed the process-lifetime telemetry at span close (a flush
    boundary): the always-on flight ring gets the span (error-marked
    when it unwound on an exception — the post-mortem breadcrumb), and
    the registry span histogram gets its duration."""
    from ..service import telemetry as tel
    try:
        if tel._flight_on():
            data = {"beginS": round(begin, 6), "durS": round(elapsed, 6)}
            if err:
                data["error"] = True
            tel.FlightRecorder.get().record("span", name, data)
        if metrics_enabled():
            tel.MetricsRegistry.get().histogram(
                "tpu_span_seconds", "trace span durations",
                name=name).observe(elapsed)
    except Exception:
        pass                   # telemetry must never fail the span


@contextmanager
def trace_span(name: str, metrics=None, metric_key: Optional[str] = None):
    """Named profiler span (NvtxWithMetrics: optionally also feeds a
    metrics timer). Always feeds the active :class:`SpanRecorder` (the
    per-query wall-clock breakdown) and the ALWAYS-ON flight recorder
    (``service/telemetry``: post-mortems without tracing pre-enabled);
    the jax profiler annotation is config-gated. When ``metrics`` is an
    exec's bag, the span also marks that exec as the innermost open one
    on this thread (``exec/metrics.exec_scope``) so attributed events —
    host syncs, recompiles, spill bytes — land on its operator node."""
    import time
    rec = SpanRecorder.active
    t0 = time.perf_counter()
    frame = rec._push(name) if rec is not None else None
    err = False
    try:
        with exec_scope(metrics):
            if _tracing_on():
                import jax
                with jax.profiler.TraceAnnotation(name):
                    yield
            else:
                yield
    except BaseException:
        err = True
        raise
    finally:
        elapsed = time.perf_counter() - t0
        if rec is not None:
            rec._pop(frame, name, elapsed, begin=t0)
        if metrics is not None and metric_key:
            metrics.inc(metric_key, elapsed)
        _telemetry_span(name, t0, elapsed, err)


class SpanRecorder:
    """Per-query wall-clock breakdown: every ``trace_span`` while a
    recorder is active contributes its SELF time (elapsed minus enclosed
    child spans) to a name -> seconds map, so the report names where the
    execute wall went without double counting nesting (the NVTX-range
    timeline of the reference, reduced to per-name totals). Partitions
    drain on a thread pool, so stacks are thread-local and concurrent
    spans can legitimately sum past the wall clock — ``report()`` carries
    the wall clock and the ``concurrency`` ratio (sum of self-time over
    wall) so such reports read as parallelism, not as confusion.

    With ``timeline=True`` (or conf ``...sql.tracing.timeline``) every
    span's (begin, duration, thread) is kept and
    :meth:`chrome_trace` exports Chrome-trace JSON."""

    active: Optional["SpanRecorder"] = None

    def __init__(self, timeline: Optional[bool] = None):
        import collections
        import threading
        self._self_s = collections.defaultdict(float)
        self._count = collections.defaultdict(int)
        self._mu = named_lock("exec.tracing.SpanRecorder._mu")
        self._tls = threading.local()
        self._timeline = _timeline_on() if timeline is None else timeline
        self._events: List[tuple] = []     # (name, begin, dur, tid, tname)
        self._t0: Optional[float] = None   # entered wall-clock origin
        self._wall: Optional[float] = None
        # the query id this recorder's spans belong to (set by the
        # collect that enters the recorder, exec/query_context.py):
        # rides every exported Chrome-trace event so merged multi-worker
        # timelines can join both workers' spans under one query
        self.query_id: Optional[str] = None

    def __enter__(self):
        import time
        self._prev = SpanRecorder.active  # lint: unguarded-ok recorder entered on the driving thread only; pool workers read .active, never swap it
        SpanRecorder.active = self  # lint: unguarded-ok single driving-thread swap; worker reads race only with query start/end, where no spans are open
        self._t0 = time.perf_counter()  # lint: unguarded-ok driving-thread-only enter bookkeeping
        return self

    def __exit__(self, *exc):
        import time
        SpanRecorder.active = self._prev  # lint: unguarded-ok same single driving-thread swap as __enter__
        if self._t0 is not None:
            self._wall = time.perf_counter() - self._t0  # lint: unguarded-ok driving-thread-only exit bookkeeping
        return False

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, name):
        # the frame carries its span name so the sync counter can
        # attribute device->host readbacks to the innermost open span
        # (the syncs-per-span breakdown the bench runner reports)
        frame = {"name": name, "child_s": 0.0}
        self._stack().append(frame)
        return frame

    def current_span(self):
        """Innermost open span name on THIS thread (None outside spans)."""
        st = self._stack()
        return st[-1]["name"] if st else None

    def _pop(self, frame, name, elapsed, begin: Optional[float] = None):
        # remove THIS frame by identity, not the stack top: spans held open
        # across generator yields (the pipelined join suspends mid-span)
        # close out of order, and popping the top would steal an unrelated
        # open frame — misattributing every enclosing span's self-time
        st = self._stack()
        idx = None
        for i in range(len(st) - 1, -1, -1):
            if st[i] is frame:
                idx = i
                break
        if idx is not None:
            del st[idx]
            if idx > 0:
                # elapsed counts as child time of the frame that was the
                # parent at open time (the one below it), even if younger
                # frames are still open above
                st[idx - 1]["child_s"] += elapsed
        self_s = max(0.0, elapsed - frame["child_s"])
        ev = None
        if self._timeline and begin is not None:
            import threading
            t = threading.current_thread()
            ev = (name, begin, elapsed, t.ident, t.name)
        with self._mu:
            self._self_s[name] += self_s
            self._count[name] += 1
            if ev is not None:
                self._events.append(ev)

    def add(self, name, seconds):
        """Account an externally-timed interval as a leaf span (semaphore
        hold time is measured acquire->release, which brackets yields and
        cannot be a context-managed span)."""
        ev = None
        if self._timeline:
            import threading
            import time
            t = threading.current_thread()
            ev = (name, time.perf_counter() - seconds, seconds,
                  t.ident, t.name)
        with self._mu:
            self._self_s[name] += seconds
            self._count[name] += 1
            if ev is not None:
                self._events.append(ev)

    def wall_s(self) -> float:
        """Wall clock between __enter__ and __exit__ (or now, while still
        open); 0.0 when the recorder was never entered."""
        if self._wall is not None:
            return self._wall
        if self._t0 is None:
            return 0.0
        import time
        return time.perf_counter() - self._t0

    def report(self) -> dict:
        """name -> {selfS, count}, most-expensive first, plus two reserved
        scalar entries: ``wallS`` (the recorder's wall clock) and
        ``concurrency`` (sum of self-time over wall — pool threads
        legitimately push this past 1.0; ~1.0 means serial execution)."""
        with self._mu:
            out: Dict[str, Any] = {
                name: {"selfS": round(s, 4), "count": self._count[name]}
                for name, s in sorted(self._self_s.items(),
                                      key=lambda kv: -kv[1])}
            total_self = sum(self._self_s.values())
        wall = self.wall_s()
        out["wallS"] = round(wall, 4)
        out["concurrency"] = round(total_self / wall, 2) if wall > 0 else 0.0
        return out

    # -- Chrome-trace / Perfetto timeline export ----------------------------
    def chrome_trace(self) -> dict:
        """The recorded spans as a Chrome-trace JSON object (the format
        chrome://tracing and ui.perfetto.dev open natively): one complete
        ("X") event per span with microsecond ts/dur relative to recorder
        entry, grouped by thread, plus thread_name metadata so the task
        pool / shuffle threads show under their real names."""
        base = self._t0 if self._t0 is not None else 0.0
        with self._mu:
            events = list(self._events)
        out: List[dict] = [{
            "ph": "M", "name": "process_name", "pid": 0, "tid": 0,
            "args": {"name": "spark-rapids-tpu query"}}]
        # synthetic track ids keyed on (ident, name): CPython REUSES
        # thread idents after a thread exits, so keying on ident alone
        # would merge a dead shuffle-conn thread's spans into whichever
        # later thread inherited its ident
        track_of: Dict[tuple, int] = {}
        for name, begin, dur, tid, tname in events:
            track = track_of.setdefault((tid, tname), len(track_of) + 1)
            ev = {
                "ph": "X", "cat": "span", "name": name, "pid": 0,
                "tid": track, "ts": round((begin - base) * 1e6, 1),
                "dur": round(dur * 1e6, 1)}
            if self.query_id is not None:
                # per-event query attribution: the merged multi-worker
                # timeline filters/joins spans on this
                ev["args"] = {"query": self.query_id}
            out.append(ev)
        for (_tid, tname), track in sorted(track_of.items(),
                                           key=lambda kv: kv[1]):
            out.append({"ph": "M", "name": "thread_name", "pid": 0,
                        "tid": track, "args": {"name": tname}})
        doc = {"traceEvents": out, "displayTimeUnit": "ms"}
        if self.query_id is not None:
            doc["queryId"] = self.query_id
        return doc

    def dump_chrome_trace(self, path: str) -> str:
        """Write :meth:`chrome_trace` to ``path`` (the per-query
        ``trace.json`` the bench runner emits); returns the path.
        Parent directories are created defensively — a --trace-dir
        naming a not-yet-existing nested path must not fail the dump."""
        import json
        import os
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


def merge_chrome_traces(traces, query_id: Optional[str] = None) -> dict:
    """Join several workers' Chrome-trace documents into ONE timeline
    (docs/observability.md §8): each source becomes a distinct ``pid``
    (its own process group in chrome://tracing / ui.perfetto.dev), its
    thread tracks and thread_name metadata ride along unchanged, and —
    when ``query_id`` is given — span ("X") events are filtered to the
    ones carrying that query id, so a merged distributed timeline shows
    exactly one query across every worker that executed it.

    ``traces`` items are Chrome-trace dicts (``SpanRecorder.chrome_trace``
    output) or paths to dumped trace.json files."""
    import json
    traces = list(traces)
    events: List[dict] = []
    for w, tr in enumerate(traces):
        if isinstance(tr, str):
            with open(tr) as f:
                tr = json.load(f)
        label = f"worker {w}"
        saw_process_meta = False
        for ev in tr.get("traceEvents", ()):
            ev = dict(ev)
            if ev.get("ph") == "X" and query_id is not None and \
                    (ev.get("args") or {}).get("query") != query_id:
                continue
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                saw_process_meta = True
                prev = (ev.get("args") or {}).get("name", "")
                ev["args"] = {"name": f"{label}: {prev}" if prev else label}
            ev["pid"] = w
            events.append(ev)
        if not saw_process_meta:
            events.append({"ph": "M", "name": "process_name", "pid": w,
                           "tid": 0, "args": {"name": label}})
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "mergedSources": len(traces)}
    if query_id is not None:
        doc["queryId"] = query_id
    return doc


def record_span(name: str, seconds: float) -> None:
    """Feed an externally-timed interval into the active recorder (no-op
    when no query is recording)."""
    rec = SpanRecorder.active
    if rec is not None:
        rec.add(name, seconds)


def start_profiler_server(port: int = 9012) -> None:
    """Expose the live jax profiler (xprof capture target)."""
    import jax
    jax.profiler.start_server(port)


# ---------------------------------------------------------------------------
# Attributed host-sync counting
# ---------------------------------------------------------------------------
#
# On tunnel/high-latency links every blocking device->host readback costs a
# full round trip (~0.1-0.35 s measured), so END-TO-END query time is
# dominated by HOW MANY syncs the engine performs, not by kernel time.
# Wall-clock swings 2-5x between runs on the same code; attributed sync
# counts are deterministic, so they are the perf-regression metric of
# record (the reference's analog is NVTX ranges + nsys counting kernel
# launches and D2H copies).

class SyncCounter:
    """Counts blocking device->host materializations while active, each
    attributed to the innermost spark_rapids_tpu frame that triggered it.
    Works by wrapping ``ArrayImpl._value`` — the single funnel every
    np.asarray / device_get / float() / int() readback goes through.

    The wrapper installs once and STAYS installed (one None check per
    readback when no counter is active — cheaper than racing property
    swaps on the live class). The entering thread's counter also becomes
    the process default so task-pool worker threads (which do the actual
    partition drains) record into it; a thread entering its own counter
    overrides the default for itself. ``_uninstall`` exists for tests
    that must restore the pristine property."""

    _tls = None                    # lazy threading.local
    #: process-lifetime total of counted syncs (telemetry registry gauge
    #: ``tpu_host_syncs_total``); best-effort like the per-counter maps
    process_total: int = 0
    _default_stack: List["SyncCounter"] = []
    # guards _default_stack: counters enter on the driving thread but
    # exits can interleave across threads (generator-suspended queries,
    # tests driving counters from workers), and bare list.append/remove
    # racing on the shared stack can drop or resurrect a default counter
    _stack_mu = named_lock("exec.tracing.SyncCounter._default_stack")
    _orig_value = None

    @classmethod
    def _get_active(cls) -> Optional["SyncCounter"]:
        tls = cls._tls
        local = getattr(tls, "active", None) if tls is not None else None
        if local is not None:
            return local
        # LOCK-FREE read: this runs on EVERY ArrayImpl._value access (the
        # readback funnel), so it must not acquire. Mutations (__enter__/
        # __exit__) serialize under _stack_mu; the read handles the
        # check-then-index window (a concurrent exit emptying the list)
        # by catching instead of locking — either counter-or-None answer
        # is valid during a swap
        try:
            return cls._default_stack[-1]
        except IndexError:
            return None

    def __init__(self):
        self.total = 0
        self.sites: dict = {}
        self.spans: dict = {}      # innermost-span name -> sync count

    # -- patch management ---------------------------------------------------
    @classmethod
    def _install(cls):
        if cls._orig_value is not None:
            return
        from jax._src import array as jarray
        orig = jarray.ArrayImpl._value

        def counting_value(self_arr):
            c = cls._get_active()
            # only count REAL syncs: a cached host value is free
            if c is not None and \
                    getattr(self_arr, "_npy_value", None) is None:
                c._record()
            return orig.fget(self_arr)

        cls._orig_value = orig  # lint: unguarded-ok one-time process-lifetime patch installed from the first entering thread
        jarray.ArrayImpl._value = property(counting_value)

    @classmethod
    def _uninstall(cls):
        if cls._orig_value is None:
            return
        from jax._src import array as jarray
        jarray.ArrayImpl._value = cls._orig_value
        cls._orig_value = None  # lint: unguarded-ok test-only restore of the pristine property

    def _record(self):
        import traceback
        self.total += 1  # lint: unguarded-ok best-effort counter: concurrent increments may undercount, the attributed counts are advisory diagnostics
        SyncCounter.process_total += 1  # lint: unguarded-ok same best-effort counter discipline, harvested as a telemetry gauge
        site = "<unknown>"
        for frame in reversed(traceback.extract_stack(limit=24)):
            fn = frame.filename
            if "spark_rapids_tpu" in fn and "tracing.py" not in fn:
                short = fn[fn.rindex("spark_rapids_tpu"):]
                site = f"{short}:{frame.lineno}"
                break
        self.sites[site] = self.sites.get(site, 0) + 1  # lint: unguarded-ok best-effort counter map, see total above
        # flight-recorder breadcrumb: which code path paid a round trip
        # right before a crash (the post-mortem question)
        from ..service.telemetry import flight_record
        flight_record("sync", site)
        # attribute to the innermost open span on this thread (the
        # analysis/sync_audit per-span breakdown): which named region of
        # the execute wall is paying link round trips
        rec = SpanRecorder.active
        span = rec.current_span() if rec is not None else None
        span = span or "<no-span>"
        self.spans[span] = self.spans.get(span, 0) + 1  # lint: unguarded-ok best-effort counter map, see total above
        # ...and to the innermost open EXEC's metrics bag, so EXPLAIN
        # ANALYZE shows which plan node paid the round trip
        from .metrics import attribute
        attribute("hostSyncs")

    # -- context ------------------------------------------------------------
    def __enter__(self):
        import threading
        cls = SyncCounter
        cls._install()
        if cls._tls is None:
            cls._tls = threading.local()
        self._prev = getattr(cls._tls, "active", None)  # lint: unguarded-ok entering thread's own field, set before the counter is shared
        cls._tls.active = self
        # the entering thread's counter is also the process default so
        # pool worker threads record into it; removal is by identity (not
        # LIFO) so interleaved exits across threads cannot resurrect a
        # finished counter as the lingering default
        with cls._stack_mu:
            cls._default_stack.append(self)
        return self

    def __exit__(self, *exc):
        SyncCounter._tls.active = self._prev
        with SyncCounter._stack_mu:
            try:
                SyncCounter._default_stack.remove(self)
            except ValueError:
                pass
        return False

    def report(self, top: int = 10) -> dict:
        ordered = sorted(self.sites.items(), key=lambda kv: -kv[1])
        spans = sorted(self.spans.items(), key=lambda kv: -kv[1])
        return {"hostSyncs": self.total,
                "syncSites": dict(ordered[:top]),
                "syncSpans": dict(spans[:top])}
