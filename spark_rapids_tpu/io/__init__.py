"""Columnar IO: Parquet/ORC/CSV via pyarrow CPU decode + device upload.

Reference: SURVEY.md §2.5 — the reference reads footers and assembles row
groups on CPU, then decodes on GPU (``Table.readParquet``,
GpuParquetScan.scala:1022). TPUs have no decode engines, so the decode
boundary shifts fully to the CPU (DESIGN.md §7): pyarrow decodes to Arrow;
upload to device is the HostColumnarToGpu step. The three reader strategies
(PERFILE / COALESCING / MULTITHREADED, GpuParquetScan.scala:1451,824,1145)
are preserved at the host level in scan.py.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional

from ..columnar import dtypes as dt


def expand_paths(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                # prune hidden/staging dirs (_temporary, .hive-staging) and
                # sort in place for deterministic traversal across hosts
                dirs[:] = sorted(d for d in dirs if not d.startswith((".", "_")))
                for f in sorted(files):
                    if not f.startswith((".", "_")) and not f.endswith(".crc"):
                        out.append(os.path.join(root, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


_HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def partition_values_for(path: str, roots: List[str]) -> List[tuple]:
    """``k=v`` directory segments between the scan root and the file,
    URL-decoded, in path order (the hive partition layout the reference
    appends post-decode, ColumnarPartitionReaderWithPartitionValues.scala +
    GpuParquetScan.scala:749-759). Returns [(name, value_str|None)]."""
    from urllib.parse import unquote
    rel = None
    for r in roots:
        root = os.path.abspath(r)
        p = os.path.abspath(path)
        if p.startswith(root + os.sep):
            rel = os.path.relpath(os.path.dirname(p), root)
            break
    if rel in (None, "."):
        return []
    out = []
    for seg in rel.split(os.sep):
        if "=" not in seg:
            continue
        k, v = seg.split("=", 1)
        v = unquote(v)
        out.append((k, None if v == _HIVE_NULL else v))
    return out


def infer_partition_dtype(values: List[Optional[str]]) -> dt.DType:
    """Spark's partition-column type inference, reduced: every non-null
    value parses as int -> bigint; as float -> double; else string."""
    non_null = [v for v in values if v is not None]
    if not non_null:
        return dt.STRING
    try:
        for v in non_null:
            int(v)
        return dt.INT64
    except ValueError:
        pass
    try:
        for v in non_null:
            float(v)
        return dt.FLOAT64
    except ValueError:
        pass
    return dt.STRING


def partition_schema(files: List[str], roots: List[str]) -> dt.Schema:
    """Partition columns discovered from the directory layout of ``files``."""
    by_name: Dict[str, List[Optional[str]]] = {}
    order: List[str] = []
    for f in files:
        for k, v in partition_values_for(f, roots):
            if k not in by_name:
                by_name[k] = []
                order.append(k)
            by_name[k].append(v)
    return dt.Schema([
        dt.Field(k, infer_partition_dtype(by_name[k]), True)
        for k in order])


def append_partition_columns(table, path: str, roots: List[str],
                             pschema: dt.Schema):
    """Arrow table + constant partition-value columns for this file."""
    import pyarrow as pa
    values = dict(partition_values_for(path, roots))
    for f in pschema:
        if f.name in table.column_names:
            continue
        raw = values.get(f.name)
        if raw is None:
            val = None
        elif f.dtype == dt.INT64:
            val = int(raw)
        elif f.dtype == dt.FLOAT64:
            val = float(raw)
        else:
            val = raw
        arr = pa.array([val] * table.num_rows, type=dt.to_arrow(f.dtype))
        table = table.append_column(f.name, arr)
    return table


def infer_schema(fmt: str, paths: List[str],
                 options: Dict[str, Any]) -> dt.Schema:
    files = expand_paths(paths)
    if not files:
        raise FileNotFoundError(f"no input files in {paths}")
    first = files[0]
    if fmt == "parquet":
        import pyarrow.parquet as pq
        arrow_schema = pq.read_schema(first)
    elif fmt == "orc":
        import pyarrow.orc as orc
        arrow_schema = orc.ORCFile(first).schema
    elif fmt == "csv":
        arrow_schema = _csv_schema(first, options)
    else:
        raise ValueError(f"unsupported format {fmt}")
    fields = []
    for name, typ in zip(arrow_schema.names, arrow_schema.types):
        fields.append(dt.Field(name, dt.from_arrow(typ)))
    # hive-layout partition columns append after the file columns
    for f in partition_schema(files, paths):
        if f.name not in {x.name for x in fields}:
            fields.append(f)
    return dt.Schema(fields)


def _csv_opts(options: Dict[str, Any]):
    import pyarrow.csv as pcsv
    header = str(options.get("header", "false")).lower() == "true"
    delim = options.get("sep", options.get("delimiter", ","))
    read_opts = pcsv.ReadOptions(autogenerate_column_names=not header)
    parse_opts = pcsv.ParseOptions(delimiter=delim)
    # Spark: only the configured nullValue (default empty string) reads as NULL
    conv = pcsv.ConvertOptions(
        null_values=[options.get("nullValue", "")], strings_can_be_null=True)
    return header, read_opts, parse_opts, conv


def _csv_schema(path: str, options: Dict[str, Any]):
    """Schema from the first block only (no full-file decode at plan time)."""
    import pyarrow.csv as pcsv
    header, read_opts, parse_opts, conv = _csv_opts(options)
    with pcsv.open_csv(path, read_options=read_opts, parse_options=parse_opts,
                       convert_options=conv) as reader:
        schema = reader.schema
    if not header:
        import pyarrow as pa
        schema = pa.schema([f.with_name(f"_c{i}")
                            for i, f in enumerate(schema)])
    return schema


def _read_csv(path: str, options: Dict[str, Any]):
    import pyarrow.csv as pcsv
    header, read_opts, parse_opts, conv = _csv_opts(options)
    table = pcsv.read_csv(path, read_options=read_opts,
                          parse_options=parse_opts, convert_options=conv)
    if not header:
        # Spark naming: _c0, _c1...
        table = table.rename_columns(
            [f"_c{i}" for i in range(table.num_columns)])
    return table


def read_file_to_arrow(fmt: str, path: str, options: Dict[str, Any],
                       columns: Optional[List[str]] = None, filters=None,
                       roots: Optional[List[str]] = None,
                       pschema: Optional[dt.Schema] = None):
    if fmt == "parquet":
        import pyarrow.parquet as pq
        # partitioning=None: k=v dir segments are appended as typed
        # columns by append_partition_columns below — pyarrow's own hive
        # inference must stay off (it fails outright on an all-NULL
        # partition dir, region=__HIVE_DEFAULT_PARTITION__)
        t = pq.read_table(path, columns=columns, filters=filters,
                          partitioning=None)
    elif fmt == "orc":
        import pyarrow.orc as orc
        t = orc.ORCFile(path).read(columns=columns)
    elif fmt == "csv":
        t = _read_csv(path, options)
        if columns:
            t = t.select(columns)
    else:
        raise ValueError(f"unsupported format {fmt}")
    if roots and pschema is not None and len(pschema):
        t = append_partition_columns(t, path, roots, pschema)
    return t


def read_to_arrow(fmt: str, paths: List[str], options: Dict[str, Any]):
    import pyarrow as pa
    files = expand_paths(paths)
    pschema = partition_schema(files, paths)
    tables = [read_file_to_arrow(fmt, f, options, roots=paths,
                                 pschema=pschema) for f in files]
    if len(tables) == 1:
        return tables[0]
    return pa.concat_tables(tables, promote_options="permissive")
